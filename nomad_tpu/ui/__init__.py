"""Web UI (ref ui/: the reference ships an Ember SPA at /ui/; this is a
single-file SPA over the same /v1/* API — jobs, nodes, allocations and
evaluations with drill-down, auto-refresh, and ACL token support, plus the
operational surfaces: job submit with plan-diff preview (ref ui job-run
routes), an allocation filesystem browser (ref ui fs routes), and an
interactive exec terminal over the exec websocket (ref ui exec routes)."""

INDEX_HTML = """<!doctype html>
<html lang="en">
<head>
<meta charset="utf-8">
<title>nomad-tpu</title>
<style>
  :root { --bg:#15181f; --panel:#1d212b; --line:#2a2f3d; --text:#e6e9f0;
          --dim:#8b93a7; --accent:#5b8dee; --ok:#39b37a; --bad:#e35d6a;
          --warn:#d9a23c; }
  * { box-sizing:border-box; }
  body { margin:0; background:var(--bg); color:var(--text);
         font:14px/1.5 system-ui, sans-serif; }
  header { display:flex; align-items:center; gap:1.5rem; padding:.8rem 1.2rem;
           background:var(--panel); border-bottom:1px solid var(--line); }
  header h1 { font-size:1rem; margin:0; color:var(--accent); }
  nav a { color:var(--dim); text-decoration:none; margin-right:1rem;
          padding:.2rem 0; }
  nav a.active { color:var(--text); border-bottom:2px solid var(--accent); }
  header input { background:var(--bg); color:var(--text);
                 border:1px solid var(--line); border-radius:4px;
                 padding:.3rem .5rem; width:16rem; }
  #search { margin-left:auto; width:14rem; }
  .kv { display:grid; grid-template-columns:14rem 1fr; gap:.15rem .8rem;
        background:var(--panel); border:1px solid var(--line);
        border-radius:6px; padding:.8rem 1rem; margin-bottom:1rem; }
  .kv dt { color:var(--dim); } .kv dd { margin:0; }
  .panel { background:var(--panel); border:1px solid var(--line);
           border-radius:6px; padding:.8rem 1rem; margin-bottom:1rem; }
  .spark { vertical-align:middle; margin-right:.6rem; }
  .sparkval { color:var(--dim); font-size:.8rem; margin-right:1.2rem; }
  .actions button { margin-bottom:.6rem; }
  .ok-note { color:var(--ok); } .warn-note { color:var(--warn); }
  main { padding:1rem 1.2rem; }
  table { width:100%; border-collapse:collapse; background:var(--panel);
          border:1px solid var(--line); border-radius:6px; overflow:hidden; }
  th, td { text-align:left; padding:.45rem .7rem;
           border-bottom:1px solid var(--line); }
  th { color:var(--dim); font-weight:500; font-size:.8rem;
       text-transform:uppercase; letter-spacing:.04em; }
  tr:last-child td { border-bottom:none; }
  tr.row:hover { background:#232838; cursor:pointer; }
  .status { display:inline-block; padding:0 .5rem; border-radius:99px;
            font-size:.8rem; }
  .s-running, .s-ready, .s-complete, .s-successful
    { background:#173527; color:var(--ok); }
  .s-pending, .s-initializing { background:#39301b; color:var(--warn); }
  .s-dead, .s-failed, .s-down, .s-lost { background:#3a2125; color:var(--bad); }
  pre { background:var(--panel); border:1px solid var(--line);
        border-radius:6px; padding:1rem; overflow:auto; max-height:70vh; }
  .err { color:var(--bad); padding:.6rem 0; }
  .crumb { color:var(--dim); margin-bottom:.8rem; }
  .crumb a { color:var(--accent); text-decoration:none; }
  textarea { width:100%; min-height:16rem; background:var(--panel);
             color:var(--text); border:1px solid var(--line);
             border-radius:6px; padding:.8rem; font:13px/1.5 monospace; }
  button { background:var(--accent); color:#fff; border:none;
           border-radius:4px; padding:.4rem .9rem; cursor:pointer;
           margin-right:.5rem; }
  button.ghost { background:var(--panel); color:var(--text);
                 border:1px solid var(--line); }
  .diff-add { color:var(--ok); } .diff-del { color:var(--bad); }
  .diff-edit { color:var(--warn); }
  #term { background:#0d0f14; border:1px solid var(--line);
          border-radius:6px; padding:.8rem; font:13px/1.45 monospace;
          height:20rem; overflow:auto; white-space:pre-wrap; }
  #termin { width:100%; background:var(--panel); color:var(--text);
            border:1px solid var(--line); border-radius:4px;
            padding:.4rem .6rem; font:13px monospace; margin-top:.4rem; }
  .fspath a { color:var(--accent); text-decoration:none; }
</style>
</head>
<body>
<header>
  <h1>nomad-tpu</h1>
  <nav>
    <a href="#/jobs">Jobs</a>
    <a href="#/nodes">Nodes</a>
    <a href="#/allocations">Allocations</a>
    <a href="#/evaluations">Evaluations</a>
    <a href="#/deployments">Deployments</a>
    <a href="#/services">Services</a>
    <a href="#/servers">Servers</a>
    <a href="#/run">Run</a>
  </nav>
  <input id="search" placeholder="Search… (Enter)"
         onkeydown="if(event.key==='Enter')location.hash='#/search/'+encodeURIComponent(this.value)" />
  <input id="token" placeholder="ACL token (X-Nomad-Token)" />
</header>
<main id="view">Loading…</main>
<script>
const view = document.getElementById('view');
const tokenInput = document.getElementById('token');
tokenInput.value = localStorage.getItem('nomad_token') || '';
tokenInput.addEventListener('change', () => {
  localStorage.setItem('nomad_token', tokenInput.value); render();
});

async function api(path, method, body) {
  const headers = {};
  if (tokenInput.value) headers['X-Nomad-Token'] = tokenInput.value;
  const opts = {headers, method: method || 'GET'};
  if (body !== undefined) {
    headers['Content-Type'] = 'application/json';
    opts.body = JSON.stringify(body);
  }
  const resp = await fetch(path, opts);
  if (!resp.ok) throw new Error(resp.status + ' ' + ((await resp.json()).error || ''));
  return resp.json();
}
const badge = s => `<span class="status s-${s}">${s}</span>`;
const esc = x => String(x ?? '').replace(/[&<>"]/g,
  c => ({'&':'&amp;','<':'&lt;','>':'&gt;','"':'&quot;'}[c]));
// UTF-8-safe base64 (btoa alone throws on non-latin1 and mojibakes UTF-8);
// also the only safe way to embed untrusted strings (file names!) inside
// inline JS handlers — base64's charset can't break out of a JS string
const b64e = s => btoa(String.fromCharCode(...new TextEncoder().encode(s)));
const b64d = s => new TextDecoder().decode(
  Uint8Array.from(atob(s), c => c.charCodeAt(0)));

function table(headers, rows, onclickPrefix) {
  return `<table><tr>${headers.map(h=>`<th>${h}</th>`).join('')}</tr>` +
    rows.map(r => `<tr class="row" onclick="location.hash='${onclickPrefix}/${r.id}'">` +
      r.cells.map(c=>`<td>${c}</td>`).join('') + '</tr>').join('') + '</table>';
}

const routes = {
  async jobs() {
    const jobs = await api('/v1/jobs');
    return table(['ID','Type','Priority','Status'], jobs.map(j => ({
      id: encodeURIComponent(j.ID),
      cells: [esc(j.ID), esc(j.Type), j.Priority, badge(esc(j.Status))]
    })), '#/job');
  },
  async job(id) {
    const j = await api('/v1/job/' + id);
    let allocs = [], deps = [], evals = [], summary = null;
    try { allocs = await api('/v1/job/' + id + '/allocations'); } catch {}
    try { deps = await api('/v1/job/' + id + '/deployments'); } catch {}
    try { evals = await api('/v1/job/' + id + '/evaluations'); } catch {}
    try { summary = await api('/v1/job/' + id + '/summary'); } catch {}
    let html = `<div class="crumb"><a href="#/jobs">jobs</a> / ${esc(j.id)}</div>`;
    if (summary && summary.summary) {
      html += '<h3>Summary</h3>' +
        '<table><tr><th>Group</th><th>Queued</th><th>Starting</th><th>Running</th>' +
        '<th>Complete</th><th>Failed</th><th>Lost</th></tr>' +
        Object.entries(summary.summary).map(([g, s]) =>
          `<tr><td>${esc(g)}</td><td>${s.queued||0}</td><td>${s.starting||0}</td>` +
          `<td>${s.running||0}</td><td>${s.complete||0}</td><td>${s.failed||0}</td>` +
          `<td>${s.lost||0}</td></tr>`).join('') + '</table>';
    }
    html += '<h3>Allocations</h3>' +
      table(['Alloc','Group','Desired','Client','Node'], allocs.map(a => ({
        id: a.ID, cells: [esc(a.ID.slice(0,8)), esc(a.TaskGroup),
          badge(esc(a.DesiredStatus)), badge(esc(a.ClientStatus)),
          esc((a.NodeID||'').slice(0,8))]
      })), '#/allocation');
    if (deps.length) {
      html += '<h3>Deployments</h3>' +
        table(['ID','Version','Status','Description'], deps.map(d => ({
          id: d.id, cells: [esc(d.id.slice(0,8)), d.job_version,
            badge(esc(d.status)), esc(d.status_description || '')]
        })), '#/deployment');
    }
    if (evals.length) {
      html += '<h3>Evaluations</h3>' +
        table(['ID','Type','Triggered By','Status','Placement Failures'],
          evals.map(e => ({
            id: e.id, cells: [esc(e.id.slice(0,8)), esc(e.type),
              esc(e.triggered_by), badge(esc(e.status)),
              Object.keys(e.failed_tg_allocs || {}).length ? 'yes' : '']
          })), '#/evaluation');
    }
    return html + `<h3>Spec</h3><pre>${esc(JSON.stringify(j, null, 2))}</pre>`;
  },
  async nodes() {
    const nodes = await api('/v1/nodes');
    return table(['ID','Name','DC','Class','Status'], nodes.map(n => ({
      id: n.ID, cells: [esc(n.ID.slice(0,8)), esc(n.Name), esc(n.Datacenter),
        esc(n.NodeClass || '-'), badge(esc(n.Status))]
    })), '#/node');
  },
  async node(id) {
    const n = await api('/v1/node/' + id);
    let allocs = [];
    try { allocs = await api('/v1/node/' + id + '/allocations'); } catch {}
    let html = `<div class="crumb"><a href="#/nodes">nodes</a> / ${esc(n.name)}</div>` +
      `<dl class="kv">
        <dt>Status</dt><dd>${badge(esc(n.status))}</dd>
        <dt>Eligibility</dt><dd>${badge(esc(n.scheduling_eligibility))}${n.drain ? ' (draining)' : ''}</dd>
        <dt>Datacenter</dt><dd>${esc(n.datacenter)}</dd>
        <dt>Class</dt><dd>${esc(n.node_class || '-')}</dd>
        <dt>Drivers</dt><dd>${esc(Object.keys(n.drivers || {}).join(', ') || '-')}</dd>
      </dl>`;
    // node operator actions (ref ui node drain/eligibility controls)
    html += `<div class="actions">
      <button onclick="nodeAction('${n.id}','drain',{DrainSpec:{}})"
        ${n.drain ? 'disabled' : ''}>Drain</button>
      <button class="ghost" onclick="nodeAction('${n.id}','drain',{MarkEligible:true})"
        ${n.drain ? '' : 'disabled'}>Stop drain</button>
      <button class="ghost" onclick="nodeAction('${n.id}','eligibility',{Eligibility:'ineligible'})"
        ${n.scheduling_eligibility === 'eligible' ? '' : 'disabled'}>Mark ineligible</button>
      <button class="ghost" onclick="nodeAction('${n.id}','eligibility',{Eligibility:'eligible'})"
        ${n.scheduling_eligibility === 'eligible' ? 'disabled' : ''}>Mark eligible</button>
      <span id="nodeout"></span></div>`;
    html += '<h3>Allocations</h3>' +
      table(['Alloc','Job','Group','Client'], allocs.map(a => ({
        id: a.ID, cells: [esc(a.ID.slice(0,8)), esc(a.JobID), esc(a.TaskGroup),
          badge(esc(a.ClientStatus))]
      })), '#/allocation');
    const events = (n.events || []).slice(-8);
    if (events.length) {
      html += '<h3>Events</h3><table><tr><th>Time</th><th>Subsystem</th><th>Message</th></tr>' +
        events.map(e => `<tr><td>${new Date((e.timestamp||0)/1e6).toLocaleTimeString()}</td>` +
          `<td>${esc(e.subsystem)}</td><td>${esc(e.message)}</td></tr>`).join('') +
        '</table>';
    }
    return html + `<h3>Node</h3><pre>${esc(JSON.stringify(n, null, 2))}</pre>`;
  },
  async allocations() {
    const allocs = await api('/v1/allocations');
    return table(['ID','Job','Group','Desired','Client'], allocs.map(a => ({
      id: a.ID, cells: [esc(a.ID.slice(0,8)), esc(a.JobID), esc(a.TaskGroup),
        badge(esc(a.DesiredStatus)), badge(esc(a.ClientStatus))]
    })), '#/allocation');
  },
  async allocation(id) {
    const a = await api('/v1/allocation/' + id);
    const tasks = Object.keys(a.task_states || {});
    // task drill-down: state, lifecycle actions, events, live stats
    let tasksHtml = '<h3>Tasks</h3>';
    for (const t of tasks) {
      const ts = a.task_states[t];
      const ev = (ts.events || []).slice(-8);
      tasksHtml += `<div class="panel"><b>${esc(t)}</b> ${badge(esc(ts.state))}` +
        (ts.failed ? ' <span class="err">failed</span>' : '') +
        ` · restarts ${ts.restarts || 0}` +
        ` <button class="ghost" onclick="taskAction('${a.id}','restart','${b64e(t)}')">Restart</button>` +
        ` <button class="ghost" onclick="taskAction('${a.id}','signal','${b64e(t)}')">SIGINT</button>` +
        `<div id="spark-${esc(t)}" style="margin:.5rem 0"></div>` +
        (ev.length ? '<table><tr><th>Time</th><th>Type</th><th>Message</th></tr>' +
          ev.map(e => `<tr><td>${new Date((e.time||0)/1e6).toLocaleTimeString()}</td>` +
            `<td>${esc(e.type)}</td><td>${esc(e.message)}</td></tr>`).join('') +
          '</table>' : '') + '</div>';
    }
    tasksHtml += `<div class="actions">
      <button class="ghost" onclick="allocAction('${a.id}','stop')">Stop allocation</button>
      <span id="allocout"></span></div>`;
    let logsHtml = '';
    for (const t of tasks) {
      for (const kind of ['stdout', 'stderr']) {
        try {
          const l = await api(`/v1/client/fs/logs/${a.id}?task=${encodeURIComponent(t)}&type=${kind}&origin=end&offset=8192`);
          if (l.Data) {
            logsHtml += `<h3>${esc(t)} · ${kind} (tail)</h3><pre>${esc(l.Data)}</pre>`;
          }
        } catch {}
      }
    }
    const taskOpts = tasks.map(t => `<option>${esc(t)}</option>`).join('');
    window._postRender = () => { fsGo(a.id, b64e('/')); statsStart(a.id); };
    return `<div class="crumb"><a href="#/allocations">allocations</a> / ${esc(a.id.slice(0,8))}</div>` +
      tasksHtml +
      `<h3>Exec</h3>
       <div>task <select id="termtask">${taskOpts}</select>
         <button onclick="termConnect('${a.id}')">Connect /bin/sh</button>
         <button class="ghost" onclick="termClose()">Disconnect</button></div>
       <div id="term">(not connected)</div>
       <input id="termin" placeholder="command… (Enter to send)"
              onkeydown="if(event.key==='Enter')termSend()" />` +
      `<h3>Filesystem</h3><div id="fspath" class="fspath"></div>
       <div id="fsview">Loading…</div>` +
      logsHtml +
      `<h3>Allocation</h3><pre>${esc(JSON.stringify(a, null, 2))}</pre>`;
  },
  async run() {
    const saved = localStorage.getItem('nomad_run_hcl') ||
      'job "example" {\\n  datacenters = ["dc1"]\\n  group "web" {\\n    task "web" {\\n      driver = "raw_exec"\\n      config {\\n        command = "sleep"\\n        args    = ["300"]\\n      }\\n      resources {\\n        cpu    = 100\\n        memory = 64\\n      }\\n    }\\n  }\\n}\\n';
    return `<h3>Run a job</h3>
      <textarea id="hcl">${esc(saved)}</textarea>
      <div style="margin:.6rem 0">
        <button class="ghost" onclick="planJob()">Plan</button>
        <button onclick="runJob()">Run</button>
      </div>
      <div id="planout"></div>`;
  },
  async evaluations() {
    const evals = await api('/v1/evaluations');
    return table(['ID','Job','Type','Triggered By','Status','Placement Failures'],
      evals.map(e => ({
        id: e.id, cells: [esc(e.id.slice(0,8)), esc(e.job_id), esc(e.type),
          esc(e.triggered_by), badge(esc(e.status)),
          Object.keys(e.failed_tg_allocs || {}).length ? 'yes' : '']
      })), '#/evaluation');
  },
  async evaluation(id) {
    const e = await api('/v1/evaluation/' + id);
    let allocs = [];
    try { allocs = await api('/v1/evaluation/' + id + '/allocations'); } catch {}
    let html = `<div class="crumb"><a href="#/evaluations">evaluations</a> / ${esc(e.id.slice(0,8))}</div>` +
      `<dl class="kv">
        <dt>Job</dt><dd><a href="#/job/${encodeURIComponent(e.job_id)}">${esc(e.job_id)}</a></dd>
        <dt>Type</dt><dd>${esc(e.type)}</dd>
        <dt>Triggered by</dt><dd>${esc(e.triggered_by)}</dd>
        <dt>Status</dt><dd>${badge(esc(e.status))} ${esc(e.status_description || '')}</dd>
        <dt>Priority</dt><dd>${e.priority}</dd>
        ${e.blocked_eval ? `<dt>Blocked eval</dt><dd><a href="#/evaluation/${e.blocked_eval}">${esc(e.blocked_eval.slice(0,8))}</a></dd>` : ''}
        ${e.queued_allocations ? `<dt>Queued allocs</dt><dd>${esc(JSON.stringify(e.queued_allocations))}</dd>` : ''}
      </dl>`;
    const failed = e.failed_tg_allocs || {};
    if (Object.keys(failed).length) {
      html += '<h3 class="err">Placement failures</h3>';
      for (const [tg, m] of Object.entries(failed)) {
        const rows = [];
        rows.push(['Nodes evaluated', m.nodes_evaluated]);
        rows.push(['Nodes available', esc(JSON.stringify(m.nodes_available || {}))]);
        for (const [cls, n] of Object.entries(m.class_filtered || {}))
          rows.push([`Class ${esc(cls)} filtered`, n]);
        for (const [c, n] of Object.entries(m.constraint_filtered || {}))
          rows.push([`Constraint ${esc(c)}`, n]);
        rows.push(['Nodes exhausted', m.nodes_exhausted]);
        for (const [d, n] of Object.entries(m.dimension_exhausted || {}))
          rows.push([`Dimension ${esc(d)} exhausted`, n]);
        for (const [q, n] of Object.entries(m.quota_exhausted || {}))
          rows.push([`Quota ${esc(q)} exhausted`, n]);
        if (m.coalesced_failures)
          rows.push(['Coalesced failures', m.coalesced_failures]);
        html += `<div class="panel"><b>${esc(tg)}</b><table>` +
          rows.filter(([,v]) => v !== undefined && v !== 0 && v !== '{}')
            .map(([k,v]) => `<tr><td>${k}</td><td>${v}</td></tr>`).join('') +
          '</table></div>';
      }
    }
    if (allocs.length) {
      html += '<h3>Placed allocations</h3>' +
        table(['Alloc','Group','Desired','Client'], allocs.map(a => ({
          id: a.ID, cells: [esc(a.ID.slice(0,8)), esc(a.TaskGroup),
            badge(esc(a.DesiredStatus)), badge(esc(a.ClientStatus))]
        })), '#/allocation');
    }
    return html;
  },
  async deployments() {
    const deps = await api('/v1/deployments');
    return table(['ID','Job','Version','Status','Description'], deps.map(d => ({
      id: d.id, cells: [esc(d.id.slice(0,8)), esc(d.job_id), d.job_version,
        badge(esc(d.status)), esc(d.status_description || '')]
    })), '#/deployment');
  },
  async deployment(id) {
    const d = await api('/v1/deployment/' + id);
    let allocs = [];
    try { allocs = await api('/v1/deployment/allocations/' + d.id); } catch {}
    const active = d.status === 'running' || d.status === 'paused';
    const needsPromote = Object.values(d.task_groups || {}).some(
      s => s.desired_canaries > 0 && !s.promoted);
    let html = `<div class="crumb"><a href="#/deployments">deployments</a> / ${esc(d.id.slice(0,8))}</div>` +
      `<dl class="kv">
        <dt>Job</dt><dd><a href="#/job/${encodeURIComponent(d.job_id)}">${esc(d.job_id)}</a> (version ${d.job_version})</dd>
        <dt>Status</dt><dd>${badge(esc(d.status))} ${esc(d.status_description || '')}</dd>
      </dl>`;
    html += `<div class="actions">
      <button onclick="deployAction('${d.id}','promote',{All:true})"
        ${active && needsPromote ? '' : 'disabled'}>Promote canaries</button>
      <button class="ghost" onclick="deployAction('${d.id}','pause',{Pause:true})"
        ${d.status === 'running' ? '' : 'disabled'}>Pause</button>
      <button class="ghost" onclick="deployAction('${d.id}','pause',{Pause:false})"
        ${d.status === 'paused' ? '' : 'disabled'}>Resume</button>
      <button class="ghost" onclick="deployAction('${d.id}','fail')"
        ${active ? '' : 'disabled'}>Fail</button>
      <span id="deployout"></span></div>`;
    html += '<h3>Task groups</h3>' +
      '<table><tr><th>Group</th><th>Promoted</th><th>Desired</th><th>Canaries</th>' +
      '<th>Placed</th><th>Healthy</th><th>Unhealthy</th><th>Progress deadline</th></tr>' +
      Object.entries(d.task_groups || {}).map(([g, s]) =>
        `<tr><td>${esc(g)}</td>` +
        `<td>${s.desired_canaries > 0 ? (s.promoted ? '<span class="ok-note">yes</span>' : '<span class="warn-note">awaiting</span>') : '-'}</td>` +
        `<td>${s.desired_total}</td><td>${s.placed_canaries ? s.placed_canaries.length : 0}/${s.desired_canaries}</td>` +
        `<td>${s.placed_allocs}</td><td>${s.healthy_allocs}</td><td>${s.unhealthy_allocs}</td>` +
        `<td>${s.progress_deadline ? (s.progress_deadline / 1e9) + 's' : '-'}</td></tr>`
      ).join('') + '</table>';
    if (allocs.length) {
      html += '<h3>Allocations</h3>' +
        table(['Alloc','Group','Desired','Client','Healthy'], allocs.map(a => ({
          id: a.ID, cells: [esc(a.ID.slice(0,8)), esc(a.TaskGroup),
            badge(esc(a.DesiredStatus)), badge(esc(a.ClientStatus)),
            a.DeploymentStatus && a.DeploymentStatus.healthy != null
              ? (a.DeploymentStatus.healthy ? 'yes' : 'no') : '-']
        })), '#/allocation');
    }
    return html;
  },
  async services() {
    const svcs = await api('/v1/services');
    return table(['Service','Job','Alloc','Address','Status','Checks'], svcs.map(s => ({
      id: s.AllocID, cells: [esc(s.ServiceName), esc(s.JobID),
        esc(s.AllocID.slice(0,8)),
        esc(s.Address ? s.Address + ':' + s.Port : '-'),
        badge(esc(s.Status)),
        esc(Object.entries(s.Checks || {}).map(([k,v]) => k + '=' + v).join(' ') || '-')]
    })), '#/allocation');
  },
  async search(rawPrefix) {
    const prefix = decodeURIComponent(rawPrefix || '');
    if (!prefix) return '<div class="crumb">type a prefix in the search box</div>';
    const r = await api('/v1/search', 'PUT', {Prefix: prefix, Context: 'all'});
    const links = {jobs: '#/job/', evals: '#/evaluation/', allocs: '#/allocation/',
                   nodes: '#/node/', deployments: '#/deployment/'};
    let html = `<div class="crumb">search results for <b>${esc(prefix)}</b></div>`;
    let any = false;
    for (const [ctx, ids] of Object.entries(r.matches || {})) {
      if (!ids || !ids.length) continue;
      any = true;
      html += `<h3>${esc(ctx)}${(r.truncations||{})[ctx] ? ' (truncated)' : ''}</h3>` +
        '<table>' + ids.map(i =>
          `<tr class="row" onclick="location.hash='${links[ctx] || '#/jobs'}${encodeURIComponent(i)}'">` +
          `<td>${esc(i)}</td></tr>`).join('') + '</table>';
    }
    return any ? html : html + '<div class="crumb">no matches</div>';
  },
  async servers() {
    const m = await api('/v1/agent/members');
    let health = {Servers: []};
    try { health = await api('/v1/operator/autopilot/health'); } catch {}
    const byId = Object.fromEntries(health.Servers.map(s => [s.ID, s]));
    return `<div class="crumb">region ${esc(m.ServerRegion)}</div>` +
      table(['Name','Address','Gossip','Leader','Healthy','Last Contact'],
        m.Members.map(s => {
          const h = byId[s.Name] || {};
          return {id: '', cells: [esc(s.Name), esc(s.Addr + ':' + s.Port),
            badge(esc(s.Status)),
            h.Leader ? 'yes' : '', badge(h.Healthy === false ? 'failed' : 'ready'),
            esc(h.LastContact == null ? '-' : h.LastContact + 's')]};
        }), '#/servers');
  },
};

// ---- job submit + plan-diff (ref ui job-run routes) ----
async function parseHcl() {
  const hcl = document.getElementById('hcl').value;
  localStorage.setItem('nomad_run_hcl', hcl);
  return api('/v1/jobs/parse', 'PUT', {JobHCL: hcl});
}
function renderDiff(diff) {
  if (!diff) return '(no diff)';
  const lines = [];
  const mark = t => t === 'Added' ? 'diff-add' : t === 'Deleted' ? 'diff-del'
    : t === 'Edited' ? 'diff-edit' : '';
  const field = (f, pad) => lines.push(
    `${pad}<span class="${mark(f.Type)}">${esc(f.Name)}: ` +
    `${esc(f.Old||'∅')} → ${esc(f.New||'∅')}</span>`);
  const objects = (objs, pad) => {
    for (const o of (objs || [])) {
      lines.push(`${pad}<span class="${mark(o.Type)}">${esc(o.Name)}: ${esc(o.Type||'None')}</span>`);
      for (const f of (o.Fields || [])) field(f, pad + '  ');
      objects(o.Objects, pad + '  ');
    }
  };
  lines.push(`<span class="${mark(diff.Type)}">job ${esc(diff.Name)}: ${esc(diff.Type||'None')}</span>`);
  for (const f of (diff.Fields || [])) field(f, '  ');
  objects(diff.Objects, '  ');
  for (const tg of (diff.TaskGroups || [])) {
    lines.push(`  <span class="${mark(tg.Type)}">group ${esc(tg.Name)}: ${esc(tg.Type||'None')}</span>`);
    for (const f of (tg.Fields || [])) field(f, '    ');
    objects(tg.Objects, '    ');
    for (const t of (tg.Tasks || [])) {
      lines.push(`    <span class="${mark(t.Type)}">task ${esc(t.Name)}: ${esc(t.Type||'None')}</span>`);
      for (const f of (t.Fields || [])) field(f, '      ');
      objects(t.Objects, '      ');
    }
  }
  return lines.join('\\n');
}
async function planJob() {
  const out = document.getElementById('planout');
  try {
    const job = await parseHcl();
    const plan = await api('/v1/job/' + encodeURIComponent(job.id) + '/plan',
      'PUT', {Job: job, Diff: true});
    let html = `<h3>Plan</h3><pre>${renderDiff(plan.Diff)}</pre>`;
    if (plan.Annotations)
      html += `<pre>${esc(JSON.stringify(plan.Annotations, null, 2))}</pre>`;
    if (plan.FailedTGAllocs && Object.keys(plan.FailedTGAllocs).length)
      html += `<div class="err">Placement failures: ` +
        esc(JSON.stringify(plan.FailedTGAllocs)) + '</div>';
    out.innerHTML = html;
  } catch (e) { out.innerHTML = `<div class="err">${esc(e.message)}</div>`; }
}
async function runJob() {
  const out = document.getElementById('planout');
  try {
    const job = await parseHcl();
    const r = await api('/v1/jobs', 'PUT', {Job: job});
    out.innerHTML = `<div>Submitted: eval <code>${esc(r.EvalID || '')}</code>
      — <a href="#/job/${encodeURIComponent(job.id)}">view job</a></div>`;
  } catch (e) { out.innerHTML = `<div class="err">${esc(e.message)}</div>`; }
}

// ---- deployment + alloc lifecycle actions (ref ui deployment adapters
// promote/fail/pause and alloc restart/signal/stop routes) ----
async function deployAction(id, action, body) {
  const out = document.getElementById('deployout');
  try {
    await api('/v1/deployment/' + action + '/' + id, 'PUT', body || {});
    render();  // show the new deployment state
  } catch (e) { if (out) out.innerHTML = `<span class="err">${esc(e.message)}</span>`; }
}
async function nodeAction(nodeId, action, body) {
  const out = document.getElementById('nodeout');
  try {
    await api(`/v1/node/${nodeId}/${action}`, 'PUT', body || {});
    render();  // show the new node state
  } catch (e) { if (out) out.innerHTML = `<span class="err">${esc(e.message)}</span>`; }
}
async function taskAction(allocId, action, taskB64) {
  const out = document.getElementById('allocout');
  const task = b64d(taskB64);
  try {
    const body = action === 'signal' ? {Signal: 'SIGINT', TaskName: task}
                                     : {TaskName: task};
    await api(`/v1/client/allocation/${allocId}/${action}`, 'PUT', body);
    if (out) out.innerHTML = `<span class="ok-note">${esc(action)} sent to ${esc(task)}</span>`;
  } catch (e) { if (out) out.innerHTML = `<span class="err">${esc(e.message)}</span>`; }
}
async function allocAction(allocId, action) {
  const out = document.getElementById('allocout');
  try {
    await api(`/v1/allocation/${allocId}/${action}`, 'PUT', {});
    if (out) out.innerHTML = `<span class="ok-note">${esc(action)} requested</span>`;
  } catch (e) { if (out) out.innerHTML = `<span class="err">${esc(e.message)}</span>`; }
}

// ---- per-task live stats sparklines (ref ui stats charts; one measure
// per chart — CPU and memory never share an axis) ----
let statsTimer = null;
const statsHist = {};  // task -> {cpu: [..], rss: [..]}
function sparkline(points, fmt) {
  if (!points.length) return '';
  const w = 140, h = 28, pad = 2;
  const max = Math.max(...points, 1e-9);
  const step = points.length > 1 ? (w - 2*pad) / (points.length - 1) : 0;
  const ys = points.map(v => h - pad - (v / max) * (h - 2*pad));
  const d = ys.map((y, i) => `${(pad + i*step).toFixed(1)},${y.toFixed(1)}`).join(' ');
  return `<svg class="spark" width="${w}" height="${h}">` +
    `<polyline points="${d}" fill="none" stroke="#5b8dee" stroke-width="2"/></svg>` +
    `<span class="sparkval">${fmt(points[points.length-1])}</span>`;
}
async function statsPoll(allocId) {
  let s;
  try { s = await api(`/v1/client/allocation/${allocId}/stats`); }
  catch { return; }
  for (const [t, u] of Object.entries(s.tasks || {})) {
    const el = document.getElementById('spark-' + t);
    if (!el) continue;
    const hist = statsHist[t] = statsHist[t] || {cpu: [], rss: []};
    hist.cpu = hist.cpu.concat([u.cpu_percent || 0]).slice(-60);
    hist.rss = hist.rss.concat([(u.rss_bytes || 0) / 1048576]).slice(-60);
    el.innerHTML =
      'cpu ' + sparkline(hist.cpu, v => v.toFixed(1) + '%') +
      'mem ' + sparkline(hist.rss, v => v.toFixed(1) + ' MiB');
  }
}
function statsStart(allocId) {
  statsStop();
  statsPoll(allocId);
  statsTimer = setInterval(() => statsPoll(allocId), 2000);
}
function statsStop() {
  if (statsTimer) { clearInterval(statsTimer); statsTimer = null; }
  for (const k of Object.keys(statsHist)) delete statsHist[k];
}

// ---- allocation fs browser (ref ui fs routes) ----
// paths ride handlers base64-encoded: file names are UNTRUSTED (any
// workload writes them) and must never reach an inline-JS string raw
async function fsGo(allocId, pathB64) {
  const path = b64d(pathB64);
  const pathDiv = document.getElementById('fspath');
  const viewDiv = document.getElementById('fsview');
  if (!pathDiv || !viewDiv) return;
  const parts = path.split('/').filter(Boolean);
  let crumbs = `<a href="javascript:fsGo('${allocId}','${b64e('/')}')">alloc</a>`;
  let acc = '';
  for (const p of parts) {
    acc += '/' + p;
    crumbs += ` / <a href="javascript:fsGo('${allocId}','${b64e(acc)}')">${esc(p)}</a>`;
  }
  pathDiv.innerHTML = crumbs;
  try {
    const entries = await api('/v1/client/fs/ls/' + allocId +
      '?path=' + encodeURIComponent(path));
    viewDiv.innerHTML = '<table><tr><th>Name</th><th>Size</th></tr>' +
      entries.map(e => {
        const full = b64e((path === '/' ? '' : path) + '/' + e.Name);
        const go = e.IsDir ? `fsGo('${allocId}','${full}')`
                           : `fsCat('${allocId}','${full}')`;
        return `<tr class="row" onclick="${go}"><td>${e.IsDir?'📁 ':''}${esc(e.Name)}</td>` +
               `<td>${e.IsDir?'-':e.Size}</td></tr>`;
      }).join('') + '</table>';
  } catch (e) { viewDiv.innerHTML = `<div class="err">${esc(e.message)}</div>`; }
}
async function fsCat(allocId, pathB64) {
  const path = b64d(pathB64);
  const viewDiv = document.getElementById('fsview');
  const parent = b64e(path.split('/').slice(0,-1).join('/') || '/');
  try {
    const doc = await api('/v1/client/fs/cat/' + allocId +
      '?path=' + encodeURIComponent(path));
    viewDiv.innerHTML = `<div class="crumb">${esc(path)}
      (<a href="javascript:fsGo('${allocId}','${parent}')">back</a>)</div>` +
      `<pre>${esc(doc.Data)}</pre>`;
  } catch (e) { viewDiv.innerHTML = `<div class="err">${esc(e.message)}</div>`; }
}

// ---- exec terminal over the exec websocket (ref ui exec routes) ----
let termWs = null;
function termWrite(text) {
  const term = document.getElementById('term');
  if (!term) return;
  term.textContent += text;
  term.scrollTop = term.scrollHeight;
}
function termConnect(allocId) {
  termClose();
  const task = document.getElementById('termtask').value;
  const proto = location.protocol === 'https:' ? 'wss:' : 'ws:';
  let url = `${proto}//${location.host}/v1/client/allocation/${allocId}/exec` +
    `?task=${encodeURIComponent(task)}&command=${encodeURIComponent('["/bin/sh"]')}`;
  if (tokenInput.value) url += `&token=${encodeURIComponent(tokenInput.value)}`;
  document.getElementById('term').textContent = '';
  termWrite('[connecting…]\\n');
  const ws = new WebSocket(url);
  termWs = ws;
  ws.onmessage = ev => {
    if (termWs !== ws) return;  // superseded by a reconnect
    try {
      const m = JSON.parse(ev.data);
      if (m.stdout && m.stdout.data) termWrite(b64d(m.stdout.data));
      if (m.stderr && m.stderr.data) termWrite(b64d(m.stderr.data));
      if (m.exited) termWrite(`\\n[exited ${(m.result||{}).exit_code}]\\n`);
      if (m.error) termWrite(`\\n[error: ${m.error}]\\n`);
    } catch {}
  };
  ws.onopen = () => { if (termWs === ws) termWrite('[connected]\\n$ '); };
  ws.onclose = () => {
    // an OLD socket closing must not null out (or scribble over) a newer
    // live connection's state
    if (termWs === ws) { termWrite('\\n[disconnected]\\n'); termWs = null; }
  };
}
function termSend() {
  const input = document.getElementById('termin');
  if (!termWs || termWs.readyState !== 1) return;
  const line = input.value + '\\n';
  termWrite(line);
  termWs.send(JSON.stringify({stdin: {data: b64e(line)}}));
  input.value = '';
}
function termClose() {
  if (termWs) { try { termWs.close(); } catch {} termWs = null; }
}

async function render() {
  const hash = location.hash || '#/jobs';
  const [, page, id] = hash.split('/');
  if (page !== 'allocation') statsStop();
  document.querySelectorAll('nav a').forEach(a =>
    a.classList.toggle('active', a.getAttribute('href') === '#/' + page));
  const fn = routes[page] || routes.jobs;
  const gen = ++renderGen;
  window._postRender = null;
  try {
    const html = await fn(id);
    if (gen !== renderGen) return;  // superseded by a newer navigation
    view.innerHTML = html;
    if (window._postRender) window._postRender();
  }
  catch (e) {
    if (gen !== renderGen) return;
    view.innerHTML = `<div class="err">${esc(e.message)}</div>`;
  }
}
let renderGen = 0;
window.addEventListener('hashchange', render);

// ---- live updates over /v1/event/stream (push instead of poll; the
// 3s poll below stays as the blocking-query-style fallback whenever the
// stream is unavailable — no broker, ACL denial, proxy buffering) ----
let streamLive = false, streamPending = false;
function refreshable() {
  const h = location.hash || '';
  // no auto-refresh on detail pages or the Run editor (it would wipe
  // in-progress HCL edits, the exec terminal, and the plan output)
  return !(h.match(/#\\/(job|node|allocation)\\//) || h.startsWith('#/run'));
}
async function eventStream() {
  try {
    const headers = {};
    if (tokenInput.value) headers['X-Nomad-Token'] = tokenInput.value;
    const resp = await fetch('/v1/event/stream', {headers});
    if (!resp.ok || !resp.body) throw new Error('stream unavailable');
    streamLive = true;
    const reader = resp.body.getReader();
    const dec = new TextDecoder();
    let buf = '';
    for (;;) {
      const {value, done} = await reader.read();
      if (done) break;
      buf += dec.decode(value, {stream: true});
      let nl, saw = false;
      while ((nl = buf.indexOf('\\n')) >= 0) {
        const line = buf.slice(0, nl); buf = buf.slice(nl + 1);
        if (!line.trim()) continue;
        try {
          const f = JSON.parse(line);
          if ((f.Events && f.Events.length) || f.LostGap) saw = true;
        } catch {}
      }
      if (saw && !streamPending && refreshable()) {
        // coalesce event bursts into at most one re-render per 500ms
        streamPending = true;
        setTimeout(() => { streamPending = false; if (refreshable()) render(); }, 500);
      }
    }
  } catch {}
  streamLive = false;
  setTimeout(eventStream, 3000);  // reconnect with backoff
}
eventStream();

setInterval(() => {
  if (streamLive || !refreshable()) return;  // push path is driving
  render();
}, 3000);
render();
</script>
</body>
</html>
"""
