"""Event-driven incremental columnar mirror of cluster state.

``ColumnarCluster.shared`` (columnar.py) rebuilds the whole dense mirror on
any nodes-table bump, and ``initial_used``/``_live_allocs_by_node`` rescan
the entire alloc table once per state generation — under a drain, where
every plan commit publishes a generation, each eval pays O(total allocs) of
host work plus a fresh host→device transfer. The :class:`ColumnarMirror`
replaces that rebuild-on-invalidate scheme with a long-lived,
raft-index-versioned state plane that subscribes to the in-process
EventBroker (all topics; Node/Alloc/PlanResult frames carry the deltas) and
applies O(delta) patches:

- node upsert/remove edits rows (capacity/reserved/usable planes, plus a
  by-node alloc rescan for a re-appearing node);
- alloc transitions add/subtract their ``sum_alloc_usage`` contribution to
  the per-node ``used`` matrix, keyed by the per-alloc usage vector the FSM
  embeds in every Alloc event;
- same-job collision counts are maintained per (job, task group).

The mirror's dense planes are also kept **device-resident**
(:class:`DeviceState`): the capacity/usable planes are ``device_put`` once
per node-axis epoch and the ``used`` plane is patched with small
dirty-row scatter updates into a fresh buffer (double-buffered against
the in-flight kernels still reading the old one), so a fused drain batch
starts from arrays already on the chip instead of re-uploading O(N)
state per eval.

Degradation contract (never silently drift): a lost-gap frame, a severed
subscription, an index skew, a sync timeout, or a periodic checksum
mismatch against a fresh rebuild all force a full rebuild from the target
snapshot, counted in ``tpu.mirror_rebuild*`` metrics.
"""

from __future__ import annotations

import logging
import os
import threading
import time
from typing import Optional

import numpy as np

from .columnar import R_COLS, ColumnarCluster

logger = logging.getLogger("nomad_tpu.tpu.mirror")

#: how long sync() waits for an expected event frame before declaring the
#: publish lost and rebuilding. The FSM publishes synchronously inside the
#: same apply that bumped the table index the sync is chasing, so the only
#: legitimate gap is the microseconds between the store swap and the
#: publish; kept SHORT because sync holds the mirror lock while waiting —
#: a lost frame (derivation bug, event-less alloc GC) should cost one
#: bounded rebuild, not a long stall of every fast-path reader
SYNC_WAIT_S = 0.05

#: every Nth incremental sync is checksummed against a from-scratch
#: ``initial_used`` recompute; 0 disables (the property tests re-enable)
VERIFY_EVERY = int(os.environ.get("NOMAD_TPU_MIRROR_VERIFY_EVERY", "64"))


def exotic_flag(alloc) -> bool:
    """Whether the alloc carries ports/bandwidth networks or devices —
    dimensions the dense planes can't verify exactly. THE single
    definition: the FSM stamps it into every Alloc event (``Exotic``),
    the mirror counts it per node row (``exotic_live``), and the plan
    applier's host dense path (core/plan_apply.py ``_alloc_exotic``)
    delegates here, so device verify and host verify can never disagree
    on which allocs force the exact per-node check."""
    resources = alloc.allocated_resources
    if resources is None:
        return False
    if resources.shared.networks:
        return True
    for tr in resources.tasks.values():
        if tr.networks or tr.devices:
            return True
    return False


def usage_vec(alloc) -> Optional[tuple]:
    """The (cpu, memory_mb, disk_mb, mbits) contribution of one alloc —
    exactly ``ColumnarCluster.sum_alloc_usage`` restricted to one element,
    so mirror patches and full rebuilds can never disagree on the math."""
    if alloc.allocated_resources is None:
        return None
    c = alloc.comparable_cached()
    bw = 0
    res = alloc.allocated_resources
    for tr in res.tasks.values():
        for net in tr.networks:
            bw += net.mbits
    for net in res.shared.networks:
        bw += net.mbits
    return (
        c.flattened.cpu.cpu_shares,
        c.flattened.memory.memory_mb,
        c.shared.disk_mb,
        bw,
    )


class MirrorCluster(ColumnarCluster):
    """A ColumnarCluster whose usage plane and collision counts are
    maintained incrementally by a :class:`ColumnarMirror`. Built over ALL
    nodes in the state (not just ready ones) so per-eval eligibility is a
    ring permutation, never a node-axis change; a node status flap costs a
    pointer swap instead of a full rebuild.

    The fast paths serve only the exact generation the mirror last synced
    to; any other generation falls back to the base class's scan-the-table
    implementations, so a stale reader can never observe a half-applied
    patch set."""

    def __init__(self, nodes, lock: threading.RLock):
        super().__init__(nodes)
        self._mirror_lock = lock
        #: reserved + Σ live-alloc contributions per row (int64, [N, R])
        self.mirror_used = self.reserved.copy()
        #: live allocs per row carrying ports/devices (dimensions the
        #: dense planes can't verify): the plan applier's device verify
        #: degrades these rows to the exact host check
        self.exotic_live = np.zeros(len(nodes), dtype=np.int32)
        #: the state generation the incremental planes currently equal
        self._synced_gen = None
        #: alloc id → (node_id, usage vec, job_id, task_group, exotic)
        self._alloc_rec: dict[str, tuple] = {}
        #: (job_id, task_group) → {node_id: live alloc count}
        self._job_counts: dict[tuple, dict] = {}

    # -- incremental fast paths -----------------------------------------
    def initial_used(self, state, plan=None) -> np.ndarray:
        gen = getattr(state, "_gen", state)
        with self._mirror_lock:
            if gen is self._synced_gen:
                used = self.mirror_used.copy()
                if plan is not None:
                    for node_id, stops in plan.node_update.items():
                        row = self.index.get(node_id)
                        if row is None:
                            continue
                        for a in stops:
                            rec = self._alloc_rec.get(a.id)
                            if rec is not None and rec[0] == node_id:
                                used[row] -= np.asarray(
                                    rec[1], dtype=np.int64
                                )
                return used
        # stale generation: the O(total allocs) rescan runs OUTSIDE the
        # lock — a reader one generation behind must not serialize the
        # other worker's sync/device refresh behind a full table scan
        return super().initial_used(state, plan)

    def collision_counts(self, state, job_id: str, tg_name: str) -> np.ndarray:
        gen = getattr(state, "_gen", state)
        with self._mirror_lock:
            if gen is self._synced_gen:
                counts = np.zeros(len(self.nodes), dtype=np.int32)
                for node_id, c in self._job_counts.get(
                    (job_id, tg_name), {}
                ).items():
                    row = self.index.get(node_id)
                    if row is not None:
                        counts[row] = c
                return counts
        return super().collision_counts(state, job_id, tg_name)


class DeviceState:
    """Device-resident kernel state for one (epoch, padded-N) pair: the
    capacity/usable planes uploaded once, and a ``used`` plane maintained
    by scatter updates of just the dirty rows. Updates deliberately COPY
    rather than donate the retired buffer: every refresh follows a
    hand-out to an asynchronously-dispatched kernel that may still be
    reading it (the collector wakes consumers at dispatch), and with two
    drain workers the other worker's batch can hold it too — donating a
    buffer a live computation reads is undefined. The old buffer is freed
    as soon as the last kernel holding it completes."""

    #: dirty-row scatter shapes are bucketed so row-count churn doesn't
    #: compile a fresh scatter program per batch
    _ROW_BUCKETS = (8, 64, 512, 4096)

    def __init__(self, epoch: int, n_pad: int, capacity, usable, used,
                 mesh=None):
        from ..debug import devprof as _devprof

        self.epoch = epoch
        self.n_pad = n_pad
        #: the device mesh these planes are row-sharded over (None =
        #: single-chip); a kernel batch must only consume a DeviceState
        #: whose mesh matches its own, or GSPMD resharding (a silent
        #: cross-device copy + a fresh compiled layout) rides the hot path
        self.mesh = mesh
        n = capacity.shape[0]
        cap = np.zeros((n_pad, R_COLS), dtype=np.int32)
        cap[:n] = np.clip(capacity, 0, 2**31 - 1)
        usa = np.ones((n_pad, 2), dtype=np.float32)
        usa[:n] = usable
        use = np.full((n_pad, R_COLS), 2**30, dtype=np.int32)
        use[:n] = np.clip(used, 0, 2**30)
        if mesh is not None:
            from jax.sharding import NamedSharding, PartitionSpec as P

            from . import shard as _shard

            rows = NamedSharding(mesh, P(_shard.AXIS, None))
            self.capacity = _devprof.device_put(cap, rows)
            self.usable = _devprof.device_put(usa, rows)
            self.used = _devprof.device_put(use, rows)
        else:
            self.capacity = _devprof.device_put(cap)
            self.usable = _devprof.device_put(usa)
            self.used = _devprof.device_put(use)
        self.pending: set[int] = set()

    @staticmethod
    def _row_bucket(n: int) -> int:
        for b in DeviceState._ROW_BUCKETS:
            if n <= b:
                return b
        return ((n + 4095) // 4096) * 4096

    def refresh(self, used_host: np.ndarray):
        """Push pending dirty rows to the device as one scatter update."""
        if not self.pending:
            return
        from ..debug import devprof as _devprof

        rows = np.fromiter(self.pending, dtype=np.int32, count=len(self.pending))
        self.pending.clear()
        b = self._row_bucket(len(rows))
        padded = np.zeros(b, dtype=np.int32)
        padded[: len(rows)] = rows  # pad lanes repeat row 0: same-value set, idempotent
        vals = np.clip(used_host[padded], 0, 2**30).astype(np.int32)
        if self.mesh is not None:
            from jax.sharding import NamedSharding, PartitionSpec as P

            # dirty rows/values replicate EXPLICITLY: an uncommitted
            # host array next to the sharded plane would hand XLA a
            # layout choice the prewarmed scatter never compiled
            rep = NamedSharding(self.mesh, P())
            padded_d = _devprof.device_put(padded, rep)
            vals_d = _devprof.device_put(vals, rep)
        else:
            padded_d = _devprof.device_put(padded)
            vals_d = _devprof.device_put(vals)
        self.used = _scatter_fn(self.mesh)(self.used, padded_d, vals_d)

    def arrays(self):
        """(capacity, usable, used) device refs — immutable snapshots: a
        later refresh produces a NEW used buffer, so an in-flight kernel's
        captured ref never changes underneath it."""
        return self.capacity, self.usable, self.used


# nta: ignore[unbounded-cache] WHY: keyed by mesh identity — one entry
# per configured mesh (at most two in practice: None + the process mesh)
_SCATTER_FNS: dict = {}


def _scatter_fn(mesh):
    """The jitted dirty-row scatter for ``mesh`` (None = single-chip).
    The sharded variant pins ``out_shardings`` to the row-sharded spec so
    the refreshed ``used`` buffer stays partitioned exactly like the one
    it replaces — GSPMD would otherwise be free to gather the output and
    hand the next kernel batch a replicated plane (one silent recompile
    plus an O(N) transfer per drain batch)."""
    key = id(mesh) if mesh is not None else None
    fn = _SCATTER_FNS.get(key)
    if fn is None:
        import jax

        if mesh is not None:
            from jax.sharding import NamedSharding, PartitionSpec as P

            from . import shard as _shard

            fn = jax.jit(
                lambda used, rows, vals: used.at[rows].set(vals),
                out_shardings=NamedSharding(mesh, P(_shard.AXIS, None)),
            )
        else:
            fn = jax.jit(lambda used, rows, vals: used.at[rows].set(vals))
        _SCATTER_FNS[key] = fn
    return fn


class _Structural(Exception):
    """A node joined or left: the node axis (and every plane keyed to it)
    must be rebuilt from the target snapshot."""


class ColumnarMirror:
    """The long-lived, event-patched columnar state plane for one server."""

    def __init__(self, state, broker, verify_every: int = VERIFY_EVERY):
        # ``state`` is accepted for construction-site symmetry but never
        # consulted: every read comes from the snapshot each sync() is
        # given — the mirror must reflect that exact generation, never
        # the live store
        self._broker = broker
        self._lock = threading.RLock()
        #: serializes sync() callers; the ONLY lock held across the
        #: bounded frame wait, so data-plane readers (device_state,
        #: MirrorCluster fast paths, stats) never stall behind it. Order:
        #: _sync_lock before _lock, never the reverse.
        self._sync_lock = threading.Lock()
        self._closed = False
        self._sub: Optional["Subscription"] = None
        self._cluster: Optional[MirrorCluster] = None
        #: highest frame index consumed (any topic)
        self._applied = 0
        #: highest frame index that touched the node/alloc planes
        self._applied_na = 0
        #: bumped whenever the node axis changes (device planes re-upload)
        self._epoch = 0
        self._device: dict[int, DeviceState] = {}
        self.verify_every = verify_every
        self._syncs = 0
        self.counters = {
            "hits": 0,
            "rebuilds": 0,
            "stale": 0,
            "events_applied": 0,
            "rebuild_reasons": {},
        }

    # ------------------------------------------------------------------
    def sync(self, snapshot) -> Optional[MirrorCluster]:
        """Bring the mirror to exactly ``snapshot``'s node/alloc state and
        return the shared MirrorCluster, or None when this snapshot is
        older than what the mirror already applied (the caller then builds
        a one-off legacy cluster instead — the mirror never runs
        backwards)."""
        from .. import metrics
        from ..events.broker import SubscriptionClosedError

        target = max(
            snapshot.table_index("nodes"), snapshot.table_index("allocs")
        )
        # _sync_lock serializes sync callers and is the only lock held
        # across the bounded frame wait; _lock (which the fast-path
        # readers contend on) is taken per-mutation. The analyzer's
        # lock-held-blocking-call finding on the old single-lock sync —
        # every device_state/stats reader stalled behind a 50ms wait for
        # a frame that may never come — is what this split burned down.
        with self._sync_lock:
            with self._lock:
                if self._closed:
                    return None
                if self._cluster is not None and self._applied_na > target:
                    self.counters["stale"] += 1
                    metrics.incr("tpu.mirror_stale")
                    return None
                if self._cluster is None or self._sub is None:
                    self._rebuild(snapshot, target, "init")
                    return self._finish(snapshot, rebuilt=True)
                sub = self._sub
                # invalidate the fast path BEFORE patching: _lock is now
                # released between frame applications, so a reader at the
                # previous generation must fall back to the scan path
                # rather than observe a half-applied patch set (_finish
                # republishes the generation once the planes are whole)
                self._cluster._synced_gen = None
            rebuilt = False
            deadline = time.monotonic() + SYNC_WAIT_S
            t0 = time.monotonic()
            while True:
                with self._lock:
                    if self._closed:
                        return None
                    if self._applied >= target:
                        break
                try:
                    # the wait: no data lock held (sync callers are
                    # already serialized by _sync_lock, so frames can't
                    # be consumed out of order)
                    frame = self._next_frame(sub, deadline)  # nta: ignore[lock-held-blocking-call] — _sync_lock exists to be held here; readers use _lock
                except SubscriptionClosedError:
                    with self._lock:
                        if self._closed:
                            return None
                        self._rebuild(snapshot, target, "severed")
                    rebuilt = True
                    break
                with self._lock:
                    # close() may have run while we waited with _lock
                    # released: a rebuild here would mint a fresh broker
                    # subscription nothing will ever close
                    if self._closed:
                        return None
                    if frame is None:
                        self._rebuild(snapshot, target, "timeout")
                        rebuilt = True
                        break
                    index, events = frame
                    if events is None:  # explicit lost-gap marker
                        self._rebuild(snapshot, target, "gap")
                        rebuilt = True
                        break
                    if index > target:
                        # the write at ``target`` published nothing we
                        # saw: resync from scratch (the rebuild's fresh
                        # subscription re-covers this frame's range — its
                        # content ≤ snapshot is in the rebuild, anything
                        # newer replays from the ring)
                        self._rebuild(snapshot, target, "skew")
                        rebuilt = True
                        break
                    try:
                        # plan frames carry the raft index the FSM linked
                        # to the committing evals' traces: the mirror's
                        # O(delta) patch becomes the last hop of each
                        # eval's span tree (submit → ... → mirror patch).
                        # enabled-gated: the per-frame lookup must cost
                        # nothing with tracing off (this is the drain
                        # hot path the overhead budget guards)
                        from ..trace import tracer

                        trace_ctxs = (
                            tracer.ctxs_for_index(index)
                            if tracer.enabled
                            else ()
                        )
                        tp0 = time.monotonic() if trace_ctxs else 0.0
                        self._apply_frame(snapshot, index, events)
                        if trace_ctxs:
                            tp1 = time.monotonic()
                            for ctx in trace_ctxs:
                                tracer.record_span(
                                    "mirror.patch", ctx, tp0, tp1,
                                    tags={"index": index},
                                )
                    except _Structural:
                        self._rebuild(snapshot, target, "node_axis")
                        rebuilt = True
                        break
            if not rebuilt:
                metrics.sample("mirror.apply_delta", time.monotonic() - t0)
            with self._lock:
                if self._closed:
                    return None
                return self._finish(snapshot, rebuilt=rebuilt)

    def _next_frame(self, sub, deadline: float):
        remaining = deadline - time.monotonic()
        if remaining <= 0:
            return None
        return sub.next(timeout=remaining)

    # ------------------------------------------------------------------
    def _finish(self, snapshot, rebuilt: bool) -> MirrorCluster:
        from .. import metrics

        cluster = self._cluster
        self._syncs += 1
        if (
            not rebuilt
            and self.verify_every
            and self._syncs % self.verify_every == 0
            and not self._verify(snapshot)
        ):
            metrics.incr("tpu.mirror_checksum_mismatch")
            self._rebuild(
                snapshot,
                max(snapshot.table_index("nodes"), snapshot.table_index("allocs")),
                "checksum",
            )
            cluster = self._cluster
            rebuilt = True
        if rebuilt:
            self.counters["rebuilds"] += 1
        else:
            self.counters["hits"] += 1
            metrics.incr("tpu.mirror_hit")
        cluster._synced_gen = getattr(snapshot, "_gen", snapshot)
        return cluster

    def _verify(self, snapshot) -> bool:
        """Checksum the incrementally-maintained ``used`` plane against the
        from-scratch recompute over the same node rows."""
        cluster = self._cluster
        fresh = ColumnarCluster.initial_used(cluster, snapshot)
        fresh_exotic = np.zeros(len(cluster.nodes), dtype=np.int32)
        for alloc in snapshot.allocs():
            if alloc.terminal_status() or not exotic_flag(alloc):
                continue
            row = cluster.index.get(alloc.node_id)
            if row is not None:
                fresh_exotic[row] += 1
        ok = np.array_equal(fresh, cluster.mirror_used) and np.array_equal(
            fresh_exotic, cluster.exotic_live
        )
        if not ok:
            logger.warning(
                "mirror checksum mismatch at index %d (max row delta %s); "
                "rebuilding",
                self._applied,
                np.abs(fresh - cluster.mirror_used).max(),
            )
        return ok

    # ------------------------------------------------------------------
    def _rebuild(self, snapshot, target: int, reason: str):
        """Full O(N + A) rebuild from ``snapshot`` + fresh subscription.
        The old subscription (if any) is dropped, so frames the rebuild
        already covers are never replayed into the new plane."""
        from .. import metrics
        from ..events.broker import TOPIC_ALL

        t0 = time.monotonic()
        if self._sub is not None:
            try:
                self._sub.close()
            except Exception:
                pass
        # subscribe BEFORE reading the snapshot tables: frames for writes
        # after ``snapshot`` queue up and are applied by later syncs;
        # frames at or before the snapshot index are filtered below
        self._sub = self._broker.subscribe(
            topics={TOPIC_ALL: ("*",)}, from_index=snapshot.latest_index()
        )
        cluster = MirrorCluster(list(snapshot.nodes()), self._lock)
        for alloc in snapshot.allocs():
            if alloc.terminal_status():
                continue
            if alloc.node_id not in cluster.index:
                continue
            self._track(cluster, alloc.id, alloc.node_id,
                        usage_vec(alloc), alloc.job_id, alloc.task_group,
                        exotic_flag(alloc))
        self._cluster = cluster
        self._applied = snapshot.latest_index()
        self._applied_na = target
        self._epoch += 1
        self._device.clear()
        metrics.incr(f"tpu.mirror_rebuild.{reason}")
        metrics.sample("mirror.rebuild", time.monotonic() - t0)
        reasons = self.counters["rebuild_reasons"]
        reasons[reason] = reasons.get(reason, 0) + 1

    # ------------------------------------------------------------------
    @staticmethod
    def _track(cluster: MirrorCluster, alloc_id, node_id, vec, job_id, tg,
               exotic: bool = True):
        row = cluster.index.get(node_id)
        if row is None:
            return
        if vec is None:
            # allocated_resources=None contributes nothing to ``used``
            # (sum_alloc_usage skips it) but MUST still count for same-job
            # collisions — the base collision_counts counts every
            # non-terminal matching alloc regardless of resources
            vec = (0, 0, 0, 0)
        cluster.mirror_used[row] += np.asarray(vec, dtype=np.int64)
        if exotic:
            cluster.exotic_live[row] += 1
        cluster._alloc_rec[alloc_id] = (node_id, vec, job_id, tg, exotic)
        jc = cluster._job_counts.setdefault((job_id, tg), {})
        jc[node_id] = jc.get(node_id, 0) + 1

    def _untrack(self, alloc_id: str) -> Optional[int]:
        """Remove one alloc's contribution; returns the dirty row or None."""
        cluster = self._cluster
        rec = cluster._alloc_rec.pop(alloc_id, None)
        if rec is None:
            return None
        node_id, vec, job_id, tg, exotic = rec
        jc = cluster._job_counts.get((job_id, tg))
        if jc is not None:
            c = jc.get(node_id, 0) - 1
            if c > 0:
                jc[node_id] = c
            else:
                jc.pop(node_id, None)
                if not jc:
                    cluster._job_counts.pop((job_id, tg), None)
        row = cluster.index.get(node_id)
        if row is None:
            return None
        cluster.mirror_used[row] -= np.asarray(vec, dtype=np.int64)
        if exotic:
            cluster.exotic_live[row] -= 1
        return row

    def _mark_dirty(self, row: int):
        for ds in self._device.values():
            ds.pending.add(int(row))

    # ------------------------------------------------------------------
    def _apply_frame(self, snapshot, index: int, events: list):
        from ..events import TOPIC_ALLOC, TOPIC_NODE, TOPIC_NODE_EVENT

        mutated = False
        for e in events:
            if e.topic == TOPIC_ALLOC:
                self._apply_alloc(e)
                mutated = True
            elif e.topic == TOPIC_NODE:
                self._apply_node(snapshot, e)
                mutated = True
            elif e.topic == TOPIC_NODE_EVENT:
                mutated = True  # nodes-table bump; resources unchanged
        self._applied = index
        if mutated:
            self._applied_na = index
        self.counters["events_applied"] += len(events)

    def _apply_alloc(self, e):
        p = e.payload
        alloc_id = p.get("ID", "")
        row = self._untrack(alloc_id)
        if row is not None:
            self._mark_dirty(row)
        if p.get("Terminal") or "Terminal" not in p:
            # terminal, or an event lacking the mirror fields entirely
            # (a fallback doc for an already-deleted alloc): nothing live
            # to track
            return
        vec = p.get("Resources")
        cluster = self._cluster
        node_id = p.get("NodeID", "")
        self._track(
            cluster, alloc_id, node_id,
            tuple(vec) if vec is not None else None,
            p.get("JobID", ""), p.get("TaskGroup", ""),
            # a payload missing the flag (shouldn't happen in-process)
            # defaults EXOTIC: the verify path then degrades that row to
            # the exact host check instead of trusting the dense planes
            bool(p.get("Exotic", True)),
        )
        r = cluster.index.get(node_id)
        if r is not None:
            self._mark_dirty(r)

    def _apply_node(self, snapshot, e):
        cluster = self._cluster
        node_id = e.key
        if e.type in ("NodeRegistration", "NodeDeregistration"):
            # node joined, left, or RE-registered (attributes/resources
            # may have changed, invalidating every node-axis plane): the
            # axis rebuilds from the target snapshot. Membership changes
            # are rare next to the status/alloc churn the O(delta) paths
            # below absorb.
            raise _Structural(node_id)
        # status / drain / eligibility flaps: same resources, same
        # attributes — swap the object so identity reads stay current, and
        # leave every dense plane untouched (the O(1) win over the old
        # rebuild-on-any-nodes-bump cache)
        node = snapshot.node_by_id(node_id)
        row = cluster.index.get(node_id)
        if node is not None and row is not None:
            cluster.nodes[row] = node

    # ------------------------------------------------------------------
    # device-resident kernel state
    # ------------------------------------------------------------------
    def device_state(self, n_pad: int, gen, mesh=None) -> Optional[tuple]:
        """Device refs (capacity, usable, used) for the node plane padded
        to ``n_pad``, valid for state generation ``gen``; None when the
        mirror has moved past that generation (caller falls back to a host
        transfer of its own snapshot arrays). With ``mesh``, the planes
        are row-sharded over it (the caller's fused batch dispatches
        sharded, so its state plane must already live partitioned); a
        cached state for a different mesh is rebuilt, never reshared."""
        with self._lock:
            cluster = self._cluster
            if cluster is None or cluster._synced_gen is not gen:
                return None
            ds = self._device.get(n_pad)
            if ds is None or ds.epoch != self._epoch or ds.mesh is not mesh:
                ds = DeviceState(
                    self._epoch, n_pad, cluster.capacity,
                    cluster.usable, cluster.mirror_used, mesh=mesh,
                )
                self._device[n_pad] = ds
            else:
                ds.refresh(cluster.mirror_used)
            return ds.arrays()

    # ------------------------------------------------------------------
    # plan-applier dense device verify (core/plan_apply.py)
    # ------------------------------------------------------------------
    def verify_handles(self, snapshot, n_pad: int, mesh=None):
        """The plan applier's device-verify view of ``snapshot``: sync the
        mirror to exactly that generation and return ``(cluster, (capacity,
        usable, used) device refs, gen)``, or None when the mirror can't
        serve it (closed, or already synced PAST the snapshot by a
        concurrent drain batch — the applier then degrades to the host
        oracle, counted in tpu.mirror_stale / plan.verify_device_degrade).
        ``mesh`` must match what the drain batches pass for the same
        n_pad (the MIN_NODES-gated active mesh): the DeviceState cache is
        keyed by n_pad, so a mesh mismatch between the two consumers
        would rebuild the full planes on every alternation instead of
        riding the dirty-row scatter."""
        cluster = self.sync(snapshot)
        if cluster is None:
            return None
        gen = getattr(snapshot, "_gen", snapshot)
        arrays = self.device_state(n_pad, gen, mesh=mesh)
        if arrays is None:
            return None
        return cluster, arrays, gen

    def locked_cluster(self, gen):
        """Context manager yielding the MirrorCluster while it is still
        synced to ``gen`` (else None), with the data lock held: the
        applier's per-plan host-side gather (rows, node objects, exotic
        counts, alloc-rec vectors) reads a consistent plane set even if a
        drain worker is concurrently syncing the mirror forward."""
        import contextlib

        @contextlib.contextmanager
        def _ctx():
            with self._lock:
                cluster = self._cluster
                if cluster is None or cluster._synced_gen is not gen:
                    yield None
                else:
                    yield cluster

        return _ctx()

    # ------------------------------------------------------------------
    def stats(self) -> dict:
        with self._lock:
            out = dict(self.counters)
            out["rebuild_reasons"] = dict(self.counters["rebuild_reasons"])
            out["applied_index"] = self._applied
            out["nodes"] = (
                len(self._cluster.nodes) if self._cluster is not None else 0
            )
            out["tracked_allocs"] = (
                len(self._cluster._alloc_rec)
                if self._cluster is not None
                else 0
            )
            return out

    def close(self):
        with self._lock:
            self._closed = True
            if self._sub is not None:
                try:
                    self._sub.close()
                except Exception:
                    pass
                self._sub = None

    # -- test hook ------------------------------------------------------
    def sever(self):
        """Cut the mirror's subscription (chaos harness): the next sync
        observes SubscriptionClosedError and must rebuild."""
        with self._lock:
            if self._sub is not None:
                self._broker._close_slow(self._sub)
