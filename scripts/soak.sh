#!/usr/bin/env sh
# Churn-soak entry point (nomad_tpu/loadgen; README "Churn-soak load
# plane" + PERF.md soak section). Runs the production-scale soak by
# default and writes the scored artifact; exit 0 = every SLO passed.
#
#   scripts/soak.sh                        # full soak -> SOAK_r01.json
#   scripts/soak.sh --scenario smoke       # the ~30s tier-1 storm
#   SOAK_ALLOCS=200000 SOAK_NODES=2000 scripts/soak.sh   # scaled down
#   scripts/soak.sh --seed 7 --print-stream              # determinism eyeball
#
# Scale knobs (env): SOAK_NODES, SOAK_ALLOCS, SOAK_CHURN_S,
# SOAK_CHURN_RATE, SOAK_WORKERS, SOAK_QUIESCE_S.
# Numbers are only comparable A/B on the same box (see PERF.md).
set -eu

cd "$(dirname "$0")/.."

out=""
for arg in "$@"; do
  case "$arg" in
    --out|--out=*|--print-stream|--list) out="explicit" ;;
  esac
done
if [ -z "$out" ]; then
  n=1
  while [ -e "$(printf 'SOAK_r%02d.json' "$n")" ]; do n=$((n + 1)); done
  set -- --out "$(printf 'SOAK_r%02d.json' "$n")" "$@"
fi

exec env JAX_PLATFORMS="${JAX_PLATFORMS:-cpu}" \
  python -m nomad_tpu.loadgen --scenario soak "$@"
