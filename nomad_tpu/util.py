"""Small shared helpers (the reference's helper/ grab-bag)."""

from __future__ import annotations

import os


def contained_path(base: str, rel: str) -> str:
    """Join ``rel`` under ``base`` and guarantee the result stays inside.

    realpath on both sides: symlinks planted inside the tree (a task
    running ``ln -s / esc``) must not escape; a bare prefix test would also
    accept sibling dirs whose names extend the base. Raises ValueError."""
    base = os.path.realpath(base)
    path = os.path.realpath(os.path.join(base, rel.lstrip("/")))
    if path != base and os.path.commonpath([base, path]) != base:
        raise ValueError(f"path escapes the base directory: {rel}")
    return path
