"""Reconciler canary / deployment-lifecycle / reschedule corpus ported
from the reference (scheduler/reconcile_test.go — cited per test),
extending tests/test_sched_port_reconcile.py with the families round 4
left unported: new-canary creation across scale changes, canary
promotion and replacement on tainted nodes, deployment cancellation and
completion, max_parallel gating, and the reschedule now/later paths."""

import pytest

from nomad_tpu import mock
from nomad_tpu.scheduler.reconcile import AllocReconciler
from nomad_tpu.structs.model import (
    ALLOC_CLIENT_STATUS_FAILED,
    ALLOC_CLIENT_STATUS_RUNNING,
    ALLOC_DESIRED_STATUS_STOP,
    DEPLOYMENT_STATUS_CANCELLED,
    DEPLOYMENT_STATUS_FAILED,
    DEPLOYMENT_STATUS_SUCCESSFUL,
    Deployment,
    DeploymentStatus,
    DeploymentTaskGroupState,
    ReschedulePolicy,
    RescheduleEvent,
    RescheduleTracker,
    TaskState,
    UpdateStrategy,
    generate_uuid,
)

MINUTE_NS = 60 * 1_000_000_000
SECOND_NS = 1_000_000_000
HOUR_NS = 60 * MINUTE_NS


def update_ignore(existing, new_job, new_tg):
    return True, False, None


def update_destructive(existing, new_job, new_tg):
    return False, True, None


def update_fn_mock(handled, fallback):
    """ref reconcile_test.go allocUpdateFnMock: per-alloc-id dispatch."""

    def fn(existing, new_job, new_tg):
        h = handled.get(existing.id)
        if h is not None:
            return h(existing, new_job, new_tg)
        return fallback(existing, new_job, new_tg)

    return fn


def canary_update():
    # ref reconcile_test.go:22 canaryUpdate
    return UpdateStrategy(
        canary=2, max_parallel=2, health_check="checks",
        min_healthy_time=10 * SECOND_NS, healthy_deadline=10 * MINUTE_NS,
        stagger=31 * SECOND_NS,
    )


def no_canary_update():
    # ref reconcile_test.go:31 noCanaryUpdate
    return UpdateStrategy(
        max_parallel=4, health_check="checks",
        min_healthy_time=10 * SECOND_NS, healthy_deadline=10 * MINUTE_NS,
        stagger=31 * SECOND_NS,
    )


def old_allocs(job, n, tg_name="web"):
    out = []
    for i in range(n):
        a = mock.alloc()
        a.job = job
        a.job_id = job.id
        a.namespace = job.namespace
        a.node_id = generate_uuid()
        a.name = f"{job.id}.{tg_name}[{i}]"
        a.task_group = tg_name
        a.client_status = ALLOC_CLIENT_STATUS_RUNNING
        out.append(a)
    return out


def make_canaries(job, deployment, state, n, tg_name="web"):
    out = []
    for i in range(n):
        c = mock.alloc()
        c.job = job
        c.job_id = job.id
        c.namespace = job.namespace
        c.node_id = generate_uuid()
        c.name = f"{job.id}.{tg_name}[{i}]"
        c.task_group = tg_name
        c.client_status = ALLOC_CLIENT_STATUS_RUNNING
        c.deployment_id = deployment.id
        state.placed_canaries = list(state.placed_canaries) + [c.id]
        out.append(c)
    return out


def reconcile(job, allocs, update_fn=update_ignore, tainted=None,
              deployment=None, batch=False, job_id=None, now_ns_=None):
    r = AllocReconciler(
        update_fn, batch, job_id or (job.id if job else "job"), job,
        deployment, allocs, tainted or {}, generate_uuid(),
        now_ns_=now_ns_,
    )
    return r.compute()


def assert_results(results, place=0, destructive=0, inplace=0, stop=0,
                   create_deployment=None):
    assert len(results.place) == place, f"place {len(results.place)}"
    assert len(results.destructive_update) == destructive, (
        f"destructive {len(results.destructive_update)}"
    )
    assert len(results.inplace_update) == inplace
    assert len(results.stop) == stop, f"stop {len(results.stop)}"
    if create_deployment is not None:
        assert (results.deployment is not None) == create_deployment


def place_indexes(results):
    return sorted(int(p.name.rsplit("[", 1)[1][:-1]) for p in results.place)


def stop_indexes(results):
    return sorted(
        int(s.alloc.name.rsplit("[", 1)[1][:-1]) for s in results.stop
    )


class TestNewCanariesPort:
    def test_new_canaries(self):
        """ref TestReconciler_NewCanaries: job change under a canary
        stanza places 2 canaries, touches nothing else, and creates a
        deployment needing promotion."""
        job = mock.job()
        job.task_groups[0].update = canary_update()
        allocs = old_allocs(job, 10)
        r = reconcile(job, allocs, update_fn=update_destructive)

        assert_results(r, place=2, create_deployment=True)
        state = r.deployment.task_groups["web"]
        assert state.desired_canaries == 2
        assert state.desired_total == 10
        upd = r.desired_tg_updates["web"]
        assert upd.canary == 2 and upd.ignore == 10
        assert place_indexes(r) == [0, 1]

    def test_new_canaries_count_greater(self):
        """ref TestReconciler_NewCanaries_CountGreater: canary count above
        the group count places that many canaries."""
        job = mock.job()
        job.task_groups[0].count = 3
        job.task_groups[0].update = canary_update()
        job.task_groups[0].update.canary = 7
        allocs = old_allocs(job, 3)
        r = reconcile(job, allocs, update_fn=update_destructive)

        assert_results(r, place=7, create_deployment=True)
        state = r.deployment.task_groups["web"]
        assert state.desired_canaries == 7
        assert state.desired_total == 3
        assert place_indexes(r) == [0, 1, 2, 3, 4, 5, 6]

    def test_new_canaries_multi_tg(self):
        """ref TestReconciler_NewCanaries_MultiTG."""
        job = mock.job()
        job.task_groups[0].update = canary_update()
        tg2 = job.task_groups[0].copy()
        job.task_groups[0].name = "tg2"
        job.task_groups.append(tg2)
        allocs = old_allocs(job, 10, tg_name="tg2") + old_allocs(
            job, 10, tg_name="web"
        )
        r = reconcile(job, allocs, update_fn=update_destructive)

        assert_results(r, place=4, create_deployment=True)
        for name in ("tg2", "web"):
            state = r.deployment.task_groups[name]
            assert state.desired_canaries == 2
            assert state.desired_total == 10
            upd = r.desired_tg_updates[name]
            assert upd.canary == 2 and upd.ignore == 10

    def test_new_canaries_scale_up(self):
        """ref TestReconciler_NewCanaries_ScaleUp: canaries precede the
        scale-up placements."""
        job = mock.job()
        job.task_groups[0].update = canary_update()
        job.task_groups[0].count = 15
        allocs = old_allocs(job, 10)
        r = reconcile(job, allocs, update_fn=update_destructive)

        assert_results(r, place=2, create_deployment=True)
        state = r.deployment.task_groups["web"]
        assert state.desired_canaries == 2
        assert state.desired_total == 15
        assert place_indexes(r) == [0, 1]

    def test_new_canaries_scale_down(self):
        """ref TestReconciler_NewCanaries_ScaleDown: the scale-down stops
        happen alongside the canary placements."""
        job = mock.job()
        job.task_groups[0].update = canary_update()
        job.task_groups[0].count = 5
        allocs = old_allocs(job, 10)
        r = reconcile(job, allocs, update_fn=update_destructive)

        assert_results(r, place=2, stop=5, create_deployment=True)
        assert place_indexes(r) == [0, 1]
        assert stop_indexes(r) == [5, 6, 7, 8, 9]

    def test_new_canaries_fill_names(self):
        """ref TestReconciler_NewCanaries_FillNames: partially placed
        canaries keep their names; the fill picks the gaps."""
        job = mock.job()
        job.task_groups[0].update = UpdateStrategy(
            canary=4, max_parallel=2, health_check="checks",
            min_healthy_time=10 * SECOND_NS,
            healthy_deadline=10 * MINUTE_NS,
        )
        d = Deployment.new_for_job(job)
        s = DeploymentTaskGroupState(
            promoted=False, desired_total=10, desired_canaries=4,
            placed_allocs=2,
        )
        d.task_groups["web"] = s
        allocs = old_allocs(job, 10)
        # canaries at the name ends: web[0] and web[3]
        for i in (0, 3):
            c = mock.alloc()
            c.job = job
            c.job_id = job.id
            c.namespace = job.namespace
            c.node_id = generate_uuid()
            c.name = f"{job.id}.web[{i}]"
            c.task_group = "web"
            c.client_status = ALLOC_CLIENT_STATUS_RUNNING
            c.deployment_id = d.id
            s.placed_canaries = list(s.placed_canaries) + [c.id]
            allocs.append(c)

        r = reconcile(
            job, allocs, update_fn=update_destructive, deployment=d
        )
        assert_results(r, place=2, create_deployment=False)
        upd = r.desired_tg_updates["web"]
        assert upd.canary == 2 and upd.ignore == 12
        assert place_indexes(r) == [1, 2]


class TestPromoteCanariesPort:
    def test_promote_canaries_unblock(self):
        """ref TestReconciler_PromoteCanaries_Unblock: after promotion the
        rolling update resumes under max_parallel, stopping old allocs."""
        job = mock.job()
        job.task_groups[0].update = canary_update()
        d = Deployment.new_for_job(job)
        s = DeploymentTaskGroupState(
            promoted=True, desired_total=10, desired_canaries=2,
            placed_allocs=2,
        )
        d.task_groups["web"] = s
        allocs = old_allocs(job, 10)
        handled = {}
        for c in make_canaries(job, d, s, 2):
            c.deployment_status = DeploymentStatus(healthy=True)
            allocs.append(c)
            handled[c.id] = update_ignore

        r = reconcile(
            job, allocs,
            update_fn=update_fn_mock(handled, update_destructive),
            deployment=d,
        )
        assert_results(r, destructive=2, stop=2, create_deployment=False)
        upd = r.desired_tg_updates["web"]
        assert upd.stop == 2
        assert upd.destructive_update == 2
        assert upd.ignore == 8
        # no canary may be stopped
        canary_ids = set(s.placed_canaries)
        assert all(x.alloc.id not in canary_ids for x in r.stop)
        assert stop_indexes(r) == [0, 1]

    def test_promote_canaries_equal_count(self):
        """ref TestReconciler_PromoteCanaries_CanariesEqualCount: promoted
        canaries equal the count — old allocs stop, deployment completes."""
        job = mock.job()
        job.task_groups[0].update = canary_update()
        job.task_groups[0].count = 2
        d = Deployment.new_for_job(job)
        s = DeploymentTaskGroupState(
            promoted=True, desired_total=2, desired_canaries=2,
            placed_allocs=2, healthy_allocs=2,
        )
        d.task_groups["web"] = s
        allocs = old_allocs(job, 2)
        handled = {}
        for c in make_canaries(job, d, s, 2):
            c.deployment_status = DeploymentStatus(healthy=True)
            allocs.append(c)
            handled[c.id] = update_ignore

        r = reconcile(
            job, allocs,
            update_fn=update_fn_mock(handled, update_destructive),
            deployment=d,
        )
        assert_results(r, stop=2, create_deployment=False)
        assert len(r.deployment_updates) == 1
        assert r.deployment_updates[0].status == DEPLOYMENT_STATUS_SUCCESSFUL
        canary_ids = set(s.placed_canaries)
        assert all(x.alloc.id not in canary_ids for x in r.stop)

    def test_stop_old_canaries(self):
        """ref TestReconciler_StopOldCanaries: a newer job version cancels
        the previous deployment, stops its canaries, and places fresh
        ones under a new deployment."""
        job = mock.job()
        job.task_groups[0].update = canary_update()
        d = Deployment.new_for_job(job)
        s = DeploymentTaskGroupState(
            promoted=False, desired_total=10, desired_canaries=2,
            placed_allocs=2,
        )
        d.task_groups["web"] = s
        job.version += 10
        allocs = old_allocs(job, 10)
        allocs.extend(make_canaries(job, d, s, 2))

        r = reconcile(
            job, allocs, update_fn=update_destructive, deployment=d
        )
        assert_results(r, place=2, stop=2, create_deployment=True)
        assert len(r.deployment_updates) == 1
        up = r.deployment_updates[0]
        assert up.deployment_id == d.id
        assert up.status == DEPLOYMENT_STATUS_CANCELLED
        new_state = r.deployment.task_groups["web"]
        assert new_state.desired_canaries == 2
        assert new_state.desired_total == 10


class TestCanaryTaintPort:
    def _canary_fixture(self):
        job = mock.job()
        job.task_groups[0].update = canary_update()
        d = Deployment.new_for_job(job)
        s = DeploymentTaskGroupState(
            promoted=False, desired_total=10, desired_canaries=2,
            placed_allocs=2,
        )
        d.task_groups["web"] = s
        allocs = old_allocs(job, 10)
        handled = {}
        for c in make_canaries(job, d, s, 2):
            allocs.append(c)
            handled[c.id] = update_ignore
        return job, d, allocs, handled

    def test_drain_node_canary(self):
        """ref TestReconciler_DrainNode_Canary: a draining canary is
        replaced BY another canary."""
        job, d, allocs, handled = self._canary_fixture()
        n = mock.node()
        n.id = allocs[11].node_id
        n.drain = True
        allocs[11].desired_transition.migrate = True
        tainted = {n.id: n}

        r = reconcile(
            job, allocs,
            update_fn=update_fn_mock(handled, update_destructive),
            tainted=tainted, deployment=d,
        )
        assert_results(r, place=1, stop=1, create_deployment=False)
        upd = r.desired_tg_updates["web"]
        assert upd.canary == 1
        assert upd.ignore == 11
        assert stop_indexes(r) == [1]
        assert place_indexes(r) == [1]

    def test_lost_node_canary(self):
        """ref TestReconciler_LostNode_Canary: a canary on a down node is
        replaced by a new canary."""
        job, d, allocs, handled = self._canary_fixture()
        n = mock.node()
        n.id = allocs[11].node_id
        n.status = "down"
        tainted = {n.id: n}

        r = reconcile(
            job, allocs,
            update_fn=update_fn_mock(handled, update_destructive),
            tainted=tainted, deployment=d,
        )
        assert_results(r, place=1, stop=1, create_deployment=False)
        upd = r.desired_tg_updates["web"]
        assert upd.canary == 1
        assert upd.ignore == 11
        assert stop_indexes(r) == [1]
        assert place_indexes(r) == [1]


class TestDeploymentLifecyclePort:
    @pytest.mark.parametrize("failed_deployment,cancel", [
        (False, True), (True, False),
    ])
    def test_cancel_deployment_job_stop(self, failed_deployment, cancel):
        """ref TestReconciler_CancelDeployment_JobStop (stopped-job rows):
        a running deployment cancels; a failed one is left alone."""
        job = mock.job()
        job.stop = True
        d = Deployment.new_for_job(job)
        if failed_deployment:
            d.status = DEPLOYMENT_STATUS_FAILED
        allocs = old_allocs(job, 10)
        r = reconcile(job, allocs, deployment=d)

        if cancel:
            assert len(r.deployment_updates) == 1
            up = r.deployment_updates[0]
            assert up.deployment_id == d.id
            assert up.status == DEPLOYMENT_STATUS_CANCELLED
        else:
            assert r.deployment_updates == []
        assert len(r.stop) == 10

    @pytest.mark.parametrize("failed_deployment,cancel", [
        (False, True), (True, False),
    ])
    def test_cancel_deployment_job_update(self, failed_deployment, cancel):
        """ref TestReconciler_CancelDeployment_JobUpdate: a newer job
        version cancels a RUNNING deployment only."""
        job = mock.job()
        d = Deployment.new_for_job(job)
        if failed_deployment:
            d.status = DEPLOYMENT_STATUS_FAILED
        job.version += 10
        allocs = old_allocs(job, 10)
        r = reconcile(job, allocs, deployment=d)

        if cancel:
            assert len(r.deployment_updates) == 1
            assert r.deployment_updates[0].status == (
                DEPLOYMENT_STATUS_CANCELLED
            )
        else:
            assert r.deployment_updates == []
        assert_results(r, create_deployment=False)
        assert r.desired_tg_updates["web"].ignore == 10

    def test_mark_deployment_complete(self):
        """ref TestReconciler_MarkDeploymentComplete: all placed and
        healthy under a promoted deployment — one successful update."""
        job = mock.job()
        job.task_groups[0].update = no_canary_update()
        d = Deployment.new_for_job(job)
        d.task_groups["web"] = DeploymentTaskGroupState(
            promoted=True, desired_total=10, placed_allocs=10,
            healthy_allocs=10,
        )
        allocs = old_allocs(job, 10)
        for a in allocs:
            a.deployment_id = d.id
            a.deployment_status = DeploymentStatus(healthy=True)
        r = reconcile(job, allocs, deployment=d)

        assert_results(r, create_deployment=False)
        assert len(r.deployment_updates) == 1
        up = r.deployment_updates[0]
        assert up.deployment_id == d.id
        assert up.status == DEPLOYMENT_STATUS_SUCCESSFUL
        assert r.desired_tg_updates["web"].ignore == 10

    def test_destructive_max_parallel_zero_means_all(self):
        """ref TestReconciler_DestructiveMaxParallel (mock.MaxParallelJob:
        the default update stanza with max_parallel=0): every alloc
        updates destructively in one round."""
        job = mock.job()
        job.task_groups[0].update = no_canary_update()
        job.task_groups[0].update.max_parallel = 0
        allocs = old_allocs(job, 10)
        r = reconcile(job, allocs, update_fn=update_destructive)
        assert_results(r, destructive=10)
        assert r.desired_tg_updates["web"].destructive_update == 10


class TestReschedulePort:
    def _reschedule_job(self, count=5):
        job = mock.job()
        job.task_groups[0].count = count
        job.task_groups[0].update = no_canary_update()
        job.task_groups[0].reschedule_policy = ReschedulePolicy(
            attempts=1, interval=24 * HOUR_NS, delay=5 * SECOND_NS,
            max_delay=1 * HOUR_NS, unlimited=False,
        )
        return job

    def test_reschedule_now_service(self):
        """ref TestReconciler_RescheduleNow_Service: one failed alloc with
        reschedule budget left places now with previous-alloc linkage; a
        failed alloc already rescheduled once only gets a bare
        replacement; desired-stop allocs are replaced."""
        now = 1_700_000_000 * SECOND_NS
        job = self._reschedule_job()
        allocs = old_allocs(job, 5)

        allocs[0].client_status = ALLOC_CLIENT_STATUS_FAILED
        allocs[0].reschedule_tracker = RescheduleTracker(events=[
            RescheduleEvent(
                reschedule_time=now - 1 * HOUR_NS,
                prev_alloc_id=generate_uuid(),
                prev_node_id=generate_uuid(),
            )
        ])
        allocs[1].task_states = {
            "web": TaskState(
                state="start", started_at=now - 1 * HOUR_NS,
                finished_at=now - 10 * SECOND_NS,
            )
        }
        allocs[1].client_status = ALLOC_CLIENT_STATUS_FAILED
        allocs[4].desired_status = ALLOC_DESIRED_STATUS_STOP

        r = reconcile(job, allocs, now_ns_=now)

        assert not r.desired_followup_evals.get("web")
        assert_results(r, place=2, stop=1, create_deployment=False)
        upd = r.desired_tg_updates["web"]
        assert upd.place == 2 and upd.ignore == 3 and upd.stop == 1
        assert place_indexes(r) == [1, 4]
        rescheduled = [
            p for p in r.place if p.previous_alloc is not None
        ]
        assert len(rescheduled) == 1

    def test_reschedule_later_service(self):
        """ref TestReconciler_RescheduleLater_Service: a failure inside
        the delay window yields a follow-up eval at now+delay and the
        failed alloc is annotated with its id."""
        now = 1_700_000_000 * SECOND_NS
        delay = 15 * SECOND_NS
        job = mock.job()
        job.task_groups[0].count = 5
        job.task_groups[0].reschedule_policy = ReschedulePolicy(
            attempts=1, interval=24 * HOUR_NS, delay=delay,
            max_delay=1 * HOUR_NS, unlimited=False,
        )
        allocs = old_allocs(job, 5)
        allocs[0].client_status = ALLOC_CLIENT_STATUS_FAILED
        allocs[0].reschedule_tracker = RescheduleTracker(events=[
            RescheduleEvent(
                reschedule_time=now - 1 * HOUR_NS,
                prev_alloc_id=generate_uuid(),
                prev_node_id=generate_uuid(),
            )
        ])
        allocs[1].task_states = {
            "web": TaskState(
                state="start", started_at=now - 1 * HOUR_NS,
                finished_at=now,
            )
        }
        allocs[1].client_status = ALLOC_CLIENT_STATUS_FAILED
        allocs[4].desired_status = ALLOC_DESIRED_STATUS_STOP

        r = reconcile(job, allocs, now_ns_=now)

        evals = r.desired_followup_evals.get("web")
        assert evals is not None and len(evals) == 1
        assert evals[0].wait_until == now + delay
        assert_results(r, place=1, create_deployment=False)
        assert len(r.attribute_updates) == 1
        annotated = next(iter(r.attribute_updates.values()))
        assert annotated.follow_up_eval_id == evals[0].id
        assert annotated.name.endswith("[1]")
        assert place_indexes(r) == [4]

    def test_reschedule_not_service(self):
        """ref TestReconciler_RescheduleNot_Service: attempts exhausted —
        the failed alloc is neither replaced nor annotated."""
        now = 1_700_000_000 * SECOND_NS
        job = mock.job()
        job.task_groups[0].count = 5
        job.task_groups[0].update = no_canary_update()
        job.task_groups[0].reschedule_policy = ReschedulePolicy(
            attempts=0, interval=24 * HOUR_NS, delay=5 * SECOND_NS,
            max_delay=1 * HOUR_NS, unlimited=False,
        )
        allocs = old_allocs(job, 5)
        allocs[1].task_states = {
            "web": TaskState(
                state="start", started_at=now - 1 * HOUR_NS,
                finished_at=now - 10 * SECOND_NS,
            )
        }
        allocs[1].client_status = ALLOC_CLIENT_STATUS_FAILED

        r = reconcile(job, allocs, now_ns_=now)

        assert not r.desired_followup_evals.get("web")
        # no reschedule: the failed alloc is left failed, nothing placed
        assert_results(r, place=0, stop=0, create_deployment=False)
        upd = r.desired_tg_updates["web"]
        assert upd.ignore == 5

    def test_batch_rerun(self):
        """ref TestReconciler_Batch_Rerun: completed batch allocs are not
        re-placed when the job is re-evaluated unchanged."""
        job = mock.job()
        job.type = "batch"
        job.task_groups[0].count = 10
        allocs = old_allocs(job, 10)
        for a in allocs:
            a.client_status = "complete"

        r = reconcile(job, allocs, batch=True)
        assert_results(r, place=0, stop=0, create_deployment=False)
        assert r.desired_tg_updates["web"].ignore == 10
