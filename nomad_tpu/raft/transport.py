"""Raft transport abstraction.

``InmemTransport`` wires raft nodes together inside one process — the
equivalent of the reference's in-process multi-server test clusters
(nomad/testing.go TestServer + TestJoin, SURVEY.md §4.2). The TCP
transport lives in nomad_tpu.rpc and registers the same three handler
entry points behind the RPC_RAFT first-byte protocol.
"""

from __future__ import annotations

import threading
from typing import Callable, Optional

from ..testing import faults as _faults


class Transport:
    """Point-to-point RPCs a raft node sends to its peers. ``target`` is
    the peer's address (transport-specific)."""

    def request_vote(self, target: str, req: dict) -> dict:
        raise NotImplementedError

    def append_entries(self, target: str, req: dict) -> dict:
        raise NotImplementedError

    def install_snapshot(self, target: str, req: dict) -> dict:
        raise NotImplementedError

    # the local raft node registers its handlers here
    def register(self, address: str, handlers: dict[str, Callable]):
        raise NotImplementedError


class InmemTransport(Transport):
    """Shared-registry transport for in-process clusters. A registry maps
    address → handler table; partitions are simulated by disconnecting
    addresses."""

    def __init__(self, registry: Optional[dict] = None):
        self.registry = registry if registry is not None else {}
        self._lock = threading.Lock()
        self._disconnected: set[str] = set()

    def register(self, address: str, handlers: dict[str, Callable]):
        with self._lock:
            self.registry[address] = handlers

    def disconnect(self, address: str):
        """Simulate a partition of ``address`` from everyone."""
        with self._lock:
            self._disconnected.add(address)

    def reconnect(self, address: str):
        with self._lock:
            self._disconnected.discard(address)

    def _call(self, target: str, method: str, req: dict) -> dict:
        with self._lock:
            if target in self._disconnected or req.get("_from") in self._disconnected:
                raise ConnectionError(f"{target} is partitioned")
            handlers = self.registry.get(target)
        if handlers is None:
            raise ConnectionError(f"no raft node at {target}")
        plane = _faults.ACTIVE
        if plane is not None:
            act = plane.on_raft(req.get("_from") or "", target, method)
            if act in ("drop", "sever"):
                raise ConnectionError(f"injected {act}: {target} {method}")
            if act == "duplicate":
                # deliver twice (duplicated datagram); the handler must be
                # idempotent per raft's term/index rules
                handlers[method](req)
        return handlers[method](req)

    def request_vote(self, target: str, req: dict) -> dict:
        return self._call(target, "request_vote", req)

    def append_entries(self, target: str, req: dict) -> dict:
        return self._call(target, "append_entries", req)

    def install_snapshot(self, target: str, req: dict) -> dict:
        return self._call(target, "install_snapshot", req)
