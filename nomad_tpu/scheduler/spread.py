"""Spread iterator: weighted spread boosts over target attributes
(ref scheduler/spread.go)."""

from __future__ import annotations

from typing import Optional

from ..structs.model import Job, Node, Spread, TaskGroup
from .context import EvalContext
from .propertyset import PropertySet, get_property
from .rank import RankedNode

IMPLICIT_TARGET = "*"


class SpreadInfo:
    __slots__ = ("weight", "desired_counts")

    def __init__(self, weight: int):
        self.weight = weight
        self.desired_counts: dict[str, float] = {}


class SpreadIterator:
    """ref spread.go:15-257"""

    def __init__(self, ctx: EvalContext, source):
        self.ctx = ctx
        self.source = source
        self.job: Optional[Job] = None
        self.tg: Optional[TaskGroup] = None
        self.job_spreads: list[Spread] = []
        self.tg_spread_info: dict[str, dict[str, SpreadInfo]] = {}
        self.sum_spread_weights = 0
        self.has_spread = False
        self.group_property_sets: dict[str, list[PropertySet]] = {}

    def reset(self):
        self.source.reset()
        for sets in self.group_property_sets.values():
            for ps in sets:
                ps.populate_proposed()

    def set_job(self, job: Job):
        self.job = job
        if job.spreads:
            self.job_spreads = job.spreads

    def set_task_group(self, tg: TaskGroup):
        self.tg = tg
        if tg.name not in self.group_property_sets:
            sets = []
            for spread in self.job_spreads:
                pset = PropertySet(self.ctx, self.job)
                pset.set_target_attribute(spread.attribute, tg.name)
                sets.append(pset)
            for spread in tg.spreads:
                pset = PropertySet(self.ctx, self.job)
                pset.set_target_attribute(spread.attribute, tg.name)
                sets.append(pset)
            self.group_property_sets[tg.name] = sets
        self.has_spread = bool(self.group_property_sets[tg.name])
        if tg.name not in self.tg_spread_info:
            self._compute_spread_info(tg)

    def has_spreads(self) -> bool:
        return self.has_spread

    def next(self) -> Optional[RankedNode]:
        while True:
            option = self.source.next()
            if option is None or not self.has_spreads():
                return option

            tg_name = self.tg.name
            property_sets = self.group_property_sets[tg_name]
            total_spread_score = 0.0
            for pset in property_sets:
                n_value, error_msg, used_count = pset.used_count(option.node, tg_name)
                # Include this placement in the count
                used_count += 1
                if error_msg:
                    total_spread_score -= 1.0
                    continue
                spread_details = self.tg_spread_info[tg_name].get(
                    pset.target_attribute
                )
                if spread_details is None:
                    continue
                if not spread_details.desired_counts:
                    # No targets: even-spread scoring
                    total_spread_score += even_spread_score_boost(pset, option.node)
                else:
                    desired_count = spread_details.desired_counts.get(n_value)
                    if desired_count is None:
                        desired_count = spread_details.desired_counts.get(
                            IMPLICIT_TARGET
                        )
                        if desired_count is None:
                            total_spread_score -= 1.0
                            continue
                    # Go float semantics: /0 yields NaN, scheduling continues
                    spread_weight = (
                        float(spread_details.weight) / self.sum_spread_weights
                        if self.sum_spread_weights
                        else float("nan")
                    )
                    if desired_count == 0:
                        # Go float division: (0-used)/0 = -Inf (used ≥ 1
                        # here) — a 0% target class is effectively never
                        # chosen while any other option exists
                        boost = float("-inf") * spread_weight
                    else:
                        boost = (
                            (desired_count - float(used_count)) / desired_count
                        ) * spread_weight
                    total_spread_score += boost

            if total_spread_score != 0.0:
                option.scores.append(total_spread_score)
                self.ctx.metrics.score_node(
                    option.node, "allocation-spread", total_spread_score
                )
            return option

    def _compute_spread_info(self, tg: TaskGroup):
        """ref spread.go:232-257"""
        spread_infos: dict[str, SpreadInfo] = {}
        total_count = tg.count
        combined = list(tg.spreads) + list(self.job_spreads)
        for spread in combined:
            si = SpreadInfo(spread.weight)
            sum_desired = 0.0
            for st in spread.spread_target:
                desired_count = (float(st.percent) / 100.0) * float(total_count)
                si.desired_counts[st.value] = desired_count
                sum_desired += desired_count
            if 0 < sum_desired < float(total_count):
                si.desired_counts[IMPLICIT_TARGET] = float(total_count) - sum_desired
            spread_infos[spread.attribute] = si
            self.sum_spread_weights += spread.weight
        self.tg_spread_info[tg.name] = spread_infos


def even_spread_score_boost(pset: PropertySet, option: Node) -> float:
    """Even-spread scoring when no targets are configured (ref spread.go:178-228)."""
    combined_use = pset.get_combined_use_map()
    if not combined_use:
        return 0.0
    n_value, ok = get_property(option, pset.target_attribute)
    if not ok:
        return -1.0
    current = combined_use.get(n_value, 0)
    min_count = 0
    max_count = 0
    for value in combined_use.values():
        if min_count == 0 or value < min_count:
            min_count = value
        if max_count == 0 or value > max_count:
            max_count = value

    if min_count == 0:
        delta_boost = -1.0
    else:
        delta = min_count - current
        delta_boost = float(delta) / float(min_count)
    if current != min_count:
        return delta_boost
    elif min_count == max_count:
        return -1.0
    elif min_count == 0:
        return 1.0
    delta = max_count - min_count
    return float(delta) / float(min_count)
