"""Mock object factory for tests and benchmarks (ref nomad/mock/mock.go).

Fixture values (4000 CPU / 8192 MB nodes, 500/256 web tasks, etc.) match the
reference's mocks so oracle-parity tests exercise identical numbers.
"""

from __future__ import annotations

from .structs import compute_class
from .structs.attribute import Attribute
from .structs.model import (
    JOB_TYPE_BATCH,
    JOB_TYPE_SERVICE,
    JOB_TYPE_SYSTEM,
    NODE_STATUS_READY,
    AllocatedCpuResources,
    AllocatedMemoryResources,
    AllocatedResources,
    AllocatedSharedResources,
    AllocatedTaskResources,
    Allocation,
    Constraint,
    Deployment,
    DriverInfo,
    EphemeralDisk,
    Evaluation,
    Job,
    MigrateStrategy,
    NetworkResource,
    Node,
    NodeCpuResources,
    NodeDevice,
    NodeDeviceResource,
    NodeDiskResources,
    NodeMemoryResources,
    NodeReservedNetworkResources,
    NodeReservedResources,
    NodeResources,
    PeriodicConfig,
    Port,
    ReschedulePolicy,
    Resources,
    RestartPolicy,
    Task,
    TaskGroup,
    UpdateStrategy,
    generate_uuid,
    now_ns,
)

MINUTE_NS = 60 * 1_000_000_000
SECOND_NS = 1_000_000_000


def node() -> Node:
    n = Node(
        id=generate_uuid(),
        secret_id=generate_uuid(),
        datacenter="dc1",
        name="foobar",
        drivers={
            "exec": DriverInfo(detected=True, healthy=True),
            "mock_driver": DriverInfo(detected=True, healthy=True),
        },
        attributes={
            "kernel.name": "linux",
            "arch": "x86",
            "nomad.version": "0.5.0",
            "driver.exec": "1",
            "driver.mock_driver": "1",
        },
        node_resources=NodeResources(
            cpu=NodeCpuResources(cpu_shares=4000),
            memory=NodeMemoryResources(memory_mb=8192),
            disk=NodeDiskResources(disk_mb=100 * 1024),
            networks=[
                NetworkResource(
                    device="eth0",
                    cidr="192.168.0.100/32",
                    ip="192.168.0.100",
                    mbits=1000,
                )
            ],
        ),
        reserved_resources=NodeReservedResources(
            cpu=NodeCpuResources(cpu_shares=100),
            memory=NodeMemoryResources(memory_mb=256),
            disk=NodeDiskResources(disk_mb=4 * 1024),
            networks=NodeReservedNetworkResources(reserved_host_ports="22"),
        ),
        links={"consul": "foobar.dc1"},
        meta={"pci-dss": "true", "database": "mysql", "version": "5.6"},
        node_class="linux-medium-pci",
        status=NODE_STATUS_READY,
    )
    compute_class(n)
    return n


def tpu_node() -> Node:
    """A node carrying a TPU device group (the reference's NvidiaNode analog,
    fingerprinting TPU chips instead of GPUs; ref mock.go NvidiaNode)."""
    n = node()
    n.node_resources.devices = [
        NodeDeviceResource(
            vendor="google",
            type="tpu",
            name="v5e",
            attributes={
                "memory": Attribute.of_int(16, "GiB"),
                "clock": Attribute.of_int(940, "MHz"),
                "hbm_bandwidth": Attribute.of_int(819, "GB/s"),
            },
            instances=[
                NodeDevice(id=generate_uuid(), healthy=True),
                NodeDevice(id=generate_uuid(), healthy=True),
            ],
        )
    ]
    compute_class(n)
    return n


# Backwards-looking alias for parity test naming against the reference.
def nvidia_node() -> Node:
    n = node()
    n.node_resources.devices = [
        NodeDeviceResource(
            vendor="nvidia",
            type="gpu",
            name="1080ti",
            attributes={
                "memory": Attribute.of_int(11, "GiB"),
                "cuda_cores": Attribute.of_int(3584, ""),
                "graphics_clock": Attribute.of_int(1480, "MHz"),
                "memory_bandwidth": Attribute.of_int(11, "GB/s"),
            },
            instances=[
                NodeDevice(id=generate_uuid(), healthy=True),
                NodeDevice(id=generate_uuid(), healthy=True),
            ],
        )
    ]
    compute_class(n)
    return n


def _web_task() -> Task:
    return Task(
        name="web",
        driver="exec",
        config={"command": "/bin/date"},
        env={"FOO": "bar"},
        resources=Resources(
            cpu=500,
            memory_mb=256,
            networks=[
                NetworkResource(
                    mbits=50,
                    dynamic_ports=[Port(label="http"), Port(label="admin")],
                )
            ],
        ),
        meta={"foo": "bar"},
    )


def job() -> Job:
    j = Job(
        region="global",
        id=f"mock-service-{generate_uuid()}",
        name="my-job",
        type=JOB_TYPE_SERVICE,
        priority=50,
        datacenters=["dc1"],
        constraints=[
            Constraint(l_target="${attr.kernel.name}", r_target="linux", operand="=")
        ],
        task_groups=[
            TaskGroup(
                name="web",
                count=10,
                ephemeral_disk=EphemeralDisk(size_mb=150),
                restart_policy=RestartPolicy(
                    attempts=3, interval=10 * MINUTE_NS, delay=1 * MINUTE_NS, mode="delay"
                ),
                reschedule_policy=ReschedulePolicy(
                    attempts=2,
                    interval=10 * MINUTE_NS,
                    delay=5 * SECOND_NS,
                    delay_function="constant",
                ),
                migrate=MigrateStrategy(
                    max_parallel=1,
                    health_check="checks",
                    min_healthy_time=10 * SECOND_NS,
                    healthy_deadline=5 * MINUTE_NS,
                ),
                tasks=[_web_task()],
                meta={"elb_check_type": "http"},
            )
        ],
        meta={"owner": "armon"},
        create_index=42,
        modify_index=99,
        job_modify_index=99,
        submit_time=now_ns(),
    )
    return j


def batch_job() -> Job:
    j = job()
    j.id = f"mock-batch-{generate_uuid()}"
    j.name = "batch-job"
    j.type = JOB_TYPE_BATCH
    j.constraints = []
    tg = j.task_groups[0]
    tg.reschedule_policy = ReschedulePolicy(
        attempts=2,
        interval=10 * MINUTE_NS,
        delay=5 * SECOND_NS,
        delay_function="constant",
    )
    tg.tasks[0].resources.networks = []
    return j


def system_job() -> Job:
    j = Job(
        region="global",
        id=f"mock-system-{generate_uuid()}",
        name="my-job",
        type=JOB_TYPE_SYSTEM,
        priority=100,
        datacenters=["dc1"],
        constraints=[
            Constraint(l_target="${attr.kernel.name}", r_target="linux", operand="=")
        ],
        task_groups=[
            TaskGroup(
                name="web",
                count=1,
                restart_policy=RestartPolicy(
                    attempts=3, interval=10 * MINUTE_NS, delay=1 * MINUTE_NS, mode="delay"
                ),
                ephemeral_disk=EphemeralDisk(),
                tasks=[
                    Task(
                        name="web",
                        driver="exec",
                        config={"command": "/bin/date"},
                        resources=Resources(
                            cpu=500,
                            memory_mb=256,
                            networks=[
                                NetworkResource(
                                    mbits=50, dynamic_ports=[Port(label="http")]
                                )
                            ],
                        ),
                    )
                ],
            )
        ],
        meta={"owner": "armon"},
        create_index=42,
        modify_index=99,
    )
    return j


def periodic_job() -> Job:
    j = job()
    j.type = JOB_TYPE_BATCH
    j.periodic = PeriodicConfig(enabled=True, spec_type="cron", spec="*/30 * * * *")
    j.status = "running"
    return j


def evaluation() -> Evaluation:
    now = now_ns()
    return Evaluation(
        id=generate_uuid(),
        priority=50,
        type=JOB_TYPE_SERVICE,
        job_id=generate_uuid(),
        status="pending",
        create_time=now,
        modify_time=now,
    )


def alloc() -> Allocation:
    a = Allocation(
        id=generate_uuid(),
        eval_id=generate_uuid(),
        node_id="12345678-abcd-efab-cdef-123456789abc",
        task_group="web",
        allocated_resources=AllocatedResources(
            tasks={
                "web": AllocatedTaskResources(
                    cpu=AllocatedCpuResources(cpu_shares=500),
                    memory=AllocatedMemoryResources(memory_mb=256),
                    networks=[
                        NetworkResource(
                            device="eth0",
                            ip="192.168.0.100",
                            reserved_ports=[Port(label="admin", value=5000)],
                            mbits=50,
                            dynamic_ports=[Port(label="http", value=9876)],
                        )
                    ],
                )
            },
            shared=AllocatedSharedResources(disk_mb=150),
        ),
        desired_status="run",
        client_status="pending",
    )
    a.job = job()
    a.job_id = a.job.id
    a.namespace = a.job.namespace
    a.name = f"{a.job_id}.web[0]"
    return a


def batch_alloc() -> Allocation:
    a = alloc()
    a.job = batch_job()
    a.job_id = a.job.id
    a.name = f"{a.job_id}.web[0]"
    return a


def deployment() -> Deployment:
    j = job()
    d = Deployment.new_for_job(j)
    return d
