"""BlockedEvals: tracks failed-placement evaluations and unblocks them when
capacity becomes available (ref nomad/blocked_evals.go:33-761).

Evals are indexed by the computed node classes they found ineligible; when a
node of a new/updated class appears, matching evals re-enter the broker.
"""

from __future__ import annotations

import threading
import time
from typing import Optional

from ..structs.model import EVAL_STATUS_PENDING, EVAL_TRIGGER_MAX_PLANS, Evaluation


class BlockedEvals:
    #: prune cadence / age floor for the capacity-change index maps (ref
    #: blocked_evals.go pruneInterval=5m / pruneThreshold=15m). An entry
    #: older than PRUNE_THRESHOLD can only change the answer for a
    #: scheduler snapshot at least that stale — which the nack/lease
    #: machinery retires long before. Without pruning these maps grow one
    #: entry per node id / computed class *forever* (the `_bad_http_addrs`
    #: unbounded-growth class; surfaced by the churn soak's node flaps).
    PRUNE_INTERVAL = 60.0
    PRUNE_THRESHOLD = 900.0

    def __init__(self, broker):
        self.broker = broker
        self.enabled = False
        self._lock = threading.Lock()
        # job key -> blocked eval (one per job; ref blocked_evals.go dedup)
        self._jobs: dict[tuple[str, str], Evaluation] = {}
        # eval id -> eval
        self._captured: dict[str, Evaluation] = {}
        # SYSTEM evals block per (job, node) instead of per job (ref
        # blocked_evals_system.go:5-27): a system job that failed on one
        # node must unblock when THAT node frees capacity, independently
        # of its evals blocked on other nodes
        self._system: dict[tuple[str, str, str], Evaluation] = {}
        self._system_by_node: dict[str, set[tuple[str, str, str]]] = {}
        # per-node capacity-change indexes: closes the same
        # capacity-arrived-while-blocking race for system evals that
        # _unblock_indexes closes per class
        self._node_unblock_indexes: dict[str, int] = {}
        # last state index at which capacity changed, globally and per class
        # (closes the race where capacity arrives while a scheduler is still
        # deciding to block; ref blocked_evals.go unblockIndexes)
        self._unblock_index = 0
        self._unblock_indexes: dict[str, int] = {}
        # last-touch timestamps driving the prune (one per index-map key)
        self._unblock_at: dict[str, float] = {}
        self._node_unblock_at: dict[str, float] = {}
        self._last_prune = time.monotonic()
        # evals that escaped computed classes unblock on any change
        self._escaped: set[str] = set()
        # superseded duplicates awaiting the leader's cancellation reap
        # (ref blocked_evals.go duplicates + GetDuplicates): dedup keeps
        # the NEWER eval per job; the loser lands here so its raft record
        # doesn't sit 'blocked' forever
        self._duplicates: list = []
        self._dup_cond = threading.Condition(self._lock)

    def set_enabled(self, enabled: bool):
        with self._lock:
            prev = self.enabled
            self.enabled = enabled
        if prev and not enabled:
            self.flush()

    # ------------------------------------------------------------------
    def block(self, ev: Evaluation):
        """Track a blocked eval (ref blocked_evals.go Block)."""
        requeue = False
        with self._lock:
            if not self.enabled:
                return
            # Capacity changed after the scheduler's snapshot: the eval may
            # already fit, so re-enqueue instead of blocking
            # (ref blocked_evals.go missedUnblock)
            if ev.snapshot_index and self._missed_unblock(ev):
                requeue = True
            if ev.node_id:
                # per-node system blocked eval (one per job+node,
                # ref blocked_evals_system.go); never touches the
                # job-level dedup maps
                if not requeue:
                    skey = (ev.namespace, ev.job_id, ev.node_id)
                    self._system[skey] = ev
                    self._system_by_node.setdefault(
                        ev.node_id, set()
                    ).add(skey)
            else:
                key = (ev.namespace, ev.job_id)
                # Dedup: one blocked eval per job; the NEWER create_index
                # wins and the loser joins the duplicates reap list
                # (ref blocked_evals.go Block dedup semantics)
                existing = self._jobs.get(key)
                if existing is not None and existing.id == ev.id:
                    # re-block of the already-tracked eval (leader restore
                    # replay, FSM + caller double-routing): refresh only
                    existing = None
                if existing is not None and not requeue:
                    if existing.create_index <= ev.create_index:
                        loser, winner = existing, ev
                    else:
                        loser, winner = ev, existing
                    self._captured.pop(existing.id, None)
                    self._escaped.discard(existing.id)
                    self._duplicates.append(loser)
                    self._dup_cond.notify_all()
                    ev = winner
                if not requeue:
                    self._jobs[key] = ev
                    self._captured[ev.id] = ev
                    if ev.escaped_computed_class:
                        self._escaped.add(ev.id)
        if requeue:
            requeued = ev.copy()
            requeued.status = EVAL_STATUS_PENDING
            self.broker.enqueue(requeued)

    def _missed_unblock(self, ev: Evaluation) -> bool:
        """Did a relevant capacity change land after the eval's snapshot?"""
        if ev.node_id:
            # system eval: only ITS node's capacity changes matter
            return (
                self._node_unblock_indexes.get(ev.node_id, 0)
                > ev.snapshot_index
            )
        if ev.escaped_computed_class:
            return self._unblock_index > ev.snapshot_index
        elig = ev.class_eligibility or {}
        for cls, index in self._unblock_indexes.items():
            if index <= ev.snapshot_index:
                continue
            if elig.get(cls, True):  # eligible or never-evaluated class
                return True
        return False

    def get_duplicates(self, timeout: float = 0.0) -> list:
        """Drain superseded duplicate evals, optionally blocking up to
        ``timeout`` for one to appear (ref blocked_evals.go GetDuplicates;
        the leader's reap loop cancels what this returns)."""
        with self._dup_cond:
            if not self._duplicates and timeout > 0:
                self._dup_cond.wait(timeout)
            out = self._duplicates
            self._duplicates = []
            return out

    def untrack(self, namespace: str, job_id: str):
        """Stop tracking a job's blocked eval (e.g. job deregistered)."""
        with self._lock:
            ev = self._jobs.pop((namespace, job_id), None)
            if ev is not None:
                self._captured.pop(ev.id, None)
                self._escaped.discard(ev.id)
            for skey in [
                k for k in self._system if k[0] == namespace and k[1] == job_id
            ]:
                self._system.pop(skey, None)
                nodes = self._system_by_node.get(skey[2])
                if nodes is not None:
                    nodes.discard(skey)

    # ------------------------------------------------------------------
    def _prune_locked(self):
        """Drop index-map entries idle past PRUNE_THRESHOLD (ref
        blocked_evals.go pruneUnblockIndexes). A dropped entry reads as 0
        in ``_missed_unblock`` — the same answer a node/class that never
        changed capacity gives — so the only behavior change is for
        snapshots older than the threshold."""
        now = time.monotonic()
        if now - self._last_prune < self.PRUNE_INTERVAL:
            return
        self._last_prune = now
        cutoff = now - self.PRUNE_THRESHOLD
        for key in [k for k, t in self._unblock_at.items() if t < cutoff]:
            del self._unblock_at[key]
            self._unblock_indexes.pop(key, None)
        for key in [k for k, t in self._node_unblock_at.items() if t < cutoff]:
            del self._node_unblock_at[key]
            self._node_unblock_indexes.pop(key, None)

    def unblock_node(self, node_id: str, index: int):
        """Capacity on one node changed (alloc became terminal, node
        re-registered or turned ready): re-enqueue the SYSTEM evals
        blocked on exactly that node (ref blocked_evals_system.go
        UnblockNode)."""
        to_unblock = []
        with self._lock:
            if not self.enabled:
                return
            self._unblock_index = max(self._unblock_index, index)
            self._node_unblock_indexes[node_id] = max(
                self._node_unblock_indexes.get(node_id, 0), index
            )
            self._node_unblock_at[node_id] = time.monotonic()
            self._prune_locked()
            for skey in self._system_by_node.pop(node_id, set()):
                ev = self._system.pop(skey, None)
                if ev is not None:
                    to_unblock.append(ev)
        for ev in to_unblock:
            requeued = ev.copy()
            requeued.status = EVAL_STATUS_PENDING
            self.broker.enqueue(requeued)

    # ------------------------------------------------------------------
    def unblock(self, computed_class: str, index: int):
        """Capacity for a node class changed: re-enqueue matching evals
        (ref blocked_evals.go Unblock)."""
        to_unblock = []
        with self._lock:
            if not self.enabled:
                return
            self._unblock_index = max(self._unblock_index, index)
            self._unblock_indexes[computed_class] = max(
                self._unblock_indexes.get(computed_class, 0), index
            )
            self._unblock_at[computed_class] = time.monotonic()
            self._prune_locked()
            for eval_id, ev in list(self._captured.items()):
                if self._should_unblock(ev, computed_class):
                    to_unblock.append(ev)
                    self._captured.pop(eval_id, None)
                    self._escaped.discard(eval_id)
                    self._jobs.pop((ev.namespace, ev.job_id), None)
        for ev in to_unblock:
            requeued = ev.copy()
            requeued.status = EVAL_STATUS_PENDING
            self.broker.enqueue(requeued)

    def unblock_all(self, index: int = 0):
        """Unblock everything (e.g. new node registered with unknown class)."""
        with self._lock:
            evals = list(self._captured.values())
            evals.extend(self._system.values())
            self._captured.clear()
            self._escaped.clear()
            self._jobs.clear()
            self._system.clear()
            self._system_by_node.clear()
        for ev in evals:
            requeued = ev.copy()
            requeued.status = EVAL_STATUS_PENDING
            self.broker.enqueue(requeued)

    @staticmethod
    def _should_unblock(ev: Evaluation, computed_class: str) -> bool:
        """ref blocked_evals.go:missedUnblock semantics (inverted): an eval
        unblocks unless it explicitly marked this class ineligible."""
        if ev.escaped_computed_class:
            return True
        elig = ev.class_eligibility or {}
        if computed_class in elig:
            return elig[computed_class]
        # Unknown class: the eval never evaluated it, so it may now fit
        return True

    def unblock_failed(self):
        """Re-enqueue evals blocked due to max plan attempts after a cooldown
        (ref blocked_evals.go UnblockFailed)."""
        with self._lock:
            failed = [
                ev
                for ev in self._captured.values()
                if ev.triggered_by == EVAL_TRIGGER_MAX_PLANS
            ]
            for ev in failed:
                self._captured.pop(ev.id, None)
                self._escaped.discard(ev.id)
                self._jobs.pop((ev.namespace, ev.job_id), None)
        for ev in failed:
            requeued = ev.copy()
            requeued.status = EVAL_STATUS_PENDING
            self.broker.enqueue(requeued)

    def flush(self):
        with self._lock:
            self._jobs.clear()
            self._captured.clear()
            self._escaped.clear()
            self._system.clear()
            self._system_by_node.clear()
            # the index maps are leadership-scoped state like everything
            # else here: a revoked leader must not carry them into its
            # next term (and an unflushed map is an unbounded one)
            self._unblock_indexes.clear()
            self._node_unblock_indexes.clear()
            self._unblock_at.clear()
            self._node_unblock_at.clear()
            self._duplicates = []

    def stats(self) -> dict:
        with self._lock:
            return {
                "total_blocked": len(self._captured) + len(self._system),
                "total_escaped": len(self._escaped),
                "total_system_blocked": len(self._system),
            }
