"""Oracle-parity tests for the tpu-batch kernel.

Identical seeded state driven through the scalar oracle and the batched
kernel must produce matching (alloc name → node) placements. This mirrors the
north-star parity requirement (BASELINE.md: ≥99% placement match).
"""

import random

import pytest

from nomad_tpu import mock
from nomad_tpu.scheduler import Harness
from nomad_tpu.structs.model import (
    Affinity,
    Constraint,
    Evaluation,
    Spread,
    SpreadTarget,
)


def build_cluster(n_nodes, cap_seed=99, dcs=("dc1",)):
    rng = random.Random(cap_seed)
    nodes = []
    for i in range(n_nodes):
        n = mock.node()
        n.node_resources.cpu.cpu_shares = rng.choice([2000, 4000, 8000])
        n.node_resources.memory.memory_mb = rng.choice([4096, 8192, 16384])
        n.datacenter = dcs[i % len(dcs)]
        nodes.append(n)
    return nodes


def make_job(count, mutate=None):
    job = mock.job()
    job.task_groups[0].count = count
    job.task_groups[0].tasks[0].resources.networks = []
    if mutate:
        mutate(job)
    return job


def run(nodes, job, sched_type, seed=5):
    h = Harness(seed=seed)
    for n in nodes:
        h.state.upsert_node(h.next_index(), n)
    h.state.upsert_job(h.next_index(), job)
    ev = Evaluation(
        id="eval-1",
        namespace=job.namespace,
        priority=job.priority,
        type="service",
        triggered_by="job-register",
        job_id=job.id,
        status="pending",
    )
    h.state.upsert_evals(h.next_index(), [ev])
    sched = h.process(sched_type, ev)
    placements = {
        a.name: a.node_id for a in h.state.allocs_by_job(job.namespace, job.id)
    }
    return placements, sched, h


def assert_parity(nodes, job, min_match=1.0):
    p_oracle, s_oracle, _ = run(nodes, job, "service")
    p_batch, s_batch, _ = run(nodes, job, "tpu-batch")
    assert set(p_oracle) == set(p_batch), (
        f"placed sets differ: oracle={len(p_oracle)} batch={len(p_batch)}"
    )
    total = len(p_oracle)
    if total == 0:
        return 1.0
    matches = sum(1 for k in p_oracle if p_oracle[k] == p_batch[k])
    frac = matches / total
    assert frac >= min_match, f"parity {frac:.3f} < {min_match} ({matches}/{total})"
    return frac


class TestKernelParity:
    def test_basic_binpack(self):
        nodes = build_cluster(20)
        assert_parity(nodes, make_job(15))

    def test_small_cluster(self):
        nodes = build_cluster(3)
        assert_parity(nodes, make_job(5))

    def test_single_node(self):
        nodes = build_cluster(1)
        assert_parity(nodes, make_job(3))

    def test_with_constraints(self):
        nodes = build_cluster(20)
        # make half the nodes fail a constraint
        for i, n in enumerate(nodes):
            n.attributes["rack_class"] = "a" if i % 2 == 0 else "b"
            from nomad_tpu.structs import compute_class

            compute_class(n)

        def mutate(job):
            job.constraints.append(
                Constraint(
                    l_target="${attr.rack_class}", r_target="a", operand="="
                )
            )

        nodes2 = [n.copy() for n in nodes]
        p_batch, _, h = run(nodes2, make_job(8, mutate), "tpu-batch")
        assert len(p_batch) == 8
        a_nodes = {h.state.node_by_id(nid).attributes["rack_class"] for nid in p_batch.values()}
        assert a_nodes == {"a"}
        assert_parity(nodes, make_job(8, mutate))

    def test_with_affinity(self):
        nodes = build_cluster(16)
        for i, n in enumerate(nodes):
            n.meta["ssd"] = "true" if i < 4 else "false"

        def mutate(job):
            job.affinities = [
                Affinity(
                    l_target="${meta.ssd}", r_target="true", operand="=", weight=50
                )
            ]

        assert_parity(nodes, make_job(10, mutate))

    def test_with_spread_targets(self):
        nodes = build_cluster(12, dcs=("dc1", "dc2"))

        def mutate(job):
            job.datacenters = ["dc1", "dc2"]
            job.spreads = [
                Spread(
                    attribute="${node.datacenter}",
                    weight=100,
                    spread_target=[
                        SpreadTarget(value="dc1", percent=50),
                        SpreadTarget(value="dc2", percent=50),
                    ],
                )
            ]

        assert_parity(nodes, make_job(8, mutate))

    def test_with_even_spread(self):
        nodes = build_cluster(12, dcs=("dc1", "dc2", "dc3"))

        def mutate(job):
            job.datacenters = ["dc1", "dc2", "dc3"]
            job.spreads = [Spread(attribute="${node.datacenter}", weight=100)]

        assert_parity(nodes, make_job(9, mutate))

    def test_resource_exhaustion_matches(self):
        nodes = build_cluster(2)
        p_oracle, s_oracle, _ = run(nodes, make_job(40), "service")
        job = make_job(40)
        p_batch, s_batch, _ = run(nodes, job, "tpu-batch")
        # same number placed, both report failures
        assert len(p_oracle) == len(p_batch)
        assert bool(s_oracle.failed_tg_allocs) == bool(s_batch.failed_tg_allocs)
        m_oracle = s_oracle.failed_tg_allocs["web"]
        m_batch = s_batch.failed_tg_allocs["web"]
        assert m_oracle.coalesced_failures == m_batch.coalesced_failures
        # failure accounting is measured, not guessed: exhausted node count
        # and the per-dimension attribution must match the oracle's
        assert m_oracle.nodes_exhausted == m_batch.nodes_exhausted
        assert dict(m_oracle.dimension_exhausted) == dict(m_batch.dimension_exhausted)
        assert m_oracle.nodes_filtered == m_batch.nodes_filtered

    def test_dynamic_port_jobs_ride_the_kernel(self):
        """Network asks with only dynamic ports ride the kernel: bandwidth
        is the dense 4th resource column, ports are assigned host-side on
        the chosen node. Placements match the oracle and every alloc gets
        distinct dynamic ports per node."""
        from nomad_tpu.structs.model import NetworkResource, Port
        from nomad_tpu.tpu import batch_sched

        nodes = build_cluster(24)

        def add_ports(job):
            task = job.task_groups[0].tasks[0]
            task.resources.networks = [
                NetworkResource(
                    mbits=10,
                    dynamic_ports=[Port(label="http"), Port(label="admin")],
                )
            ]

        job = make_job(40, mutate=add_ports)
        before = batch_sched.counters_snapshot()
        p_oracle, _, _ = run(nodes, job, "service")
        p_batch, _, h = run(nodes, job, "tpu-batch")
        after = batch_sched.counters_snapshot()
        assert after["kernel_evals"] > before["kernel_evals"], (
            "port job must ride the kernel, not fall back"
        )
        assert p_oracle == p_batch

        # per-node port uniqueness + offers present
        by_node: dict = {}
        for a in h.state.allocs_by_job(job.namespace, job.id):
            tr = a.allocated_resources.tasks["web"]
            assert len(tr.networks) == 1
            ports = [p.value for p in tr.networks[0].dynamic_ports]
            assert len(ports) == 2 and all(v > 0 for v in ports)
            by_node.setdefault(a.node_id, []).extend(ports)
        for node_id, ports in by_node.items():
            assert len(ports) == len(set(ports)), (
                f"duplicate ports on node {node_id[:8]}: {sorted(ports)}"
            )

    def test_bandwidth_exhaustion_matches_oracle(self):
        """The 4th column enforces AssignNetwork's bandwidth dimension:
        nodes run out of mbits exactly like the oracle says."""
        from nomad_tpu.structs.model import NetworkResource, Port

        nodes = build_cluster(6)
        for n in nodes:
            n.node_resources.cpu.cpu_shares = 100000
            n.node_resources.memory.memory_mb = 100000
            n.node_resources.networks[0].mbits = 100

        def add_hungry_net(job):
            task = job.task_groups[0].tasks[0]
            task.resources.cpu = 10
            task.resources.memory_mb = 10
            task.resources.networks = [
                NetworkResource(mbits=60, dynamic_ports=[Port(label="p")])
            ]

        # 12 asks of 60mbits over 6 nodes with 100mbits: exactly one per
        # node fits (the second would exceed bandwidth)
        job = make_job(12, mutate=add_hungry_net)
        p_oracle, s_oracle, _ = run(nodes, job, "service")
        p_batch, s_batch, _ = run(nodes, job, "tpu-batch")
        assert len(p_oracle) == 6
        assert p_oracle == p_batch
        assert len({v for v in p_batch.values()}) == 6  # one per node

    def test_multi_nic_network_jobs_escape_to_oracle(self):
        """AssignNetwork enforces bandwidth per device: a cluster with
        dual-NIC nodes routes network evals to the oracle, so placements
        (and counts) match exactly instead of over-packing summed NICs."""
        from nomad_tpu.structs.model import NetworkResource, Port
        from nomad_tpu.tpu import batch_sched

        nodes = build_cluster(10)
        for n in nodes:
            n.node_resources.cpu.cpu_shares = 100000
            n.node_resources.memory.memory_mb = 100000
        # 5 dual-NIC nodes (150+150) + 5 single-NIC nodes (300)
        for i, n in enumerate(nodes):
            if i < 5:
                n.node_resources.networks = [
                    NetworkResource(device="eth0", ip="192.168.1.1", cidr="192.168.1.1/32", mbits=150),
                    NetworkResource(device="eth1", ip="192.168.1.2", cidr="192.168.1.2/32", mbits=150),
                ]
            else:
                n.node_resources.networks = [
                    NetworkResource(device="eth0", ip="192.168.1.1", cidr="192.168.1.1/32", mbits=300),
                ]

        def add_net(job):
            task = job.task_groups[0].tasks[0]
            task.resources.cpu = 10
            task.resources.memory_mb = 10
            task.resources.networks = [
                NetworkResource(mbits=100, dynamic_ports=[Port(label="p")])
            ]

        job = make_job(25, mutate=add_net)
        before = batch_sched.counters_snapshot()
        p_oracle, _, _ = run(nodes, job, "service")
        p_batch, _, _ = run(nodes, job, "tpu-batch")
        after = batch_sched.counters_snapshot()
        assert len(p_oracle) == 25  # per-device accounting fits them all
        assert p_oracle == p_batch
        assert (
            after["fallback_reasons"].get("multi_nic_network", 0)
            > before["fallback_reasons"].get("multi_nic_network", 0)
        )

    def test_bandwidth_failure_metric_label(self):
        """Bandwidth-bound failures report the oracle's dimension label,
        not 'disk' (first_dim must cover the 4th column)."""
        from nomad_tpu.structs.model import NetworkResource, Port

        nodes = build_cluster(4)
        for n in nodes:
            n.node_resources.cpu.cpu_shares = 100000
            n.node_resources.memory.memory_mb = 100000
            n.node_resources.networks[0].mbits = 50

        def add_net(job):
            task = job.task_groups[0].tasks[0]
            task.resources.cpu = 10
            task.resources.memory_mb = 10
            task.resources.networks = [
                NetworkResource(mbits=40, dynamic_ports=[Port(label="p")])
            ]

        job = make_job(12, mutate=add_net)  # 1 fits per node, 8 fail
        _, s_oracle, _ = run(nodes, job, "service")
        _, s_batch, _ = run(nodes, job, "tpu-batch")
        m_oracle = s_oracle.failed_tg_allocs["web"]
        m_batch = s_batch.failed_tg_allocs["web"]
        assert "network: bandwidth exceeded" in m_oracle.dimension_exhausted
        assert "network: bandwidth exceeded" in m_batch.dimension_exhausted
        assert "disk" not in m_batch.dimension_exhausted

    def test_larger_parity_ratio(self):
        # 100 nodes x 80 allocs: allow tiny divergence from float rounding
        nodes = build_cluster(100)
        frac = assert_parity(nodes, make_job(80), min_match=0.99)
        assert frac >= 0.99

    def test_chunked_spread_targets_parity(self):
        # count > 64 with spread → chunked global-argmax path
        nodes = build_cluster(40, dcs=("dc1", "dc2", "dc3", "dc4"))

        def mutate(job):
            job.datacenters = ["dc1", "dc2", "dc3", "dc4"]
            job.spreads = [
                Spread(
                    attribute="${node.datacenter}",
                    weight=100,
                    spread_target=[
                        SpreadTarget(value=f"dc{i}", percent=25) for i in (1, 2, 3, 4)
                    ],
                )
            ]

        from nomad_tpu.tpu import batch_sched

        frac = assert_parity(nodes, make_job(120, mutate), min_match=0.98)
        assert batch_sched.LAST_KERNEL_STATS.get("mode") == "runs"
        assert frac >= 0.98

    def test_chunked_even_spread_parity(self):
        nodes = build_cluster(30, dcs=("dc1", "dc2", "dc3"))

        def mutate(job):
            job.datacenters = ["dc1", "dc2", "dc3"]
            job.spreads = [Spread(attribute="${node.datacenter}", weight=100)]

        from nomad_tpu.tpu import batch_sched

        assert_parity(nodes, make_job(90, mutate), min_match=0.98)
        assert batch_sched.LAST_KERNEL_STATS.get("mode") == "runs"

    def test_chunked_affinity_parity(self):
        nodes = build_cluster(50)
        for i, n in enumerate(nodes):
            n.meta["ssd"] = "true" if i < 10 else "false"

        def mutate(job):
            job.affinities = [
                Affinity(
                    l_target="${meta.ssd}", r_target="true", operand="=", weight=50
                )
            ]

        from nomad_tpu.tpu import batch_sched

        assert_parity(nodes, make_job(100, mutate), min_match=0.98)
        assert batch_sched.LAST_KERNEL_STATS.get("mode") == "runs"

    def test_chunked_spread_and_affinity(self):
        nodes = build_cluster(40, dcs=("dc1", "dc2"))
        for i, n in enumerate(nodes):
            n.meta["ssd"] = "true" if i % 3 == 0 else "false"

        def mutate(job):
            job.datacenters = ["dc1", "dc2"]
            job.spreads = [Spread(attribute="${node.datacenter}", weight=100)]
            job.affinities = [
                Affinity(
                    l_target="${meta.ssd}", r_target="true", operand="=", weight=50
                )
            ]

        from nomad_tpu.tpu import batch_sched

        assert_parity(nodes, make_job(80, mutate), min_match=0.97)
        assert batch_sched.LAST_KERNEL_STATS.get("mode") == "runs"

    def test_system_planes_parity(self):
        """tpu-system places the same set as the oracle system scheduler,
        with infeasible nodes filtered identically (one plane build instead
        of one stack walk per node)."""
        from nomad_tpu import mock as mock_mod
        from nomad_tpu.structs import compute_class
        from nomad_tpu.structs.model import Constraint
        from nomad_tpu.tpu import batch_sched

        nodes = build_cluster(60)
        for i, n in enumerate(nodes):
            n.attributes["rack_class"] = "a" if i % 3 else "b"
            compute_class(n)

        def sys_job():
            j = mock_mod.system_job()
            j.constraints = [
                Constraint(l_target="${attr.kernel.name}", r_target="linux", operand="="),
                Constraint(l_target="${attr.rack_class}", r_target="a", operand="="),
            ]
            j.task_groups[0].tasks[0].resources.networks = []
            return j

        job = sys_job()
        _, _, h_oracle = run(nodes, job, "system")
        job2 = job.copy()
        _, _, h_batch = run([n.copy() for n in nodes], job2, "tpu-system")
        # system allocs share one name per group — compare by node set
        oracle_nodes = {
            a.node_id for a in h_oracle.state.allocs_by_job(job.namespace, job.id)
        }
        batch_nodes = {
            a.node_id for a in h_batch.state.allocs_by_job(job2.namespace, job2.id)
        }
        assert len(oracle_nodes) == len(batch_nodes) == 40
        # same rack-'a' filter applied on both paths (node objects are
        # copies, so compare by attribute)
        assert all(
            h_batch.state.node_by_id(nid).attributes["rack_class"] == "a"
            for nid in batch_nodes
        )
        assert batch_sched.SCHED_COUNTERS["modes"].get("system-planes", 0) >= 1

    def test_system_planes_fit_fallback(self):
        """A full node routes through the exact single-node walk and fails
        with real metrics, while the rest place densely."""
        nodes = build_cluster(40)
        full = nodes[0]
        full.node_resources.cpu.cpu_shares = 10  # too small for the task

        from nomad_tpu import mock as mock_mod

        job = mock_mod.system_job()
        job.task_groups[0].tasks[0].resources.networks = []
        job.task_groups[0].tasks[0].resources.cpu = 100
        _, sched, h = run(nodes, job, "tpu-system")
        placed_nodes = {
            a.node_id for a in h.state.allocs_by_job(job.namespace, job.id)
        }
        assert len(placed_nodes) == 39
        assert full.id not in placed_nodes
        assert sched.failed_tg_allocs, "full node surfaces failure metrics"

    def test_fallback_on_networks(self):
        # job with dynamic ports must fall back to the oracle path and still place
        nodes = build_cluster(5)
        job = mock.job()  # has networks
        job.task_groups[0].count = 5
        p_batch, sched, _ = run(nodes, job, "tpu-batch")
        assert len(p_batch) == 5

    def test_fallback_on_distinct_hosts(self):
        nodes = build_cluster(8)

        def mutate(job):
            job.constraints.append(Constraint(operand="distinct_hosts"))

        p_batch, _, _ = run(nodes, make_job(6, mutate), "tpu-batch")
        assert len(p_batch) == 6
        assert len(set(p_batch.values())) == 6


class TestVectorOracleParity:
    """The float64 numpy stepper (factory ``oracle-np``, tpu/exact_np.py)
    must reproduce the scalar iterator chain EXACTLY — it is the bench's
    wide-coverage oracle, so spot divergence here would poison the whole
    parity argument. Counts stay above the small-eval gate so the stepper
    (not the scalar fallback) actually runs; the mode counter proves it."""

    def _assert_exact(self, nodes, job):
        from nomad_tpu.tpu import batch_sched

        before = batch_sched.counters_snapshot()["modes"].get("exact-np", 0)
        p_oracle, _, _ = run(nodes, job, "service")
        p_np, _, _ = run(nodes, job, "oracle-np")
        after = batch_sched.counters_snapshot()["modes"].get("exact-np", 0)
        assert after > before, "stepper did not run (fell back?)"
        assert p_oracle == p_np

    def test_basic_binpack(self):
        self._assert_exact(build_cluster(20), make_job(15))

    def test_bounded_limit_rotation(self):
        # no affinity/spread => log2-bounded candidate window and a live
        # rotating cursor across Selects
        self._assert_exact(build_cluster(40), make_job(30))

    def test_with_constraints(self):
        nodes = build_cluster(20)
        for i, n in enumerate(nodes):
            n.attributes["rack_class"] = "a" if i % 2 == 0 else "b"
            from nomad_tpu.structs import compute_class

            compute_class(n)

        def mutate(job):
            job.constraints.append(
                Constraint(l_target="${attr.rack_class}", r_target="a", operand="=")
            )

        self._assert_exact(nodes, make_job(12, mutate))

    def test_with_affinity(self):
        nodes = build_cluster(16)
        for i, n in enumerate(nodes):
            n.meta["ssd"] = "true" if i < 4 else "false"

        def mutate(job):
            job.affinities = [
                Affinity(l_target="${meta.ssd}", r_target="true", operand="=", weight=50)
            ]

        self._assert_exact(nodes, make_job(12, mutate))

    def test_with_spread_targets(self):
        nodes = build_cluster(12, dcs=("dc1", "dc2"))

        def mutate(job):
            job.datacenters = ["dc1", "dc2"]
            job.spreads = [
                Spread(
                    attribute="${node.datacenter}",
                    weight=100,
                    spread_target=[
                        SpreadTarget(value="dc1", percent=50),
                        SpreadTarget(value="dc2", percent=50),
                    ],
                )
            ]

        self._assert_exact(nodes, make_job(10, mutate))

    def test_with_even_spread(self):
        nodes = build_cluster(12, dcs=("dc1", "dc2", "dc3"))

        def mutate(job):
            job.datacenters = ["dc1", "dc2", "dc3"]
            job.spreads = [Spread(attribute="${node.datacenter}", weight=100)]

        self._assert_exact(nodes, make_job(9, mutate))

    def test_exhaustion(self):
        # more asks than the cluster fits: the unplaced tail and failure
        # metrics must match the scalar chain
        nodes = build_cluster(3)
        job = make_job(60)
        p_oracle, s_oracle, _ = run(nodes, job, "service")
        p_np, s_np, _ = run(nodes, job, "oracle-np")
        assert p_oracle == p_np
        m_o = s_oracle.failed_tg_allocs["web"]
        m_n = s_np.failed_tg_allocs["web"]
        assert m_o.coalesced_failures == m_n.coalesced_failures
        assert m_o.nodes_exhausted == m_n.nodes_exhausted

    def test_larger_scale_spread(self):
        nodes = build_cluster(120, dcs=("dc1", "dc2", "dc3", "dc4"))

        def mutate(job):
            job.datacenters = ["dc1", "dc2", "dc3", "dc4"]
            job.spreads = [
                Spread(
                    attribute="${node.datacenter}",
                    weight=100,
                    spread_target=[
                        SpreadTarget(value=f"dc{i}", percent=25) for i in (1, 2, 3, 4)
                    ],
                )
            ]

        self._assert_exact(nodes, make_job(200, mutate))
