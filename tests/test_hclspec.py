"""Typed plugin config specs (ref plugins/shared/hclspec/hcl_spec.proto:
Attr/Block/BlockList/Default/Literal composition, pathed decode errors)."""

import pytest

from nomad_tpu.drivers.docker import DockerDriver
from nomad_tpu.plugins.external import PluginError, validate_plugin_config
from nomad_tpu.plugins.hclspec import (
    Attr,
    Block,
    BlockList,
    Default,
    Literal,
    SpecError,
    validate_spec,
)


class TestAttrTypes:
    def test_primitives(self):
        spec = {
            "name": Attr("string"),
            "count": Attr("number"),
            "on": Attr("bool"),
        }
        out = validate_spec(spec, {"name": "x", "count": 2.5, "on": True})
        assert out == {"name": "x", "count": 2.5, "on": True}

    def test_bool_is_not_a_number(self):
        with pytest.raises(SpecError, match="count: must be number, got bool"):
            validate_spec({"count": Attr("number")}, {"count": True})

    def test_list_and_map_types(self):
        spec = {"args": Attr("list(string)"), "env": Attr("map(string)")}
        out = validate_spec(
            spec, {"args": ["a", "b"], "env": {"K": "v"}}
        )
        assert out == {"args": ["a", "b"], "env": {"K": "v"}}

    def test_list_element_error_carries_index(self):
        with pytest.raises(SpecError, match=r"args\[1\]: must be string"):
            validate_spec({"args": Attr("list(string)")}, {"args": ["a", 3]})

    def test_map_value_error_carries_key(self):
        with pytest.raises(SpecError, match=r"ports\.http: must be number"):
            validate_spec(
                {"ports": Attr("map(number)")}, {"ports": {"http": "80"}}
            )


class TestBlocks:
    SPEC = {
        "image": Attr("string", required=True),
        "auth": Block({
            "username": Attr("string"),
            "password": Attr("string"),
        }),
        "mounts": BlockList({
            "target": Attr("string", required=True),
            "volume_options": Block({"labels": Attr("map(string)")}),
        }),
    }

    def test_nested_decode(self):
        out = validate_spec(self.SPEC, {
            "image": "redis:7",
            "auth": {"username": "u", "password": "p"},
            "mounts": [
                {"target": "/data",
                 "volume_options": {"labels": {"a": "b"}}},
            ],
        })
        assert out["mounts"][0]["volume_options"]["labels"] == {"a": "b"}

    def test_single_block_accepted_for_block_list(self):
        out = validate_spec(self.SPEC, {
            "image": "redis:7", "mounts": {"target": "/data"},
        })
        assert out["mounts"] == [{"target": "/data"}]

    def test_bad_nested_value_yields_pathed_error_not_keyerror(self):
        with pytest.raises(
            SpecError,
            match=r"mounts\[0\]\.volume_options\.labels\.a: must be string",
        ):
            validate_spec(self.SPEC, {
                "image": "redis:7",
                "mounts": [
                    {"target": "/d",
                     "volume_options": {"labels": {"a": 1}}},
                ],
            })

    def test_unknown_nested_key_pathed(self):
        with pytest.raises(SpecError, match=r"auth\.passwrod: unknown"):
            validate_spec(self.SPEC, {
                "image": "x", "auth": {"passwrod": "oops"},
            })

    def test_missing_required_nested_field(self):
        with pytest.raises(
            SpecError, match=r"mounts\[0\]\.target: required"
        ):
            validate_spec(self.SPEC, {"image": "x", "mounts": [{}]})

    def test_block_list_min_max(self):
        spec = {"groups": BlockList({"name": Attr("string")}, min=1, max=2)}
        with pytest.raises(SpecError, match="at least 1"):
            validate_spec(spec, {"groups": []})
        with pytest.raises(SpecError, match="at most 2"):
            validate_spec(spec, {"groups": [{}, {}, {}]})


class TestDefaultsAndLiterals:
    def test_default_folds_when_absent(self):
        spec = {"retries": Default(Attr("number"), 3)}
        assert validate_spec(spec, {}) == {"retries": 3}
        assert validate_spec(spec, {"retries": 5}) == {"retries": 5}

    def test_literal_always_injected(self):
        spec = {"version": Literal("v1")}
        assert validate_spec(spec, {}) == {"version": "v1"}

    def test_legacy_flat_schema_lifts(self):
        out = validate_plugin_config(
            {
                "addr": {"type": "string", "required": True},
                "port": {"type": "number", "default": 8080},
            },
            {"addr": "1.2.3.4"},
        )
        assert out == {"addr": "1.2.3.4", "port": 8080}
        with pytest.raises(PluginError, match="addr: required"):
            validate_plugin_config(
                {"addr": {"type": "string", "required": True}}, {}
            )
        with pytest.raises(PluginError, match="bogus: unknown"):
            validate_plugin_config({}, {"bogus": 1})


class TestDockerTaskConfigSpec:
    def test_full_valid_config_decodes(self):
        drv = DockerDriver.__new__(DockerDriver)  # no docker binary probe
        out = drv.validate_task_config({
            "image": "redis:7",
            "args": ["--maxmemory", "64mb"],
            "port_map": {"db": 6379},
            "labels": {"team": "infra"},
            "auth": {"username": "u", "password": "p"},
            "mounts": [
                {"type": "volume", "target": "/data", "source": "vol1",
                 "volume_options": {
                     "no_copy": True,
                     "driver_config": {
                         "name": "local", "options": {"o": "bind"}
                     },
                 }},
            ],
            "devices": [{"host_path": "/dev/fuse"}],
        })
        assert out["port_map"] == {"db": 6379}
        assert out["mounts"][0]["volume_options"]["no_copy"] is True

    def test_bad_nested_docker_config_is_pathed(self):
        drv = DockerDriver.__new__(DockerDriver)
        with pytest.raises(
            RuntimeError,
            match=r"mounts\[0\]\.volume_options\.no_copy: must be bool",
        ):
            drv.validate_task_config({
                "image": "redis:7",
                "mounts": [
                    {"target": "/d", "volume_options": {"no_copy": "yes"}},
                ],
            })

    def test_devices_require_host_path(self):
        drv = DockerDriver.__new__(DockerDriver)
        with pytest.raises(
            RuntimeError, match=r"devices\[0\]\.host_path: required"
        ):
            drv.validate_task_config(
                {"image": "x", "devices": [{"container_path": "/dev/x"}]}
            )

    def test_namespace_and_address_keys_validate(self):
        """Keys start_task consumes must validate (regression: the spec
        omitted them, so previously-valid jobs using static container IPs
        or host namespaces were rejected with 'unknown config key')."""
        drv = DockerDriver.__new__(DockerDriver)
        out = drv.validate_task_config({
            "image": "redis:7",
            "network_mode": "bridge",
            "ipv4_address": "172.18.0.10",
            "ipv6_address": "2001:db8::10",
            "pid_mode": "host",
            "ipc_mode": "host",
            "uts_mode": "host",
            "userns_mode": "host",
        })
        assert out["ipv4_address"] == "172.18.0.10"
        assert out["userns_mode"] == "host"
        with pytest.raises(RuntimeError, match=r"pid_mode: must be string"):
            drv.validate_task_config({"image": "x", "pid_mode": 1})

    def test_typo_key_rejected_with_path(self):
        drv = DockerDriver.__new__(DockerDriver)
        with pytest.raises(RuntimeError, match="imge: unknown config key"):
            drv.validate_task_config({"imge": "redis:7"})

    def test_port_map_values_must_be_numbers(self):
        drv = DockerDriver.__new__(DockerDriver)
        with pytest.raises(
            RuntimeError, match=r"port_map\.db: must be number"
        ):
            drv.validate_task_config(
                {"image": "x", "port_map": {"db": "6379"}}
            )
