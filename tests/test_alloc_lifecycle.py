"""Alloc lifecycle surface: stop (server-side reschedule), restart and
signal (client-side, local and forwarded) — ref alloc_endpoint.go Stop,
client_alloc_endpoint.go Restart/Signal, drivers SignalTask."""

import time

import pytest

import nomad_tpu.mock as mock
from nomad_tpu.agent import ClientAgent, DevAgent, ServerAgent
from nomad_tpu.api.client import ApiClient, APIError
from nomad_tpu.api.http import HTTPServer


def wait_until(fn, timeout=20.0, msg="condition"):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if fn():
            return
        time.sleep(0.05)
    raise AssertionError(f"timed out waiting for {msg}")


@pytest.fixture()
def dev():
    agent = DevAgent(num_clients=1, server_config={"seed": 23})
    agent.start()
    http = HTTPServer(agent.server, port=0, agent=agent)
    http.start()
    client = ApiClient(address=http.address)
    yield agent, client
    http.stop()
    agent.stop()


def run_long_job(agent, count=1, run_for="60s"):
    job = mock.job()
    tg = job.task_groups[0]
    tg.count = count
    tg.tasks[0].driver = "mock_driver"
    tg.tasks[0].config = {"run_for": run_for}
    tg.tasks[0].resources.networks = []
    agent.server.job_register(job)
    wait_until(
        lambda: len(
            [
                a
                for a in agent.server.state.allocs_by_job(job.namespace, job.id)
                if a.client_status == "running"
            ]
        )
        == count,
        msg="allocs running",
    )
    return job


class TestLocalRestartSignal:
    def test_restart_relaunches_without_budget(self, dev):
        agent, client = dev
        job = run_long_job(agent)
        (alloc,) = agent.server.state.allocs_by_job(job.namespace, job.id)
        out = client.alloc_restart(alloc.id)
        assert out["tasks"] == ["web"]
        runner = agent.clients[0].alloc_runners[alloc.id]
        tr = runner.task_runners["web"]
        wait_until(
            lambda: tr.state.state == "running" and tr.state.restarts == 1,
            msg="task running again after restart",
        )
        # user restarts bypass the restart-policy budget
        assert tr._restarts_in_interval == []

    def test_signal_reaches_driver(self, dev):
        agent, client = dev
        job = run_long_job(agent)
        (alloc,) = agent.server.state.allocs_by_job(job.namespace, job.id)
        out = client.alloc_signal(alloc.id, signal="SIGHUP")
        assert out["tasks"] == ["web"]
        runner = agent.clients[0].alloc_runners[alloc.id]
        handle = runner.task_runners["web"].handle
        assert handle.signals == ["SIGHUP"]

    def test_signal_real_process(self, dev):
        """raw_exec delivers an OS signal the task can trap."""
        agent, client = dev
        job = mock.job()
        tg = job.task_groups[0]
        tg.count = 1
        tg.tasks[0].driver = "raw_exec"
        tg.tasks[0].config = {
            "command": "/bin/sh",
            "args": [
                "-c",
                'trap "echo got-hup > sig.txt" HUP; '
                "while true; do sleep 0.1; done",
            ],
        }
        tg.tasks[0].resources.networks = []
        agent.server.job_register(job)
        wait_until(
            lambda: any(
                a.client_status == "running"
                for a in agent.server.state.allocs_by_job(
                    job.namespace, job.id
                )
            ),
            msg="raw_exec task running",
        )
        (alloc,) = agent.server.state.allocs_by_job(job.namespace, job.id)
        client.alloc_signal(alloc.id, signal="HUP")
        runner = agent.clients[0].alloc_runners[alloc.id]
        import os

        sig_file = os.path.join(runner.task_dir("web"), "sig.txt")
        wait_until(
            lambda: os.path.exists(sig_file), msg="signal trapped by task"
        )

    def test_unknown_task_404(self, dev):
        agent, client = dev
        job = run_long_job(agent)
        (alloc,) = agent.server.state.allocs_by_job(job.namespace, job.id)
        with pytest.raises(APIError) as err:
            client.alloc_restart(alloc.id, task="nope")
        assert err.value.status == 404

    def test_signal_completed_task_400(self, dev):
        agent, client = dev
        job = mock.batch_job()
        tg = job.task_groups[0]
        tg.count = 1
        tg.tasks[0].driver = "mock_driver"
        tg.tasks[0].config = {"run_for": "0s"}
        tg.tasks[0].resources.networks = []
        agent.server.job_register(job)
        wait_until(
            lambda: [
                a.client_status
                for a in agent.server.state.allocs_by_job(
                    job.namespace, job.id
                )
            ]
            == ["complete"],
            msg="batch task complete",
        )
        (alloc,) = agent.server.state.allocs_by_job(job.namespace, job.id)
        with pytest.raises(APIError) as err:
            client.alloc_signal(alloc.id)
        assert err.value.status == 400


class TestGracefulKill:
    def test_kill_signal_reaches_task_before_escalation(self, dev):
        """kill_signal delivers the configured signal; the task traps it,
        cleans up, and exits inside kill_timeout (ref task kill_signal/
        kill_timeout semantics)."""
        agent, client = dev
        job = mock.job()
        tg = job.task_groups[0]
        tg.count = 1
        task = tg.tasks[0]
        task.driver = "raw_exec"
        task.kill_signal = "SIGUSR1"
        task.kill_timeout = int(5 * 1e9)
        task.config = {
            "command": "/bin/sh",
            "args": [
                "-c",
                'trap "echo graceful > cleanup.txt; exit 0" USR1; '
                "while true; do sleep 0.1; done",
            ],
        }
        task.resources.networks = []
        agent.server.job_register(job)
        wait_until(
            lambda: any(
                a.client_status == "running"
                for a in agent.server.state.allocs_by_job(job.namespace, job.id)
            ),
            msg="task running",
        )
        (alloc,) = agent.server.state.allocs_by_job(job.namespace, job.id)
        runner = agent.clients[0].alloc_runners[alloc.id]
        import os

        cleanup = os.path.join(runner.task_dir("web"), "cleanup.txt")
        client.alloc_stop(alloc.id)
        wait_until(
            lambda: os.path.exists(cleanup),
            msg="task trapped the configured kill signal",
        )

    def test_shutdown_delay_waits_before_kill(self, dev):
        agent, _ = dev
        job = mock.job()
        job.id = "delay-job"
        tg = job.task_groups[0]
        tg.count = 1
        task = tg.tasks[0]
        task.driver = "mock_driver"
        task.shutdown_delay = int(0.5 * 1e9)
        task.config = {"run_for": "60s"}
        task.resources.networks = []
        agent.server.job_register(job)
        wait_until(
            lambda: any(
                a.client_status == "running"
                for a in agent.server.state.allocs_by_job(job.namespace, job.id)
            ),
            msg="task running",
        )
        (alloc,) = agent.server.state.allocs_by_job(job.namespace, job.id)
        runner = agent.clients[0].alloc_runners[alloc.id]
        tr = runner.task_runners["web"]
        t0 = time.monotonic()
        tr.stop()
        elapsed = time.monotonic() - t0
        assert elapsed >= 0.5, "kill must wait out the shutdown delay"
        events = [e["type"] for e in tr.state.events]
        assert "Waiting" in events


class TestAllocStop:
    def test_stop_reschedules_elsewhere(self, dev):
        agent, client = dev
        job = run_long_job(agent)
        (alloc,) = agent.server.state.allocs_by_job(job.namespace, job.id)
        out = client.alloc_stop(alloc.id)
        assert out["EvalID"]
        wait_until(
            lambda: (
                agent.server.state.alloc_by_id(alloc.id).desired_status
                == "stop"
            ),
            msg="original alloc stopped",
        )
        # the alloc-stop eval places a replacement
        wait_until(
            lambda: any(
                a.id != alloc.id and not a.terminal_status()
                for a in agent.server.state.allocs_by_job(
                    job.namespace, job.id
                )
            ),
            msg="replacement placed",
        )
        ev = agent.server.state.eval_by_id(out["EvalID"])
        assert ev.triggered_by == "alloc-stop"

    def test_stop_unknown_alloc_404(self, dev):
        _, client = dev
        with pytest.raises(APIError) as err:
            client.alloc_stop("00000000-0000-0000-0000-00000000dead")
        assert err.value.status == 404


class TestRemoteForwarding:
    def test_restart_and_signal_forward_to_remote_client(self):
        server = ServerAgent("ls0", config={"seed": 31, "heartbeat_ttl": 5.0})
        server.start(num_workers=2)
        node_agent = ClientAgent([server.address])
        http = HTTPServer(server.server, port=0, agent=None)
        http.start()
        api = ApiClient(address=http.address)
        try:
            node_agent.start()
            wait_until(
                lambda: server.server.state.node_by_id(node_agent.node.id)
                is not None,
                msg="node registered",
            )
            job = mock.job()
            tg = job.task_groups[0]
            tg.count = 1
            tg.tasks[0].driver = "mock_driver"
            tg.tasks[0].config = {"run_for": "60s"}
            tg.tasks[0].resources.networks = []
            server.server.job_register(job)
            wait_until(
                lambda: any(
                    a.client_status == "running"
                    for a in server.server.state.allocs_by_job(
                        job.namespace, job.id
                    )
                ),
                msg="remote alloc running",
            )
            (alloc,) = server.server.state.allocs_by_job(
                job.namespace, job.id
            )
            out = api.alloc_signal(alloc.id, signal="SIGUSR1")
            assert out["tasks"] == ["web"]
            runner = node_agent.client.alloc_runners[alloc.id]
            assert runner.task_runners["web"].handle.signals == ["SIGUSR1"]

            out = api.alloc_restart(alloc.id)
            assert out["tasks"] == ["web"]
            tr = runner.task_runners["web"]
            wait_until(
                lambda: tr.state.state == "running"
                and tr.state.restarts == 1,
                msg="remote task restarted",
            )
        finally:
            http.stop()
            node_agent.stop()
            server.stop()
