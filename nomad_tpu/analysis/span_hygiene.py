"""Span-hygiene checkers for the trace plane (nomad_tpu/trace).

The trace plane's value rests on two invariants the tree must keep:

- **every manually-started span is closed on all exits** — an unclosed
  span is a leaked entry in the store's open buffer AND a hole in the
  tree (its children become orphans);
- **a span body must not wrap a lock-held blocking call** — a span
  context adds nothing there (lockgraph already flags the call), and
  spans normalizing such blocks makes the lock-scope smell look
  sanctioned.

Rules (scoped to the trace plane's reachable surface: ``core/``,
``tpu/``, ``rpc/``):

- ``span-unclosed`` — a call to ``start_span``/``start_root`` whose
  result is not a ``with`` item and not ``.end()``-ed inside a
  ``finally`` block of the same function. The tracer-owned eval root
  (``eval_root``/``finish_eval``) is lifecycle-managed across calls and
  exempt by design.
- ``span-lock-blocking`` — a blocking call (the lockgraph seed set +
  wait/join/sleep) inside a ``with tracer.span(...)`` /
  ``tracer.root(...)`` body while a lexically-enclosing ``with`` holds
  a lock (an item whose name contains ``lock`` or ``cond``).
"""

from __future__ import annotations

import ast

from .framework import Finding, Project, dotted, register

_SCOPES = ("nomad_tpu/core/", "nomad_tpu/tpu/", "nomad_tpu/rpc/")

#: manual-span constructors whose result the caller must close
_MANUAL_STARTS = {"start_span", "start_root"}
#: contextmanager span constructors (the sanctioned shape)
_SPAN_CMS = {"span", "root"}

#: blocking tails (lockgraph's seed set + the generic primitives)
_BLOCKING_TAILS = {
    "block_until_ready", "snapshot_min_index", "raft_apply",
    "recv", "accept", "wait", "join", "sleep", "sendall",
}


def _in_scope(relpath: str) -> bool:
    return any(relpath.startswith(p) for p in _SCOPES)


def _call_tail(node: ast.Call) -> str:
    return dotted(node.func).rsplit(".", 1)[-1]


def _is_span_cm(node: ast.AST) -> bool:
    return isinstance(node, ast.Call) and _call_tail(node) in _SPAN_CMS and (
        "trace" in dotted(node.func) or dotted(node.func).startswith("tracer")
    )


def _is_lockish(node: ast.AST) -> bool:
    """A with-item that looks like a lock acquisition."""
    if isinstance(node, ast.Call):
        node = node.func
    name = dotted(node).lower()
    return "lock" in name or "cond" in name


@register(
    "span-unclosed",
    "manually-started span not closed on all exits (use a `with` span, "
    "record_span, or end() in a finally)",
)
def check_span_unclosed(project: Project) -> list[Finding]:
    findings = []
    for mod in project.modules:
        if not _in_scope(mod.relpath):
            continue
        for fn in ast.walk(mod.tree):
            if not isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            # with-items are closed by construction
            with_items = set()
            finally_ended = set()  # names .end()-ed inside a finally
            for node in ast.walk(fn):
                if isinstance(node, (ast.With, ast.AsyncWith)):
                    for item in node.items:
                        with_items.add(id(item.context_expr))
                elif isinstance(node, ast.Try):
                    for final_stmt in node.finalbody:
                        for call in ast.walk(final_stmt):
                            if (
                                isinstance(call, ast.Call)
                                and _call_tail(call) == "end"
                            ):
                                recv = dotted(call.func).rsplit(".", 1)[0]
                                finally_ended.add(recv)
            for node in ast.walk(fn):
                if not isinstance(node, ast.Call):
                    continue
                if _call_tail(node) not in _MANUAL_STARTS:
                    continue
                if id(node) in with_items:
                    continue
                # assigned to a name that is end()-ed in a finally?
                parent_assign = None
                for stmt in ast.walk(fn):
                    if isinstance(stmt, ast.Assign) and stmt.value is node:
                        parent_assign = stmt
                        break
                if parent_assign is not None:
                    targets = {dotted(t) for t in parent_assign.targets}
                    if targets & finally_ended:
                        continue
                findings.append(
                    Finding(
                        "span-unclosed", mod.relpath, node.lineno,
                        f"{_call_tail(node)}() result is not closed on "
                        "all exits: use `with tracer.span(...)`, "
                        "record_span(), or end() in a finally",
                    )
                )
    return findings


@register(
    "span-lock-blocking",
    "span body wraps a lock-held blocking call (lockgraph's "
    "lock-held-blocking-call made span-visible)",
)
def check_span_lock_blocking(project: Project) -> list[Finding]:
    findings = []
    for mod in project.modules:
        if not _in_scope(mod.relpath):
            continue
        for fn in ast.walk(mod.tree):
            if not isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            _walk_spans(fn.body, lock_held=False, in_span=False,
                        mod=mod, findings=findings)
    return findings


def _walk_spans(stmts, lock_held: bool, in_span: bool, mod, findings):
    for stmt in stmts:
        held = lock_held
        spanned = in_span
        if isinstance(stmt, (ast.With, ast.AsyncWith)):
            for item in stmt.items:
                expr = item.context_expr
                if _is_span_cm(expr):
                    spanned = True
                elif _is_lockish(expr):
                    held = True
            _walk_spans(stmt.body, held, spanned, mod, findings)
            continue
        if spanned and held:
            # simple statements are scanned whole; compound statements
            # contribute their HEADER expressions (if/while tests, for
            # iterators) — bodies are reached by the recursion below, so
            # each call is scanned exactly once
            if not hasattr(stmt, "body"):
                scan_roots = [stmt]
            else:
                scan_roots = [
                    expr
                    for expr in (
                        getattr(stmt, "test", None),
                        getattr(stmt, "iter", None),
                    )
                    if expr is not None
                ]
            for root in scan_roots:
                for node in ast.walk(root):
                    if (
                        isinstance(node, ast.Call)
                        and _call_tail(node) in _BLOCKING_TAILS
                    ):
                        findings.append(
                            Finding(
                                "span-lock-blocking", mod.relpath,
                                node.lineno,
                                f"blocking call {dotted(node.func)}() "
                                "inside a span body while a lock is "
                                "held — fix the lock scope, don't "
                                "trace over it",
                            )
                        )
        # recurse into nested blocks (if/for/try/while bodies)
        for field_name in ("body", "orelse", "finalbody", "handlers"):
            sub = getattr(stmt, field_name, None)
            if not sub:
                continue
            if field_name == "handlers":
                for handler in sub:
                    _walk_spans(handler.body, held, spanned, mod, findings)
            elif isinstance(stmt, (ast.With, ast.AsyncWith)):
                continue  # already recursed above
            else:
                _walk_spans(sub, held, spanned, mod, findings)
    return findings
