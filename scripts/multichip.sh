#!/usr/bin/env bash
# Sharded multichip suite on a CPU-virtualized 8-device mesh: the scored
# bench (writes MULTICHIP_rNN.json + prints MULTICHIP_SUMMARY) followed
# by the sharded test file. Scale knobs:
#   MULTICHIP_DEVICES (default 8)  mesh width
#   MULTICHIP_NODES   (default 2048)  node axis
#   MULTICHIP_ALLOCS  (default 512)  placements
# Real-TPU boxes: drop the XLA_FLAGS/JAX_PLATFORMS overrides and the
# same code paths drive the hardware mesh.
set -euo pipefail
cd "$(dirname "$0")/.."

DEVICES="${MULTICHIP_DEVICES:-8}"
export XLA_FLAGS="${XLA_FLAGS:---xla_force_host_platform_device_count=${DEVICES}}"
export JAX_PLATFORMS="${JAX_PLATFORMS:-cpu}"
# the persistent compile cache stores CPU-AOT entries whose machine
# feature flags may not match this host (cpu_aot_loader SIGILL warning)
export NOMAD_TPU_COMPILE_CACHE="${NOMAD_TPU_COMPILE_CACHE:-off}"
# wavefront scored section (tpu/wavefront.py): on by default; =0 skips
export MULTICHIP_WAVEFRONT="${MULTICHIP_WAVEFRONT:-1}"

python -m nomad_tpu.tpu.multichip "$@"

echo "--- sharded test suite ---"
python -m pytest tests/test_multichip.py -q -p no:cacheprovider
