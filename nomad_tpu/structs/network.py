"""Per-node network/port accounting: the NetworkIndex.

Semantics mirror the reference (nomad/structs/network.go:35-417): available
networks/bandwidth per device, used ports tracked per-IP in a 65536-bit
bitmap, reserved-port collision detection, and AssignNetwork picking an IP +
dynamic ports — stochastic probing first (20 tries), falling back to a precise
bitmap scan. Randomness is injected via an explicit ``random.Random`` so the
scheduler can run deterministically (seeded) for oracle-parity testing.
"""

from __future__ import annotations

import ipaddress
import random
from typing import Callable, Optional

from .bitmap import Bitmap
from .model import (
    MAX_DYNAMIC_PORT,
    MAX_VALID_PORT,
    MIN_DYNAMIC_PORT,
    Allocation,
    NetworkResource,
    Node,
)

MAX_RAND_PORT_ATTEMPTS = 20


def parse_port_ranges(spec: str) -> list[int]:
    """Parse '80,100-200,205' into a sorted port list (ref structs.go
    ParsePortRanges)."""
    ports: set[int] = set()
    for part in spec.split(","):
        part = part.strip()
        if not part:
            continue
        if "-" in part:
            lo_s, hi_s = part.split("-", 1)
            lo, hi = int(lo_s), int(hi_s)
            if lo > hi:
                raise ValueError(f"invalid port range {part}")
            ports.update(range(lo, hi + 1))
        else:
            ports.add(int(part))
    return sorted(ports)


class NetworkIndex:
    """Index of available and used network resources on one node."""

    def __init__(self, rng: Optional[random.Random] = None):
        self.avail_networks: list[NetworkResource] = []
        self.avail_bandwidth: dict[str, int] = {}
        self.used_ports: dict[str, Bitmap] = {}
        self.used_bandwidth: dict[str, int] = {}
        # lazy: seeding a fresh Mersenne state costs ~ms-scale urandom
        # reads, and the plan-verify hot path builds a NetworkIndex per
        # touched node without ever assigning a port
        self._rng = rng

    @property
    def rng(self) -> random.Random:
        if self._rng is None:
            self._rng = random.Random()
        return self._rng

    def release(self):
        """No-op (the Go version pools bitmaps; numpy makes this unnecessary)."""

    def overcommitted(self) -> bool:
        return any(
            used > self.avail_bandwidth.get(device, 0)
            for device, used in self.used_bandwidth.items()
        )

    def set_node(self, node: Node) -> bool:
        """Record the node's available networks + reserved host ports.
        Returns True on a reserved-port collision (ref network.go:72-104)."""
        collide = False
        if node.node_resources is not None:
            for n in node.node_resources.networks:
                if n.device:
                    self.avail_networks.append(n)
                    self.avail_bandwidth[n.device] = n.mbits
        if (
            node.reserved_resources is not None
            and node.reserved_resources.networks.reserved_host_ports
        ):
            collide = self.add_reserved_port_range(
                node.reserved_resources.networks.reserved_host_ports
            )
        return collide

    def add_allocs(self, allocs: list[Allocation]) -> bool:
        """Record ports used by non-terminal allocs; True on collision
        (ref network.go:108-148)."""
        collide = False
        for alloc in allocs:
            if alloc.terminal_status():
                continue
            if alloc.allocated_resources is None:
                continue
            for network in alloc.allocated_resources.shared.networks:
                if self.add_reserved(network):
                    collide = True
            for task in alloc.allocated_resources.tasks.values():
                if not task.networks:
                    continue
                if self.add_reserved(task.networks[0]):
                    collide = True
        return collide

    def add_reserved(self, n: NetworkResource) -> bool:
        """Mark a network resource's ports/bandwidth used; True on collision
        (ref network.go:152-184)."""
        collide = False
        used = self.used_ports.get(n.ip)
        if used is None:
            used = Bitmap(MAX_VALID_PORT)
            self.used_ports[n.ip] = used
        for ports in (n.reserved_ports, n.dynamic_ports):
            for port in ports:
                if port.value < 0 or port.value >= MAX_VALID_PORT:
                    return True
                if used.check(port.value):
                    collide = True
                else:
                    used.set(port.value)
        self.used_bandwidth[n.device] = self.used_bandwidth.get(n.device, 0) + n.mbits
        return collide

    def add_reserved_port_range(self, ports: str) -> bool:
        """Reserve a comma/range port spec on every known IP
        (ref network.go:189-227)."""
        try:
            res_ports = parse_port_ranges(ports)
        except ValueError:
            return False
        collide = False
        for n in self.avail_networks:
            if n.ip not in self.used_ports:
                self.used_ports[n.ip] = Bitmap(MAX_VALID_PORT)
        for used in self.used_ports.values():
            for port in res_ports:
                if port < 0 or port >= MAX_VALID_PORT:
                    return True
                if used.check(port):
                    collide = True
                else:
                    used.set(port)
        return collide

    def _yield_ips(self, cb: Callable[[NetworkResource, str], bool]):
        """Invoke cb for each IP in each available CIDR until it returns True
        (ref network.go:231-252)."""
        for n in self.avail_networks:
            try:
                net = ipaddress.ip_network(n.cidr, strict=False)
            except ValueError:
                continue
            for ip in net:
                if cb(n, str(ip)):
                    return

    def assign_network(
        self, ask: NetworkResource
    ) -> tuple[Optional[NetworkResource], str]:
        """Assign an IP + ports for the ask; (offer, "") on success or
        (None, reason) (ref network.go:256-330)."""
        err = "no networks available"
        out: Optional[NetworkResource] = None

        def attempt(n: NetworkResource, ip_str: str) -> bool:
            nonlocal err, out
            avail_bw = self.avail_bandwidth.get(n.device, 0)
            used_bw = self.used_bandwidth.get(n.device, 0)
            if used_bw + ask.mbits > avail_bw:
                err = "bandwidth exceeded"
                return False
            used = self.used_ports.get(ip_str)
            for port in ask.reserved_ports:
                if port.value < 0 or port.value >= MAX_VALID_PORT:
                    err = f"invalid port {port.value} (out of range)"
                    return False
                if used is not None and used.check(port.value):
                    err = "reserved port collision"
                    return False

            offer = NetworkResource(
                mode=ask.mode,
                device=n.device,
                ip=ip_str,
                mbits=ask.mbits,
                reserved_ports=[p.copy() for p in ask.reserved_ports],
                dynamic_ports=[p.copy() for p in ask.dynamic_ports],
            )

            dyn_ports = self._dynamic_ports_stochastic(used, ask)
            if dyn_ports is None:
                dyn_ports, perr = self._dynamic_ports_precise(used, ask)
                if dyn_ports is None:
                    err = perr
                    return False

            for i, port in enumerate(dyn_ports):
                offer.dynamic_ports[i].value = port
                if offer.dynamic_ports[i].to == -1:
                    offer.dynamic_ports[i].to = port

            out = offer
            err = ""
            return True

        self._yield_ips(attempt)
        return out, err

    def _dynamic_ports_precise(
        self, node_used: Optional[Bitmap], ask: NetworkResource
    ) -> tuple[Optional[list[int]], str]:
        """Precise dynamic-port pick via bitmap scan (ref network.go:336-372)."""
        used_set = node_used.copy() if node_used is not None else Bitmap(MAX_VALID_PORT)
        for port in ask.reserved_ports:
            used_set.set(port.value)
        available = used_set.indexes_in_range(False, MIN_DYNAMIC_PORT, MAX_DYNAMIC_PORT)
        num_dyn = len(ask.dynamic_ports)
        if len(available) < num_dyn:
            return None, "dynamic port selection failed"
        num_available = len(available)
        for i in range(num_dyn):
            j = self.rng.randrange(num_available)
            available[i], available[j] = available[j], available[i]
        return available[:num_dyn], ""

    def _dynamic_ports_stochastic(
        self, node_used: Optional[Bitmap], ask: NetworkResource
    ) -> Optional[list[int]]:
        """Stochastic dynamic-port pick, bounded probes (ref network.go:379-407)."""
        reserved = [p.value for p in ask.reserved_ports]
        dynamic: list[int] = []
        for _ in range(len(ask.dynamic_ports)):
            attempts = 0
            while True:
                attempts += 1
                if attempts > MAX_RAND_PORT_ATTEMPTS:
                    return None
                rand_port = MIN_DYNAMIC_PORT + self.rng.randrange(
                    MAX_DYNAMIC_PORT - MIN_DYNAMIC_PORT
                )
                if node_used is not None and node_used.check(rand_port):
                    continue
                if rand_port in reserved or rand_port in dynamic:
                    continue
                dynamic.append(rand_port)
                break
        return dynamic
