"""Fixed-size bitmap for port accounting and alloc name indexes.

Semantics follow the reference bitmap (nomad/structs/bitmap.go), but the
representation is a numpy bool array so the TPU columnar mirror can view the
same buffer as a dense ``bool[N, 65536]`` port plane without conversion.
"""

from __future__ import annotations

import numpy as np


class Bitmap:
    __slots__ = ("bits",)

    def __init__(self, size: int):
        if size == 0:
            raise ValueError("bitmap must be positive size")
        self.bits = np.zeros(size, dtype=bool)

    @property
    def size(self) -> int:
        return self.bits.shape[0]

    def set(self, idx: int):
        self.bits[idx] = True

    def unset(self, idx: int):
        self.bits[idx] = False

    def check(self, idx: int) -> bool:
        return bool(self.bits[idx])

    def clear(self):
        self.bits[:] = False

    def copy(self) -> "Bitmap":
        b = Bitmap(self.size)
        b.bits = self.bits.copy()
        return b

    def indexes_in_range(self, set_value: bool, lo: int, hi: int) -> list[int]:
        """Indexes in [lo, hi] whose value equals set_value
        (ref bitmap.go IndexesInRange)."""
        window = self.bits[lo : hi + 1]
        idx = np.nonzero(window == set_value)[0]
        return (idx + lo).tolist()
