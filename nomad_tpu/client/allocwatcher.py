"""Previous-allocation watcher + ephemeral disk migration (ref
client/allocwatcher/: the upstream_allocs/await-prev hook and the local/
remote disk migrators behind sticky/migrate ephemeral_disk).

A replacement allocation (``previous_allocation`` set) with a sticky or
migrating ephemeral disk waits for its predecessor to go terminal, then
inherits the predecessor's shared ``alloc/`` data: moved directly when the
predecessor ran on this node, or pulled file-by-file through the server's
client-fs forwarding hop when it ran elsewhere (migrate=true)."""

from __future__ import annotations

import logging
import os
import shutil
import time

logger = logging.getLogger("nomad_tpu.client.allocwatcher")

TERMINAL = ("complete", "failed", "lost")


def _prev_terminal(client, prev_id: str) -> bool:
    """Terminal check that prefers the local runner's live state (cheap)
    and falls back to asking a server."""
    runner = client.alloc_runners.get(prev_id)
    if runner is not None:
        return runner.client_status() in TERMINAL
    getter = getattr(client.server, "alloc_get", None)
    if getter is not None:
        doc = getter(prev_id)
    else:
        alloc = client.server.state.alloc_by_id(prev_id)
        doc = None if alloc is None else {"client_status": alloc.client_status}
    if doc is None:
        return True  # GC'd predecessor: nothing to wait for
    return doc.get("client_status") in TERMINAL


def await_previous(client, alloc, tg, timeout: float = 60.0) -> None:
    """Block (bounded) until the previous allocation is terminal, then
    migrate its ephemeral disk when the task group asks for it."""
    prev_id = alloc.previous_allocation
    if not prev_id or tg is None:
        return
    disk = tg.ephemeral_disk
    if not (disk.sticky or disk.migrate):
        return

    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        try:
            if _prev_terminal(client, prev_id):
                break
        except Exception:
            logger.exception("previous-alloc status check failed")
            break
        time.sleep(0.2)

    prev_dir = os.path.join(client.data_dir, "allocs", prev_id, "alloc")
    new_dir = os.path.join(client.data_dir, "allocs", alloc.id, "alloc")
    if os.path.isdir(prev_dir):
        _migrate_local(prev_dir, new_dir)
    elif disk.migrate:
        _migrate_remote(client, prev_id, new_dir)


def _migrate_local(prev_dir: str, new_dir: str) -> None:
    """Move the predecessor's shared dir contents into the new alloc
    (ref allocwatcher local migrator — same node, plain rename)."""
    os.makedirs(new_dir, exist_ok=True)
    for name in os.listdir(prev_dir):
        src = os.path.join(prev_dir, name)
        dst = os.path.join(new_dir, name)
        try:
            if os.path.exists(dst):
                continue
            shutil.move(src, dst)
        except OSError:
            logger.exception("local disk migration of %s failed", name)


def _migrate_remote(client, prev_id: str, new_dir: str) -> None:
    """Pull alloc/ files from the predecessor's node through the server's
    ClientFS forwarding hop (ref allocwatcher remote migrator over the
    streaming FS API)."""
    forward = getattr(client.server, "forward_client_fs", None)
    if forward is None:
        return
    os.makedirs(new_dir, exist_ok=True)

    def pull(rel: str):
        try:
            entries = forward(prev_id, "List", {"path": "alloc/" + rel})
        except Exception:
            logger.exception("remote migration list %r failed", rel)
            return
        for entry in entries:
            name = entry["Name"]
            sub = os.path.join(rel, name) if rel else name
            local = os.path.join(new_dir, sub)
            if entry.get("IsDir"):
                os.makedirs(local, exist_ok=True)
                pull(sub)
                continue
            try:
                chunks = []
                offset = 0
                while True:
                    chunk = forward(
                        prev_id,
                        "Cat",
                        {
                            "path": "alloc/" + sub,
                            "offset": offset,
                            "limit": 1 << 20,
                        },
                    )
                    piece = chunk.get("Data", "")
                    chunks.append(piece)
                    offset = chunk.get("Offset", offset + len(piece))
                    if offset >= chunk.get("Size", 0) or not piece:
                        break
                os.makedirs(os.path.dirname(local), exist_ok=True)
                with open(local, "w") as f:
                    f.write("".join(chunks))
            except Exception:
                logger.exception("remote migration of %s failed", sub)

    pull("")
