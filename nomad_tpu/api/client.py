"""HTTP API client (ref api/ — the Go SDK's typed client surface)."""

from __future__ import annotations

import json
import os
import urllib.error
import urllib.parse
import urllib.request
from typing import Any, Optional


def _q(segment: str) -> str:
    """Percent-encode one path segment: derived child job IDs embed '/'
    (``<id>/periodic-<ts>``) and must travel as a single segment."""
    return urllib.parse.quote(segment, safe="")


class APIError(Exception):
    def __init__(self, status: int, message: str):
        super().__init__(f"{status}: {message}")
        self.status = status


class ApiClient:
    """ref api/api.go Client"""

    def __init__(
        self,
        address: Optional[str] = None,
        namespace: str = "default",
        token: Optional[str] = None,
    ):
        self.address = (
            address
            or os.environ.get("NOMAD_TPU_ADDR")
            or "http://127.0.0.1:4646"
        ).rstrip("/")
        self.namespace = namespace
        # bearer secret sent as X-Nomad-Token (ref api.Client SecretID)
        self.token = token or os.environ.get("NOMAD_TPU_TOKEN") or ""

    def alloc_exec_session(
        self, alloc_id: str, task: str, cmd: list, tty: bool = False
    ):
        """Open the interactive exec websocket (ref api/allocations.go
        Exec); returns an ExecWsSession with send_stdin/recv_frame/close."""
        from .ws import WsClient

        params = urllib.parse.urlencode(
            {
                "task": task,
                "command": json.dumps(list(cmd)),
                "tty": "true" if tty else "false",
            }
        )
        address = self.address
        tls = address.startswith("https://")
        for prefix in ("http://", "https://"):
            if address.startswith(prefix):
                address = address[len(prefix):]
        ws = WsClient(
            address,
            f"/v1/client/allocation/{_q(alloc_id)}/exec?{params}",
            token=self.token,
            tls=tls,
        )
        return ExecWsSession(ws)

    def _request(self, method: str, path: str, params=None, body=None,
                 headers=None, raw=False):
        url = self.address + path
        params = dict(params or {})
        # the client's namespace rides every request unless overridden
        # (ref api.Client QueryOptions.Namespace)
        if self.namespace != "default" and "namespace" not in params and "?" not in path:
            params["namespace"] = self.namespace
        if params:
            url += "?" + urllib.parse.urlencode(params)
        data = json.dumps(body).encode() if body is not None else None
        req = urllib.request.Request(url, data=data, method=method)
        req.add_header("Content-Type", "application/json")
        for k, v in (headers or {}).items():
            req.add_header(k, v)
        if self.token:
            req.add_header("X-Nomad-Token", self.token)
        try:
            with urllib.request.urlopen(req, timeout=330) as resp:
                content = resp.read()
                index = resp.headers.get("X-Nomad-Index")
                index = int(index) if index else None
                if raw:
                    # binary surfaces (the debug-bundle tarball): bytes
                    # as served, no JSON decode
                    return content, index
                return json.loads(content or b"null"), index
        except urllib.error.HTTPError as e:
            try:
                message = json.loads(e.read()).get("error", str(e))
            except Exception:
                message = str(e)
            raise APIError(e.code, message) from e

    def get(self, url: str, **params):
        return self._request("GET", url, params=params or None)

    def put(self, url: str, body=None, **params):
        return self._request("PUT", url, params=params or None, body=body)

    def delete(self, url: str, **params):
        return self._request("DELETE", url, params=params or None)

    # -- typed helpers ---------------------------------------------------
    def jobs(self, prefix: str = ""):
        return self.get("/v1/jobs", **({"prefix": prefix} if prefix else {}))[0]

    def register_job(self, job_dict: dict) -> dict:
        return self.put("/v1/jobs", body={"Job": job_dict})[0]

    def plan_job(self, job_dict: dict, diff: bool = True) -> dict:
        return self.put(
            f"/v1/job/{_q(job_dict.get('id', ''))}/plan",
            body={"Job": job_dict, "Diff": diff},
        )[0]

    def job(self, job_id: str) -> dict:
        return self.get(f"/v1/job/{_q(job_id)}")[0]

    def deregister_job(self, job_id: str, purge: bool = False) -> dict:
        params = {"purge": "true"} if purge else {}
        return self.delete(f"/v1/job/{_q(job_id)}", **params)[0]

    def job_allocations(self, job_id: str):
        return self.get(f"/v1/job/{_q(job_id)}/allocations")[0]

    def job_evaluations(self, job_id: str):
        return self.get(f"/v1/job/{_q(job_id)}/evaluations")[0]

    def job_summary(self, job_id: str):
        return self.get(f"/v1/job/{_q(job_id)}/summary")[0]

    def nodes(self):
        return self.get("/v1/nodes")[0]

    def node(self, node_id: str):
        return self.get(f"/v1/node/{node_id}")[0]

    def node_allocations(self, node_id: str):
        return self.get(f"/v1/node/{node_id}/allocations")[0]

    def drain_node(
        self,
        node_id: str,
        enable: bool = True,
        deadline_ns: int = 0,
        ignore_system_jobs: bool = False,
    ):
        body = {"DrainSpec": None}
        if enable:
            body["DrainSpec"] = {
                "Deadline": deadline_ns,
                "IgnoreSystemJobs": ignore_system_jobs,
            }
        return self.put(f"/v1/node/{node_id}/drain", body=body)[0]

    def allocations(self, prefix: str = ""):
        return self.get(
            "/v1/allocations", **({"prefix": prefix} if prefix else {})
        )[0]

    def allocation(self, alloc_id: str):
        return self.get(f"/v1/allocation/{alloc_id}")[0]

    def evaluations(self):
        return self.get("/v1/evaluations")[0]

    def evaluation(self, eval_id: str):
        return self.get(f"/v1/evaluation/{eval_id}")[0]

    def deployments(self):
        return self.get("/v1/deployments")[0]

    def deployment(self, deployment_id: str):
        return self.get(f"/v1/deployment/{deployment_id}")[0]

    def deployment_allocations(self, deployment_id: str):
        return self.get(f"/v1/deployment/allocations/{deployment_id}")[0]

    def deployment_promote(self, deployment_id: str, groups=None):
        body = {"Groups": groups} if groups else {"All": True}
        return self.put(f"/v1/deployment/promote/{deployment_id}", body=body)[0]

    def deployment_fail(self, deployment_id: str):
        return self.put(f"/v1/deployment/fail/{deployment_id}")[0]

    def deployment_pause(self, deployment_id: str, pause: bool = True):
        return self.put(
            f"/v1/deployment/pause/{deployment_id}", body={"Pause": pause}
        )[0]

    def job_deployments(self, job_id: str):
        return self.get(f"/v1/job/{_q(job_id)}/deployments")[0]

    def job_revert(self, job_id: str, version: int):
        return self.put(
            f"/v1/job/{_q(job_id)}/revert", body={"JobVersion": version}
        )[0]

    def job_versions(self, job_id: str):
        return self.get(f"/v1/job/{_q(job_id)}/versions")[0]

    def job_dispatch(self, job_id: str, payload: str = "", meta=None):
        import base64 as _b64

        body = {
            "Payload": _b64.b64encode(payload.encode()).decode() if payload else "",
            "Meta": meta or {},
        }
        return self.put(f"/v1/job/{_q(job_id)}/dispatch", body=body)[0]

    def job_periodic_force(self, job_id: str):
        return self.put(f"/v1/job/{_q(job_id)}/periodic/force")[0]

    def agent_self(self):
        return self.get("/v1/agent/self")[0]

    def metrics(self):
        return self.get("/v1/metrics")[0]

    # -- trace plane (OBSERVABILITY.md) ---------------------------------
    def traces(self, limit: int = 50, slowest: bool = False,
               errors: bool = False) -> dict:
        params = {"limit": limit}
        if slowest:
            params["slowest"] = "true"
        if errors:
            params["errors"] = "true"
        return self.get("/v1/trace", **params)[0]

    def trace(self, trace_id: str) -> dict:
        return self.get(f"/v1/trace/{_q(trace_id)}")[0]

    def trace_critical_path(self, tail: float = 0.99) -> dict:
        return self.get("/v1/trace/critical-path", tail=tail)[0]

    def device_stats(self) -> dict:
        """The device plane's ``tpu_devprof`` payload from a live
        server: compile ledger + HLO collective census, transfer
        totals, collective-round counters (the ``operator device`` CLI
        surface; OBSERVABILITY.md "The device plane")."""
        return self.metrics().get("tpu_devprof") or {}

    # -- debug plane (OBSERVABILITY.md: profiler / bundles) --------------
    def debug_pprof(self, profile: str = "", seconds: float = None,
                    hz: float = None) -> dict:
        """``/debug/pprof/<profile>`` (enable_debug-gated): the default
        empty profile returns the one-shot thread-stacks+gc dump;
        ``profile="profile"`` with ``seconds=N`` runs the sampling
        wall-clock profiler and returns its folded-stack report."""
        params = {}
        if seconds is not None:
            params["seconds"] = seconds
        if hz is not None:
            params["hz"] = hz
        return self.get(f"/debug/pprof/{profile}", **params)[0]

    def debug_bundle_json(self, seconds: float = 1.0) -> dict:
        """The bundle's manifest + parsed contents inline (?format=json)."""
        return self.get(
            "/v1/debug/bundle", seconds=seconds, format="json"
        )[0]

    def debug_bundle(self, seconds: float = 1.0,
                     output: Optional[str] = None) -> bytes:
        """Capture a debug bundle tarball from the agent (the `operator
        debug` wire call); returns the gzip bytes and writes them to
        ``output`` when given."""
        data, _ = self._request(
            "GET", "/v1/debug/bundle", params={"seconds": seconds},
            raw=True,
        )
        if output:
            with open(output, "wb") as f:
                f.write(data)
        return data

    def validate_job(self, job_dict: dict) -> dict:
        return self.put("/v1/validate/job", body={"Job": job_dict})[0]

    def agent_members(self) -> dict:
        return self.get("/v1/agent/members")[0]

    def agent_join(self, address: str) -> dict:
        return self.put("/v1/agent/join", address=address)[0]

    def agent_force_leave(self, node: str) -> dict:
        return self.put("/v1/agent/force-leave", node=node)[0]

    def agent_servers(self) -> list:
        return self.get("/v1/agent/servers")[0]

    def agent_health(self) -> dict:
        return self.get("/v1/agent/health")[0]

    def status_peers(self) -> list:
        return self.get("/v1/status/peers")[0]

    def node_purge(self, node_id: str) -> dict:
        return self.put(f"/v1/node/{_q(node_id)}/purge")[0]

    def eval_allocations(self, eval_id: str) -> list:
        return self.get(f"/v1/evaluation/{_q(eval_id)}/allocations")[0]

    def raft_configuration(self) -> dict:
        return self.get("/v1/operator/raft/configuration")[0]

    def raft_remove_peer(self, peer_id: str) -> dict:
        return self.delete("/v1/operator/raft/peer", id=peer_id)[0]

    def autopilot_configuration(self) -> dict:
        return self.get("/v1/operator/autopilot/configuration")[0]

    def autopilot_set_configuration(self, config: dict) -> dict:
        return self.put("/v1/operator/autopilot/configuration", body=config)[0]

    def autopilot_health(self) -> dict:
        return self.get("/v1/operator/autopilot/health")[0]

    def reconcile_summaries(self) -> dict:
        return self.put("/v1/system/reconcile/summaries")[0]

    def system_gc(self) -> dict:
        return self.put("/v1/system/gc")[0]

    def acl_token_self(self) -> dict:
        return self.get("/v1/acl/token/self")[0]

    def alloc_stop(self, alloc_id: str) -> dict:
        return self.put(f"/v1/allocation/{_q(alloc_id)}/stop")[0]

    def alloc_restart(self, alloc_id: str, task: str = "") -> dict:
        return self.put(
            f"/v1/client/allocation/{_q(alloc_id)}/restart",
            body={"TaskName": task},
        )[0]

    def alloc_signal(
        self, alloc_id: str, signal: str = "SIGINT", task: str = ""
    ) -> dict:
        return self.put(
            f"/v1/client/allocation/{_q(alloc_id)}/signal",
            body={"Signal": signal, "TaskName": task},
        )[0]

    def job_evaluate(self, job_id: str, force_reschedule: bool = False) -> dict:
        return self.put(
            f"/v1/job/{_q(job_id)}/evaluate",
            body={"EvalOptions": {"ForceReschedule": force_reschedule}},
        )[0]

    def agent_monitor(self, index: int = 0, log_level: str = "") -> dict:
        params = {"index": index}
        if log_level:
            params["log_level"] = log_level
        return self.get("/v1/agent/monitor", **params)[0]

    def acl_bootstrap(self) -> dict:
        return self.put("/v1/acl/bootstrap")[0]

    def acl_policies(self) -> list:
        return self.get("/v1/acl/policies")[0]

    def acl_policy(self, name: str) -> dict:
        return self.get(f"/v1/acl/policy/{_q(name)}")[0]

    def acl_put_policy(self, name: str, rules: str, description: str = "") -> dict:
        return self.put(
            f"/v1/acl/policy/{_q(name)}",
            body={"Rules": rules, "Description": description},
        )[0]

    def acl_delete_policy(self, name: str) -> dict:
        return self.delete(f"/v1/acl/policy/{_q(name)}")[0]

    def acl_tokens(self) -> list:
        return self.get("/v1/acl/tokens")[0]

    def acl_token(self, accessor: str) -> dict:
        return self.get(f"/v1/acl/token/{_q(accessor)}")[0]

    def acl_create_token(
        self, name: str = "", type: str = "client", policies=None, global_token=False
    ) -> dict:
        return self.put(
            "/v1/acl/token",
            body={
                "Name": name,
                "Type": type,
                "Policies": list(policies or []),
                "Global": global_token,
            },
        )[0]

    def acl_delete_token(self, accessor: str) -> dict:
        return self.delete(f"/v1/acl/token/{_q(accessor)}")[0]

    def client_stats(self, node_id: str = "") -> dict:
        params = {"node_id": node_id} if node_id else {}
        return self.get("/v1/client/stats", **params)[0]

    def event_stream(
        self,
        topics=None,
        index: int = 0,
        namespace: Optional[str] = None,
        heartbeat: Optional[float] = None,
        snapshot: Optional[bool] = None,
    ) -> "EventStream":
        """Subscribe to /v1/event/stream (ref api/event.go EventStream):
        returns an iterator of frame dicts. ``topics`` is a list of
        "Topic" / "Topic:key" specs (default: all topics); ``index=N``
        resumes after raft index N (pass the last index you received).
        ``snapshot`` forces snapshot-on-subscribe on/off (None defers to
        the server's configured default): with it on, a cold subscribe —
        or a resume that fell past the ring's retention — starts with
        {"Snapshot": ...} state batches stamped at raft index N, then a
        {"SnapshotDone": ...} marker, then deltas from N, instead of a
        lost-gap bail. Heartbeat frames are filtered out; snapshot,
        lost-gap and error frames are yielded so callers see the sync
        contract explicitly."""
        params: list = [("topic", t) for t in (topics or [])]
        if index:
            params.append(("index", str(index)))
        if snapshot is not None:
            params.append(("snapshot", "true" if snapshot else "false"))
        # unlike every other endpoint the server-side default here is the
        # wildcard, so "default" must travel explicitly — omitting it
        # would silently widen the stream to every namespace
        ns = namespace if namespace is not None else self.namespace
        if ns:
            params.append(("namespace", ns))
        if heartbeat is not None:
            params.append(("heartbeat", str(heartbeat)))
        url = self.address + "/v1/event/stream"
        if params:
            url += "?" + urllib.parse.urlencode(params)
        req = urllib.request.Request(url, method="GET")
        if self.token:
            req.add_header("X-Nomad-Token", self.token)
        try:
            resp = urllib.request.urlopen(req, timeout=330)
        except urllib.error.HTTPError as e:
            try:
                message = json.loads(e.read()).get("error", str(e))
            except Exception:
                message = str(e)
            raise APIError(e.code, message) from e
        return EventStream(resp)

    def alloc_stats(self, alloc_id: str) -> dict:
        return self.get(f"/v1/client/allocation/{_q(alloc_id)}/stats")[0]


class EventStream:
    """Iterator over /v1/event/stream frames: yields dicts shaped
    {"Index": N, "Events": [...]}, {"Snapshot": True, "Index": N,
    "Events": [...]}, {"SnapshotDone": True, "Index": N},
    {"LostGap": True, "Index": N}, or {"Error": msg, "ResumeIndex": N};
    heartbeat frames are skipped. Tracks ``last_index`` so a severed
    consumer can reconnect with
    ``client.event_stream(index=stream.last_index)`` for exactly-once
    resumption. Lost-gap and snapshot frames ADVANCE ``last_index`` to
    their carried index: the gap marker's floor is the only index a
    reconnect can make progress from — resuming from the stale local
    index would replay the same gap forever — and a snapshot covers
    state through its stamp by construction."""

    def __init__(self, resp):
        self._resp = resp
        self.last_index = 0
        self.closed = False

    def __iter__(self):
        return self

    def __next__(self) -> dict:
        while True:
            try:
                line = self._resp.readline()
            except (OSError, ValueError, AttributeError):
                # AttributeError: close() from another thread mid-read
                # nulls http.client's buffered fp
                self.close()
                raise StopIteration
            if not line:
                self.close()
                raise StopIteration
            line = line.strip()
            if not line:
                continue
            try:
                frame = json.loads(line)
            except json.JSONDecodeError:
                continue
            if not frame:
                continue  # heartbeat
            if frame.get("Index") and (
                (frame.get("Events") and not frame.get("Snapshot"))
                or frame.get("LostGap")
                or frame.get("SnapshotDone")
            ):
                # snapshot BATCHES don't advance the resume point — only
                # the SnapshotDone marker does: a consumer severed
                # mid-snapshot must re-sync, not resume past state it
                # never received
                self.last_index = max(self.last_index, int(frame["Index"]))
            return frame

    def close(self):
        self.closed = True
        try:
            self._resp.close()
        except OSError:
            pass


class ExecWsSession:
    """Typed wrapper over the exec websocket's JSON frames (ref
    api/allocations.go execSession): base64 payloads decoded to bytes."""

    def __init__(self, ws):
        self._ws = ws

    def send_stdin(self, data: bytes):
        import base64

        self._ws.send(
            json.dumps({"stdin": {"data": base64.b64encode(data).decode()}})
        )

    def close_stdin(self):
        self._ws.send(json.dumps({"stdin": {"close": True}}))

    def resize(self, rows: int, cols: int):
        self._ws.send(
            json.dumps({"tty_size": {"height": rows, "width": cols}})
        )

    def recv_frame(self, timeout=None) -> Optional[dict]:
        """Next decoded frame: {"stdout"/"stderr": bytes} or
        {"exited": True, "exit_code": N} or {"error": msg}; None at
        websocket close."""
        import base64

        from .ws import WsClosed

        try:
            payload = self._ws.recv(timeout=timeout)
        except WsClosed:
            return None
        try:
            obj = json.loads(payload.decode())
        except (json.JSONDecodeError, UnicodeDecodeError):
            return None
        out = {}
        for key in ("stdout", "stderr"):
            part = obj.get(key) or {}
            if part.get("data"):
                out[key] = __import__("base64").b64decode(part["data"])
        if obj.get("exited"):
            out["exited"] = True
            out["exit_code"] = (obj.get("result") or {}).get("exit_code", 0)
        if obj.get("error"):
            out["error"] = obj["error"]
        return out

    def close(self):
        self._ws.close()
