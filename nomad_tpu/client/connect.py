"""Connect service-mesh sidecars (ref Nomad 0.10's Consul Connect
integration: job_endpoint_hook_connect.go injects an envoy sidecar task,
Consul routes sidecar→sidecar). The nomad-native analog runs lightweight
TCP proxies inside the client:

- every task service with ``connect { sidecar_service {} }`` gets an
  inbound sidecar listener that forwards to the service's local port; its
  address is published through alloc updates as ``connect_proxies`` and
  appears in the catalog as ``<svc>-sidecar-proxy``,
- every declared upstream gets a local listener on ``local_bind_port``
  whose connections are dialed to a live ``<destination>-sidecar-proxy``
  instance resolved from the catalog at connect time.

No mTLS (the reference delegates that to Consul's CA); the mesh topology,
discovery, and port indirection are faithful."""

from __future__ import annotations

import logging
import socket
import threading
from typing import Optional

logger = logging.getLogger("nomad_tpu.client.connect")

BUFSIZE = 65536


def _pump(a: socket.socket, b: socket.socket):
    """One direction of a proxied connection."""
    try:
        while True:
            data = a.recv(BUFSIZE)
            if not data:
                break
            b.sendall(data)
    except OSError:
        pass
    finally:
        for s in (a, b):
            try:
                s.shutdown(socket.SHUT_RDWR)
            except OSError:
                pass


class _Listener:
    """Accept loop forwarding each connection to dial()'s target.
    ``tls_context`` (server-side) wraps accepted connections — the
    inbound half of sidecar mTLS."""

    def __init__(self, bind: tuple[str, int], dial, name: str,
                 tls_context=None):
        self._sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._sock.bind(bind)
        self._sock.listen(64)
        self.addr = self._sock.getsockname()
        self._dial = dial
        self._name = name
        self._tls = tls_context
        self._stop = threading.Event()
        self._thread = threading.Thread(
            target=self._accept_loop, daemon=True,
            name=f"connect-accept-{name}",
        )
        self._thread.start()

    def stop(self):
        self._stop.set()
        try:
            self._sock.close()
        except OSError:
            pass

    def _accept_loop(self):
        while not self._stop.is_set():
            try:
                conn, _ = self._sock.accept()
            except OSError:
                return
            threading.Thread(
                target=self._handle, args=(conn,), daemon=True,
                name="connect-proxy-conn",
            ).start()

    def _handle(self, conn: socket.socket):
        if self._tls is not None:
            try:
                conn = self._tls.wrap_socket(conn, server_side=True)
            except Exception as e:
                logger.warning("%s: mTLS handshake failed: %s", self._name, e)
                try:
                    conn.close()
                except OSError:
                    pass
                return
        target = None
        try:
            target = self._dial()
        except Exception as e:
            logger.warning("%s: dial failed: %s", self._name, e)
        if target is None:
            conn.close()
            return
        threading.Thread(
            target=_pump, args=(conn, target), daemon=True,
            name="connect-proxy-pump",
        ).start()
        _pump(target, conn)


class ConnectHook:
    """Per-alloc sidecar manager: inbound listeners for connect services,
    outbound listeners for their upstreams."""

    def __init__(self, client, alloc, tg):
        self.client = client
        self.alloc = alloc
        self.tg = tg
        self._listeners: list[_Listener] = []
        #: service name → {"ip", "port"} for the alloc update publisher
        self.proxies: dict[str, dict] = {}

    def _connect_services(self):
        for task in self.tg.tasks:
            for svc in task.services:
                if svc.connect is not None and svc.connect.sidecar_service is not None:
                    yield task, svc

    def _service_local_port(self, task, svc) -> Optional[int]:
        resources = self.alloc.allocated_resources
        tr = resources.tasks.get(task.name) if resources is not None else None
        if tr is None:
            return None
        for net in tr.networks:
            for p in list(net.reserved_ports) + list(net.dynamic_ports):
                if p.label == svc.port_label:
                    return p.value
        return None

    def start(self) -> bool:
        """Returns True when any sidecar was started (the caller then
        publishes an alloc update carrying the endpoints)."""
        started = False
        for task, svc in self._connect_services():
            sidecar = svc.connect.sidecar_service
            local_port = self._service_local_port(task, svc)

            if local_port is not None:
                def dial_local(port=local_port):
                    return socket.create_connection(("127.0.0.1", port), 10)

                inbound = _Listener(
                    ("127.0.0.1", 0),
                    dial_local,
                    f"sidecar:{svc.name}",
                    # inbound hop authenticates peers under the cluster CA
                    tls_context=getattr(
                        self.client, "tls_server_context", None
                    ),
                )
                self._listeners.append(inbound)
                self.proxies[svc.name] = {
                    "ip": inbound.addr[0],
                    "port": inbound.addr[1],
                }
                started = True

            proxy = sidecar.proxy
            for upstream in (proxy.upstreams if proxy is not None else []):
                dest = upstream.destination_name

                def dial_upstream(dest=dest):
                    resolved = self._resolve(dest)
                    if resolved is None:
                        raise OSError(f"no live sidecar for {dest!r}")
                    target, is_sidecar = resolved
                    sock = socket.create_connection(target, 10)
                    ctx = getattr(self.client, "tls_client_context", None)
                    if ctx is not None and is_sidecar:
                        # sidecar→sidecar hop presents our cluster
                        # identity; plain-service fallbacks stay raw TCP
                        sock = ctx.wrap_socket(sock)
                    return sock

                outbound = _Listener(
                    ("127.0.0.1", upstream.local_bind_port),
                    dial_upstream,
                    f"upstream:{dest}",
                )
                self._listeners.append(outbound)
                started = True
        return started

    def _resolve(self, dest: str) -> Optional[tuple[tuple[str, int], bool]]:
        """((ip, port), is_sidecar) of a live sidecar for the destination,
        else the plain service (non-connect destinations stay reachable)."""
        lookup = getattr(self.client.server, "catalog_service", None)
        if lookup is None:
            return None
        for name, is_sidecar in (
            (f"{dest}-sidecar-proxy", True),
            (dest, False),
        ):
            try:
                entries = lookup(name)
            except Exception:
                logger.exception("catalog lookup for %s failed", name)
                return None
            for entry in entries:
                if entry.get("Status") == "passing" and entry.get("Port"):
                    return (
                        entry.get("Address") or "127.0.0.1",
                        entry["Port"],
                    ), is_sidecar
        return None

    def stop(self):
        for listener in self._listeners:
            listener.stop()
        self._listeners = []
