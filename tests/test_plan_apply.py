"""Plan-applier hardening: EvalToken split-brain guard, dense verify
parity (host AND device-resident), the pipelined overlay apply loop, and
the ported reference slice (snapshot-min-index wait, partial eviction,
queue ordering)
(ref plan_endpoint.go:19-52, plan_apply.go:49-180, plan_apply_test.go,
plan_queue_test.go)."""

import random
import threading
import time

import pytest

import nomad_tpu.mock as mock
from nomad_tpu.core.broker import BrokerError, EvalBroker
from nomad_tpu.core.plan_apply import (
    DENSE_VERIFY_THRESHOLD,
    Planner,
    evaluate_node_plan,
    evaluate_plan,
)
from nomad_tpu.core.server import Server
from nomad_tpu.raft import InmemTransport, RaftConfig
from nomad_tpu.state import StateStore
from nomad_tpu.structs.model import (
    Allocation,
    AllocatedCpuResources,
    AllocatedMemoryResources,
    AllocatedResources,
    AllocatedSharedResources,
    AllocatedTaskResources,
    Plan,
    generate_uuid,
)


_JOB = mock.job()


def make_alloc(node_id, cpu=500, mem=256, disk=10):
    return Allocation(
        id=generate_uuid(),
        job_id=_JOB.id,
        job=_JOB,
        node_id=node_id,
        task_group="web",
        allocated_resources=AllocatedResources(
            tasks={
                "web": AllocatedTaskResources(
                    cpu=AllocatedCpuResources(cpu_shares=cpu),
                    memory=AllocatedMemoryResources(memory_mb=mem),
                )
            },
            shared=AllocatedSharedResources(disk_mb=disk),
        ),
        desired_status="run",
        client_status="pending",
    )


class TestEvalTokenGuard:
    def _server(self):
        cfg = {
            "seed": 42,
            "heartbeat_ttl": 600.0,
            "raft": {
                "node_id": "s0",
                "address": "raft0",
                "voters": {"s0": "raft0"},
                "transport": InmemTransport(),
                "config": RaftConfig(
                    heartbeat_interval=0.02,
                    election_timeout_min=0.05,
                    election_timeout_max=0.10,
                ),
            },
        }
        s = Server(cfg)
        s.start(num_workers=0, wait_for_leader=5.0)
        return s

    def test_stale_token_plan_rejected(self):
        """A worker whose eval was nacked and re-dequeued elsewhere cannot
        commit its stale plan (plan_endpoint.go:30-35)."""
        server = self._server()
        try:
            ev = mock.evaluation()
            server.state.upsert_evals(server.state.latest_index() + 1, [ev])
            server.eval_broker.enqueue(ev)
            got, token1 = server.eval_broker.dequeue(["service"], timeout=2.0)
            assert got is not None

            # the eval is nacked (worker presumed dead) and re-dequeued
            server.eval_broker.nack(ev.id, token1)
            got2, token2 = server.eval_broker.dequeue(["service"], timeout=5.0)
            assert got2 is not None and token2 != token1

            stale_plan = Plan(eval_id=ev.id, eval_token=token1, priority=50)
            with pytest.raises(BrokerError):
                server.plan_submit(stale_plan)

            # the live token passes the guard and reaches the queue
            live_plan = Plan(eval_id=ev.id, eval_token=token2, priority=50)
            result, err = server.plan_submit(live_plan)
            assert err is None and result is not None
        finally:
            server.stop()

    def test_nack_timer_paused_while_queued(self):
        """The nack timer doesn't fire while a plan is in the queue and is
        re-armed afterwards."""
        broker = EvalBroker(nack_timeout=0.2)
        broker.set_enabled(True)
        ev = mock.evaluation()
        broker.enqueue(ev)
        got, token = broker.dequeue(["service"], timeout=1.0)
        assert got is not None
        broker.pause_nack_timeout(ev.id, token)
        time.sleep(0.5)  # well past the nack timeout
        t, ok = broker.outstanding(ev.id)
        assert ok and t == token, "eval must still be outstanding while paused"
        broker.resume_nack_timeout(ev.id, token)
        time.sleep(0.5)
        _, ok = broker.outstanding(ev.id)
        assert not ok, "resumed timer must fire and nack"


class TestDenseVerifyParity:
    def _cluster(self, n_nodes=6):
        state = StateStore()
        nodes = []
        for i in range(n_nodes):
            n = mock.node()
            n.node_resources.cpu.cpu_shares = 2000
            n.node_resources.memory.memory_mb = 4096
            nodes.append(n)
        state.upsert_nodes(1, nodes)
        return state, nodes

    def _big_plan(self, nodes, per_node, cpu=100, mem=1):
        plan = Plan(priority=50)
        for n in nodes:
            plan.node_allocation[n.id] = [
                make_alloc(n.id, cpu=cpu, mem=mem, disk=1) for _ in range(per_node)
            ]
        return plan

    def test_dense_matches_scalar(self, monkeypatch):
        """Same plan through the dense and scalar paths: identical
        committed sets, including a node that must be rejected."""
        state, nodes = self._cluster()
        # preload one node so the plan overflows it
        state.upsert_allocs(2, [make_alloc(nodes[0].id, cpu=1900)])

        per_node = max(1, DENSE_VERIFY_THRESHOLD // len(nodes) + 1)
        # fits on fresh nodes (43 x 40 = 1720 < 2000 cpu) but not on the
        # preloaded one — the two paths must split the set identically
        plan = self._big_plan(nodes, per_node, cpu=40)
        snap = state.snapshot()

        dense_result = evaluate_plan(snap, plan)
        assert dense_result.node_allocation, "fresh nodes must commit"

        import nomad_tpu.core.plan_apply as pa

        monkeypatch.setattr(pa, "DENSE_VERIFY_THRESHOLD", 10**9)
        scalar_result = evaluate_plan(snap, plan)

        assert set(dense_result.node_allocation) == set(scalar_result.node_allocation)
        assert nodes[0].id not in dense_result.node_allocation
        assert dense_result.refresh_index == scalar_result.refresh_index

    def test_exotic_allocs_take_exact_path(self):
        """Allocs carrying ports verify through exact NetworkIndex checks
        even on the dense path (reserved-port collisions aren't modeled
        densely)."""
        from nomad_tpu.structs.model import NetworkResource, Port

        state, nodes = self._cluster(2)
        target = nodes[0]

        def port_alloc():
            a = make_alloc(target.id, cpu=100, mem=64)
            a.allocated_resources.tasks["web"].networks = [
                NetworkResource(
                    device="eth0",
                    ip="192.168.0.100",
                    mbits=10,
                    reserved_ports=[Port(label="http", value=8080)],
                )
            ]
            return a

        plan = Plan(priority=50)
        # two allocs fighting for the same reserved port on one node
        plan.node_allocation[target.id] = [port_alloc(), port_alloc()]
        # pad other nodes to push the plan over the dense threshold
        plan.node_allocation[nodes[1].id] = [
            make_alloc(nodes[1].id, cpu=1, mem=1, disk=1)
            for _ in range(DENSE_VERIFY_THRESHOLD)
        ]
        snap = state.snapshot()
        result = evaluate_plan(snap, plan)
        assert target.id not in result.node_allocation, "port collision caught"
        assert nodes[1].id in result.node_allocation

    def test_node_checks_preserved(self):
        state, nodes = self._cluster(2)
        down = nodes[0]
        state.update_node_status(3, down.id, "down")
        plan = self._big_plan(nodes, DENSE_VERIFY_THRESHOLD, cpu=1)
        result = evaluate_plan(state.snapshot(), plan)
        assert down.id not in result.node_allocation
        assert nodes[1].id in result.node_allocation


class TestOverlappedApply:
    def test_conflicting_plans_serialize(self):
        """Two plans that each fill the same node, submitted back-to-back:
        the second must see the first's optimistic result and be rejected
        (no double-booking during the overlap window)."""
        state = StateStore()
        node = mock.node()
        node.node_resources.cpu.cpu_shares = 1000
        node.node_resources.memory.memory_mb = 4096
        state.upsert_node(1, node)

        planner = Planner(state)
        planner.start()
        try:
            plan_a = Plan(priority=50)
            plan_a.node_allocation[node.id] = [make_alloc(node.id, cpu=800, mem=64)]
            plan_b = Plan(priority=50)
            plan_b.node_allocation[node.id] = [make_alloc(node.id, cpu=800, mem=64)]

            pa_ = planner.queue.enqueue(plan_a)
            pb_ = planner.queue.enqueue(plan_b)
            ra, ea = pa_.wait(timeout=10.0)
            rb, eb = pb_.wait(timeout=10.0)
            assert ea is None and eb is None

            committed = [
                r for r in (ra, rb) if r is not None and r.node_allocation
            ]
            assert len(committed) == 1, "exactly one plan may book the node"
            rejected = rb if committed[0] is ra else ra
            assert rejected.refresh_index, "loser gets a refresh index"

            # the winner's alloc is really in state
            assert len(state.allocs_by_node_terminal(node.id, False)) == 1
        finally:
            planner.stop()


def _mirror_for(state):
    """A live ColumnarMirror over ``state``: the view reads the store's
    committed planes directly — no broker, no frames, no rebuilds."""
    from nomad_tpu.tpu.mirror import ColumnarMirror

    return ColumnarMirror(state)


class TestDeviceVerifyParity:
    """The acceptance pin: device-verify == host-oracle verify over ≥100
    seeded plans, including exotic rows, down/ineligible nodes, stops,
    preemptions, int32-clip edges, node-axis view refreshes, kernel-fault
    degradation, and a closed mirror (full degrade)."""

    def _cluster(self, rng, n_nodes=24):
        from nomad_tpu.structs.model import NetworkResource, Port

        state = StateStore()
        nodes = []
        for i in range(n_nodes):
            n = mock.node()
            n.node_resources.cpu.cpu_shares = rng.choice([1000, 2000, 4000])
            n.node_resources.memory.memory_mb = rng.choice([2048, 4096])
            nodes.append(n)
        state.upsert_nodes(1, nodes)
        # preloaded allocs: plain + exotic (reserved ports)
        idx = 2
        preloaded = []
        for n in nodes:
            for _ in range(rng.randint(0, 3)):
                a = make_alloc(
                    n.id, cpu=rng.choice([100, 400, 900]),
                    mem=rng.choice([64, 256]),
                )
                if rng.random() < 0.2:
                    a.allocated_resources.tasks["web"].networks = [
                        NetworkResource(
                            device="eth0", ip="192.168.0.100", mbits=10,
                            reserved_ports=[
                                Port(label="http", value=rng.randint(8000, 8005))
                            ],
                        )
                    ]
                preloaded.append(a)
        state.upsert_allocs(idx, preloaded)
        # a few nodes down / ineligible
        state.update_node_status(3, nodes[0].id, "down")
        from nomad_tpu.structs.model import NODE_SCHED_INELIGIBLE

        nodes[1].scheduling_eligibility = NODE_SCHED_INELIGIBLE
        return state, nodes, preloaded

    def _seeded_plan(self, rng, nodes, preloaded):
        from nomad_tpu.structs.model import NetworkResource, Port

        plan = Plan(priority=50)
        for n in rng.sample(nodes, rng.randint(1, len(nodes))):
            allocs = []
            for _ in range(rng.randint(1, 4)):
                a = make_alloc(
                    n.id, cpu=rng.choice([50, 300, 1200]),
                    mem=rng.choice([16, 128, 1024]),
                )
                if rng.random() < 0.1:
                    a.allocated_resources.tasks["web"].networks = [
                        NetworkResource(
                            device="eth0", ip="192.168.0.100", mbits=5,
                            reserved_ports=[Port(label="x", value=9000)],
                        )
                    ]
                allocs.append(a)
            plan.node_allocation[n.id] = allocs
            if rng.random() < 0.3:
                stops = [
                    a for a in preloaded
                    if a.node_id == n.id and rng.random() < 0.5
                ]
                if stops:
                    plan.node_update[n.id] = stops
            if rng.random() < 0.1:
                preempt = [a for a in preloaded if a.node_id == n.id][:1]
                if preempt:
                    plan.node_preemptions[n.id] = preempt
        if rng.random() < 0.1:
            plan.all_at_once = True
        return plan

    @staticmethod
    def _committed_sets(result):
        return (
            {k: [a.id for a in v] for k, v in result.node_allocation.items()},
            {k: [a.id for a in v] for k, v in result.node_update.items()},
            {k: [a.id for a in v] for k, v in result.node_preemptions.items()},
            bool(result.refresh_index),
        )

    def _device_result(self, planner, snap, plan):
        dev_ctx = planner._device_ctx(snap, [_FakePending(plan)])
        if dev_ctx is None:
            return None
        from nomad_tpu.core.plan_apply import _OverlayEpoch

        return planner._evaluate_plan_device(
            dev_ctx, snap, plan, planner.overlay.deltas(), _OverlayEpoch(),
            lambda: snap,
        )

    def test_device_matches_host_over_seeded_plans(self):
        rng = random.Random(20260804)
        state, nodes, preloaded = self._cluster(rng)
        planner = Planner(state)
        mirror = _mirror_for(state)
        planner.mirror_fn = lambda: mirror
        planner.device_verify_min = 1  # exercise the device path per plan
        snap = state.snapshot()
        device_checked = 0
        for i in range(120):
            plan = self._seeded_plan(rng, nodes, preloaded)
            host = evaluate_plan(snap, plan)
            dev = self._device_result(planner, snap, plan)
            if i == 60:
                # node-axis churn mid-stream: the committed planes bump
                # their epoch, the next sync re-derives the view (a
                # refresh, NOT a rebuild), and parity must survive it
                state.upsert_node(state.latest_index() + 1, mock.node())
                snap = state.snapshot()
            if dev is None:
                continue
            device_checked += 1
            assert self._committed_sets(dev) == self._committed_sets(host), (
                f"device/host divergence on seeded plan {i}"
            )
            assert dev.refresh_index == host.refresh_index
        assert device_checked >= 100, (
            f"device path exercised only {device_checked} times"
        )
        assert mirror.counters["view_refreshes"] >= 1  # axis churn re-derived
        assert mirror.counters["rebuilds"] == 0  # ...but never rebuilt
        mirror.close()

    def test_int32_clip_rows_degrade_to_exact(self):
        """A row whose used plane exceeds the device int32-clip range must
        take the exact host check — the clipped plane would under-report
        usage and could confirm an over-commit."""
        state = StateStore()
        n = mock.node()
        n.node_resources.cpu.cpu_shares = 2**31 - 1
        n.node_resources.memory.memory_mb = 4096
        state.upsert_node(1, n)
        big = make_alloc(n.id, cpu=2**30 + 7, mem=1)
        state.upsert_allocs(2, [big])
        planner = Planner(state)
        mirror = _mirror_for(state)
        planner.mirror_fn = lambda: mirror
        planner.device_verify_min = 1
        snap = state.snapshot()
        plan = Plan(priority=50)
        plan.node_allocation[n.id] = [make_alloc(n.id, cpu=100, mem=1)]
        host = evaluate_plan(snap, plan)
        dev = self._device_result(planner, snap, plan)
        assert dev is not None
        assert TestDeviceVerifyParity._committed_sets(dev) == (
            TestDeviceVerifyParity._committed_sets(host)
        )
        mirror.close()

    def test_kernel_fault_degrades_to_host(self):
        from nomad_tpu.testing import faults

        state = StateStore()
        nodes = [mock.node() for _ in range(3)]
        state.upsert_nodes(1, nodes)
        planner = Planner(state)
        mirror = _mirror_for(state)
        planner.mirror_fn = lambda: mirror
        planner.device_verify_min = 1
        snap = state.snapshot()
        # plain placements on healthy nodes: guaranteed candidate rows,
        # so the verify really reaches the kernel dispatch
        plan = Plan(priority=50)
        for n in nodes:
            plan.node_allocation[n.id] = [make_alloc(n.id, cpu=100, mem=64)]
        plane = faults.install(faults.FaultPlane(seed=3))
        try:
            plane.rule("point", "error", method="tpu.kernel")
            dev = self._device_result(planner, snap, plan)
            # the kernel fault gate fires inside verify_rows: whole plan
            # degrades to the host oracle (None), never a wrong verdict
            assert dev is None
        finally:
            faults.uninstall()
            mirror.close()

    def test_device_verify_through_apply_loop(self):
        """End-to-end: the running apply loop takes the device path (min
        placements 1) and two conflicting plans still serialize — the
        second is rejected off the overlay/stacked accounting exactly as
        on the host path."""
        state = StateStore()
        node = mock.node()
        node.node_resources.cpu.cpu_shares = 1000
        node.node_resources.memory.memory_mb = 4096
        state.upsert_node(1, node)
        planner = Planner(state)
        mirror = _mirror_for(state)
        planner.mirror_fn = lambda: mirror
        planner.device_verify_min = 1
        planner.start()
        try:
            def plan():
                p = Plan(priority=50)
                p.node_allocation[node.id] = [
                    make_alloc(node.id, cpu=800, mem=64)
                ]
                return p

            pa_ = planner.queue.enqueue(plan())
            pb_ = planner.queue.enqueue(plan())
            ra, ea = pa_.wait(timeout=10.0)
            rb, eb = pb_.wait(timeout=10.0)
            assert ea is None and eb is None
            committed = [
                r for r in (ra, rb) if r is not None and r.node_allocation
            ]
            assert len(committed) == 1, "device path double-booked"
            assert len(state.allocs_by_node_terminal(node.id, False)) == 1
        finally:
            planner.stop()
            mirror.close()

    def test_closed_mirror_fully_degrades(self):
        rng = random.Random(13)
        state, nodes, preloaded = self._cluster(rng, n_nodes=4)
        planner = Planner(state)
        mirror = _mirror_for(state)
        planner.mirror_fn = lambda: mirror
        planner.device_verify_min = 1
        mirror.close()
        snap = state.snapshot()
        plan = self._seeded_plan(rng, nodes, preloaded)
        assert planner._device_ctx(snap, [_FakePending(plan)]) is None


class _FakePending:
    """Just enough PendingPlan surface for _device_ctx's size gate."""

    def __init__(self, plan):
        self.plan = plan


class TestPipelinedApply:
    """ROADMAP item 1b: verify(N+1) while commit(N) is in flight, with
    the overlay carrying N's adds; rollback on failure; floors on
    unresolved outcomes."""

    def _node(self, state, cpu=1000):
        node = mock.node()
        node.node_resources.cpu.cpu_shares = cpu
        node.node_resources.memory.memory_mb = 4096
        state.upsert_node(1, node)
        return node

    def test_commits_overlap_in_flight(self):
        """Two independent batches must have their consensus commits in
        flight SIMULTANEOUSLY (the pipeline, not just verify overlap)."""
        state = StateStore()
        nodes = [mock.node() for _ in range(2)]
        for i, n in enumerate(nodes):
            state.upsert_node(i + 1, n)

        in_flight = []
        release = threading.Event()
        both_started = threading.Event()
        lock = threading.Lock()

        def commit_batch(items):
            with lock:
                in_flight.append(len(items))
                if len(in_flight) >= 2:
                    both_started.set()
            assert release.wait(10), "second commit never dispatched"
            index = 0
            for plan, result, pevals in items:
                index = state.upsert_plan_results(None, plan, result)
            return index

        planner = Planner(state)
        planner.commit_batch_fn = commit_batch
        planner.max_inflight = 2
        planner.start()
        try:
            def plan_for(n):
                p = Plan(priority=50)
                p.node_allocation[n.id] = [make_alloc(n.id, cpu=100, mem=64)]
                return p

            pa_ = planner.queue.enqueue(plan_for(nodes[0]))
            time.sleep(0.1)  # batch A dispatched, commit parked
            pb_ = planner.queue.enqueue(plan_for(nodes[1]))
            assert both_started.wait(5), (
                "commit(N+1) waited for commit(N): the applier still "
                "serializes on raft.apply"
            )
            release.set()
            ra, ea = pa_.wait(timeout=10.0)
            rb, eb = pb_.wait(timeout=10.0)
            assert ea is None and ra.node_allocation
            assert eb is None and rb.node_allocation
        finally:
            release.set()
            planner.stop()

    def test_overlay_guards_against_inflight_double_book(self):
        """A plan verified while a conflicting batch's commit is in
        flight must see the overlay's adds and reject — without the
        applier joining the commit."""
        state = StateStore()
        node = self._node(state, cpu=1000)

        release = threading.Event()
        started = threading.Event()

        def commit_batch(items):
            started.set()
            assert release.wait(10)
            index = 0
            for plan, result, pevals in items:
                index = state.upsert_plan_results(None, plan, result)
            return index

        planner = Planner(state)
        planner.commit_batch_fn = commit_batch
        planner.start()
        try:
            pa_ = planner.queue.enqueue(self._plan(node, cpu=800))
            assert started.wait(5)
            pb_ = planner.queue.enqueue(self._plan(node, cpu=800))
            rb, eb = pb_.wait(timeout=5.0)
            # B answered from the overlay BEFORE A's commit released
            assert eb is None and not rb.node_allocation
            assert rb.refresh_index
            release.set()
            ra, ea = pa_.wait(timeout=10.0)
            assert ea is None and ra.node_allocation
            assert len(state.allocs_by_node_terminal(node.id, False)) == 1
        finally:
            release.set()
            planner.stop()

    @staticmethod
    def _plan(node, cpu):
        p = Plan(priority=50)
        p.node_allocation[node.id] = [make_alloc(node.id, cpu=cpu, mem=64)]
        return p

    def test_stack_failure_mid_batch_commits_prefix_requeues_rest(self):
        """Regression for the partial-snapshot hole: when post-accept
        stacking raises at entry i, _verify_batch must return EXACTLY
        the verified prefix (entry i included — it was accepted before
        the stack broke) as entries, hand every later plan back as a
        leftover for requeue, and must NOT have verified or responded to
        any leftover — verifying them against the partial stacked
        snapshot would double-book entry i's capacity."""
        from nomad_tpu.core.plan_apply import PendingPlan

        state = StateStore()
        node = self._node(state, cpu=1000)
        planner = Planner(state)  # never started: direct _verify_batch

        live = [PendingPlan(self._plan(node, cpu=100)) for _ in range(4)]
        base = state.snapshot()

        real = planner._optimistic_snapshot
        calls = {"n": 0}

        def flaky(snap, plan, result):
            # calls 1..2 stack entries 0..1; call 3 (stacking entry 2
            # into the live base) explodes mid-batch
            calls["n"] += 1
            if calls["n"] == 3:
                raise RuntimeError("columnar stack exploded")
            return real(snap, plan, result)

        planner._optimistic_snapshot = flaky
        entries, leftovers, noops, epoch = planner._verify_batch(live, base)

        assert [p for p, _ in entries] == live[:3]
        assert all(r.node_allocation for _, r in entries)
        assert leftovers == live[3:]
        assert noops == []
        for p in leftovers:
            assert p.result is None and p.error is None
            assert not p._done.is_set(), "leftover was responded to"

    def test_overlay_rolls_back_on_commit_failure(self):
        """A failed commit's phantom adds must leave the overlay: the
        same capacity must be grantable to the next plan."""
        state = StateStore()
        node = self._node(state, cpu=1000)

        fail_first = {"armed": True}
        release = threading.Event()
        started = threading.Event()

        def commit_batch(items):
            if fail_first["armed"]:
                fail_first["armed"] = False
                started.set()
                assert release.wait(10)
                raise RuntimeError("injected commit failure")
            index = 0
            for plan, result, pevals in items:
                index = state.upsert_plan_results(None, plan, result)
            return index

        planner = Planner(state)
        planner.commit_batch_fn = commit_batch
        planner.start()
        try:
            pa_ = planner.queue.enqueue(self._plan(node, cpu=800))
            assert started.wait(5)
            # B conflicts while A's (doomed) commit is in flight:
            # conservatively rejected off the overlay
            pb_ = planner.queue.enqueue(self._plan(node, cpu=800))
            rb, eb = pb_.wait(timeout=5.0)
            assert eb is None and rb.refresh_index
            release.set()
            ra, ea = pa_.wait(timeout=10.0)
            assert ea is not None, "failed commit must surface to worker"
            # C takes the capacity the rolled-back epoch released
            pc_ = planner.queue.enqueue(self._plan(node, cpu=800))
            rc, ec = pc_.wait(timeout=10.0)
            assert ec is None and rc.node_allocation, (
                "overlay rollback lost the failed batch's capacity"
            )
            assert len(state.allocs_by_node_terminal(node.id, False)) == 1
        finally:
            release.set()
            planner.stop()

    def test_epoch_never_pruned_on_alloc_id_reuse(self):
        """The e2e-drive regression: plans legitimately REUSE alloc ids
        (in-place updates, refresh/nack retries), so an id's presence in
        a snapshot must never prune an in-flight epoch — the overlay may
        only drop an epoch once its HARVESTED commit index is covered by
        the base. Pre-fix, the in-flight batch below was pruned because
        its first placed id already existed in state (the in-place
        update), and plan C double-booked node n2."""
        state = StateStore()
        n1, n2 = mock.node(), mock.node()
        for n in (n1, n2):
            n.node_resources.cpu.cpu_shares = 1000
            n.node_resources.memory.memory_mb = 4096
        state.upsert_node(1, n1)
        state.upsert_node(2, n2)
        old = make_alloc(n1.id, cpu=100, mem=64)
        state.upsert_allocs(3, [old])

        release = threading.Event()
        started = threading.Event()
        first = {"armed": True}

        def commit_batch(items):
            if first["armed"]:
                first["armed"] = False
                started.set()
                assert release.wait(10)
            index = 0
            for plan, result, pevals in items:
                index = state.upsert_plan_results(None, plan, result)
            return index

        planner = Planner(state)
        planner.commit_batch_fn = commit_batch
        # ONE batch: an in-place update of `old` (same alloc id — the
        # id-reuse trigger, first in verify order) + a fresh 800-cpu
        # placement on n2. Queue both before start so they fold.
        update = make_alloc(n1.id, cpu=100, mem=64)
        update.id = old.id
        plan_a = Plan(priority=90)
        plan_a.node_allocation[n1.id] = [update]
        plan_b = Plan(priority=50)
        plan_b.node_allocation[n2.id] = [make_alloc(n2.id, cpu=800, mem=64)]
        planner.queue.set_enabled(True)
        pa_ = planner.queue.enqueue(plan_a)
        pb_ = planner.queue.enqueue(plan_b)
        planner.start()
        try:
            assert started.wait(5)
            # while the batch's entry is in flight, C contends for n2:
            # the epoch (with B's 800-cpu add) must still be credited
            plan_c = Plan(priority=50)
            plan_c.node_allocation[n2.id] = [
                make_alloc(n2.id, cpu=800, mem=64)
            ]
            pc_ = planner.queue.enqueue(plan_c)
            rc, ec = pc_.wait(timeout=5.0)
            assert ec is None and not rc.node_allocation, (
                "epoch pruned on reused alloc id: plan C double-booked n2"
            )
            assert rc.refresh_index
            release.set()
            for p in (pa_, pb_):
                r, e = p.wait(timeout=10.0)
                assert e is None and r.node_allocation
            assert len(state.allocs_by_node_terminal(n2.id, False)) == 1
        finally:
            release.set()
            planner.stop()

    def test_unresolved_timeout_floors_and_rolls_back(self):
        """ApplyTimeout + failed barrier (commit_timeout_unresolved): the
        epoch rolls back AND the floor forces every later verify past the
        in-flight entry — when it lands late, no double-booking (the PR 6
        over-commit class must stay dead under overlap)."""
        from nomad_tpu.raft import ApplyTimeout
        from nomad_tpu.structs.funcs import allocs_fit

        state = StateStore()
        node = self._node(state, cpu=1000)
        applied = threading.Event()
        seen = {"first": None}

        def commit_batch(items):
            if seen["first"] is None:
                seen["first"] = items
                entry_index = state.latest_index() + 1

                def late_apply():
                    time.sleep(0.4)
                    for plan, result, pevals in items:
                        state.upsert_plan_results(None, plan, result)
                    applied.set()

                threading.Thread(
                    target=late_apply, daemon=True,
                    name="test-late-apply",
                ).start()
                raise ApplyTimeout(entry_index)
            index = 0
            for plan, result, pevals in items:
                index = state.upsert_plan_results(None, plan, result)
            return index

        def barrier_fn(exc):
            raise RuntimeError("barrier failed; outcome unknown")

        planner = Planner(state)
        planner.commit_batch_fn = commit_batch
        planner.barrier_fn = barrier_fn
        planner.start()
        try:
            pa_ = planner.queue.enqueue(self._plan(node, cpu=600))
            ra, ea = pa_.wait(timeout=10.0)
            assert ea is not None, "unresolved outcome must fail the plan"
            # B must wait out the floor: by then A's entry has landed and
            # B sees its usage
            pb_ = planner.queue.enqueue(self._plan(node, cpu=600))
            rb, eb = pb_.wait(timeout=10.0)
            assert eb is None and rb is not None
            assert rb.refresh_index and not rb.node_allocation, (
                "plan B committed against state missing the in-flight "
                "entry — the over-commit class is back"
            )
            assert applied.is_set()
            live = state.snapshot().allocs_by_node_terminal(node.id, False)
            fit, dim, used = allocs_fit(node, live, None, True)
            assert fit, f"over-committed: {dim}"
        finally:
            planner.stop()


class TestReferencePortSlice:
    """Ported slice of plan_apply_test.go / plan_endpoint_test.go /
    plan_queue_test.go: snapshot-min-index wait, partial-eviction
    results, queue ordering."""

    def test_snapshot_min_index_wait(self):
        """A plan stamped with a SnapshotIndex ahead of the store must
        not verify until the store reaches it (ref plan_apply.go
        snapshotMinIndex / TestPlanApply_applyPlan watchdog)."""
        state = StateStore()
        node = mock.node()
        state.upsert_node(1, node)
        planner = Planner(state)
        planner.start()
        try:
            plan = Plan(priority=50)
            plan.node_allocation[node.id] = [make_alloc(node.id, cpu=100)]
            plan.snapshot_index = 3  # the store is at 1
            pending = planner.queue.enqueue(plan)
            time.sleep(0.4)
            assert pending.result is None and pending.error is None, (
                "applier verified below the plan's snapshot index"
            )
            state.upsert_node(3, mock.node())  # the awaited write lands
            result, err = pending.wait(timeout=5.0)
            assert err is None and result.node_allocation
        finally:
            planner.stop()

    def test_partial_eviction_allows_placement(self):
        """Evicting an existing alloc in the same plan frees its capacity
        for the plan's own placement (ref plan_apply_test.go
        TestPlanApply_EvalPlan_Partial eviction accounting)."""
        state = StateStore()
        node = mock.node()
        node.node_resources.cpu.cpu_shares = 1000
        state.upsert_node(1, node)
        old = make_alloc(node.id, cpu=900, mem=64)
        state.upsert_allocs(2, [old])

        plan = Plan(priority=50)
        plan.node_update[node.id] = [old]
        plan.node_allocation[node.id] = [make_alloc(node.id, cpu=900, mem=64)]
        result = evaluate_plan(state.snapshot(), plan)
        assert node.id in result.node_allocation, (
            "eviction credit not applied within the plan"
        )
        assert node.id in result.node_update
        assert not result.refresh_index

    def test_partial_commit_keeps_passing_nodes(self):
        """One overfull node fails; the other commits; the result carries
        a refresh index (ref TestPlanApply_EvalPlan_Partial)."""
        state = StateStore()
        n1, n2 = mock.node(), mock.node()
        n1.node_resources.cpu.cpu_shares = 100
        n2.node_resources.cpu.cpu_shares = 4000
        state.upsert_node(1, n1)
        state.upsert_node(2, n2)
        plan = Plan(priority=50)
        plan.node_allocation[n1.id] = [make_alloc(n1.id, cpu=900)]
        plan.node_allocation[n2.id] = [make_alloc(n2.id, cpu=900)]
        result = evaluate_plan(state.snapshot(), plan)
        assert n2.id in result.node_allocation
        assert n1.id not in result.node_allocation
        assert result.refresh_index

    def test_all_at_once_rejects_whole_plan(self):
        """AllAtOnce: one failing node rejects the whole plan
        (ref TestPlanApply_EvalPlan_Partial_AllAtOnce)."""
        state = StateStore()
        n1, n2 = mock.node(), mock.node()
        n1.node_resources.cpu.cpu_shares = 100
        state.upsert_node(1, n1)
        state.upsert_node(2, n2)
        plan = Plan(priority=50, all_at_once=True)
        plan.node_allocation[n1.id] = [make_alloc(n1.id, cpu=900)]
        plan.node_allocation[n2.id] = [make_alloc(n2.id, cpu=100)]
        result = evaluate_plan(state.snapshot(), plan)
        assert not result.node_allocation
        assert result.refresh_index

    def test_queue_priority_and_fifo_ordering(self):
        """PlanQueue pops by priority, FIFO within a priority (ref
        plan_queue_test.go TestPlanQueue_Dequeue_Priority/FIFO)."""
        from nomad_tpu.core.plan_apply import PlanQueue

        q = PlanQueue()
        q.set_enabled(True)
        low = Plan(priority=10)
        mid_a = Plan(priority=50)
        mid_b = Plan(priority=50)
        high = Plan(priority=90)
        q.enqueue(mid_a)
        q.enqueue(low)
        q.enqueue(high)
        q.enqueue(mid_b)
        order = [q.dequeue(timeout=0.1).plan for _ in range(4)]
        assert order == [high, mid_a, mid_b, low]

    def test_disabled_queue_fails_submissions(self):
        from nomad_tpu.core.plan_apply import PlanQueue

        q = PlanQueue()
        pending = q.enqueue(Plan(priority=50))
        result, err = pending.wait(timeout=0.5)
        assert result is None and err is not None


class TestBatchedApply:
    def test_independent_plans_fold_into_one_commit(self):
        """Plans queued behind the head commit in ONE raft-style commit
        call (the batched fsync amortization); every submitter is answered
        with its own result and all placements land."""
        state = StateStore()
        nodes = [mock.node() for _ in range(8)]
        for i, n in enumerate(nodes):
            state.upsert_node(i + 1, n)

        commit_calls = []
        planner = Planner(state)

        def batch_commit(items):
            commit_calls.append(len(items))
            index = 0
            for plan, result, pevals in items:
                index = state.upsert_plan_results(
                    None, plan, result, preemption_evals=pevals
                )
            return index

        planner.commit_batch_fn = batch_commit
        # queue all plans BEFORE the applier starts so they pile up
        # behind one dequeue and ride a single batch
        plans = []
        for n in nodes:
            p = Plan(priority=50)
            p.node_allocation[n.id] = [make_alloc(n.id, cpu=100, mem=64)]
            plans.append(p)
        planner.queue.set_enabled(True)
        pendings = [planner.queue.enqueue(p) for p in plans]
        planner.start()
        try:
            results = [p.wait(timeout=10.0) for p in pendings]
            for r, e in results:
                assert e is None
                assert r.node_allocation
            # all 8 plans landed; the batch path folded them into far
            # fewer commit calls than plans
            assert sum(commit_calls) == 8
            assert len(commit_calls) < 8, commit_calls
            for n in nodes:
                assert len(state.allocs_by_node_terminal(n.id, False)) == 1
        finally:
            planner.stop()

    def test_conflicts_within_one_batch_partial_commit(self):
        """Two plans in the SAME batch over-booking one node: the second
        verifies against the first's stacked optimistic snapshot and gets
        a refresh, not a double-booking."""
        state = StateStore()
        node = mock.node()
        node.node_resources.cpu.cpu_shares = 1000
        state.upsert_node(1, node)

        planner = Planner(state)
        plan_a = Plan(priority=50)
        plan_a.node_allocation[node.id] = [make_alloc(node.id, cpu=800, mem=64)]
        plan_b = Plan(priority=50)
        plan_b.node_allocation[node.id] = [make_alloc(node.id, cpu=800, mem=64)]
        planner.queue.set_enabled(True)
        pa_ = planner.queue.enqueue(plan_a)
        pb_ = planner.queue.enqueue(plan_b)
        planner.start()
        try:
            ra, ea = pa_.wait(timeout=10.0)
            rb, eb = pb_.wait(timeout=10.0)
            assert ea is None and eb is None
            committed = [
                r for r in (ra, rb) if r is not None and r.node_allocation
            ]
            assert len(committed) == 1
            loser = rb if committed[0] is ra else ra
            assert loser.refresh_index
            assert len(state.allocs_by_node_terminal(node.id, False)) == 1
        finally:
            planner.stop()
