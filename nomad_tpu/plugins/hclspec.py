"""Typed plugin-configuration specs (the hclspec role,
ref plugins/shared/hclspec/hcl_spec.proto: Attr, Block, BlockList,
Literal, Default compose into a schema that decodes + validates nested
plugin config with defaults and PATHED errors).

The reference expresses driver/device plugin config schemas as an
hclspec protobuf evaluated against HCL; here the same composition is a
small tree of spec nodes evaluated against the already-parsed dict the
jobspec layer produces. Flat legacy schemas ({key: {"type", "default",
"required"}}) lift into Attr nodes so existing plugins keep working.
"""

from __future__ import annotations

from typing import Any, Optional


class SpecError(ValueError):
    """A config value failed its spec; ``path`` names the exact field
    (e.g. ``mounts[1].volume_options.labels``) the way the reference's
    hclspec decode errors do."""

    def __init__(self, path: str, message: str):
        super().__init__(f"config {path or '<root>'}: {message}")
        self.path = path


_PRIMITIVES = {
    "string": (str,),
    "number": (int, float),
    "bool": (bool,),
    "any": (object,),
}


def _check_primitive(path: str, typ: str, value):
    expected = _PRIMITIVES.get(typ)
    if expected is None:
        raise SpecError(path, f"unknown spec type {typ!r}")
    if typ == "number" and isinstance(value, bool):
        # bool is an int subclass; a number attr must still reject it
        raise SpecError(path, "must be number, got bool")
    if not isinstance(value, expected):
        raise SpecError(
            path, f"must be {typ}, got {type(value).__name__}"
        )
    return value


class Attr:
    """A typed attribute (ref hcl_spec.proto Attr): ``type`` is a
    primitive name, ``list(<prim>)`` or ``map(<prim>)``."""

    def __init__(self, type: str = "string", required: bool = False):
        self.type = type
        self.required = required

    def validate(self, path: str, value):
        t = self.type
        if t.startswith("list(") and t.endswith(")"):
            inner = t[5:-1]
            if not isinstance(value, list):
                raise SpecError(
                    path, f"must be {t}, got {type(value).__name__}"
                )
            return [
                _check_primitive(f"{path}[{i}]", inner, v)
                for i, v in enumerate(value)
            ]
        if t.startswith("map(") and t.endswith(")"):
            inner = t[4:-1]
            if not isinstance(value, dict):
                raise SpecError(
                    path, f"must be {t}, got {type(value).__name__}"
                )
            return {
                str(k): _check_primitive(f"{path}.{k}", inner, v)
                for k, v in value.items()
            }
        return _check_primitive(path, t, value)


class Literal:
    """A fixed value injected into the decoded config
    (ref hcl_spec.proto Literal)."""

    def __init__(self, value):
        self.value = value

    def validate(self, path: str, value):  # pragma: no cover - not called
        return self.value


class Default:
    """Wraps a spec with a default used when the key is absent
    (ref hcl_spec.proto Default)."""

    def __init__(self, primary, default):
        self.primary = primary
        self.default = default

    def validate(self, path: str, value):
        return self.primary.validate(path, value)


class Block:
    """One nested block of named entries (ref hcl_spec.proto Block)."""

    def __init__(self, spec: dict, required: bool = False):
        self.spec = dict(spec)
        self.required = required

    def validate(self, path: str, value):
        if not isinstance(value, dict):
            raise SpecError(
                path, f"must be a block, got {type(value).__name__}"
            )
        return validate_spec(self.spec, value, path=path)


class BlockList:
    """A repeated nested block (ref hcl_spec.proto BlockList); job specs
    hand single blocks through as a bare dict, accepted as [dict]."""

    def __init__(self, spec: dict, min: int = 0, max: int = 0):
        self.spec = dict(spec)
        self.min = min
        self.max = max

    def validate(self, path: str, value):
        if isinstance(value, dict):
            value = [value]
        if not isinstance(value, list):
            raise SpecError(
                path, f"must be a block list, got {type(value).__name__}"
            )
        if len(value) < self.min:
            raise SpecError(path, f"needs at least {self.min} block(s)")
        if self.max and len(value) > self.max:
            raise SpecError(path, f"allows at most {self.max} block(s)")
        return [
            Block(self.spec).validate(f"{path}[{i}]", v)
            for i, v in enumerate(value)
        ]


def _lift(node):
    """Legacy flat entries ({\"type\", \"required\", \"default\"}) lift
    into Attr/Default nodes; real spec nodes pass through."""
    if isinstance(node, (Attr, Block, BlockList, Default, Literal)):
        return node
    if isinstance(node, dict):
        attr = Attr(node.get("type", "string"), bool(node.get("required")))
        if "default" in node:
            return Default(attr, node["default"])
        return attr
    raise SpecError("", f"invalid spec node {node!r}")


def validate_spec(spec: dict, config: dict, path: str = "") -> dict:
    """Decode ``config`` against ``spec``: unknown keys, type mismatches,
    and missing required entries raise SpecError with the field's full
    path; defaults and literals fold into the result."""
    if not isinstance(config, dict):
        raise SpecError(path, f"must be a block, got {type(config).__name__}")
    spec = {k: _lift(v) for k, v in (spec or {}).items()}

    def at(key):
        return f"{path}.{key}" if path else key

    for key in config:
        if key not in spec:
            raise SpecError(at(key), "unknown config key")
    out = {}
    for key, node in spec.items():
        if isinstance(node, Literal):
            out[key] = node.value
            continue
        if key in config:
            out[key] = node.validate(at(key), config[key])
        elif isinstance(node, Default):
            out[key] = node.default
        elif getattr(node, "required", False) or (
            isinstance(node, BlockList) and node.min > 0
        ):
            raise SpecError(at(key), "required but missing")
    return out
