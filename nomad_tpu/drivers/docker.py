"""Docker task driver (ref drivers/docker/driver.go), built on the docker
CLI rather than the engine API socket: run/wait/stop/kill/rm/inspect cover
the reference driver's container lifecycle, `docker logs -f` feeds the
task log files (the docklog companion's role), and recovery re-attaches to
a still-running container by name (RecoverTask).

Task config:
  image         required
  command/args  override the image entrypoint
  network_mode  --network value
  volumes       ["host:container", ...]
  labels        {k: v} container labels
  port_map      {label: container_port} publish task ports
  force_pull    pull the image even when present
"""

from __future__ import annotations

import os
import shutil
import subprocess
import threading
import time
import uuid

from ..client.driver import Driver, TaskHandle, task_log_dir
from ..structs.model import Task


class DockerDriver(Driver):
    name = "docker"

    def __init__(self, binary: str = ""):
        self._docker = binary or shutil.which("docker")
        self._version = ""
        self._healthy = False
        if self._docker:
            self._version = self._probe_version()
            self._healthy = bool(self._version)

    def _run(self, *args, timeout: float = 60.0) -> subprocess.CompletedProcess:
        return subprocess.run(
            [self._docker, *args],
            capture_output=True,
            text=True,
            timeout=timeout,
        )

    def _probe_version(self) -> str:
        """Engine (server) version; empty when the daemon is unreachable —
        the CLI alone doesn't make the driver healthy (ref docker
        fingerprint's dockerd connectivity check)."""
        try:
            out = self._run(
                "version", "--format", "{{.Server.Version}}", timeout=10
            )
            if out.returncode == 0:
                return out.stdout.strip()
        except (OSError, subprocess.TimeoutExpired):
            pass
        return ""

    def fingerprint(self) -> dict:
        attrs = {}
        if self._healthy:
            attrs["driver.docker.version"] = self._version
        return {
            "detected": bool(self._docker),
            "healthy": self._healthy,
            "attributes": attrs,
        }

    # ------------------------------------------------------------------
    def start_task(self, task: Task, task_dir: str) -> TaskHandle:
        if not self._healthy:
            raise RuntimeError("docker daemon is not available on this node")
        cfg = task.config or {}
        image = cfg.get("image")
        if not image:
            raise RuntimeError("docker requires an image")
        container = f"nomad-{task.name}-{uuid.uuid4().hex[:8]}"

        if cfg.get("force_pull"):
            pulled = self._run("pull", image, timeout=600)
            if pulled.returncode != 0:
                raise RuntimeError(f"docker pull failed: {pulled.stderr.strip()}")

        argv = ["run", "-d", "--name", container]
        if task.resources.memory_mb:
            argv += ["--memory", f"{task.resources.memory_mb}m"]
        if task.resources.cpu:
            argv += ["--cpu-shares", str(task.resources.cpu)]
        for k, v in (task.env or {}).items():
            argv += ["-e", f"{k}={v}"]
        for volume in cfg.get("volumes", []):
            argv += ["-v", str(volume)]
        if cfg.get("network_mode"):
            argv += ["--network", str(cfg["network_mode"])]
        for k, v in (cfg.get("labels") or {}).items():
            argv += ["--label", f"{k}={v}"]
        # port publishing: task port labels → container ports
        # (ref docker driver's port_map + publishedPorts)
        port_map = cfg.get("port_map") or {}
        ports = {}
        for net in task.resources.networks:
            for p in list(net.reserved_ports) + list(net.dynamic_ports):
                ports[p.label] = p.value
        for label, container_port in port_map.items():
            host_port = ports.get(label)
            if host_port:
                argv += ["-p", f"{host_port}:{container_port}"]
        argv.append(image)
        if cfg.get("command"):
            argv.append(str(cfg["command"]))
        argv += [str(a) for a in cfg.get("args", [])]

        out = self._run(*argv, timeout=600)
        if out.returncode != 0:
            raise RuntimeError(f"docker run failed: {out.stderr.strip()}")

        handle = TaskHandle(
            task_name=task.name, driver=self.name, started_at=time.time_ns()
        )
        handle._container = container
        self._supervise(handle, container, task_dir)
        return handle

    def _supervise(self, handle: TaskHandle, container: str, task_dir: str):
        """Wait for exit + follow logs into the task log files (the
        docklog companion process's role, drivers/docker/docklog/)."""
        if task_dir:
            log_dir = task_log_dir(task_dir)
            os.makedirs(log_dir, exist_ok=True)
            stdout = open(
                os.path.join(log_dir, f"{handle.task_name}.stdout.0"), "ab"
            )
            stderr = open(
                os.path.join(log_dir, f"{handle.task_name}.stderr.0"), "ab"
            )
            try:
                follower = subprocess.Popen(
                    [self._docker, "logs", "-f", container],
                    stdout=stdout,
                    stderr=stderr,
                )
                handle._log_follower = follower
            except OSError:
                pass
            finally:
                stdout.close()
                stderr.close()

        def waiter():
            code = 130
            try:
                out = subprocess.run(
                    [self._docker, "wait", container],
                    capture_output=True,
                    text=True,
                )
                if out.returncode == 0:
                    code = int(out.stdout.strip().splitlines()[-1])
            except (OSError, ValueError, IndexError):
                pass
            follower = getattr(handle, "_log_follower", None)
            if follower is not None and follower.poll() is None:
                try:
                    follower.terminate()
                except OSError:
                    pass
            if not handle._done.is_set():
                handle.finish(code)

        threading.Thread(target=waiter, daemon=True).start()

    # ------------------------------------------------------------------
    def stop_task(self, handle: TaskHandle, timeout: float = 5.0,
                  signal_name: str = ""):
        container = getattr(handle, "_container", None)
        if container is None or handle._done.is_set():
            return
        try:
            if signal_name:
                # custom kill_signal first; docker stop's escalation
                # window then delivers SIGKILL if the task lingers
                name = str(signal_name).upper()
                if not name.startswith("SIG"):
                    name = "SIG" + name
                self._run("kill", "--signal", name, container, timeout=30)
                if handle.wait(timeout):
                    return
            self._run(
                "stop", "-t", str(int(timeout)), container,
                timeout=timeout + 30,
            )
        except (OSError, subprocess.TimeoutExpired):
            pass

    def destroy_task(self, handle: TaskHandle):
        container = getattr(handle, "_container", None)
        if container is None:
            return
        try:
            self._run("rm", "-f", container, timeout=60)
        except (OSError, subprocess.TimeoutExpired):
            pass

    def signal_task(self, handle: TaskHandle, signal_name: str):
        container = getattr(handle, "_container", None)
        if container is None or handle._done.is_set():
            raise ValueError("task is not running")
        name = str(signal_name).upper()
        if not name.startswith("SIG"):
            name = "SIG" + name
        out = self._run("kill", "--signal", name, container, timeout=30)
        if out.returncode != 0:
            raise ValueError(f"docker kill failed: {out.stderr.strip()}")

    def exec_streaming(self, handle: TaskHandle, cmd: list, tty: bool = False,
                       task_dir: str = "", env=None):
        """Exec inside the container (`docker exec`, the in-context path
        the reference drives via the docker API's exec endpoints,
        drivers/docker/driver.go ExecTaskStreaming)."""
        from ..client.execstream import ExecProcess

        container = getattr(handle, "_container", None)
        if container is None or handle._done.is_set():
            raise ValueError("task is not running")
        argv = [self._docker, "exec", "-i"]
        if tty:
            argv.append("-t")
        argv += [container] + list(cmd)
        return ExecProcess(argv, tty=tty)

    def inspect_task(self, handle: TaskHandle) -> dict:
        base = super().inspect_task(handle)
        base["container"] = getattr(handle, "_container", None)
        return base

    # -- recovery (ref docker RecoverTask by reattaching to the container)
    def handle_data(self, handle: TaskHandle) -> dict:
        return {
            "driver": self.name,
            "task_name": handle.task_name,
            "container": getattr(handle, "_container", None),
            "started_at": handle.started_at,
        }

    def recover_task(self, task: Task, data: dict):
        container = data.get("container")
        if not container or not self._healthy:
            return None
        try:
            out = self._run(
                "inspect", "--format", "{{.State.Running}}", container,
                timeout=30,
            )
        except (OSError, subprocess.TimeoutExpired):
            return None
        if out.returncode != 0 or out.stdout.strip() != "true":
            return None
        handle = TaskHandle(
            task_name=task.name,
            driver=self.name,
            started_at=int(data.get("started_at", 0)),
            recovered=True,
        )
        handle._container = container
        self._supervise(handle, container, "")
        return handle
