"""Plan queue + plan applier: the optimistic-concurrency arbiter
(ref nomad/plan_queue.go:40-260, plan_apply.go:49-689).

Many schedulers plan in parallel against snapshots; this single serialized
applier re-checks every touched node's allocations against the latest state
(AllocsFit with devices), commits fully or partially, and hands back a
RefreshIndex so the scheduler can retry against fresher state. The per-node
verification is a dense check over the plan's touched nodes — the same masked
fit-matrix the TPU kernel computes, evaluated host-side at commit time.
"""

from __future__ import annotations

import heapq
import itertools
import threading
import time
from typing import Optional

from .. import metrics
from ..state.store import StateSnapshot, StateStore
from ..testing import faults as _faults
from .overload import DeadlineExceeded
from ..trace import tracer
from ..structs.funcs import allocs_fit
from ..structs.model import (
    NODE_SCHED_INELIGIBLE,
    NODE_STATUS_READY,
    Evaluation,
    Plan,
    PlanResult,
    remove_allocs,
)


class PendingPlan:
    """A queued plan + its completion future (ref plan_queue.go pendingPlan)."""

    def __init__(self, plan: Plan):
        self.plan = plan
        self.result: Optional[PlanResult] = None
        self.error: Optional[Exception] = None
        self.enqueued_at = time.monotonic()
        # the submitting eval's trace context, resolved once at enqueue:
        # the applier's queue-wait/verify/commit spans attach to it from
        # the applier thread without another registry lookup. The
        # CURRENT span (the worker's plan.submit, active on the
        # enqueuing thread) wins over the eval root so the applier
        # stages nest INSIDE plan.submit — critical-path attribution
        # then splits submit into queue-wait/verify/commit instead of
        # double-counting two parallel branches of the same wall time;
        # direct callers (Planner.apply, tests) fall back to the root
        self.trace_ctx = tracer.current() or tracer.ctx_for_eval(
            plan.eval_id
        )
        self._done = threading.Event()

    def respond(self, result: Optional[PlanResult], error: Optional[Exception]):
        self.result = result
        self.error = error
        self._done.set()

    def wait(self, timeout: Optional[float] = None) -> tuple[Optional[PlanResult], Optional[Exception]]:
        self._done.wait(timeout)
        return self.result, self.error


class PlanQueue:
    """Priority queue of pending plans (ref plan_queue.go:40-260)."""

    def __init__(self):
        self.enabled = False
        self._lock = threading.Lock()
        self._cond = threading.Condition(self._lock)
        self._heap: list = []
        self._counter = itertools.count()

    def set_enabled(self, enabled: bool):
        with self._lock:
            self.enabled = enabled
            if not enabled:
                # fail queued plans so submitting workers unblock immediately
                for _, _, pending in self._heap:
                    pending.respond(None, RuntimeError("plan queue is disabled"))
                self._heap = []
            self._cond.notify_all()

    def enqueue(self, plan: Plan) -> PendingPlan:
        pending = PendingPlan(plan)
        with self._lock:
            if not self.enabled:
                pending.respond(None, RuntimeError("plan queue is disabled"))
                return pending
            heapq.heappush(
                self._heap, (-plan.priority, next(self._counter), pending)
            )
            self._cond.notify_all()
        return pending

    def depth(self) -> int:
        """Plans waiting for the applier (observability: the bench's
        worker-scaling curve samples this to show where the control plane
        saturates; ref plan_queue.go Stats)."""
        with self._lock:
            return len(self._heap)

    def dequeue(self, timeout: Optional[float] = None) -> Optional[PendingPlan]:
        deadline = None if timeout is None else time.monotonic() + timeout
        with self._cond:
            while not self._heap:
                remaining = None if deadline is None else deadline - time.monotonic()
                if remaining is not None and remaining <= 0:
                    return None
                self._cond.wait(remaining if remaining is not None else 1.0)
            return heapq.heappop(self._heap)[2]

    def drain(self, max_n: int) -> list[PendingPlan]:
        """Pop up to ``max_n`` already-queued plans without waiting — the
        applier batches whatever has accumulated behind the plan it just
        dequeued into one consensus round."""
        out: list[PendingPlan] = []
        with self._lock:
            while self._heap and len(out) < max_n:
                out.append(heapq.heappop(self._heap)[2])
        return out

    def requeue(self, pendings: list[PendingPlan]):
        """Return unprocessed plans to the queue (rare applier bail-out)."""
        with self._lock:
            if not self.enabled:
                for p in pendings:
                    p.respond(None, RuntimeError("plan queue is disabled"))
                return
            for p in pendings:
                heapq.heappush(
                    self._heap, (-p.plan.priority, next(self._counter), p)
                )
            self._cond.notify_all()


def evaluate_node_plan(
    snap: StateSnapshot, plan: Plan, node_id: str
) -> tuple[bool, str]:
    """Re-check one node's proposed allocs against latest state
    (ref plan_apply.go:628-681)."""
    if not plan.node_allocation.get(node_id):
        return True, ""

    node = snap.node_by_id(node_id)
    if node is None:
        return False, "node does not exist"
    if node.status != NODE_STATUS_READY:
        return False, "node is not ready for placements"
    if node.scheduling_eligibility == NODE_SCHED_INELIGIBLE:
        return False, "node is not eligible for draining"

    existing = snap.allocs_by_node_terminal(node_id, False)
    remove = []
    remove.extend(plan.node_update.get(node_id, []))
    remove.extend(plan.node_preemptions.get(node_id, []))
    remove.extend(plan.node_allocation.get(node_id, []))
    proposed = remove_allocs(existing, remove)
    proposed = proposed + plan.node_allocation.get(node_id, [])

    fit, reason, _ = allocs_fit(node, proposed, None, True)
    return fit, reason


#: plans with at least this many placements verify through the dense path
DENSE_VERIFY_THRESHOLD = 256


def _alloc_triple(alloc) -> tuple[int, int, int]:
    """(cpu, memory_mb, disk_mb) of an allocation without materializing
    ComparableResources objects (the allocs_fit summation, funcs.go:104-117,
    done as plain ints for the dense verify path)."""
    resources = alloc.allocated_resources
    cpu = 0
    mem = 0
    for tr in resources.tasks.values():
        cpu += tr.cpu.cpu_shares
        mem += tr.memory.memory_mb
    return cpu, mem, resources.shared.disk_mb


def _alloc_exotic(alloc) -> bool:
    """Whether the alloc carries ports/bandwidth or devices — dimensions the
    dense verify doesn't model, forcing the exact per-node check. Delegates
    to the mirror plane's single definition (tpu/mirror.py exotic_flag) so
    the host dense path, the device verify, and the mirror's per-row
    exotic counts can never disagree."""
    from ..state.planes import exotic_flag

    return exotic_flag(alloc)


def _dense_node_fit(snap: StateSnapshot, plan: Plan, node_ids: list[str]) -> dict[str, tuple[bool, str]]:
    """Batched fit verdicts for the plan's touched nodes. Two wins over the
    per-node exact path: the alloc table is scanned ONCE (not once per
    node), and usage sums are plain int triples instead of
    ComparableResources object math. Nodes whose allocs carry ports or
    devices, and nodes that fail this check (which need the exact failing
    reason), fall back to evaluate_node_plan."""
    # one pass over the alloc table instead of one scan per touched node
    # (allocs_by_node_terminal is O(total allocs) per call)
    touched = set(node_ids)
    existing_by_node: dict[str, list] = {nid: [] for nid in node_ids}
    for a in snap.allocs():
        if a.node_id in touched and not a.terminal_status():
            existing_by_node[a.node_id].append(a)

    verdicts: dict[str, tuple[bool, str]] = {}
    for node_id in node_ids:
        if not plan.node_allocation.get(node_id):
            verdicts[node_id] = (True, "")
            continue
        node = snap.node_by_id(node_id)
        if node is None:
            verdicts[node_id] = (False, "node does not exist")
            continue
        if node.status != NODE_STATUS_READY:
            verdicts[node_id] = (False, "node is not ready for placements")
            continue
        if node.scheduling_eligibility == NODE_SCHED_INELIGIBLE:
            verdicts[node_id] = (False, "node is not eligible for draining")
            continue

        res = node.node_resources
        cap = (res.cpu.cpu_shares, res.memory.memory_mb, res.disk.disk_mb)
        cpu = mem = disk = 0
        if node.reserved_resources is not None:
            rr = node.reserved_resources
            cpu, mem, disk = (
                rr.cpu.cpu_shares, rr.memory.memory_mb, rr.disk.disk_mb
            )

        removed = {
            a.id
            for a in (
                plan.node_update.get(node_id, [])
                + plan.node_preemptions.get(node_id, [])
                + plan.node_allocation.get(node_id, [])
            )
        }
        exotic = False
        for a in existing_by_node[node_id]:
            if a.id in removed or a.allocated_resources is None:
                continue
            if _alloc_exotic(a):
                exotic = True
                break
            c, m, d = _alloc_triple(a)
            cpu += c
            mem += m
            disk += d
        if not exotic:
            for a in plan.node_allocation.get(node_id, []):
                if a.allocated_resources is None:
                    continue
                if _alloc_exotic(a):
                    exotic = True
                    break
                c, m, d = _alloc_triple(a)
                cpu += c
                mem += m
                disk += d

        if exotic or cpu > cap[0] or mem > cap[1] or disk > cap[2]:
            # exact path: exotic dimensions, or failure needing the precise
            # failing reason (and a double-check)
            verdicts[node_id] = evaluate_node_plan(snap, plan, node_id)
        else:
            verdicts[node_id] = (True, "")
    return verdicts


def _plan_node_ids(plan: Plan) -> list[str]:
    return list(dict.fromkeys(
        list(plan.node_update.keys()) + list(plan.node_allocation.keys())
    ))


def _assemble_result(plan: Plan, node_ids: list[str], fit_fn,
                     refresh_index: int) -> PlanResult:
    """Build the committable subset from per-node fit verdicts — THE
    shared tail of the host and device verify paths (ref
    plan_apply.go:399-560). One implementation so the two oracles can
    never drift on assembly semantics (all_at_once, preempt-only
    pass-through, canary correction)."""
    result = PlanResult(
        deployment=plan.deployment.copy() if plan.deployment else None,
        deployment_updates=plan.deployment_updates,
    )
    partial_commit = False
    for node_id in node_ids:
        fit, _reason = fit_fn(node_id)
        if not fit:
            partial_commit = True
            if plan.all_at_once:
                return PlanResult(refresh_index=refresh_index)
            continue
        if plan.node_update.get(node_id):
            result.node_update[node_id] = plan.node_update[node_id]
        if plan.node_allocation.get(node_id):
            result.node_allocation[node_id] = plan.node_allocation[node_id]
        if plan.node_preemptions.get(node_id):
            result.node_preemptions[node_id] = plan.node_preemptions[node_id]

    # evict/preempt-only nodes always commit
    for node_id, preempted in plan.node_preemptions.items():
        if node_id not in node_ids and preempted:
            result.node_preemptions[node_id] = preempted

    if partial_commit:
        result.refresh_index = refresh_index
        _correct_deployment_canaries(result)
    return result


def evaluate_plan(snap: StateSnapshot, plan: Plan) -> PlanResult:
    """Determine the committable subset of a plan
    (ref plan_apply.go:399-560)."""
    node_ids = _plan_node_ids(plan)

    total_placements = sum(len(v) for v in plan.node_allocation.values())
    dense = None
    if total_placements >= DENSE_VERIFY_THRESHOLD:
        dense = _dense_node_fit(snap, plan, node_ids)

    def fit_for(node_id):
        if dense is not None:
            return dense[node_id]
        return evaluate_node_plan(snap, plan, node_id)

    return _assemble_result(plan, node_ids, fit_for, snap.latest_index())


def _correct_deployment_canaries(result: PlanResult):
    """Drop canaries that were not actually placed after a partial commit
    (ref plan_apply.go:592-625)."""
    if result.deployment is None:
        return
    placed = {
        a.id for allocs in result.node_allocation.values() for a in allocs
    }
    for group in result.deployment.task_groups.values():
        group.placed_canaries = [c for c in group.placed_canaries if c in placed]


#: minimum placements before a plan takes the DEVICE dense verify — below
#: this the host paths win outright (a jit dispatch costs more than the
#: whole host check for a handful of rows); shares the spirit (and scale)
#: of DENSE_VERIFY_THRESHOLD. Tunable via plan_pipeline{device_verify_min}.
DEVICE_VERIFY_MIN_PLACEMENTS = 256


def _usage_vec(alloc) -> tuple:
    from ..state.planes import usage_vec

    return usage_vec(alloc) or (0, 0, 0, 0)


class _OverlayEpoch:
    """One verified-but-uncommitted batch's contribution to the in-flight
    overlay: the ADD side of its used-plane deltas, the placed-alloc
    vectors (so a later plan stopping an uncommitted alloc can cancel the
    credited add), the adds-only results for host-snapshot replay, and —
    once the commit thread is harvested — the entry's committed raft
    index, which is the ONLY prune authority. Content-based pruning
    ("the placed alloc id is in the snapshot, so the entry applied") is
    UNSOUND: in-place updates and refresh/nack retries legitimately
    reuse alloc ids, so an id's presence can come from an EARLIER entry
    — dropping the epoch then loses its sibling plans' uncommitted adds
    (observed as real over-commits in the e2e drive)."""

    __slots__ = ("deltas", "placed", "replay", "index")

    def __init__(self):
        # epoch lifetime is ONE batch (≤ max_apply_batch plans): the
        # whole object leaves the overlay at prune (entry committed and
        # visible in the base) or rollback (entry failed/unresolved), so
        # per-epoch growth is bounded by the batch fold cap
        #: node_id -> accumulated (cpu, mem, disk, mbits) ADD delta
        self.deltas: dict[str, list] = {}  # nta: ignore[unbounded-cache] WHY: bounded by one batch's placements; epoch dropped at prune/rollback
        #: alloc_id -> (node_id, usage vec) for uncommitted placements
        self.placed: dict[str, tuple] = {}  # nta: ignore[unbounded-cache] WHY: bounded by one batch's placements; epoch dropped at prune/rollback
        #: [(plan, adds-only PlanResult)] — host verify replays these onto
        #: its base snapshot (upsert_plan_results consumes only the result
        #: maps, so a result carrying just node_allocation replays exactly
        #: the ADD side)
        self.replay: list = []  # nta: ignore[unbounded-cache] WHY: ≤ max_apply_batch entries; epoch dropped at prune/rollback
        #: the entry's committed raft index, stamped at harvest; None
        #: while the commit is still in flight (never prunable)
        self.index: Optional[int] = None

    def absorb(self, plan: Plan, result: PlanResult):
        """Record ``result``'s placements. ONLY the add side: an
        uncommitted batch's REMOVALS are never credited to later batches —
        a later plan relying on capacity freed by a stop that then fails
        to commit would over-commit the node (the PR 6 over-commit class,
        resurrected via pipelining). Within one batch/raft entry stops DO
        credit (the entry is atomic) — that is the stacked-snapshot /
        batch-delta accounting in _verify_batch, not this overlay."""
        if not result.node_allocation:
            return
        self.replay.append(
            (plan, PlanResult(node_allocation=result.node_allocation))
        )
        for node_id, allocs in result.node_allocation.items():
            slot = self.deltas.setdefault(node_id, [0, 0, 0, 0])
            for a in allocs:
                vec = _usage_vec(a)
                for i in range(4):
                    slot[i] += vec[i]
                self.placed[a.id] = (node_id, vec)

    def empty(self) -> bool:
        return not self.replay


class InFlightOverlay:
    """Used-plane ADD deltas of every verified batch whose raft entry has
    not yet been proven committed (ROADMAP item 1b): the applier verifies
    new batches against base-snapshot + overlay instead of blocking the
    loop on each ``raft.apply``.

    Outcome contract (enforced tree-wide by the ``overlay-unresolved``
    analysis rule): every consumer of this overlay must also handle the
    ``plan.commit_timeout_unresolved`` outcome — a commit that failed
    with its entry outcome UNKNOWN (ApplyTimeout + failed barrier) is
    rolled back here like any failure, but its ``raft_index`` floor must
    still gate the apply loop's snapshots: the entry may yet land, and
    only a snapshot at-or-past it can be trusted not to miss it."""

    def __init__(self):
        self._epochs: list[_OverlayEpoch] = []

    def push(self, epoch: _OverlayEpoch):
        if not epoch.empty():
            self._epochs.append(epoch)

    def rollback(self, epoch: _OverlayEpoch) -> bool:
        """Drop a failed (or unresolved) batch's phantom adds. For the
        unresolved case the caller ALSO keeps the floor from the raised
        error's ``raft_index`` — rollback alone is not outcome handling."""
        try:
            self._epochs.remove(epoch)
            return True
        except ValueError:
            return False

    def prune(self, snap: StateSnapshot) -> int:
        """Drop epochs whose HARVESTED commit index ``snap`` provably
        covers (their adds now live in the base). Un-harvested epochs
        (index None) are never pruned even if the entry already applied
        to the store — keeping one is merely conservative (double-counted
        adds reject, never over-commit) and the window is one loop
        iteration, while any content-based shortcut is unsound (alloc ids
        recur across entries via in-place updates and retries)."""
        before = len(self._epochs)
        latest = snap.latest_index()
        self._epochs = [
            e for e in self._epochs
            if e.index is None or e.index > latest
        ]
        return before - len(self._epochs)

    def depth(self) -> int:
        return len(self._epochs)

    def deltas(self) -> dict[str, list]:
        """Merged node_id -> (cpu, mem, disk, mbits) add deltas."""
        out: dict[str, list] = {}
        for epoch in self._epochs:
            for node_id, vec in epoch.deltas.items():
                slot = out.setdefault(node_id, [0, 0, 0, 0])
                for i in range(4):
                    slot[i] += vec[i]
        return out

    def placed_vec(self, alloc_id: str, node_id: str) -> Optional[tuple]:
        """Usage vec of an uncommitted placement on ``node_id``, if any."""
        for epoch in self._epochs:
            rec = epoch.placed.get(alloc_id)
            if rec is not None and rec[0] == node_id:
                return rec[1]
        return None

    def replay_onto(self, snap: StateSnapshot, stack_fn) -> StateSnapshot:
        """Host-path base: stack every epoch's adds-only results onto
        ``snap`` (the same accounting the device path reads numerically)."""
        for epoch in self._epochs:
            for plan, adds in epoch.replay:
                snap = stack_fn(snap, plan, adds)
        return snap


class Planner:
    """The leader's pipelined plan-apply loop (ref plan_apply.go:71-180;
    ROADMAP item 1): verify batches against base-snapshot + in-flight
    overlay while up to ``max_inflight`` prior batches' raft entries are
    still committing, with the dense verify running against the
    ColumnarMirror's device-resident planes when a mirror is wired."""

    def __init__(self, state: StateStore):
        self.state = state
        self.queue = PlanQueue()
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self.preemption_evals_fn = None  # hook: build follow-up evals for preempted allocs
        self.on_preemption_evals = None  # hook: enqueue them after commit
        # hook: (plan) -> bool; re-validates the plan's eval token at
        # dequeue time — a worker that timed out waiting leaves its plan
        # orphaned in the queue, and committing it after the eval moved on
        # would double-place (the enqueue-time guard alone can't catch it)
        self.token_check_fn = None
        # consensus commit hook: (plan, result, preemption_evals) -> index.
        # When set (server wiring), the verified result is replicated via
        # raft ApplyPlanResults instead of written directly (plan_apply.go
        # applyPlan → raftApplyFuture).
        self.commit_fn = None
        # batch commit hook: ([(plan, result, preemption_evals)]) -> index;
        # commits several independently-verified plans in ONE raft entry.
        self.commit_batch_fn = None
        # hook: (timeout_exc) -> None; commits+applies a consensus barrier
        # (raft noop) and PROVES the timed-out entry applied, raising if it
        # cannot. A raft apply that timed out has already stored its entry,
        # which may yet commit — a barrier proposed behind it applying in
        # the SAME TERM (exc.raft_term; terms are monotonic, so an
        # unchanged current term means leadership was never lost) proves by
        # log matching that the entry applied too.
        self.barrier_fn = None
        # per-instance fold cap (server stanza `plan_apply_batch`); the
        # class constant stays as the default so direct constructions and
        # old call sites keep the historical behavior
        self.max_apply_batch = self.MAX_APPLY_BATCH
        # pipeline depth: verified batches whose commits may be in flight
        # simultaneously (plan_pipeline{max_inflight}). 1 = the classic
        # join-before-dispatch applier; the default overlaps verify(N+1)
        # with commit(N) without ever joining on the hot path
        self.max_inflight = self.MAX_INFLIGHT
        # hook: () -> ColumnarMirror | None (server wiring); enables the
        # device-resident dense verify for big plans
        self.mirror_fn = None
        # device verify enable + size gate (plan_pipeline{device_verify,
        # device_verify_min})
        self.device_verify = True
        self.device_verify_min = DEVICE_VERIFY_MIN_PLACEMENTS
        #: ADD deltas of uncommitted batches; the verify base rides
        #: base-snapshot + this (mutated only by the apply loop; depth()
        #: is sampled cross-thread by the flight recorder)
        self.overlay = InFlightOverlay()

    def start(self):
        self.queue.set_enabled(True)
        self._stop.clear()
        self._thread = threading.Thread(
            target=self._apply_loop, daemon=True, name="plan-applier"
        )
        self._thread.start()

    def stop(self):
        self._stop.set()
        self.queue.set_enabled(False)
        if self._thread is not None:
            self._thread.join(timeout=2.0)

    #: default max plans folded into one consensus round; bounded so a
    #: commit failure (which fails the whole batch) stays cheap to retry.
    #: Tunable per server via the `plan_apply_batch` stanza key (set on
    #: ``max_apply_batch``); observed fold sizes land in the
    #: plan.apply_batch_size histogram so the knob can be tuned against
    #: the worker-scaling knee without a code change.
    MAX_APPLY_BATCH = 16

    #: default pipeline depth (concurrent uncommitted raft entries). Safe
    #: by the overlay's adds-only credit discipline: concurrently-proposed
    #: entries upsert ABSOLUTE alloc docs, so their log order never
    #: changes final state, and a batch verified against an in-flight
    #: sibling's adds is conservative whichever entry lands first.
    MAX_INFLIGHT = 2

    def _device_ctx(self, base_snap, live):
        """Per-batch handles for the dense device verify, or None when it
        can't/shouldn't run (no mirror wired, every plan under the size
        gate, or the mirror already moved past this snapshot). The
        context: (mirror, cluster, device arrays, gen, mesh)."""
        if not self.device_verify or self.mirror_fn is None:
            return None
        if not any(
            sum(len(v) for v in p.plan.node_allocation.values())
            >= self.device_verify_min
            for p in live
        ):
            return None
        mirror = self.mirror_fn()
        if mirror is None:
            return None
        try:
            from ..tpu import shard as _shard
            from ..tpu.shard import node_bucket

            n_real = len(base_snap.nodes())
            # the MIN_NODES-gated mesh, exactly as the drain batches
            # resolve it: both consumers must agree per n_pad or the
            # mirror's DeviceState cache thrashes full-plane rebuilds
            mesh = _shard.active_mesh(n_real)
            handles = mirror.verify_handles(
                base_snap, node_bucket(n_real, mesh), mesh=mesh
            )
        except Exception:
            metrics.incr("plan.verify_device_degrade.handles")
            return None
        if handles is None:
            metrics.incr("plan.verify_device_degrade.stale")
            return None
        cluster, arrays, gen = handles
        return (mirror, cluster, arrays, gen, mesh)

    def _evaluate_plan_device(
        self, dev_ctx, base_snap, plan, overlay_deltas, epoch, stacked_fn
    ):
        """Dense device verify of one plan against the mirror's
        device-resident planes + the in-flight overlay (ROADMAP item 1a):
        a vectorized node-axis fit check shaped exactly like the planner
        kernel. Parity with the host oracle by construction: the device
        only ever CONFIRMS fits — rows it cannot model (ports/devices,
        int32-clip range, unknown allocs) and rows that fail the dense
        check are answered by the exact host path (``stacked_fn`` hands
        back the same stacked snapshot the host verify would use).
        Returns a PlanResult, or None to degrade the whole plan to the
        host path."""
        total_placements = sum(
            len(v) for v in plan.node_allocation.values()
        )
        if total_placements < self.device_verify_min:
            return None

        node_ids = _plan_node_ids(plan)
        mirror, _cluster, (cap_dev, _usable, used_dev), gen, mesh = dev_ctx

        #: per-node verdicts decided host-side (status checks and hard
        #: failures); rows absent here ride the kernel or the exact path
        verdicts: dict[str, tuple] = {}
        exact_nodes: list[str] = []
        rows: list[int] = []
        row_nodes: list[str] = []
        row_deltas: list = []
        import numpy as np

        clip = 2**30
        with mirror.locked_cluster(gen) as cluster:
            if cluster is None:
                # a drain batch synced the mirror forward mid-batch: the
                # device planes no longer match this snapshot
                metrics.incr("plan.verify_device_degrade.stale")
                return None
            for node_id in node_ids:
                if not plan.node_allocation.get(node_id):
                    verdicts[node_id] = (True, "")
                    continue
                row = cluster.index.get(node_id)
                if row is None:
                    # node outside the mirror's axis (not in state):
                    # degrade — the host path mints the exact reason
                    metrics.incr("plan.verify_device_degrade.rows")
                    return None
                node = cluster.nodes[row]
                if node.status != NODE_STATUS_READY:
                    verdicts[node_id] = (
                        False, "node is not ready for placements"
                    )
                    continue
                if node.scheduling_eligibility == NODE_SCHED_INELIGIBLE:
                    verdicts[node_id] = (
                        False, "node is not eligible for draining"
                    )
                    continue
                if cluster.exotic_live[row] > 0:
                    exact_nodes.append(node_id)
                    continue
                # THIS plan's removals credit (stop + place commit in the
                # same raft entry); sub vectors resolve against base-live
                # allocs, uncommitted overlay placements, and this
                # batch's own placements — anything else is already gone
                # and contributes nothing (matching remove_allocs)
                removed = {
                    a.id
                    for a in (
                        plan.node_update.get(node_id, [])
                        + plan.node_preemptions.get(node_id, [])
                        + plan.node_allocation.get(node_id, [])
                    )
                }
                delta = np.zeros(4, dtype=np.int64)
                exotic = False
                for a in plan.node_allocation.get(node_id, []):
                    if a.allocated_resources is not None and _alloc_exotic(a):
                        exotic = True
                        break
                    delta += np.asarray(_usage_vec(a), dtype=np.int64)
                if exotic:
                    exact_nodes.append(node_id)
                    continue
                for aid in removed:
                    rec = cluster._alloc_rec.get(aid)
                    if rec is not None and rec[0] == node_id:
                        delta -= np.asarray(rec[1], dtype=np.int64)
                        continue
                    vec = None
                    pr = epoch.placed.get(aid)
                    if pr is not None and pr[0] == node_id:
                        vec = pr[1]
                    elif overlay_deltas is not None:
                        vec = self.overlay.placed_vec(aid, node_id)
                    if vec is not None:
                        delta -= np.asarray(vec, dtype=np.int64)
                if overlay_deltas:
                    ov = overlay_deltas.get(node_id)
                    if ov is not None:
                        delta += np.asarray(ov, dtype=np.int64)
                bv = epoch.deltas.get(node_id)
                if bv is not None:
                    delta += np.asarray(bv, dtype=np.int64)
                used_row = cluster.mirror_used[row]
                if (
                    used_row.max() >= clip
                    or used_row.min() < 0
                    or np.abs(delta).max() >= clip
                ):
                    # outside the device planes' int32-clip range: the
                    # clipped plane could mask a real overflow — exact
                    exact_nodes.append(node_id)
                    continue
                rows.append(row)
                row_nodes.append(node_id)
                row_deltas.append(delta)

        if rows:
            try:
                from ..tpu.mirror import DeviceState
                from ..tpu import kernel as _kernel

                k = len(rows)
                b = DeviceState._row_bucket(k)
                padded = np.zeros(b, dtype=np.int32)
                padded[:k] = rows
                deltas_arr = np.zeros((b, 4), dtype=np.int32)
                deltas_arr[:k] = np.stack(row_deltas)
                fits = np.asarray(
                    _kernel.verify_rows(cap_dev, used_dev, padded, deltas_arr)
                )[:k]
            except Exception:
                # device fault: the planner-kernel degradation contract
                # (KernelFault class) — whole plan to the host oracle
                metrics.incr("plan.verify_device_degrade.kernel_fault")
                return None
            for node_id, fit in zip(row_nodes, fits):
                if bool(fit):
                    verdicts[node_id] = (True, "")
                else:
                    # dense failure: the exact host check mints the
                    # failing reason (and double-checks) — identical to
                    # the host dense path's failure handling
                    exact_nodes.append(node_id)

        for node_id in exact_nodes:
            verdicts[node_id] = evaluate_node_plan(
                stacked_fn(), plan, node_id
            )

        # the SAME assembly as the host oracle (shared helper), with
        # refresh indexes minted from the REAL base snapshot
        return _assemble_result(
            plan, node_ids, verdicts.__getitem__, base_snap.latest_index()
        )

    class _StackFailure(Exception):
        """_optimistic_snapshot raised while building the host verify
        base: the remaining plans can't be verified safely this round."""

    def _verify_batch(self, live, base_snap, dev_ctx=None):
        """Verify each plan against base-snapshot + in-flight overlay +
        the CUMULATIVE results of this batch, so neither a sibling in this
        batch nor an uncommitted in-flight batch can be double-booked.
        Returns (entries, leftovers, noops, epoch): entries = [(pending,
        result)] to commit in one raft entry, leftovers = plans to
        requeue when optimistic stacking fails mid-batch (verifying them
        against a base missing an accepted sibling would double-book),
        noops = fully-rejected plans whose response must carry a REAL
        index (see _respond_refreshed — a stacked snapshot's latest_index
        is synthetic), and epoch = the batch's overlay contribution (the
        caller pushes it when dispatching the commit)."""
        entries = []
        noops = []
        epoch = _OverlayEpoch()
        overlay_deltas = (
            self.overlay.deltas() if dev_ctx is not None else None
        )
        stacked_box: list = [None]

        def stacked_fn():
            # lazy host verify base: base + overlay adds + accepted
            # siblings; built once, then kept current by post-accept
            # stacking below
            if stacked_box[0] is None:
                try:
                    s = self.overlay.replay_onto(
                        base_snap, self._optimistic_snapshot
                    )
                    for p2, r2 in entries:
                        s = self._optimistic_snapshot(s, p2.plan, r2)
                except Exception as e:
                    raise Planner._StackFailure() from e
                stacked_box[0] = s
            return stacked_box[0]

        for i, p in enumerate(live):
            try:
                with tracer.span(
                    "plan.evaluate", parent=p.trace_ctx,
                    metric="plan.evaluate",
                ):
                    result = None
                    if dev_ctx is not None:
                        with tracer.span(
                            "plan.verify_device",
                            metric="plan.verify_device",
                        ):
                            result = self._evaluate_plan_device(
                                dev_ctx, base_snap, p.plan,
                                overlay_deltas, epoch, stacked_fn,
                            )
                    if result is None:
                        result = evaluate_plan(stacked_fn(), p.plan)
            except Planner._StackFailure:
                # can't build a safe verify base mid-flight: requeue this
                # plan and the rest; the apply loop resynchronizes
                return entries, live[i:], noops, epoch
            except Exception as e:
                p.respond(None, e)
                continue
            if result.is_no_op() and result.refresh_index:
                noops.append((p, result))
                continue
            entries.append((p, result))
            epoch.absorb(p.plan, result)
            if stacked_box[0] is not None:
                try:
                    stacked_box[0] = self._optimistic_snapshot(
                        stacked_box[0], p.plan, result
                    )
                except Exception:
                    # entry i IS being committed but the stacked base is
                    # missing its placements: requeue the rest — verifying
                    # them against it would double-book entry i's capacity
                    return entries, live[i + 1:], noops, epoch
        return entries, [], noops, epoch

    def _commit_resolving(self, commit, trace_ctxs=()):
        """Run a consensus commit, resolving indeterminate timeouts.

        A raft apply that times out has ALREADY stored its entry in the
        log — the entry may still commit seconds later. Treating the
        timeout as "nothing happened" lets every subsequent batch verify
        against snapshots missing the in-flight entry, double-booking its
        capacity when it lands (the over-commit class the first full-scale
        soak surfaced: raft-apply p99 was ~4x the apply timeout under
        storm backlog). On timeout, a barrier committed BEHIND the entry
        proves by log matching that the entry applied; the commit then
        reports the entry's real index. If the barrier itself fails, the
        original timeout propagates — still carrying ``raft_index`` so the
        apply loop can floor its snapshots past the unresolved entry."""
        try:
            return commit()
        except TimeoutError as e:
            index = getattr(e, "raft_index", None)
            if index is None or self.barrier_fn is None:
                raise
            tb0 = time.monotonic()
            try:
                self.barrier_fn(e)
            except Exception:
                metrics.incr("plan.commit_timeout_unresolved")
                tb1 = time.monotonic()
                for ctx in trace_ctxs:
                    # the indeterminacy resolution is a real stage of the
                    # eval's lifecycle: FAILED barrier visible in the tree
                    tracer.record_span(
                        "plan.commit_barrier", ctx, tb0, tb1,
                        tags={"resolved": False, "index": index},
                        error="barrier failed; entry outcome unknown",
                    )
                raise e
            metrics.incr("plan.commit_timeout_resolved")
            tb1 = time.monotonic()
            for ctx in trace_ctxs:
                tracer.record_span(
                    "plan.commit_barrier", ctx, tb0, tb1,
                    tags={"resolved": True, "index": index},
                )
            return index

    def _respond_refreshed(self, noops, index: Optional[int] = None):
        """Answer fully-rejected plans with a refresh index that is REAL:
        the just-committed batch's index when one exists (it contains the
        whole optimistic world the rejection was computed against), else
        the store's current index. Never the synthetic optimistic index —
        a worker must not block on an index that only exists inside the
        applier's scratch overlay."""
        if not noops:
            return
        real = index if index is not None else self.state.latest_index()
        for p, result in noops:
            result.refresh_index = min(result.refresh_index, real)
            p.respond(result, None)

    def _harvest(self, outstanding: list, block: bool = False):
        """Collect finished commits off the pipeline: fold their committed
        indexes into ``prev_index`` (returned), fold any unresolved-entry
        floor, and roll the overlay back for batches whose commit FAILED
        (their adds were phantoms). A commit that failed with
        ``plan.commit_timeout_unresolved`` (ApplyTimeout + failed barrier)
        also rolls back — but its entry may still land, so its
        ``raft_index`` rides the returned floor and gates every later
        snapshot. With ``block``, the OLDEST commit is joined first (the
        pipeline-depth backpressure point)."""
        prev_index = 0
        floor = 0
        if block and outstanding:
            outstanding[0][0].join()
        done = [o for o in outstanding if not o[0].is_alive()]
        for t, box, epoch in done:
            t.join()
            outstanding.remove((t, box, epoch))
            index = box.get("index", 0)
            if index:
                prev_index = max(prev_index, index)
                # stamp the entry's real index: prune drops the epoch
                # once a base snapshot provably covers it (the ONLY
                # sound prune authority — see _OverlayEpoch)
                epoch.index = index
            else:
                # failed (or unresolved) commit: the epoch's adds never
                # materialized — later batches must stop verifying
                # against them
                if self.overlay.rollback(epoch):
                    metrics.incr("plan.overlay_rollback")
            floor = max(floor, box.get("floor", 0))
        return prev_index, floor

    def _apply_loop(self):
        """The pipelined applier (ref plan_apply.go:49-180; ROADMAP item
        1b): queued plans fold into one raft entry (MAX_APPLY_BATCH), the
        batch verifies against base-snapshot + the in-flight overlay
        (adds of up to ``max_inflight`` uncommitted batches), and its
        commit dispatches WITHOUT joining the previous one — the loop
        never blocks on ``raft.apply`` until the pipeline is full. The
        submitting workers are still answered only after their commit
        really lands (_async_commit_batch). Safety: the overlay credits
        only the ADD side of uncommitted batches (conservative whichever
        entries land), failed commits roll their epochs back at harvest,
        and unresolved outcomes floor every later snapshot past the
        in-flight entry."""
        outstanding: list = []  # [(thread, box, epoch)], dispatch order
        prev_index = 0
        # snapshots must never be taken below this index: a commit that
        # failed INDETERMINATELY (apply timeout + failed barrier) may still
        # land at its entry index — verifying any batch against state below
        # it risks double-booking the in-flight entry's capacity
        floor = 0

        while not self._stop.is_set():
            head = self.queue.dequeue(timeout=0.2)
            if head is None:
                if outstanding:
                    hi, hf = self._harvest(outstanding)
                    prev_index = max(prev_index, hi)
                    floor = max(floor, hf)
                if self.overlay.depth():
                    # idle housekeeping: without this, committed epochs
                    # (and their Plan/Allocation graphs) outlive the
                    # burst that created them, and overlay_depth()
                    # reports in-flight batches on a quiesced server
                    self.overlay.prune(self.state.snapshot())
                continue
            batch = [head] + self.queue.drain(self.max_apply_batch - 1)
            now = time.monotonic()
            live = []
            for p in batch:
                # time spent waiting for the applier: the stage that names
                # the saturation point when workers outrun the commit
                tracer.record_span(
                    "plan.queue_wait", p.trace_ctx, p.enqueued_at, now,
                    metric="plan.queue_wait",
                )
                if self.token_check_fn is not None and not self.token_check_fn(
                    p.plan
                ):
                    # the submitting worker gave up (timeout) and its eval
                    # moved on — committing the orphan would double-place
                    p.respond(
                        None,
                        RuntimeError("plan rejected: eval token no longer live"),
                    )
                elif p.plan.deadline and time.time_ns() >= p.plan.deadline:
                    # the overload plane's applier gate (core/overload.py):
                    # the eval's deadline passed while its plan queued —
                    # verifying and paying a consensus round for work
                    # nobody is waiting on would deepen the backlog that
                    # expired it. The worker turns this into a terminal
                    # deadline_exceeded eval outcome.
                    metrics.incr("overload.deadline_exceeded.applier")
                    p.respond(
                        None,
                        DeadlineExceeded(
                            "plan rejected: deadline exceeded before "
                            "verify/commit",
                            where="applier",
                        ),
                    )
                else:
                    live.append(p)
            if not live:
                continue

            # harvest finished commits; block on the oldest only when the
            # pipeline is at depth (the backpressure that bounds overlay
            # growth and worker-visible commit latency)
            hi, hf = self._harvest(outstanding)
            prev_index = max(prev_index, hi)
            floor = max(floor, hf)
            while len(outstanding) >= max(1, self.max_inflight):
                hi, hf = self._harvest(outstanding, block=True)
                prev_index = max(prev_index, hi)
                floor = max(floor, hf)

            batch_min = max(p.plan.snapshot_index for p in live)
            min_index = max(prev_index, batch_min, floor)
            try:
                snap = self.state.snapshot_min_index(min_index, timeout=5.0)
            except Exception as e:
                for p in live:
                    p.respond(None, e)
                continue
            # drop overlay epochs the snapshot provably contains: their
            # adds are in the base now (keeping one is conservative, but
            # systematically double-counts)
            t_ov = time.monotonic()
            pruned = self.overlay.prune(snap)
            tracer.record_span(
                "plan.overlay", live[0].trace_ctx, t_ov, time.monotonic(),
                tags={"depth": self.overlay.depth(), "pruned": pruned,
                      "inflight": len(outstanding)},
            )

            dev_ctx = self._device_ctx(snap, live)
            entries, leftovers, noops, epoch = self._verify_batch(
                live, snap, dev_ctx
            )
            if leftovers:
                # stacking failed mid-batch: requeue and resynchronize —
                # join the whole pipeline so the next round verifies
                # against committed reality
                self.queue.requeue(leftovers)
                while outstanding:
                    hi, hf = self._harvest(outstanding, block=True)
                    prev_index = max(prev_index, hi)
                    floor = max(floor, hf)
            if not entries:
                self._respond_refreshed(noops)
                continue

            self.overlay.push(epoch)
            box: dict = {}
            t = threading.Thread(
                target=self._async_commit_batch,
                args=(entries, noops, box),
                daemon=True,
                name="plan-commit",
            )
            t.start()
            outstanding.append((t, box, epoch))

        for t, _box, _epoch in outstanding:
            t.join(timeout=2.0)

    def overlay_depth(self) -> int:
        """In-flight verified-but-uncommitted batches (the flight
        recorder's ``overlay_depth`` sample key)."""
        return self.overlay.depth()

    def _optimistic_snapshot(
        self, snap: StateSnapshot, plan: Plan, result: PlanResult
    ) -> StateSnapshot:
        """A snapshot with ``result`` applied on top of ``snap`` without
        publishing anything: a scratch store adopts the immutable generation
        and copy-on-writes a private one (the reference's optimistic
        snapshot, plan_apply.go:72-76)."""
        scratch = StateStore()
        scratch._gen = snap._gen
        scratch.upsert_plan_results(None, plan, result)
        return scratch.snapshot()

    def _async_commit_batch(
        self, entries: list[tuple[PendingPlan, PlanResult]], noops: list,
        box: dict,
    ):
        """Commit a batch of verified results in one consensus round and
        answer every submitting worker (ref plan_apply.go:367
        asyncPlanWait; batching amortizes the raft fsync). Fully-rejected
        siblings (``noops``) are answered here too, carrying the commit's
        REAL index as their refresh point — the optimistic index they were
        verified at exists only inside the applier's scratch overlay."""
        tc0 = time.monotonic()
        ctxs = [p.trace_ctx for p, _ in entries if p.trace_ctx is not None]
        try:
            # chaos seam: a rule here fails/partitions the leader at the
            # worst moment — results verified, consensus not yet reached
            _faults.fault_point("plan.raft_apply")
            # observed fold size (how many plans actually share this
            # consensus round) — the histogram operators tune
            # `plan_apply_batch` against
            metrics.observe("plan.apply_batch_size", len(entries))
            items = []
            for pending, result in entries:
                preemption_evals: list[Evaluation] = []
                if (
                    self.preemption_evals_fn is not None
                    and result.node_preemptions
                ):
                    preemption_evals = self.preemption_evals_fn(result)
                items.append((pending.plan, result, preemption_evals))
            if self.commit_batch_fn is not None:
                with metrics.measure("plan.raft_apply"):
                    index = self._commit_resolving(
                        lambda: self.commit_batch_fn(items),
                        trace_ctxs=ctxs,
                    )
            elif self.commit_fn is not None:
                with metrics.measure("plan.raft_apply"):
                    index = 0
                    for (pending, _), (plan, result, pevals) in zip(
                        entries, items
                    ):
                        # per-plan commits: a barrier resolution belongs
                        # to THIS plan's trace only, not the whole batch
                        index = self._commit_resolving(
                            lambda p=plan, r=result, pe=pevals: self.commit_fn(
                                p, r, pe
                            ),
                            trace_ctxs=(
                                (pending.trace_ctx,)
                                if pending.trace_ctx is not None
                                else ()
                            ),
                        )
            else:
                index = 0
                for plan, result, pevals in items:
                    index = self.state.upsert_plan_results(
                        None, plan, result, preemption_evals=pevals
                    )
                    if pevals and self.on_preemption_evals is not None:
                        self.on_preemption_evals(
                            [self.state.eval_by_id(e.id) for e in pevals]
                        )
            box["index"] = index
            tc1 = time.monotonic()
            for pending, result in entries:
                result.alloc_index = index
                if result.refresh_index:
                    # partial commits carry a refresh point: clamp the
                    # synthetic optimistic index to the real committed one
                    result.refresh_index = min(result.refresh_index, index)
                tracer.record_span(
                    "plan.commit", pending.trace_ctx, tc0, tc1,
                    tags={"batch": len(entries), "index": index},
                )
                pending.respond(result, None)
            self._respond_refreshed(noops, index)
        except _faults.SimulatedCrash:
            # injected leader death mid-commit: the entry never reached
            # consensus. Answer the workers with failure so their evals
            # nack-requeue — the same outcome a real dead leader produces
            # for them via RPC failure — instead of leaving them parked on
            # a 30s wait with a dead commit thread
            err = RuntimeError("plan commit crashed (injected leader death)")
            for pending, _ in entries:
                pending.respond(None, err)
            for pending, _ in noops:
                pending.respond(None, err)
        except Exception as e:
            # an unresolved in-flight entry (timeout + failed barrier) may
            # still land: floor the apply loop's snapshots past it so no
            # batch is ever verified against state that could be missing it
            floor = getattr(e, "raft_index", 0)
            if floor:
                box["floor"] = max(box.get("floor", 0), floor)
            tc1 = time.monotonic()
            for pending, _ in entries:
                tracer.record_span(
                    "plan.commit", pending.trace_ctx, tc0, tc1,
                    tags={"batch": len(entries)}, error=repr(e),
                )
                pending.respond(None, e)
            for pending, _ in noops:
                pending.respond(None, e)

    def _async_commit(self, pending: PendingPlan, result: PlanResult, box: dict):
        """Commit the verified result via consensus and answer the worker
        (ref plan_apply.go:367 asyncPlanWait)."""
        try:
            plan = pending.plan
            preemption_evals: list[Evaluation] = []
            if self.preemption_evals_fn is not None and result.node_preemptions:
                preemption_evals = self.preemption_evals_fn(result)
            if self.commit_fn is not None:
                with metrics.measure("plan.raft_apply"):
                    index = self._commit_resolving(
                        lambda: self.commit_fn(plan, result, preemption_evals)
                    )
            else:
                index = self.state.upsert_plan_results(
                    None, plan, result, preemption_evals=preemption_evals
                )
                if preemption_evals and self.on_preemption_evals is not None:
                    self.on_preemption_evals(
                        [self.state.eval_by_id(e.id) for e in preemption_evals]
                    )
            result.alloc_index = index
            box["index"] = index
            pending.respond(result, None)
        except Exception as e:
            if getattr(e, "raft_index", 0):
                box["floor"] = max(box.get("floor", 0), e.raft_index)
            pending.respond(None, e)

    def apply(self, plan: Plan) -> PlanResult:
        """Synchronous verify + commit against the latest snapshot (the
        non-overlapped path kept for direct callers/tests)."""
        snap = self.state.snapshot()
        result = evaluate_plan(snap, plan)
        if result.is_no_op() and result.refresh_index:
            return result
        pending = PendingPlan(plan)
        self._async_commit(pending, result, {})
        res, err = pending.wait(timeout=30.0)
        if err is not None:
            raise err
        return res
