"""Event broker: FSM-sourced, index-ordered cluster events fanned out to
subscribers (ref nomad/stream/event_broker.go, event_buffer.go,
subscription.go + nomad/state/events.go eventsFromChanges).

Every server (leader or follower) derives the same events from the same
applied raft log, so any server can serve ``/v1/event/stream`` — exactly
the property the reference gets from sourcing events in the FSM rather
than in the leader's endpoints. Events are held in ONE bounded ring
buffer shared by all subscribers (oldest entries dropped when full) and
each subscriber drains its own bounded queue:

- a subscriber that asks for ``index=N`` replays retained events with
  index > N from the ring; when the ring has already overwritten part of
  that range the subscription starts with an explicit lost-gap marker
  instead of silently skipping (the chaos invariant);
- a subscriber that stops draining (slow consumer) is CLOSED, not
  buffered without bound — the close carries a resume floor (the highest
  index the ring has evicted) so reconnecting with ``index=floor``
  replays everything still retained, and a consumer resuming from its
  own older index observes the gap explicitly (ref event_broker.go's
  ErrSubscriberClosed path).

The ring's contents are deliberately NOT snapshotted: after a restore
the broker resets to the restored state index and live subscribers are
closed with that index (re-derivable state, same as the reference's
in-memory event buffer).
"""

from __future__ import annotations

import threading
from collections import deque
from dataclasses import dataclass, field
from typing import Iterable, Optional

TOPIC_JOB = "Job"
TOPIC_EVAL = "Eval"
TOPIC_ALLOC = "Alloc"
TOPIC_DEPLOYMENT = "Deployment"
TOPIC_NODE = "Node"
TOPIC_NODE_EVENT = "NodeEvent"
TOPIC_PLAN_RESULT = "PlanResult"
TOPIC_ALL = "*"

ALL_TOPICS = (
    TOPIC_JOB,
    TOPIC_EVAL,
    TOPIC_ALLOC,
    TOPIC_DEPLOYMENT,
    TOPIC_NODE,
    TOPIC_NODE_EVENT,
    TOPIC_PLAN_RESULT,
)

#: topics whose events are cluster-scoped (no namespace): gated by the
#: node:read coarse capability rather than a namespace capability
NODE_TOPICS = (TOPIC_NODE, TOPIC_NODE_EVENT)


def required_capability(topic: str) -> str:
    """The ACL requirement for subscribing to ``topic`` (ref
    command/agent/event_endpoint.go aclCheckForEvents): node-scoped
    topics need node:read, everything else the namespace's read-job."""
    if topic in NODE_TOPICS:
        return "node:read"
    return "ns:read-job"


def event_visible(acl, event: "Event") -> bool:
    """Per-event ACL filter applied at delivery (the subscribe-time check
    used the caller-chosen namespace; each event re-checks against ITS
    namespace, the same cross-namespace rule as list endpoints)."""
    if acl is None or acl.management:
        return True
    if event.topic in NODE_TOPICS:
        return acl.allow_node_read()
    return acl.allow_namespace_operation(
        event.namespace or "default", "read-job"
    )


@dataclass
class Event:
    """One typed cluster event (ref stream/event.go Event)."""

    topic: str
    type: str
    key: str
    index: int
    namespace: str = ""
    payload: dict = field(default_factory=dict)
    #: secondary match keys (ref structs.Event.FilterKeys): an Alloc
    #: event matches subscriptions keyed by its job/eval/deployment id
    filter_keys: tuple = ()

    def to_dict(self) -> dict:
        return {
            "Topic": self.topic,
            "Type": self.type,
            "Key": self.key,
            "Namespace": self.namespace,
            "FilterKeys": list(self.filter_keys),
            "Index": self.index,
            "Payload": self.payload,
        }


class SubscriptionClosedError(Exception):
    """Raised from Subscription.next once the broker has closed the
    subscription. ``resume_index`` is the highest index already evicted
    from the ring at close time (the resume floor): reconnecting with
    ``index=resume_index`` replays every frame still retained — nothing
    is silently skipped — and a consumer resuming from its OWN older
    index instead gets the explicit lost-gap marker."""

    def __init__(self, reason: str, resume_index: int):
        super().__init__(reason)
        self.reason = reason
        self.resume_index = resume_index


class Subscription:
    """One consumer's bounded queue over the broker's fan-out (ref
    stream/subscription.go). Frames are ``(index, [Event, ...])``; a
    lost-gap frame is ``(index, None)`` meaning events up to ``index``
    were overwritten before this subscriber could read them."""

    def __init__(
        self,
        broker: "EventBroker",
        topics: dict[str, set[str]],
        acl=None,
        namespace: str = "*",
        max_queued: int = 1024,
    ):
        self.broker = broker
        self.topics = topics
        self.acl = acl
        self.namespace = namespace
        self.max_queued = max_queued
        self._queue: deque = deque()
        self._cond = threading.Condition()
        self._closed = False
        self._close_reason = ""
        self._resume_index = 0

    # -- filtering ------------------------------------------------------
    def _topic_keys(self, topic: str) -> Optional[set[str]]:
        keys = self.topics.get(topic)
        if keys is None:
            keys = self.topics.get(TOPIC_ALL)
        return keys

    def matches(self, event: Event) -> bool:
        keys = self._topic_keys(event.topic)
        if keys is None:
            return False
        if TOPIC_ALL not in keys:
            if event.key not in keys and not keys.intersection(
                event.filter_keys
            ):
                return False
        if (
            self.namespace not in ("*", "")
            and event.namespace
            and event.namespace != self.namespace
        ):
            return False
        return event_visible(self.acl, event)

    # -- delivery (broker side, under the broker lock) ------------------
    def _offer(self, index: int, events: list[Event]) -> bool:
        """Enqueue one frame; False means this subscriber is too slow and
        must be closed (no-slow-consumer backpressure)."""
        wanted = [e for e in events if self.matches(e)]
        if not wanted:
            return True
        with self._cond:
            if self._closed:
                return True
            if len(self._queue) >= self.max_queued:
                return False
            self._queue.append((index, wanted))
            self._cond.notify_all()
        return True

    def _offer_gap(self, through_index: int):
        with self._cond:
            if not self._closed:
                self._queue.append((through_index, None))
                self._cond.notify_all()

    def _close(self, reason: str, resume_index: int):
        with self._cond:
            if self._closed:
                return
            self._closed = True
            self._close_reason = reason
            self._resume_index = resume_index
            self._cond.notify_all()

    # -- consumer side --------------------------------------------------
    def next(self, timeout: Optional[float] = None):
        """Next frame ``(index, [Event, ...])`` (or ``(index, None)`` for
        a lost gap), ``None`` on timeout, SubscriptionClosedError once the
        broker closed this subscription and its queue is drained."""
        with self._cond:
            self._cond.wait_for(
                lambda: self._queue or self._closed, timeout
            )
            if self._queue:
                return self._queue.popleft()
            if self._closed:
                raise SubscriptionClosedError(
                    self._close_reason or "subscription closed",
                    self._resume_index,
                )
            return None

    def close(self):
        """Consumer-initiated unsubscribe."""
        self.broker.unsubscribe(self)
        self._close("unsubscribed", self._resume_index)

    @property
    def closed(self) -> bool:
        with self._cond:
            return self._closed


class EventBroker:
    """Bounded ring of published frames + subscriber fan-out (ref
    stream/event_broker.go EventBroker)."""

    def __init__(self, size: int = 4096, subscriber_buffer: int = 1024):
        #: max EVENTS retained across all frames (oldest dropped first)
        self.size = max(1, int(size))
        self.subscriber_buffer = max(1, int(subscriber_buffer))
        self._lock = threading.Lock()
        #: ring of (index, [Event, ...]) frames, index-ascending
        self._frames: deque = deque()
        self._n_events = 0
        self._latest_index = 0
        #: highest index ever evicted from the ring (lost-gap watermark)
        self._dropped_through = 0
        self._subs: list[Subscription] = []
        self._published = 0
        self._closed_slow = 0

    # -- publish (FSM apply path) ---------------------------------------
    def publish(self, index: int, events: list[Event]):
        if not events:
            return
        with self._lock:
            self._latest_index = max(self._latest_index, index)
            self._frames.append((index, list(events)))
            self._n_events += len(events)
            self._published += len(events)
            while self._n_events > self.size and len(self._frames) > 1:
                old_index, old_events = self._frames.popleft()
                self._n_events -= len(old_events)
                self._dropped_through = max(self._dropped_through, old_index)
            subs = list(self._subs)
        for sub in subs:
            if not sub._offer(index, events):
                self._close_slow(sub)

    def _resume_floor_locked(self) -> int:
        """The index to advertise on a close: reconnecting with
        ``index=floor`` replays every frame still retained (from_index is
        exclusive), so nothing retained is silently skipped — and a
        consumer resuming from its own older index still gets the
        explicit gap marker."""
        return self._dropped_through

    def _close_slow(self, sub: Subscription):
        with self._lock:
            if sub in self._subs:
                self._subs.remove(sub)
            self._closed_slow += 1
            resume = self._resume_floor_locked()
        sub._close(
            "subscription closed: slow consumer (queue overflow)", resume
        )

    # -- subscribe ------------------------------------------------------
    def subscribe(
        self,
        topics: Optional[dict[str, Iterable[str]]] = None,
        from_index: int = 0,
        acl=None,
        namespace: str = "*",
        max_queued: Optional[int] = None,
    ) -> Subscription:
        """Register a subscriber. ``topics`` maps topic → keys ("*" for
        all); ``from_index=N`` replays retained events with index > N
        (the blocking-query convention: pass the last index you saw).
        An explicit resume (N > 0) older than the ring's retention gets a
        lost-gap frame first, then everything still retained.
        ``from_index=0`` is a FRESH subscribe — "whatever is retained,
        then live" — and makes no completeness claim, so it never emits a
        gap frame (every fresh subscriber on a long-lived cluster would
        otherwise start with one)."""
        norm: dict[str, set[str]] = {}
        for topic, keys in (topics or {TOPIC_ALL: ("*",)}).items():
            keyset = {k for k in keys} or {"*"}
            norm[topic] = keyset
        sub = Subscription(
            self,
            norm,
            acl=acl,
            namespace=namespace,
            max_queued=max_queued or self.subscriber_buffer,
        )
        with self._lock:
            replay = [
                (index, events)
                for index, events in self._frames
                if index > from_index
            ]
            # cap the replay to the NEWEST frames that fit the queue with
            # headroom for live publishes — an uncapped replay would close
            # the subscription mid-replay on any cluster retaining more
            # frames than one queue, so index-less consumers (the UI)
            # could never reach the live tail
            cap = max(1, sub.max_queued - 1)
            trimmed_through = 0
            if len(replay) > cap:
                trimmed_through = replay[-cap - 1][0]
                replay = replay[-cap:]
            if from_index and (
                self._dropped_through > from_index or trimmed_through
            ):
                # an explicit resume lost part of its range (ring eviction
                # and/or replay trim): say so, never silently skip. A
                # fresh subscribe (from_index=0) makes no completeness
                # claim, so trims there stay silent.
                sub._offer_gap(
                    max(self._dropped_through, trimmed_through)
                )
            for index, events in replay:
                sub._offer(index, events)
            self._subs.append(sub)
        return sub

    def unsubscribe(self, sub: Subscription):
        with self._lock:
            if sub in self._subs:
                self._subs.remove(sub)

    # -- introspection --------------------------------------------------
    def oldest_index(self) -> int:
        """Oldest raft index still retained (resume floor)."""
        with self._lock:
            if self._frames:
                return self._frames[0][0]
            return self._latest_index

    def latest_index(self) -> int:
        with self._lock:
            return self._latest_index

    def stats(self) -> dict:
        with self._lock:
            return {
                "events_buffered": self._n_events,
                "events_published": self._published,
                "subscribers": len(self._subs),
                "slow_consumers_closed": self._closed_slow,
                "oldest_index": (
                    self._frames[0][0] if self._frames else self._latest_index
                ),
                "latest_index": self._latest_index,
            }

    def acl_changed(self):
        """ACL token/policy writes applied: close every token-backed
        subscription so its capabilities re-resolve on reconnect (ref
        event_broker.go closing subscriptions on ACL changes — a revoked
        token must not keep streaming until it disconnects by itself).
        Anonymous/ACL-off subscriptions (acl=None, in-proc consumers like
        the deployment watcher) are untouched."""
        with self._lock:
            affected = [s for s in self._subs if s.acl is not None]
            for sub in affected:
                self._subs.remove(sub)
            resume = self._resume_floor_locked()
        for sub in affected:
            sub._close("subscription closed: ACL change", resume)

    # -- lifecycle ------------------------------------------------------
    def reset(self, index: int):
        """Restore-path reset (FSM.restore): the ring is re-derivable
        state, so drop it and close live subscribers with the restored
        index as their resume point."""
        with self._lock:
            self._frames.clear()
            self._n_events = 0
            self._latest_index = index
            self._dropped_through = index
            subs, self._subs = self._subs, []
        for sub in subs:
            sub._close("event buffer reset (snapshot restore)", index)

    def shutdown(self):
        with self._lock:
            subs, self._subs = self._subs, []
            resume = self._resume_floor_locked()
        for sub in subs:
            sub._close("event broker shut down", resume)
