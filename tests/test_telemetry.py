"""Telemetry push-sink fan-out (ref command/agent/config.go:500-577: the
reference fans metrics out to statsite/statsd/datadog sinks on a
collection interval; pull via /v1/metrics remains primary)."""

import socket
import time

from nomad_tpu import metrics


def recv_lines(sock, deadline=5.0):
    sock.settimeout(deadline)
    lines = []
    try:
        data, _ = sock.recvfrom(65536)
        lines.extend(data.decode().split("\n"))
    except socket.timeout:
        pass
    return lines


class TestStatsdSink:
    def setup_method(self):
        metrics.reset()
        self.sock = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
        self.sock.bind(("127.0.0.1", 0))
        self.addr = f"127.0.0.1:{self.sock.getsockname()[1]}"

    def teardown_method(self):
        self.sock.close()
        metrics.reset()

    def test_counters_and_timers_reach_udp_listener(self):
        metrics.incr("plan.submitted", 3)
        metrics.sample("rpc.job_register", 0.012)
        sink = metrics.StatsdSink(self.addr)
        try:
            snap = metrics.snapshot()
            sink.emit(snap["counters"], snap["timers"])
            lines = recv_lines(self.sock)
            assert "nomad.plan.submitted:3|c" in lines
            assert any(
                l.startswith("nomad.rpc.job_register.mean:") and l.endswith("|ms")
                for l in lines
            )
            assert any(
                l.startswith("nomad.rpc.job_register.p99:") for l in lines
            )
        finally:
            sink.close()

    def test_counter_deltas_not_totals(self):
        sink = metrics.StatsdSink(self.addr)
        try:
            metrics.incr("evals.processed", 5)
            snap = metrics.snapshot()
            sink.emit(snap["counters"], {})
            assert "nomad.evals.processed:5|c" in recv_lines(self.sock)

            metrics.incr("evals.processed", 2)
            snap = metrics.snapshot()
            sink.emit(snap["counters"], {})
            # second flush carries only the delta, so the receiver's own
            # accumulation stays correct
            assert "nomad.evals.processed:2|c" in recv_lines(self.sock)

            # no change -> nothing emitted for that counter
            snap = metrics.snapshot()
            sink.emit(snap["counters"], {})
            assert not any(
                "evals.processed" in l for l in recv_lines(self.sock, 0.5)
            )
        finally:
            sink.close()

    def test_large_batches_split_under_mtu(self):
        for i in range(200):
            metrics.incr(f"bulk.counter_{i:03d}")
        sink = metrics.StatsdSink(self.addr)
        try:
            snap = metrics.snapshot()
            sink.emit(snap["counters"], {})
            got = set()
            self.sock.settimeout(2.0)
            try:
                while len(got) < 200:
                    data, _ = self.sock.recvfrom(65536)
                    assert len(data) <= metrics.StatsdSink.MAX_DATAGRAM
                    got.update(
                        l.split(":")[0] for l in data.decode().split("\n")
                    )
            except socket.timeout:
                pass
            assert len(got) == 200
        finally:
            sink.close()

    def test_configure_telemetry_flushes_on_interval(self):
        flusher = metrics.configure_telemetry(
            {"telemetry": {
                "statsd_address": self.addr,
                "collection_interval": 0.05,
            }}
        )
        assert flusher is not None
        try:
            metrics.incr("flusher.ticks", 7)
            deadline = time.monotonic() + 5
            seen = []
            while time.monotonic() < deadline:
                seen = recv_lines(self.sock, 1.0)
                if "nomad.flusher.ticks:7|c" in seen:
                    break
            assert "nomad.flusher.ticks:7|c" in seen, seen
        finally:
            flusher.stop()

    def test_configure_telemetry_absent_stanza_is_none(self):
        assert metrics.configure_telemetry({}) is None
        assert metrics.configure_telemetry({"telemetry": {}}) is None


class TestDogstatsdSink:
    """dogstatsd = statsd + |#k:v tag blocks (the go-metrics datadog
    sink role, selected by telemetry{datadog_address})."""

    def setup_method(self):
        metrics.reset()
        self.sock = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
        self.sock.bind(("127.0.0.1", 0))
        self.addr = f"127.0.0.1:{self.sock.getsockname()[1]}"

    def teardown_method(self):
        self.sock.close()
        metrics.reset()

    def test_tags_ride_every_line(self):
        metrics.incr("plan.submitted", 2)
        metrics.sample("rpc.plan", 0.004)
        sink = metrics.DogstatsdSink(
            self.addr, tags={"node": "n1", "region": "global"}
        )
        try:
            snap = metrics.snapshot()
            sink.emit(snap["counters"], snap["timers"])
            lines = [l for l in recv_lines(self.sock) if l]
            assert lines
            assert all(l.endswith("|#node:n1,region:global") for l in lines), lines
            assert "nomad.plan.submitted:2|c|#node:n1,region:global" in lines
        finally:
            sink.close()

    def test_no_tags_is_plain_statsd(self):
        metrics.incr("a.b", 1)
        sink = metrics.DogstatsdSink(self.addr)
        try:
            snap = metrics.snapshot()
            sink.emit(snap["counters"], snap["timers"])
            assert "nomad.a.b:1|c" in recv_lines(self.sock)
        finally:
            sink.close()

    def test_configured_from_stanza(self):
        flusher = metrics.configure_telemetry(
            {"telemetry": {
                "datadog_address": self.addr,
                "datadog_tags": ["dc:dc1"],
                "collection_interval": 0.05,
            }}
        )
        assert flusher is not None
        try:
            metrics.incr("dd.ticks", 3)
            deadline = time.monotonic() + 5
            while time.monotonic() < deadline:
                if "nomad.dd.ticks:3|c|#dc:dc1" in recv_lines(self.sock, 1.0):
                    return
            raise AssertionError("tagged metric never arrived")
        finally:
            flusher.stop()


class TestStatsiteSink:
    """statsite = the same line protocol over one persistent TCP
    connection (telemetry{statsite_address})."""

    def setup_method(self):
        metrics.reset()
        self.listener = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self.listener.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self.listener.bind(("127.0.0.1", 0))
        self.listener.listen(2)
        self.addr = f"127.0.0.1:{self.listener.getsockname()[1]}"

    def teardown_method(self):
        self.listener.close()
        metrics.reset()

    def _accept_lines(self, deadline=5.0):
        self.listener.settimeout(deadline)
        conn, _ = self.listener.accept()
        conn.settimeout(deadline)
        data = b""
        try:
            while not data.endswith(b"\n"):
                chunk = conn.recv(65536)
                if not chunk:
                    break
                data += chunk
        except socket.timeout:
            pass
        finally:
            conn.close()
        return data.decode().splitlines()

    def test_lines_reach_tcp_listener(self):
        metrics.incr("plan.submitted", 4)
        metrics.sample("rpc.plan", 0.002)
        sink = metrics.StatsiteSink(self.addr)
        try:
            snap = metrics.snapshot()
            sink.emit(snap["counters"], snap["timers"])
            lines = self._accept_lines()
            assert "nomad.plan.submitted:4|c" in lines
            assert any(
                l.startswith("nomad.rpc.plan.mean:") and l.endswith("|ms")
                for l in lines
            )
        finally:
            sink.close()

    def test_reconnects_after_receiver_restart(self):
        sink = metrics.StatsiteSink(self.addr)
        try:
            metrics.incr("s.ticks", 1)
            snap = metrics.snapshot()
            sink.emit(snap["counters"], {})
            assert "nomad.s.ticks:1|c" in self._accept_lines()
            # the receiver closed that connection. A write into the
            # half-closed socket may "succeed" before the RST arrives, so
            # flush until the sink notices and redials — it must land on
            # a fresh connection within a few attempts, never raise.
            for attempt in range(10):
                sink.emit({"s.reconnect": float(attempt + 1)}, {})
                try:
                    lines = self._accept_lines(0.5)
                except socket.timeout:
                    continue
                assert any(
                    l.startswith("nomad.s.reconnect:") for l in lines
                )
                return
            raise AssertionError("sink never redialed the receiver")
        finally:
            sink.close()

    def test_unreachable_receiver_never_raises(self):
        self.listener.close()
        sink = metrics.StatsiteSink(self.addr)
        try:
            metrics.incr("x.y", 1)
            snap = metrics.snapshot()
            sink.emit(snap["counters"], {})  # best-effort: swallows
        finally:
            sink.close()
