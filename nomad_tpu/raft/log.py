"""Durable raft log + stable store (ref: the reference persists its raft
log in raft-boltdb — SURVEY.md §2.9 BoltDB ledger row; dev mode uses an
in-memory store, nomad/server.go:105 raftInmem).

``FileLogStore`` is an append-only record log: each record is
``[u32 length][u32 crc32][msgpack payload]``. Torn tails from a crash are
detected by CRC and truncated on open. Compaction after a snapshot rewrites
the retained suffix into a fresh file. The stable store is a tiny
atomically-rewritten msgpack KV used for currentTerm/votedFor.
"""

from __future__ import annotations

import os
import struct
import threading
import zlib
from dataclasses import dataclass, field
from typing import Optional

import msgpack

# entry types
CMD = "cmd"  # FSM command: data = (msg_type, payload)
NOOP = "noop"  # leader-establishment barrier entry
CONFIG = "config"  # membership change: data = {"voters": {id: addr}}


@dataclass
class LogEntry:
    index: int
    term: int
    etype: str = CMD
    data: object = None

    def pack(self) -> bytes:
        return msgpack.packb(
            [self.index, self.term, self.etype, self.data], use_bin_type=True
        )

    @classmethod
    def unpack(cls, raw: bytes) -> "LogEntry":
        index, term, etype, data = msgpack.unpackb(raw, raw=False)
        return cls(index=index, term=term, etype=etype, data=data)


class InmemLogStore:
    """Dev-mode / test log store (ref raftInmem, server.go:105)."""

    def __init__(self):
        self._lock = threading.Lock()
        self._entries: dict[int, LogEntry] = {}
        self._first = 0
        self._last = 0

    def first_index(self) -> int:
        return self._first

    def last_index(self) -> int:
        return self._last

    def get(self, index: int) -> Optional[LogEntry]:
        with self._lock:
            return self._entries.get(index)

    def store_entries(self, entries: list[LogEntry]):
        with self._lock:
            for e in entries:
                self._entries[e.index] = e
                if self._first == 0:
                    self._first = e.index
                self._last = max(self._last, e.index)

    def delete_range(self, lo: int, hi: int):
        """Delete entries in [lo, hi] (conflict truncation or compaction)."""
        with self._lock:
            for i in range(lo, hi + 1):
                self._entries.pop(i, None)
            if not self._entries:
                self._first = self._last = 0
            else:
                self._first = min(self._entries)
                self._last = max(self._entries)


_REC_HDR = struct.Struct("<II")  # length, crc32


class FileLogStore:
    """Crash-safe append-only log file with CRC-framed records."""

    def __init__(self, path: str):
        self.path = path
        self._lock = threading.Lock()
        self._entries: dict[int, LogEntry] = {}
        self._first = 0
        self._last = 0
        os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
        self._replay()
        self._f = open(self.path, "ab")

    def _replay(self):
        if not os.path.exists(self.path):
            return
        good = 0
        with open(self.path, "rb") as f:
            while True:
                hdr = f.read(_REC_HDR.size)
                if len(hdr) < _REC_HDR.size:
                    break
                length, crc = _REC_HDR.unpack(hdr)
                payload = f.read(length)
                if len(payload) < length or zlib.crc32(payload) != crc:
                    break  # torn tail
                rec = msgpack.unpackb(payload, raw=False)
                if rec[0] == "entry":
                    e = LogEntry.unpack(rec[1])
                    self._entries[e.index] = e
                elif rec[0] == "truncate":  # logical delete_range marker
                    lo, hi = rec[1], rec[2]
                    for i in range(lo, hi + 1):
                        self._entries.pop(i, None)
                good = f.tell()
        # chop a torn tail so future appends are clean
        if os.path.getsize(self.path) > good:
            with open(self.path, "r+b") as f:
                f.truncate(good)
        if self._entries:
            self._first = min(self._entries)
            self._last = max(self._entries)

    def _append_record(self, rec) -> None:
        payload = msgpack.packb(rec, use_bin_type=True)
        self._f.write(_REC_HDR.pack(len(payload), zlib.crc32(payload)))
        self._f.write(payload)
        self._f.flush()
        os.fsync(self._f.fileno())

    def first_index(self) -> int:
        return self._first

    def last_index(self) -> int:
        return self._last

    def get(self, index: int) -> Optional[LogEntry]:
        with self._lock:
            return self._entries.get(index)

    def store_entries(self, entries: list[LogEntry]):
        with self._lock:
            for e in entries:
                self._append_record(["entry", e.pack()])
                self._entries[e.index] = e
                if self._first == 0:
                    self._first = e.index
                self._last = max(self._last, e.index)

    def delete_range(self, lo: int, hi: int):
        with self._lock:
            self._append_record(["truncate", lo, hi])
            for i in range(lo, hi + 1):
                self._entries.pop(i, None)
            if not self._entries:
                self._first = self._last = 0
            else:
                self._first = min(self._entries)
                self._last = max(self._entries)
            # rewrite when the file is mostly tombstones
            if len(self._entries) * 4 < (hi - lo + 1):
                self._compact_locked()

    def _compact_locked(self):
        tmp = self.path + ".tmp"
        self._f.close()
        with open(tmp, "wb") as f:
            for i in sorted(self._entries):
                payload = msgpack.packb(
                    ["entry", self._entries[i].pack()], use_bin_type=True
                )
                f.write(_REC_HDR.pack(len(payload), zlib.crc32(payload)))
                f.write(payload)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, self.path)
        self._f = open(self.path, "ab")

    def close(self):
        self._f.close()


class StableStore:
    """Atomically-rewritten msgpack KV for currentTerm/votedFor."""

    def __init__(self, path: Optional[str] = None):
        self.path = path
        # nta: ignore[unbounded-cache] WHY: the durable stable store
        # (currentTerm/votedFor); the key set is protocol-fixed
        self._data: dict = {}
        self._lock = threading.Lock()
        if path and os.path.exists(path):
            with open(path, "rb") as f:
                raw = f.read()
            if raw:
                self._data = msgpack.unpackb(raw, raw=False)

    def get(self, key: str, default=None):
        with self._lock:
            return self._data.get(key, default)

    def set(self, key: str, value):
        with self._lock:
            self._data[key] = value
            if self.path:
                tmp = self.path + ".tmp"
                with open(tmp, "wb") as f:
                    f.write(msgpack.packb(self._data, use_bin_type=True))
                    f.flush()
                    os.fsync(f.fileno())
                os.replace(tmp, self.path)

    def set_many(self, **kv):
        with self._lock:
            self._data.update(kv)
            if self.path:
                tmp = self.path + ".tmp"
                with open(tmp, "wb") as f:
                    f.write(msgpack.packb(self._data, use_bin_type=True))
                    f.flush()
                    os.fsync(f.fileno())
                os.replace(tmp, self.path)


@dataclass
class Snapshot:
    last_index: int
    last_term: int
    data: bytes
    voters: dict = field(default_factory=dict)


class SnapshotStore:
    """Retains the most recent FSM snapshots (ref snapshotsRetained=2,
    server.go:60). ``path=None`` keeps them in memory (dev mode)."""

    RETAIN = 2

    def __init__(self, path: Optional[str] = None):
        self.path = path
        self._mem: list[Snapshot] = []
        if path:
            os.makedirs(path, exist_ok=True)

    def save(self, snap: Snapshot):
        if self.path is None:
            self._mem.append(snap)
            self._mem = self._mem[-self.RETAIN:]
            return
        name = f"snap-{snap.last_index:020d}-{snap.last_term:010d}.bin"
        tmp = os.path.join(self.path, name + ".tmp")
        with open(tmp, "wb") as f:
            f.write(
                msgpack.packb(
                    {
                        "last_index": snap.last_index,
                        "last_term": snap.last_term,
                        "voters": snap.voters,
                        "data": snap.data,
                    },
                    use_bin_type=True,
                )
            )
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, os.path.join(self.path, name))
        snaps = sorted(os.listdir(self.path))
        for old in snaps[:-self.RETAIN]:
            os.unlink(os.path.join(self.path, old))

    def latest(self) -> Optional[Snapshot]:
        if self.path is None:
            return self._mem[-1] if self._mem else None
        snaps = sorted(
            n for n in os.listdir(self.path) if n.startswith("snap-")
        )
        if not snaps:
            return None
        with open(os.path.join(self.path, snaps[-1]), "rb") as f:
            d = msgpack.unpackb(f.read(), raw=False)
        return Snapshot(
            last_index=d["last_index"],
            last_term=d["last_term"],
            data=d["data"],
            voters=d.get("voters", {}),
        )
