"""Jobspec, HTTP API, and CLI tests (ref jobspec/parse_test.go,
command/agent/*_endpoint_test.go)."""

import json
import time

import pytest

from nomad_tpu.jobspec import parse_hcl, parse_job, parse_duration
from nomad_tpu.jobspec.hcl import HCLError


class TestHCL:
    def test_basic_types(self):
        out = parse_hcl(
            """
            str = "hello"
            num = 42
            fl = 1.5
            yes = true
            no = false
            list = ["a", "b"]
            obj { k = "v" }
            """
        )
        assert out == {
            "str": "hello",
            "num": 42,
            "fl": 1.5,
            "yes": True,
            "no": False,
            "list": ["a", "b"],
            "obj": {"k": "v"},
        }

    def test_labeled_blocks_nest(self):
        out = parse_hcl('job "a" { group "g" { count = 2 } }')
        assert out == {"job": {"a": {"group": {"g": {"count": 2}}}}}

    def test_repeated_blocks_become_lists(self):
        out = parse_hcl(
            """
            constraint { attribute = "x" }
            constraint { attribute = "y" }
            """
        )
        assert [c["attribute"] for c in out["constraint"]] == ["x", "y"]

    def test_comments_and_escapes(self):
        out = parse_hcl(
            """
            # comment
            // also comment
            /* block
               comment */
            v = "a\\"b\\nc"
            """
        )
        assert out["v"] == 'a"b\nc'

    def test_error_on_garbage(self):
        with pytest.raises(HCLError):
            parse_hcl("key = = =")

    def test_durations(self):
        assert parse_duration("30s") == 30 * 10**9
        assert parse_duration("10m") == 600 * 10**9
        assert parse_duration("1h30m") == 5400 * 10**9
        assert parse_duration("250ms") == 250 * 10**6
        with pytest.raises(HCLError):
            parse_duration("abc")


class TestJobspec:
    SPEC = """
    job "web" {
      datacenters = ["dc1", "dc2"]
      type = "service"
      priority = 70

      constraint {
        attribute = "${attr.kernel.name}"
        value = "linux"
      }

      group "frontend" {
        count = 3
        spread {
          attribute = "${node.datacenter}"
          weight = 100
          target "dc1" { percent = 60 }
          target "dc2" { percent = 40 }
        }
        task "nginx" {
          driver = "mock_driver"
          config { run_for = "10" }
          resources {
            cpu = 200
            memory = 128
            network {
              mbits = 5
              port "http" {}
            }
          }
        }
      }
    }
    """

    def test_parse(self):
        job = parse_job(self.SPEC)
        assert job.id == "web" and job.priority == 70
        assert job.datacenters == ["dc1", "dc2"]
        assert job.constraints[0].r_target == "linux"
        tg = job.task_groups[0]
        assert tg.count == 3
        assert tg.spreads[0].spread_target[1].percent == 40
        assert tg.tasks[0].resources.networks[0].dynamic_ports[0].label == "http"

    def test_parse_and_schedule(self):
        # parsed jobs flow through the scheduler unmodified
        from nomad_tpu import mock
        from nomad_tpu.scheduler import Harness
        from nomad_tpu.structs.model import Evaluation, generate_uuid

        job = parse_job(self.SPEC)
        # strip ports so the fast path handles it; constraint/spread kept
        job.task_groups[0].tasks[0].resources.networks = []
        h = Harness(seed=1)
        for i in range(4):
            n = mock.node()
            n.datacenter = "dc1" if i % 2 == 0 else "dc2"
            h.state.upsert_node(h.next_index(), n)
        h.state.upsert_job(h.next_index(), job)
        ev = Evaluation(
            id=generate_uuid(), namespace=job.namespace, priority=job.priority,
            type="service", triggered_by="job-register", job_id=job.id,
            status="pending",
        )
        h.state.upsert_evals(h.next_index(), [ev])
        h.process("service", ev)
        assert len(h.state.allocs_by_job(job.namespace, job.id)) == 3

    def test_multiple_jobs_rejected(self):
        with pytest.raises(HCLError):
            parse_job('job "a" {}\njob "b" {}')


@pytest.fixture(scope="module")
def http_cluster():
    from nomad_tpu.agent import DevAgent
    from nomad_tpu.api import ApiClient, HTTPServer

    agent = DevAgent(num_clients=1, server_config={"seed": 3})
    agent.start()
    http = HTTPServer(agent.server, port=0, agent=agent)
    http.start()
    client = ApiClient(address=http.address)
    yield agent, http, client
    http.stop()
    agent.stop()


class TestHTTPAPI:
    def test_register_and_query_job(self, http_cluster):
        agent, http, client = http_cluster
        job = parse_job(TestJobspec.SPEC)
        job.datacenters = ["dc1"]
        resp = client.register_job(job.to_dict())
        assert resp["EvalID"]

        deadline = time.time() + 10
        while time.time() < deadline:
            ev = client.evaluation(resp["EvalID"])
            if ev["status"] == "complete":
                break
            time.sleep(0.1)
        assert ev["status"] == "complete"

        jobs = client.jobs()
        assert any(j["ID"] == "web" for j in jobs)
        got = client.job("web")
        assert got["priority"] == 70
        allocs = client.job_allocations("web")
        assert len(allocs) == 3
        summary = client.job_summary("web")
        assert "frontend" in summary["summary"]

    def test_nodes_and_allocs(self, http_cluster):
        agent, http, client = http_cluster
        nodes = client.nodes()
        assert len(nodes) == 1
        node = client.node(nodes[0]["ID"][:8])  # prefix lookup
        assert node["status"] == "ready"
        allocs = client.allocations()
        if allocs:
            alloc = client.allocation(allocs[0]["ID"])
            assert alloc["id"] == allocs[0]["ID"]

    def test_404(self, http_cluster):
        from nomad_tpu.api import APIError

        _, _, client = http_cluster
        with pytest.raises(APIError) as e:
            client.job("nonexistent")
        assert e.value.status == 404

    def test_metrics_and_agent_self(self, http_cluster):
        _, _, client = http_cluster
        m = client.metrics()
        assert "broker" in m and "state_index" in m
        info = client.agent_self()
        assert info["member"]["Status"] == "alive"

    def test_encoded_child_job_id_resolves(self, http_cluster):
        """Derived child job IDs contain '/'; percent-encoded they must
        resolve through every /v1/job/:id route (ADVICE r1)."""
        _, _, client = http_cluster
        job = parse_job(TestJobspec.SPEC)
        job.id = job.name = "cron-parent"
        job.datacenters = ["dc1"]
        job.task_groups[0].count = 0
        from nomad_tpu.structs.model import PeriodicConfig

        # periodic requires a batch job (the ported job-endpoint
        # validation rejects periodic service jobs before raft)
        job.type = "batch"
        job.periodic = PeriodicConfig(enabled=True, spec="0 0 1 1 *")
        client.register_job(job.to_dict())
        out = client.job_periodic_force("cron-parent")
        child_id = out["DispatchedJobID"]
        assert "/" in child_id
        got = client.job(child_id)  # client percent-encodes the segment
        assert got["id"] == child_id
        assert client.job_summary(child_id) is not None
        assert client.job_allocations(child_id) == []

    def test_blocking_query_wakes(self, http_cluster):
        import threading

        agent, http, client = http_cluster
        idx = client.get("/v1/jobs")[1]
        results = []

        def blocked():
            jobs, new_idx = client.get("/v1/jobs", index=idx, wait="10s")
            results.append(new_idx)

        t = threading.Thread(target=blocked)
        t.start()
        time.sleep(0.2)
        job = parse_job(TestJobspec.SPEC)
        job.id = job.name = "wakeup-job"
        job.task_groups[0].count = 0
        client.register_job(job.to_dict())
        t.join(timeout=12)
        assert results and results[0] > idx


class TestCLI:
    def test_cli_against_http(self, http_cluster, capsys, tmp_path):
        from nomad_tpu.cli.main import main

        agent, http, client = http_cluster
        addr = ["-address", http.address]

        assert main(addr + ["job", "status"]) == 0
        out = capsys.readouterr().out
        assert "web" in out

        assert main(addr + ["node", "status"]) == 0
        out = capsys.readouterr().out
        assert "ready" in out

        spec = tmp_path / "test.nomad"
        assert main(["job", "init", str(spec)]) == 0
        capsys.readouterr()
        assert main(addr + ["job", "run", "-detach", str(spec)]) == 0
        out = capsys.readouterr().out
        assert "Evaluation" in out

        assert main(addr + ["job", "stop", "example"]) == 0
        capsys.readouterr()
        assert main(addr + ["version"]) == 0
