"""Structural job diff for the ``job plan`` dry-run surface
(ref nomad/structs/diff.go: Job.Diff/TaskGroupDiff/TaskDiff producing
Added/Deleted/Edited field and object trees rendered by the CLI).

The reference hand-writes per-struct Diff methods over ~2K lines; here one
recursive differ walks the dataclasses generically, producing the same
shape: {Type, Name, Fields: [...], Objects: [...], TaskGroups/Tasks} with
Type ∈ {Added, Deleted, Edited, None}. Bookkeeping fields that churn on
every write (indexes, status, submit time) are excluded like the
reference's diffable(false) tags."""

from __future__ import annotations

import json

from dataclasses import fields, is_dataclass
from typing import Any, Optional


def _canonical(v: Any) -> str:
    """Key-order-insensitive string form for free-form container values."""
    try:
        return json.dumps(v, sort_keys=True, default=str)
    except (TypeError, ValueError):
        return repr(v)

DIFF_TYPE_NONE = "None"
DIFF_TYPE_ADDED = "Added"
DIFF_TYPE_DELETED = "Deleted"
DIFF_TYPE_EDITED = "Edited"

#: fields never diffed (server bookkeeping; ref structs.go diff tags)
_EXCLUDED = {
    "create_index",
    "modify_index",
    "job_modify_index",
    "submit_time",
    "status",
    "status_description",
    "stable",
    "version",
    "computed_class",
    "status_updated_at",
    "events",
}


def _is_scalar(v: Any) -> bool:
    return isinstance(v, (str, int, float, bool)) or v is None


def _scalar_str(v: Any) -> str:
    if v is None:
        return ""
    if isinstance(v, bool):
        return "true" if v else "false"
    return str(v)


def _field_diff(name: str, old: Any, new: Any) -> Optional[dict]:
    old_s, new_s = _scalar_str(old), _scalar_str(new)
    if old_s == new_s:
        return None
    if old is None or old == "" and new_s:
        kind = DIFF_TYPE_ADDED
    elif new is None or new == "" and old_s:
        kind = DIFF_TYPE_DELETED
    else:
        kind = DIFF_TYPE_EDITED
    return {"Type": kind, "Name": name, "Old": old_s, "New": new_s}


def _object_name(v: Any, default: str) -> str:
    for attr in ("name", "id", "label", "l_target", "attribute"):
        val = getattr(v, attr, None)
        if val:
            return str(val)
    return default


def diff_objects(name: str, old: Any, new: Any) -> Optional[dict]:
    """Recursive diff of two dataclass instances (either may be None)."""
    if old is None and new is None:
        return None
    diff_type = DIFF_TYPE_EDITED
    if old is None:
        diff_type = DIFF_TYPE_ADDED
    elif new is None:
        diff_type = DIFF_TYPE_DELETED

    template = new if new is not None else old
    field_diffs: list[dict] = []
    object_diffs: list[dict] = []

    for f in fields(template):
        if f.name in _EXCLUDED or f.name.startswith("_"):
            continue
        ov = getattr(old, f.name, None) if old is not None else None
        nv = getattr(new, f.name, None) if new is not None else None

        if _is_scalar(ov) and _is_scalar(nv):
            d = _field_diff(f.name, ov, nv)
            if d:
                field_diffs.append(d)
        elif isinstance(ov, dict) or isinstance(nv, dict):
            ov = ov or {}
            nv = nv or {}
            for key in sorted(set(ov) | set(nv), key=str):
                a, b = ov.get(key), nv.get(key)
                if _is_scalar(a) and _is_scalar(b):
                    d = _field_diff(f"{f.name}[{key}]", a, b)
                    if d:
                        field_diffs.append(d)
                elif is_dataclass(a) or is_dataclass(b):
                    d = diff_objects(f"{f.name}[{key}]", a, b)
                    if d:
                        object_diffs.append(d)
                else:
                    # free-form container values (task config's nested
                    # lists/dicts — e.g. args): compare a canonical,
                    # key-order-insensitive serialization; recursing into
                    # fields() would blow up on non-dataclass values and
                    # repr() would flag reordered-but-equal dicts
                    d = _field_diff(
                        f"{f.name}[{key}]",
                        None if a is None else _canonical(a),
                        None if b is None else _canonical(b),
                    )
                    if d:
                        field_diffs.append(d)
        elif isinstance(ov, (list, tuple)) or isinstance(nv, (list, tuple)):
            object_diffs.extend(_diff_lists(f.name, ov or [], nv or []))
        elif is_dataclass(ov) or is_dataclass(nv):
            d = diff_objects(f.name, ov, nv)
            if d:
                object_diffs.append(d)

    if not field_diffs and not object_diffs and diff_type == DIFF_TYPE_EDITED:
        return None
    return {
        "Type": diff_type,
        "Name": name,
        "Fields": field_diffs,
        "Objects": object_diffs,
    }


def _diff_lists(name: str, old: list, new: list) -> list[dict]:
    """Lists pair by object name (constraints, affinities, networks...) or
    by position for scalar lists."""
    out: list[dict] = []
    if all(_is_scalar(v) for v in list(old) + list(new)):
        old_set = [_scalar_str(v) for v in old]
        new_set = [_scalar_str(v) for v in new]
        for v in old_set:
            if v not in new_set:
                out.append(
                    {
                        "Type": DIFF_TYPE_DELETED,
                        "Name": name,
                        "Fields": [
                            {"Type": DIFF_TYPE_DELETED, "Name": name, "Old": v, "New": ""}
                        ],
                        "Objects": [],
                    }
                )
        for v in new_set:
            if v not in old_set:
                out.append(
                    {
                        "Type": DIFF_TYPE_ADDED,
                        "Name": name,
                        "Fields": [
                            {"Type": DIFF_TYPE_ADDED, "Name": name, "Old": "", "New": v}
                        ],
                        "Objects": [],
                    }
                )
        return out

    def keyed(items):
        # duplicate display names (e.g. two constraints on one l_target)
        # get positional suffixes so neither is silently dropped; the
        # suffix order pairs k-th duplicate with k-th duplicate
        out = {}
        for i, v in enumerate(items):
            key = _object_name(v, f"{name}[{i}]")
            base, n = key, 2
            while key in out:
                key = f"{base} #{n}"
                n += 1
            out[key] = v
        return out

    old_by = keyed(old)
    new_by = keyed(new)
    for key in sorted(set(old_by) | set(new_by), key=str):
        d = diff_objects(f"{name} ({key})" if key else name, old_by.get(key), new_by.get(key))
        if d:
            out.append(d)
    return out


def job_diff(old, new) -> dict:
    """Top-level job diff (ref diff.go Job.Diff): job fields plus per-task-
    group diffs with nested task diffs."""
    diff_type = DIFF_TYPE_EDITED
    if old is None:
        diff_type = DIFF_TYPE_ADDED
    elif new is None:
        diff_type = DIFF_TYPE_DELETED

    template = new if new is not None else old
    base = diff_objects(template.id if template else "", old, new) or {
        "Type": DIFF_TYPE_NONE,
        "Name": template.id if template else "",
        "Fields": [],
        "Objects": [],
    }
    # task groups get their own section (the CLI renders them specially)
    base["Objects"] = [
        o for o in base["Objects"] if not o["Name"].startswith("task_groups")
    ]

    old_tgs = {tg.name: tg for tg in (old.task_groups if old else [])}
    new_tgs = {tg.name: tg for tg in (new.task_groups if new else [])}
    tg_diffs = []
    for tg_name in sorted(set(old_tgs) | set(new_tgs)):
        otg, ntg = old_tgs.get(tg_name), new_tgs.get(tg_name)
        d = diff_objects(tg_name, otg, ntg)
        if d is None:
            d = {
                "Type": DIFF_TYPE_NONE,
                "Name": tg_name,
                "Fields": [],
                "Objects": [],
            }
        # task diffs nested one level down, like TaskGroupDiff.Tasks
        d["Objects"] = [
            o for o in d.get("Objects", []) if not o["Name"].startswith("tasks")
        ]
        old_tasks = {t.name: t for t in (otg.tasks if otg else [])}
        new_tasks = {t.name: t for t in (ntg.tasks if ntg else [])}
        task_diffs = []
        for t_name in sorted(set(old_tasks) | set(new_tasks)):
            td = diff_objects(t_name, old_tasks.get(t_name), new_tasks.get(t_name))
            if td:
                task_diffs.append(td)
        d["Tasks"] = task_diffs
        if (
            d["Type"] == DIFF_TYPE_NONE
            and not d["Fields"]
            and not d["Objects"]
            and not task_diffs
        ):
            continue
        tg_diffs.append(d)
    base["TaskGroups"] = tg_diffs
    base["Type"] = (
        diff_type
        if old is None or new is None
        else (
            DIFF_TYPE_EDITED
            if base["Fields"] or base["Objects"] or tg_diffs
            else DIFF_TYPE_NONE
        )
    )
    return base
