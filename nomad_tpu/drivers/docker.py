"""Docker task driver (ref drivers/docker/driver.go + config.go), built on
the docker CLI rather than the engine API socket: run/wait/stop/kill/rm/
inspect cover the reference driver's container lifecycle, `docker logs -f`
feeds the task log files (the docklog companion's role), and recovery
re-attaches to a still-running container by name (RecoverTask).

Task config (the reference's taskConfigSpec surface, drivers/docker/
config.go; unknown keys are rejected like hclspec would):
  image            required
  command/args     override the image CMD
  entrypoint       override the image ENTRYPOINT (list)
  auth             {username, password, server_address} registry login
  force_pull       pull the image even when present
  load             image tarball (relative to the task dir) docker-load'd
  network_mode     --network value
  network_aliases  extra names on the container network
  ipv4_address / ipv6_address / mac_address / hostname
  port_map         {label: container_port} publish NetworkIndex ports
  volumes          ["host:container[:ro]", ...] (+ volume_driver)
  mounts           [{type: bind|volume|tmpfs, target, source, readonly}]
  devices          [{host_path, container_path, cgroup_permissions}]
  dns_servers / dns_search_domains / dns_options / extra_hosts
  privileged       requires plugin config allow_privileged
  cap_add/cap_drop capabilities, checked against plugin allow_caps
  ulimit           {name: "soft[:hard]"}
  sysctl           {key: value}
  security_opt / storage_opt
  pid_mode / ipc_mode / uts_mode / userns_mode
  readonly_rootfs / shm_size (bytes) / pids_limit
  cpu_hard_limit   CFS quota from resources.cpu (+ cpu_cfs_period)
  memory_hard_limit  MB; resources.memory_mb becomes the soft reservation
  work_dir / interactive / tty
  logging          {driver|type, config: {k: v}} → --log-driver/--log-opt
  labels           {k: v} container labels
"""

from __future__ import annotations

import os
import shutil
import subprocess
import threading
import time
import uuid

from ..client.driver import Driver, TaskHandle, task_log_dir
from ..structs.model import Task


class DockerConfigError(RuntimeError):
    """Invalid task config; surfaces as a task event via the runner's
    driver-failure path (ref drivers/docker/config.go validation)."""


#: the reference's default capability whitelist (drivers/docker/driver.go
#: nvidia-era defaults; linux defaults minus the risky ones)
DEFAULT_ALLOWED_CAPS = (
    "CHOWN,DAC_OVERRIDE,FSETID,FOWNER,MKNOD,NET_RAW,SETGID,SETUID,"
    "SETFCAP,SETPCAP,NET_BIND_SERVICE,SYS_CHROOT,KILL,AUDIT_WRITE"
)

#: every task-config key the builder understands; anything else is a
#: config error (the hclspec role: a typo'd stanza must not silently no-op)
_KNOWN_CONFIG_KEYS = {
    "image", "command", "args", "entrypoint", "auth", "force_pull", "load",
    "network_mode", "network_aliases", "ipv4_address", "ipv6_address",
    "mac_address", "hostname", "port_map", "volumes", "volume_driver",
    "mounts", "devices", "dns_servers", "dns_search_domains", "dns_options",
    "extra_hosts", "privileged", "cap_add", "cap_drop", "ulimit", "sysctl",
    "security_opt", "storage_opt", "pid_mode", "ipc_mode", "uts_mode",
    "userns_mode", "readonly_rootfs", "shm_size", "pids_limit",
    "cpu_hard_limit", "cpu_cfs_period", "memory_hard_limit", "work_dir",
    "interactive", "tty", "logging", "labels",
}


class ImageCoordinator:
    """Refcounted image pull + delayed GC (ref drivers/docker/
    coordinator.go:72-90): an image is pulled at most once no matter how
    many tasks reference it concurrently, and removed only after its last
    reference drops AND a grace delay elapses (a replacement task often
    reuses the image moments later)."""

    def __init__(self, driver: "DockerDriver", remove_delay: float = 180.0):
        self.driver = driver
        self.remove_delay = remove_delay
        self.cleanup = True
        self._lock = threading.Lock()
        self._refs: dict[str, set] = {}  # image -> container names
        self._pulls: dict[str, threading.Lock] = {}  # serialize per image
        self._timers: dict[str, threading.Timer] = {}

    def acquire(
        self,
        image: str,
        container: str,
        force_pull: bool = False,
        config_dir: str = "",
    ):
        """Reference an image, pulling it if absent (or force_pull). A
        pending delayed-delete for the image is cancelled."""
        while True:
            with self._lock:
                timer = self._timers.pop(image, None)
                pull_lock = self._pulls.setdefault(image, threading.Lock())
            if timer is not None:
                timer.cancel()
            with pull_lock:  # one puller; others wait and reuse
                with self._lock:
                    if self._pulls.get(image) is not pull_lock:
                        # _remove evicted this lock while we waited on it:
                        # later acquirers are serializing on a replacement,
                        # so first_ref bookkeeping under the stale lock
                        # could let them skip the presence check while we
                        # are still mid-pull. Start over on the live lock.
                        continue
                    refs = self._refs.setdefault(image, set())
                    first_ref = not refs
                    refs.add(container)
                need_pull = force_pull or (
                    first_ref and not self._present(image, config_dir)
                )
                if need_pull:
                    out = self.driver._run(
                        "pull", image, timeout=600, config_dir=config_dir
                    )
                    if out.returncode != 0:
                        self.release(image, container)
                        raise RuntimeError(
                            f"docker pull failed: {out.stderr.strip()}"
                        )
                return

    def _present(self, image: str, config_dir: str = "") -> bool:
        try:
            out = self.driver._run(
                "image", "inspect", image, timeout=30, config_dir=config_dir
            )
            return out.returncode == 0
        except (OSError, subprocess.TimeoutExpired):
            return False

    def release(self, image: str, container: str):
        """Drop a reference; the last one schedules the delayed delete."""
        with self._lock:
            refs = self._refs.get(image)
            if refs is None:
                return
            refs.discard(container)
            if refs or not self.cleanup:
                return
            # nta: ignore[thread-unnamed] WHY: Timer() takes no name
            # kwarg; named on the next line before start()
            timer = threading.Timer(self.remove_delay, self._remove, (image,))
            timer.name = "docker-image-remove-timer"
            timer.daemon = True
            self._timers[image] = timer
        timer.start()

    def _remove(self, image: str):
        # serialize with acquire() under the per-image pull lock: a timer
        # that already fired can't be cancelled, so without this a racing
        # acquire could pass its presence check right before the rmi lands
        # and the task's `docker run` would find no image
        with self._lock:
            self._timers.pop(image, None)
            pull_lock = self._pulls.setdefault(image, threading.Lock())
        with pull_lock:
            with self._lock:
                if self._refs.get(image):
                    return  # re-acquired during the delay
                self._refs.pop(image, None)
            try:
                self.driver._run("rmi", image, timeout=120)
            except (OSError, subprocess.TimeoutExpired):
                pass
            with self._lock:
                # the image is gone and unreferenced: drop its pull lock
                # too, or a long-lived client leaks one Lock per distinct
                # image ever pulled (the unbounded-cache class). A waiter
                # already blocked on this lock object detects the eviction
                # (identity check in acquire()) and restarts on the
                # replacement lock, so all acquirers stay serialized.
                if self._pulls.get(image) is pull_lock and not self._refs.get(
                    image
                ):
                    del self._pulls[image]


class DockerDriver(Driver):
    name = "docker"

    def __init__(self, binary: str = ""):
        super().__init__()
        self._docker = binary or shutil.which("docker")
        self._version = ""
        self._healthy = False
        if self._docker:
            self._version = self._probe_version()
            self._healthy = bool(self._version)
        self.coordinator = ImageCoordinator(self)

    def config_schema(self) -> dict:
        return {
            "image_gc_delay_s": {"type": "number", "default": 180},
            "image_cleanup": {"type": "bool", "default": True},
            # ref docker plugin config allow_privileged / allow_caps
            "allow_privileged": {"type": "bool", "default": False},
            "allow_caps": {"type": "string", "default": DEFAULT_ALLOWED_CAPS},
        }

    @staticmethod
    def task_config_spec() -> dict:
        """The docker TaskConfig as a typed hclspec tree (ref
        drivers/docker/driver.go taskConfigSpec, expressed through
        plugins/shared/hclspec/hcl_spec.proto node types): nested blocks
        for auth/mounts/devices/logging, typed maps for
        labels/sysctl/ulimit/port_map/storage_opt, string lists for the
        dns/caps surfaces. validate_task_config rejects a typo'd stanza
        with the failing field's full path before any image pull."""
        from ..plugins.hclspec import Attr, Block, BlockList

        return {
            "image": Attr("string", required=True),
            "command": Attr("string"),
            "args": Attr("list(string)"),
            "entrypoint": Attr("list(string)"),
            "work_dir": Attr("string"),
            "hostname": Attr("string"),
            "interactive": Attr("bool"),
            "tty": Attr("bool"),
            "force_pull": Attr("bool"),
            "load": Attr("string"),
            "privileged": Attr("bool"),
            "readonly_rootfs": Attr("bool"),
            "network_mode": Attr("string"),
            "network_aliases": Attr("list(string)"),
            "ipv4_address": Attr("string"),
            "ipv6_address": Attr("string"),
            "mac_address": Attr("string"),
            # namespace modes start_task consumes (docker.py:465-472; ref
            # drivers/docker/config.go:261-310) — validate_spec rejects
            # unknown keys, so omitting these failed previously-valid jobs
            "pid_mode": Attr("string"),
            "ipc_mode": Attr("string"),
            "uts_mode": Attr("string"),
            "userns_mode": Attr("string"),
            "memory_hard_limit": Attr("number"),
            "cpu_hard_limit": Attr("bool"),
            "cpu_cfs_period": Attr("number"),
            "pids_limit": Attr("number"),
            "shm_size": Attr("number"),
            "volume_driver": Attr("string"),
            "volumes": Attr("list(string)"),
            "extra_hosts": Attr("list(string)"),
            "dns_servers": Attr("list(string)"),
            "dns_search_domains": Attr("list(string)"),
            "dns_options": Attr("list(string)"),
            "security_opt": Attr("list(string)"),
            "cap_add": Attr("list(string)"),
            "cap_drop": Attr("list(string)"),
            "labels": Attr("map(string)"),
            "sysctl": Attr("map(string)"),
            "ulimit": Attr("map(string)"),
            "port_map": Attr("map(number)"),
            "storage_opt": Attr("map(string)"),
            "auth": Block({
                "username": Attr("string"),
                "password": Attr("string"),
                "email": Attr("string"),
                "server_address": Attr("string"),
            }),
            "logging": Block({
                "type": Attr("string"),
                "driver": Attr("string"),
                "config": Attr("map(string)"),
            }),
            "mounts": BlockList({
                "type": Attr("string"),
                "target": Attr("string"),
                "source": Attr("string"),
                "readonly": Attr("bool"),
                "volume_options": Block({
                    "no_copy": Attr("bool"),
                    "labels": Attr("map(string)"),
                    "driver_config": Block({
                        "name": Attr("string"),
                        "options": Attr("map(string)"),
                    }),
                }),
                "bind_options": Block({
                    "propagation": Attr("string"),
                }),
                "tmpfs_options": Block({
                    "size": Attr("number"),
                    "mode": Attr("number"),
                }),
            }),
            "devices": BlockList({
                "host_path": Attr("string", required=True),
                "container_path": Attr("string"),
                "cgroup_permissions": Attr("string"),
            }),
        }

    def validate_task_config(self, cfg: dict) -> dict:
        from ..plugins.hclspec import SpecError, validate_spec

        try:
            return validate_spec(self.task_config_spec(), cfg or {})
        except SpecError as e:
            raise DockerConfigError(f"docker task {e}") from e

    def set_config(self, config: dict):
        super().set_config(config)
        if "image_gc_delay_s" in config:
            self.coordinator.remove_delay = float(config["image_gc_delay_s"])
        if "image_cleanup" in config:
            self.coordinator.cleanup = bool(config["image_cleanup"])

    def _run(
        self, *args, timeout: float = 60.0, config_dir: str = ""
    ) -> subprocess.CompletedProcess:
        argv = [self._docker]
        if config_dir:
            argv += ["--config", config_dir]
        return subprocess.run(
            argv + list(args),
            capture_output=True,
            text=True,
            timeout=timeout,
        )

    def _auth_config_dir(self, auth: dict, task_dir: str) -> str:
        """Materialize a docker CLI config with registry credentials for
        this task (ref docker driver auth options: the reference passes
        auth per pull via the engine API; the CLI equivalent is a private
        --config dir under the task's secrets)."""
        import base64
        import json as json_mod

        server = str(auth.get("server_address", "https://index.docker.io/v1/"))
        userpass = f"{auth.get('username', '')}:{auth.get('password', '')}"
        cfg_dir = os.path.join(task_dir or ".", "secrets", "docker")
        os.makedirs(cfg_dir, exist_ok=True)
        with open(os.path.join(cfg_dir, "config.json"), "w") as f:
            json_mod.dump(
                {
                    "auths": {
                        server: {
                            "auth": base64.b64encode(
                                userpass.encode()
                            ).decode()
                        }
                    }
                },
                f,
            )
        try:
            os.chmod(os.path.join(cfg_dir, "config.json"), 0o600)
        except OSError:
            pass
        return cfg_dir

    def _probe_version(self) -> str:
        """Engine (server) version; empty when the daemon is unreachable —
        the CLI alone doesn't make the driver healthy (ref docker
        fingerprint's dockerd connectivity check)."""
        try:
            out = self._run(
                "version", "--format", "{{.Server.Version}}", timeout=10
            )
            if out.returncode == 0:
                return out.stdout.strip()
        except (OSError, subprocess.TimeoutExpired):
            pass
        return ""

    def fingerprint(self) -> dict:
        attrs = {}
        if self._healthy:
            attrs["driver.docker.version"] = self._version
        return {
            "detected": bool(self._docker),
            "healthy": self._healthy,
            "attributes": attrs,
        }

    # ------------------------------------------------------------------
    def start_task(self, task: Task, task_dir: str) -> TaskHandle:
        if not self._healthy:
            raise RuntimeError("docker daemon is not available on this node")
        # typed-spec decode FIRST: a typo'd or mistyped stanza fails with
        # the field's full path before any image pull is paid
        cfg = self.validate_task_config(task.config or {})
        image = cfg.get("image")
        if not image:
            raise RuntimeError("docker requires an image")
        container = f"nomad-{task.name}-{uuid.uuid4().hex[:8]}"

        # config validation FIRST: a typo'd stanza must fail before any
        # image pull is paid or a coordinator reference is taken
        argv = self._container_args(task, cfg, container, task_dir)

        # registry auth (task config auth{}) rides a task-private CLI
        # config; the refcounted coordinator pulls each image at most once
        # and GCs it after the last reference + delay
        config_dir = ""
        if cfg.get("auth"):
            config_dir = self._auth_config_dir(dict(cfg["auth"]), task_dir)
        if cfg.get("load"):
            # image arrives as a tarball in the task dir (artifact stanza),
            # not from a registry (config.go `load`)
            tar = os.path.join(task_dir or ".", str(cfg["load"]))
            out = self._run("load", "-i", tar, timeout=600)
            if out.returncode != 0:
                raise DockerConfigError(
                    f"docker load {cfg['load']!r} failed: {out.stderr.strip()}"
                )
        self.coordinator.acquire(
            image,
            container,
            force_pull=bool(cfg.get("force_pull")),
            config_dir=config_dir,
        )

        try:
            out = self._run(*argv, timeout=600, config_dir=config_dir)
        except (OSError, subprocess.TimeoutExpired):
            self.coordinator.release(image, container)
            raise
        if out.returncode != 0:
            self.coordinator.release(image, container)
            raise RuntimeError(f"docker run failed: {out.stderr.strip()}")

        handle = TaskHandle(
            task_name=task.name, driver=self.name, started_at=time.time_ns()
        )
        handle._container = container
        handle._image = image
        self._supervise(handle, container, task_dir)
        return handle

    def _container_args(
        self, task: Task, cfg: dict, container: str, task_dir: str
    ) -> list:
        """`docker run` argv for the task's full container-config surface
        (ref drivers/docker/config.go taskConfigSpec → driver.go
        createContainerConfig). Config errors raise DockerConfigError,
        which the task runner records as a driver-failure task event."""
        unknown = set(cfg) - _KNOWN_CONFIG_KEYS
        if unknown:
            raise DockerConfigError(
                f"unknown docker config keys: {', '.join(sorted(unknown))}"
            )

        argv = ["run", "-d", "--name", container]

        # -- resources (driver.go memory/cpu wiring) --------------------
        hard_mb = cfg.get("memory_hard_limit")
        if hard_mb:
            if task.resources.memory_mb and int(hard_mb) < task.resources.memory_mb:
                raise DockerConfigError(
                    f"memory_hard_limit ({hard_mb}MB) must be at least the "
                    f"task's memory reservation ({task.resources.memory_mb}MB)"
                )
            argv += ["--memory", f"{int(hard_mb)}m"]
            if task.resources.memory_mb:
                argv += [
                    "--memory-reservation", f"{task.resources.memory_mb}m"
                ]
        elif task.resources.memory_mb:
            argv += ["--memory", f"{task.resources.memory_mb}m"]
        if task.resources.cpu:
            argv += ["--cpu-shares", str(task.resources.cpu)]
        if cfg.get("cpu_hard_limit"):
            # CFS quota from the task's MHz share (driver.go cpu_hard_limit:
            # quota = period * cpu / node_mhz is engine-side; the CLI path
            # uses the same period knob with quota scaled by shares/1024)
            period = int(cfg.get("cpu_cfs_period", 100000))
            if not 1000 <= period <= 1000000:
                raise DockerConfigError(
                    "cpu_cfs_period must be in [1000, 1000000]"
                )
            quota = max(int(period * task.resources.cpu / 1024), 1000)
            argv += ["--cpu-period", str(period), "--cpu-quota", str(quota)]
        if cfg.get("pids_limit"):
            argv += ["--pids-limit", str(int(cfg["pids_limit"]))]
        if cfg.get("shm_size"):
            argv += ["--shm-size", str(int(cfg["shm_size"]))]

        # -- identity / namespaces --------------------------------------
        if cfg.get("hostname"):
            argv += ["--hostname", str(cfg["hostname"])]
        if cfg.get("mac_address"):
            argv += ["--mac-address", str(cfg["mac_address"])]
        for key, flag in (
            ("pid_mode", "--pid"),
            ("ipc_mode", "--ipc"),
            ("uts_mode", "--uts"),
            ("userns_mode", "--userns"),
        ):
            if cfg.get(key):
                argv += [flag, str(cfg[key])]
        if task.user:
            argv += ["--user", str(task.user)]
        if cfg.get("work_dir"):
            argv += ["--workdir", str(cfg["work_dir"])]

        # -- privilege / capabilities (gated by plugin config) ----------
        if cfg.get("privileged"):
            if not self.plugin_config.get("allow_privileged", False):
                raise DockerConfigError(
                    "privileged containers are disabled on this node "
                    "(plugin config allow_privileged)"
                )
            argv += ["--privileged"]
        allowed = {
            c.strip().upper()
            for c in str(
                self.plugin_config.get("allow_caps", DEFAULT_ALLOWED_CAPS)
            ).split(",")
            if c.strip()
        }
        for cap in cfg.get("cap_add") or []:
            cap_u = str(cap).upper()
            if "ALL" not in allowed and cap_u not in allowed:
                raise DockerConfigError(
                    f"cap_add {cap_u} is not in the allowed capability list"
                )
            argv += ["--cap-add", cap_u]
        for cap in cfg.get("cap_drop") or []:
            argv += ["--cap-drop", str(cap).upper()]
        for opt in cfg.get("security_opt") or []:
            argv += ["--security-opt", str(opt)]
        for k, v in (cfg.get("storage_opt") or {}).items():
            argv += ["--storage-opt", f"{k}={v}"]
        if cfg.get("readonly_rootfs"):
            argv += ["--read-only"]
        for k, v in (cfg.get("sysctl") or {}).items():
            argv += ["--sysctl", f"{k}={v}"]
        for name, lim in (cfg.get("ulimit") or {}).items():
            lim = str(lim)
            try:
                # negatives are legal (-1 = unlimited, e.g. memlock)
                parts = [int(p) for p in lim.split(":")]
            except ValueError:
                parts = []
            if not 1 <= len(parts) <= 2:
                raise DockerConfigError(
                    f"ulimit {name} must be 'soft[:hard]' numbers, got {lim!r}"
                )
            argv += ["--ulimit", f"{name}={lim}"]

        # -- networking -------------------------------------------------
        if cfg.get("network_mode"):
            argv += ["--network", str(cfg["network_mode"])]
        for alias in cfg.get("network_aliases") or []:
            argv += ["--network-alias", str(alias)]
        if cfg.get("ipv4_address"):
            argv += ["--ip", str(cfg["ipv4_address"])]
        if cfg.get("ipv6_address"):
            argv += ["--ip6", str(cfg["ipv6_address"])]
        for server in cfg.get("dns_servers") or []:
            argv += ["--dns", str(server)]
        for domain in cfg.get("dns_search_domains") or []:
            argv += ["--dns-search", str(domain)]
        for opt in cfg.get("dns_options") or []:
            argv += ["--dns-option", str(opt)]
        for host in cfg.get("extra_hosts") or []:
            if ":" not in str(host):
                raise DockerConfigError(
                    f"extra_hosts entry {host!r} must be 'hostname:ip'"
                )
            argv += ["--add-host", str(host)]

        # port publishing: task port labels → container ports (the
        # reference's port_map + publishedPorts; host ports come from
        # NetworkIndex's per-node assignment, never from the jobspec)
        port_map = cfg.get("port_map") or {}
        ports = {}
        for net in task.resources.networks:
            for p in list(net.reserved_ports) + list(net.dynamic_ports):
                ports[p.label] = p.value
        for label, container_port in port_map.items():
            host_port = ports.get(label)
            if host_port is None:
                raise DockerConfigError(
                    f"port_map references undeclared port label {label!r}"
                )
            if not host_port:
                # an unassigned dynamic port (value 0) would let docker
                # bind an arbitrary host port Nomad doesn't advertise
                raise DockerConfigError(
                    f"port label {label!r} has no assigned host port"
                )
            argv += ["-p", f"{host_port}:{container_port}"]

        # -- storage ----------------------------------------------------
        for volume in cfg.get("volumes") or []:
            argv += ["-v", str(volume)]
        if cfg.get("volume_driver"):
            argv += ["--volume-driver", str(cfg["volume_driver"])]
        for m in cfg.get("mounts") or []:
            m = dict(m or {})
            mtype = str(m.get("type", "volume"))
            if mtype not in ("bind", "volume", "tmpfs"):
                raise DockerConfigError(
                    f"mount type {mtype!r} must be bind|volume|tmpfs"
                )
            target = m.get("target")
            if not target:
                raise DockerConfigError("mount requires a target")
            parts = [f"type={mtype}", f"target={target}"]
            if m.get("source"):
                parts.append(f"source={m['source']}")
            elif mtype == "bind":
                raise DockerConfigError("bind mount requires a source")
            if m.get("readonly"):
                parts.append("readonly")
            argv += ["--mount", ",".join(parts)]
        for d in cfg.get("devices") or []:
            d = dict(d or {})
            host_path = d.get("host_path")
            if not host_path:
                raise DockerConfigError("device requires host_path")
            # docker's spec is host[:container[:perms]]; permissions
            # require the container path, which defaults to the host path
            # (a requested permission must never silently widen to rwm)
            container_path = d.get("container_path") or (
                str(host_path) if d.get("cgroup_permissions") else ""
            )
            spec = str(host_path)
            if container_path:
                spec += f":{container_path}"
                if d.get("cgroup_permissions"):
                    perms = str(d["cgroup_permissions"])
                    if not (perms and set(perms) <= set("rwm")):
                        raise DockerConfigError(
                            f"device cgroup_permissions {perms!r} must be "
                            "drawn from 'rwm'"
                        )
                    spec += f":{perms}"
            argv += ["--device", spec]

        # -- logging / misc ---------------------------------------------
        logging_cfg = cfg.get("logging") or {}
        log_driver = logging_cfg.get("driver") or logging_cfg.get("type")
        if log_driver:
            argv += ["--log-driver", str(log_driver)]
            for k, v in (logging_cfg.get("config") or {}).items():
                argv += ["--log-opt", f"{k}={v}"]
        for k, v in (cfg.get("labels") or {}).items():
            argv += ["--label", f"{k}={v}"]
        if cfg.get("interactive"):
            argv += ["-i"]
        if cfg.get("tty"):
            argv += ["-t"]
        for k, v in (task.env or {}).items():
            argv += ["-e", f"{k}={v}"]

        # --entrypoint takes one binary; extra entrypoint elements become
        # the leading container args (the CLI shape of config.go's list)
        entrypoint = cfg.get("entrypoint")
        ep_rest: list = []
        if entrypoint:
            if isinstance(entrypoint, str):
                entrypoint = [entrypoint]
            argv += ["--entrypoint", str(entrypoint[0])]
            ep_rest = [str(e) for e in entrypoint[1:]]

        argv.append(str(cfg["image"]))
        argv += ep_rest
        if cfg.get("command"):
            argv.append(str(cfg["command"]))
        argv += [str(a) for a in cfg.get("args", [])]
        return argv

    def _supervise(self, handle: TaskHandle, container: str, task_dir: str):
        """Wait for exit + follow logs into the task log files (the
        docklog companion process's role, drivers/docker/docklog/)."""
        if task_dir:
            log_dir = task_log_dir(task_dir)
            os.makedirs(log_dir, exist_ok=True)
            stdout = open(
                os.path.join(log_dir, f"{handle.task_name}.stdout.0"), "ab"
            )
            stderr = open(
                os.path.join(log_dir, f"{handle.task_name}.stderr.0"), "ab"
            )
            try:
                follower = subprocess.Popen(
                    [self._docker, "logs", "-f", container],
                    stdout=stdout,
                    stderr=stderr,
                )
                handle._log_follower = follower
            except OSError:
                pass
            finally:
                stdout.close()
                stderr.close()

        def waiter():
            code = 130
            try:
                out = subprocess.run(
                    [self._docker, "wait", container],
                    capture_output=True,
                    text=True,
                )
                if out.returncode == 0:
                    code = int(out.stdout.strip().splitlines()[-1])
            except (OSError, ValueError, IndexError):
                pass
            follower = getattr(handle, "_log_follower", None)
            if follower is not None and follower.poll() is None:
                try:
                    follower.terminate()
                except OSError:
                    pass
            if not handle._done.is_set():
                handle.finish(code)

        threading.Thread(
            target=waiter, daemon=True, name="docker-exec-waiter"
        ).start()

    # ------------------------------------------------------------------
    def stop_task(self, handle: TaskHandle, timeout: float = 5.0,
                  signal_name: str = ""):
        container = getattr(handle, "_container", None)
        if container is None or handle._done.is_set():
            return
        try:
            if signal_name:
                # custom kill_signal first; docker stop's escalation
                # window then delivers SIGKILL if the task lingers
                name = str(signal_name).upper()
                if not name.startswith("SIG"):
                    name = "SIG" + name
                self._run("kill", "--signal", name, container, timeout=30)
                if handle.wait(timeout):
                    return
            out = self._run(
                "stop", "-t", str(int(timeout)), container,
                timeout=timeout + 30,
            )
            if out.returncode != 0 and not handle._done.is_set():
                # a wedged container must be LOUD (VERDICT r2 weak #7): the
                # runner records this as a task event instead of leaking
                # the container silently
                raise RuntimeError(
                    f"docker stop {container} failed: {out.stderr.strip()}"
                )
        except (OSError, subprocess.TimeoutExpired) as e:
            raise RuntimeError(f"docker stop {container} failed: {e}") from e

    def destroy_task(self, handle: TaskHandle):
        container = getattr(handle, "_container", None)
        if container is None:
            return
        try:
            out = self._run("rm", "-f", container, timeout=60)
            if out.returncode != 0 and "No such container" not in out.stderr:
                raise RuntimeError(
                    f"docker rm {container} failed: {out.stderr.strip()}"
                )
        except (OSError, subprocess.TimeoutExpired) as e:
            raise RuntimeError(f"docker rm {container} failed: {e}") from e
        finally:
            image = getattr(handle, "_image", None)
            if image:
                self.coordinator.release(image, container)

    def signal_task(self, handle: TaskHandle, signal_name: str):
        container = getattr(handle, "_container", None)
        if container is None or handle._done.is_set():
            raise ValueError("task is not running")
        name = str(signal_name).upper()
        if not name.startswith("SIG"):
            name = "SIG" + name
        out = self._run("kill", "--signal", name, container, timeout=30)
        if out.returncode != 0:
            raise ValueError(f"docker kill failed: {out.stderr.strip()}")

    def exec_streaming(self, handle: TaskHandle, cmd: list, tty: bool = False,
                       task_dir: str = "", env=None):
        """Exec inside the container (`docker exec`, the in-context path
        the reference drives via the docker API's exec endpoints,
        drivers/docker/driver.go ExecTaskStreaming)."""
        from ..client.execstream import ExecProcess

        container = getattr(handle, "_container", None)
        if container is None or handle._done.is_set():
            raise ValueError("task is not running")
        argv = [self._docker, "exec", "-i"]
        if tty:
            argv.append("-t")
        argv += [container] + list(cmd)
        return ExecProcess(argv, tty=tty)

    def task_stats(self, handle: TaskHandle) -> dict:
        """Container stats via `docker stats --no-stream` (the driver's
        own stats source, ref drivers/docker/stats.go — container
        processes are containerd's children, not ours, so the pid-tree
        default sees nothing)."""
        import json as json_mod
        import time as time_mod

        usage = {
            "cpu_time_s": 0.0,
            "cpu_percent": 0.0,
            "rss_bytes": 0,
            "pids": 0,
            "timestamp": time_mod.time_ns(),
        }
        container = getattr(handle, "_container", None)
        if container is None or handle._done.is_set():
            return usage
        try:
            out = self._run(
                "stats", "--no-stream", "--format", "{{json .}}", container,
                timeout=30,
            )
        except (OSError, subprocess.TimeoutExpired):
            return usage
        if out.returncode != 0:
            return usage
        try:
            doc = json_mod.loads(out.stdout.strip().splitlines()[-1])
        except (json_mod.JSONDecodeError, IndexError):
            return usage
        usage["cpu_percent"] = _parse_percent(doc.get("CPUPerc", "0%"))
        usage["rss_bytes"] = _parse_size(
            (doc.get("MemUsage", "0B / 0B").split("/") or ["0B"])[0]
        )
        try:
            usage["pids"] = int(doc.get("PIDs", 0))
        except (TypeError, ValueError):
            pass
        return usage

    def inspect_task(self, handle: TaskHandle) -> dict:
        base = super().inspect_task(handle)
        base["container"] = getattr(handle, "_container", None)
        return base

    # -- recovery (ref docker RecoverTask by reattaching to the container)
    def handle_data(self, handle: TaskHandle) -> dict:
        return {
            "driver": self.name,
            "task_name": handle.task_name,
            "container": getattr(handle, "_container", None),
            "started_at": handle.started_at,
        }

    def recover_task(self, task: Task, data: dict):
        container = data.get("container")
        if not container or not self._healthy:
            return None
        try:
            out = self._run(
                "inspect", "--format", "{{.State.Running}}", container,
                timeout=30,
            )
        except (OSError, subprocess.TimeoutExpired):
            return None
        if out.returncode != 0 or out.stdout.strip() != "true":
            return None
        handle = TaskHandle(
            task_name=task.name,
            driver=self.name,
            started_at=int(data.get("started_at", 0)),
            recovered=True,
        )
        handle._container = container
        self._supervise(handle, container, "")
        return handle


def _parse_percent(text: str) -> float:
    try:
        return float(str(text).strip().rstrip("%"))
    except ValueError:
        return 0.0


def _parse_size(text: str) -> int:
    """'12.3MiB' → bytes (docker stats human units)."""
    units = {
        "b": 1,
        "kb": 1000, "kib": 1024,
        "mb": 1000**2, "mib": 1024**2,
        "gb": 1000**3, "gib": 1024**3,
        "tb": 1000**4, "tib": 1024**4,
    }
    t = str(text).strip().lower()
    for suffix in sorted(units, key=len, reverse=True):
        if t.endswith(suffix):
            try:
                return int(float(t[: -len(suffix)]) * units[suffix])
            except ValueError:
                return 0
    try:
        return int(float(t))
    except ValueError:
        return 0
