"""JAX hot-path hygiene checkers.

The device plane (``nomad_tpu/tpu/``) lives or dies on two invariants:
jit'd code must stay pure and device-resident (a stray ``float()`` or
``np.asarray`` on a tracer forces a host sync in the middle of the fused
scan), and every shape reaching a compiled entry point must round
through the ONE padding policy (``batch_sched._bucket``) — the warmup
ladder once compiled shape 51200 while production padded the 50K-alloc
headline to 50176, so the prewarmed program was never the one that ran.

Rules:

- ``jit-host-sync`` — inside jit-compiled code: ``.item()``,
  ``np.asarray``/``np.array``, or ``float()``/``int()``/``bool()`` on a
  non-constant, non-static argument (static_argnums parameters are
  compile-time Python values and exempt);
- ``jit-impure-call`` — ``time.time``/``monotonic``/``perf_counter``,
  ``random.*`` or ``np.random.*`` reachable inside jit'd code (traced
  once at compile time: the "randomness" freezes into the program);
- ``device-put-in-loop`` — ``device_put`` lexically inside a
  ``for``/``while`` body (one transfer per iteration; batch it);
- ``shape-literal-unbucketed`` — an integer literal ≥ 1024 used directly
  as a dimension in an array constructor or ``.lower()`` call in
  ``tpu/`` without rounding through ``_bucket``/``bucket_shape``;
- ``tile-shape-unbucketed`` — inside tile/paged code in ``tpu/``, an
  integer literal ≥ 64 used as an array/``.lower()`` dimension without
  rounding through ``tile_rows`` (the paged planner's tile-bucket
  policy): a literal bypasses the power-of-two + mesh-multiple
  rounding, so the compiled tile program misses the production bucket;
- ``jit-shape-unbucketed`` — a locally-computed size (from ``len()``,
  arithmetic, or a literal) passed to a known jit entry point without
  rounding through ``_bucket`` (deliberate static args get a suppression
  with a WHY);
- ``transfer-uncounted`` — a raw ``device_put`` in ``tpu/`` that does
  not route through the counted wrapper (``devprof.device_put``): the
  devprof h2d transfer ledger is only trustworthy if EVERY placement
  site feeds it, and item 2's dispatch-path rewrite will mint new ones.
"""

from __future__ import annotations

import ast
from typing import Iterable, Optional

from .framework import Finding, ModuleInfo, Project, dotted, register

#: names that mark an expression as rounded through the padding policy
#: (tile_rows is the paged planner's tile-bucket policy, tpu/paging.py)
_BUCKET_FNS = {"_bucket", "bucket_shape", "_row_bucket", "tile_rows"}

_ARRAY_CTORS = {"zeros", "ones", "full", "empty", "tile", "arange"}

_IMPURE = {
    "time.time",
    "time.monotonic",
    "time.perf_counter",
    "time.time_ns",
}


def _jit_functions(mod: ModuleInfo) -> list[ast.AST]:
    """Function defs compiled by jax.jit in this module: decorated defs
    (``@jax.jit``, ``@partial(jax.jit, ...)``/``@functools.partial``),
    defs wrapped by ``name = jax.jit(f)``, and lambdas passed straight
    to ``jax.jit(...)``."""
    out = []
    wrapped_names = set()
    for node in ast.walk(mod.tree):
        if isinstance(node, ast.Call) and _is_jit_call(node):
            for arg in node.args:
                if isinstance(arg, ast.Lambda):
                    out.append(arg)
                elif isinstance(arg, ast.Name):
                    wrapped_names.add(arg.id)
    for node in ast.walk(mod.tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            if node.name in wrapped_names or any(
                _is_jit_decorator(d) for d in node.decorator_list
            ):
                out.append(node)
    return out


def _is_jit_call(node: ast.Call) -> bool:
    name = dotted(node.func)
    return name in ("jax.jit", "jit") or (
        name in ("functools.partial", "partial")
        and node.args
        and dotted(node.args[0]) in ("jax.jit", "jit")
    )


def _is_jit_decorator(dec: ast.AST) -> bool:
    if isinstance(dec, ast.Call):
        return _is_jit_call(dec)
    return dotted(dec) in ("jax.jit", "jit")


def _static_params(fn: ast.AST) -> set[str]:
    """Parameter names marked static via static_argnums/static_argnames
    on the jit decorator — plain Python values at trace time, exempt
    from host-sync rules."""
    if not isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
        return set()
    params = [a.arg for a in fn.args.args]
    static: set[str] = set()
    for dec in fn.decorator_list:
        if not (isinstance(dec, ast.Call) and _is_jit_call(dec)):
            continue
        for kw in dec.keywords:
            if kw.arg == "static_argnums":
                for el in _int_elements(kw.value):
                    if 0 <= el < len(params):
                        static.add(params[el])
            elif kw.arg == "static_argnames":
                for el in _str_elements(kw.value):
                    static.add(el)
    return static


def _int_elements(node: ast.AST) -> Iterable[int]:
    if isinstance(node, ast.Constant) and isinstance(node.value, int):
        yield node.value
    elif isinstance(node, (ast.Tuple, ast.List)):
        for el in node.elts:
            yield from _int_elements(el)


def _str_elements(node: ast.AST) -> Iterable[str]:
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        yield node.value
    elif isinstance(node, (ast.Tuple, ast.List)):
        for el in node.elts:
            yield from _str_elements(el)


@register(
    "jit-host-sync",
    "host-sync forcer inside jit'd code: .item(), np.asarray/np.array, "
    "or float()/int()/bool() on a traced value",
)
def check_host_sync(project: Project) -> list[Finding]:
    findings = []
    for mod in project.modules:
        for fn in _jit_functions(mod):
            static = _static_params(fn)
            body = fn.body if isinstance(fn.body, list) else [fn.body]
            for stmt in body:
                for node in ast.walk(stmt):
                    if not isinstance(node, ast.Call):
                        continue
                    name = dotted(node.func)
                    if name.endswith(".item") and not node.args:
                        findings.append(
                            Finding(
                                "jit-host-sync", mod.relpath, node.lineno,
                                f"{name}() forces a host sync inside "
                                "jit'd code",
                            )
                        )
                    elif name in ("np.asarray", "np.array", "numpy.asarray",
                                  "numpy.array"):
                        findings.append(
                            Finding(
                                "jit-host-sync", mod.relpath, node.lineno,
                                f"{name}() on a traced value forces a "
                                "host transfer inside jit'd code",
                            )
                        )
                    elif (
                        name in ("float", "int", "bool")
                        and len(node.args) == 1
                        and not isinstance(node.args[0], ast.Constant)
                        and not (
                            isinstance(node.args[0], ast.Name)
                            and node.args[0].id in static
                        )
                    ):
                        findings.append(
                            Finding(
                                "jit-host-sync", mod.relpath, node.lineno,
                                f"{name}({dotted(node.args[0])}) "
                                "concretizes a traced value inside "
                                "jit'd code",
                            )
                        )
    return findings


@register(
    "jit-impure-call",
    "Python time/random reachable inside jit'd code: traced once at "
    "compile time, frozen into the program",
)
def check_impure(project: Project) -> list[Finding]:
    findings = []
    for mod in project.modules:
        for fn in _jit_functions(mod):
            body = fn.body if isinstance(fn.body, list) else [fn.body]
            for stmt in body:
                for node in ast.walk(stmt):
                    if not isinstance(node, ast.Call):
                        continue
                    name = dotted(node.func)
                    if name in _IMPURE or name.startswith(
                        ("random.", "np.random.", "numpy.random.")
                    ):
                        findings.append(
                            Finding(
                                "jit-impure-call", mod.relpath, node.lineno,
                                f"{name}() inside jit'd code is evaluated "
                                "once at trace time, not per call",
                            )
                        )
    return findings


@register(
    "device-put-in-loop",
    "device_put inside a loop body: one host->device transfer per "
    "iteration — batch the upload",
)
def check_device_put_in_loop(project: Project) -> list[Finding]:
    findings = []
    for mod in project.modules:
        for loop in ast.walk(mod.tree):
            if not isinstance(loop, (ast.For, ast.While)):
                continue
            for node in ast.walk(loop):
                if node is loop:
                    continue
                if isinstance(node, ast.Call) and dotted(node.func).endswith(
                    "device_put"
                ):
                    findings.append(
                        Finding(
                            "device-put-in-loop", mod.relpath, node.lineno,
                            f"{dotted(node.func)}() inside a "
                            f"{'for' if isinstance(loop, ast.For) else 'while'}"
                            " loop",
                        )
                    )
    return findings


def _under_bucket(node: ast.AST, parents: dict) -> bool:
    cur = parents.get(node)
    while cur is not None:
        if isinstance(cur, ast.Call):
            tail = dotted(cur.func).rsplit(".", 1)[-1]
            if tail in _BUCKET_FNS:
                return True
        cur = parents.get(cur)
    return False


def _parent_map(tree: ast.AST) -> dict:
    parents: dict = {}
    for node in ast.walk(tree):
        for child in ast.iter_child_nodes(node):
            parents[child] = node
    return parents


#: dims below this are tile/lane constants, not cluster-scale shapes
SHAPE_LITERAL_MIN = 1024


@register(
    "shape-literal-unbucketed",
    "large integer literal used directly as an array dimension in tpu/ "
    "without rounding through _bucket (the 51200-vs-50176 bug class)",
)
def check_shape_literals(project: Project) -> list[Finding]:
    findings = []
    for mod in project.iter_modules("nomad_tpu/tpu/"):
        parents = _parent_map(mod.tree)
        for node in ast.walk(mod.tree):
            if not isinstance(node, ast.Call):
                continue
            tail = dotted(node.func).rsplit(".", 1)[-1]
            if tail not in _ARRAY_CTORS and tail != "lower":
                continue
            for arg in node.args:
                for lit in ast.walk(arg):
                    if not (
                        isinstance(lit, ast.Constant)
                        and isinstance(lit.value, int)
                        and lit.value >= SHAPE_LITERAL_MIN
                    ):
                        continue
                    if _under_bucket(lit, parents):
                        continue
                    findings.append(
                        Finding(
                            "shape-literal-unbucketed", mod.relpath,
                            lit.lineno,
                            f"literal dim {lit.value} in {tail}() does "
                            "not round through _bucket; production "
                            "padding will compile a different shape",
                        )
                    )
    return findings


#: tile dims below this are lane/column constants, not tile shapes
TILE_LITERAL_MIN = 64


@register(
    "tile-shape-unbucketed",
    "integer literal used as a tile dimension in tile/paged code "
    "without rounding through tile_rows (the paged planner's "
    "tile-bucket policy)",
)
def check_tile_shapes(project: Project) -> list[Finding]:
    findings = []
    for mod in project.iter_modules("nomad_tpu/tpu/"):
        parents = _parent_map(mod.tree)
        for fn in ast.walk(mod.tree):
            if not isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            if "tile" not in fn.name and "paged" not in fn.name:
                continue
            for node in ast.walk(fn):
                if not isinstance(node, ast.Call):
                    continue
                tail = dotted(node.func).rsplit(".", 1)[-1]
                if tail not in _ARRAY_CTORS and tail != "lower":
                    continue
                for arg in node.args:
                    for lit in ast.walk(arg):
                        if not (
                            isinstance(lit, ast.Constant)
                            and isinstance(lit.value, int)
                            and lit.value >= TILE_LITERAL_MIN
                        ):
                            continue
                        if _under_bucket(lit, parents):
                            continue
                        findings.append(
                            Finding(
                                "tile-shape-unbucketed", mod.relpath,
                                lit.lineno,
                                f"literal tile dim {lit.value} in "
                                f"{tail}() does not round through "
                                "tile_rows; the compiled tile program "
                                "misses the production tile bucket",
                            )
                        )
    return findings


#: dotted prefixes that ARE the counted transfer wrapper (or carry it):
#: devprof.device_put counts the bytes before delegating to jax
_COUNTED_PUT_PREFIXES = ("devprof", "_devprof", "_devprof_put", "_dp")


@register(
    "transfer-uncounted",
    "raw device_put in tpu/ outside the counted devprof wrapper: the "
    "h2d transfer ledger goes blind to this placement site",
)
def check_transfer_uncounted(project: Project) -> list[Finding]:
    findings = []
    for mod in project.iter_modules("nomad_tpu/tpu/"):
        for node in ast.walk(mod.tree):
            if not isinstance(node, ast.Call):
                continue
            name = dotted(node.func)
            if not name.endswith("device_put"):
                continue
            prefix = name.rsplit(".", 1)[0] if "." in name else ""
            if prefix.rsplit(".", 1)[-1] in _COUNTED_PUT_PREFIXES:
                continue
            findings.append(
                Finding(
                    "transfer-uncounted", mod.relpath, node.lineno,
                    f"{name}() bypasses the counted wrapper "
                    "(devprof.device_put): its bytes never reach the "
                    "h2d ledger",
                )
            )
    return findings


def _jit_entry_names(project: Project) -> set[str]:
    """Names of jit-compiled callables across the project: decorated
    defs and ``name = jax.jit(...)`` assignments."""
    names: set[str] = set()
    for mod in project.modules:
        for node in ast.walk(mod.tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                if any(_is_jit_decorator(d) for d in node.decorator_list):
                    names.add(node.name)
            elif isinstance(node, ast.Assign) and len(node.targets) == 1:
                tgt = node.targets[0]
                if (
                    isinstance(tgt, ast.Name)
                    and isinstance(node.value, ast.Call)
                    and _is_jit_call(node.value)
                ):
                    names.add(tgt.id)
    return names


@register(
    "jit-shape-unbucketed",
    "locally-computed size passed to a jit entry point without rounding "
    "through _bucket: each distinct value compiles a fresh program",
)
def check_jit_shapes(project: Project) -> list[Finding]:
    entries = _jit_entry_names(project)
    if not entries:
        return []
    findings = []
    for mod in project.iter_modules("nomad_tpu/tpu/"):
        for fn in ast.walk(mod.tree):
            if not isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            bucketed: set[str] = set()
            raw: set[str] = set()  # size-like names NOT via _bucket
            for stmt in fn.body:
                for node in ast.walk(stmt):
                    if not (
                        isinstance(node, ast.Assign)
                        and len(node.targets) == 1
                        and isinstance(node.targets[0], ast.Name)
                    ):
                        continue
                    name = node.targets[0].id
                    val = node.value
                    if (
                        isinstance(val, ast.Call)
                        and dotted(val.func).rsplit(".", 1)[-1]
                        in _BUCKET_FNS
                    ):
                        bucketed.add(name)
                        raw.discard(name)
                    elif isinstance(val, ast.Call) and dotted(
                        val.func
                    ) == "len":
                        raw.add(name)
                    elif isinstance(val, ast.BinOp) or (
                        isinstance(val, ast.Constant)
                        and isinstance(val.value, int)
                        and val.value >= SHAPE_LITERAL_MIN
                    ):
                        raw.add(name)
            if not raw:
                continue
            for stmt in fn.body:
                for node in ast.walk(stmt):
                    if not isinstance(node, ast.Call):
                        continue
                    tail = dotted(node.func).rsplit(".", 1)[-1]
                    if tail not in entries:
                        continue
                    for arg in node.args:
                        if (
                            isinstance(arg, ast.Name)
                            and arg.id in raw
                            and arg.id not in bucketed
                        ):
                            findings.append(
                                Finding(
                                    "jit-shape-unbucketed", mod.relpath,
                                    node.lineno,
                                    f"{arg.id} reaches jit entry "
                                    f"{tail}() without rounding through "
                                    "_bucket",
                                )
                            )
    return findings
