"""In-process metrics registry (the armon/go-metrics role: the reference
wraps every RPC/scheduler stage in MeasureSince and publishes gauges;
ref command/agent/config.go:500-577 telemetry). Counters, gauges, and
windowed timers with count/mean/p99, exported by /v1/metrics in both JSON
and prometheus exposition."""

from __future__ import annotations

import threading
import time
from contextlib import contextmanager

_LOCK = threading.Lock()
_COUNTERS: dict[str, float] = {}
_TIMERS: dict[str, list[float]] = {}
_HISTS: dict[str, dict[int, int]] = {}
# keyed by metric name (code-bounded); each entry is a bounded deque of
# the last few exemplar links — reset() clears it, which the
# unbounded-cache rule sees, so no suppression is needed
_EXEMPLARS: dict[str, list] = {}

TIMER_WINDOW = 512  # samples retained per timer
EXEMPLARS_PER_METRIC = 4  # most-recent trace links kept per timer


def incr(name: str, value: float = 1.0):
    with _LOCK:
        _COUNTERS[name] = _COUNTERS.get(name, 0.0) + value


def _bucket_floor(value) -> int:
    """Base-2 bucket lower bound: 0, 1, 2, 4, 8, ... — at most ~64
    buckets per histogram regardless of the observed value range."""
    iv = int(value)
    if iv <= 0:
        return 0
    return 1 << (iv.bit_length() - 1)


def observe(name: str, value):
    """Bounded base-2 bucketed histogram (e.g. the plan.apply_batch_size
    distribution): counts per power-of-two bucket, keyed by the bucket's
    lower bound. The earlier exact-integer-value counting was unbounded
    cardinality under soak (one dict key per distinct observed value —
    the `unbounded-cache` checker's own blind spot); base-2 buckets cap
    every histogram at ~64 keys while keeping the /v1/metrics output
    shape ({name: {int: count}}) unchanged."""
    with _LOCK:
        hist = _HISTS.setdefault(name, {})
        key = _bucket_floor(value)
        hist[key] = hist.get(key, 0) + 1


def sample(name: str, seconds: float, exemplar: str = None):
    """Record one timer sample; ``exemplar`` links the sample to a
    retained trace id (hot-path histograms carry these so /v1/metrics
    p99s are one hop from the span trees that produced them)."""
    with _LOCK:
        bucket = _TIMERS.setdefault(name, [])
        bucket.append(seconds)
        if len(bucket) > TIMER_WINDOW:
            del bucket[: len(bucket) - TIMER_WINDOW]
        if exemplar:
            ex = _EXEMPLARS.setdefault(name, [])
            ex.append(
                {"trace_id": exemplar, "value_ms": round(seconds * 1e3, 3)}
            )
            if len(ex) > EXEMPLARS_PER_METRIC:
                del ex[: len(ex) - EXEMPLARS_PER_METRIC]


def percentile(name: str, q: float):
    """Approximate percentile ``q`` in [0, 1] for a timer (exact over
    the retained window, in seconds) or a bucketed histogram (the
    bucket's upper bound). Returns None for an unknown name."""
    with _LOCK:
        samples = list(_TIMERS.get(name, ()))
        hist = dict(_HISTS.get(name, ()))
    if samples:
        ordered = sorted(samples)
        return ordered[min(len(ordered) - 1, int(len(ordered) * q))]
    if hist:
        total = sum(hist.values())
        target = min(total - 1, int(total * q))
        seen = 0
        for key in sorted(hist):
            seen += hist[key]
            if seen > target:
                return key if key == 0 else 2 * key - 1
    return None


@contextmanager
def measure(name: str):
    """MeasureSince analog: times the with-block into ``name``."""
    t0 = time.monotonic()
    try:
        yield
    finally:
        sample(name, time.monotonic() - t0)


def snapshot() -> dict:
    """{counters, timers: {name: {count, mean_ms, p99_ms, max_ms}},
    hists: {name: {bucket_floor: count}}, exemplars: {name: [...]}}"""
    with _LOCK:
        counters = dict(_COUNTERS)
        timers = {k: list(v) for k, v in _TIMERS.items()}
        hists = {k: dict(v) for k, v in _HISTS.items()}
        exemplars = {k: list(v) for k, v in _EXEMPLARS.items() if v}
    out_timers = {}
    for name, samples in timers.items():
        if not samples:
            continue
        ordered = sorted(samples)
        p99 = ordered[min(len(ordered) - 1, int(len(ordered) * 0.99))]
        out_timers[name] = {
            "count": len(ordered),
            "mean_ms": round(sum(ordered) / len(ordered) * 1e3, 3),
            "p99_ms": round(p99 * 1e3, 3),
            "max_ms": round(ordered[-1] * 1e3, 3),
        }
    return {
        "counters": counters,
        "timers": out_timers,
        "hists": hists,
        "exemplars": exemplars,
    }


def reset():
    """Test hook."""
    with _LOCK:
        _COUNTERS.clear()
        _TIMERS.clear()
        _HISTS.clear()
        _EXEMPLARS.clear()


# ---------------------------------------------------------------------------
# Push sinks (the go-metrics FanoutSink role: the reference fans every
# metric out to statsite/statsd/datadog/circonus sinks configured in the
# telemetry stanza, command/agent/config.go:500-577). Pull via /v1/metrics
# stays the primary surface; sinks PUSH the same registry on an interval.
# ---------------------------------------------------------------------------


class StatsdSink:
    """statsd line-protocol over UDP (the go-metrics statsd sink role):
    counters as ``name:delta|c``, timer means as ``name:ms|ms``. Deltas are
    tracked per sink so restarts of the receiver don't double-count.
    Datagrams are batched newline-separated under ~1400 bytes (one MTU)."""

    MAX_DATAGRAM = 1400

    def __init__(self, address: str, prefix: str = "nomad"):
        import socket

        host, _, port = address.rpartition(":")
        self.addr = (host or "127.0.0.1", int(port))
        self.prefix = prefix
        self._sock = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
        # nta: ignore[unbounded-cache] WHY: keyed by metric name — the
        # name set is code-bounded (no per-request interpolation)
        self._last_counters: dict[str, float] = {}

    def _fmt(self, name: str) -> str:
        return f"{self.prefix}.{name}".replace(":", "_").replace("|", "_")

    def _suffix(self) -> str:
        """Per-line suffix hook (dogstatsd appends its tag block)."""
        return ""

    def _lines(self, counters: dict, timers: dict) -> list[str]:
        suffix = self._suffix()
        lines = []
        for name, total in sorted(counters.items()):
            delta = total - self._last_counters.get(name, 0.0)
            self._last_counters[name] = total
            if delta:
                lines.append(f"{self._fmt(name)}:{delta:g}|c{suffix}")
        for name, stats in sorted(timers.items()):
            lines.append(
                f"{self._fmt(name)}.mean:{stats['mean_ms']:g}|ms{suffix}"
            )
            lines.append(
                f"{self._fmt(name)}.p99:{stats['p99_ms']:g}|ms{suffix}"
            )
        return lines

    def emit(self, counters: dict, timers: dict):
        batch = b""
        for line in self._lines(counters, timers):
            data = line.encode()
            if batch and len(batch) + 1 + len(data) > self.MAX_DATAGRAM:
                self._send(batch)
                batch = b""
            batch = batch + b"\n" + data if batch else data
        if batch:
            self._send(batch)

    def _send(self, payload: bytes):
        try:
            self._sock.sendto(payload, self.addr)
        except OSError:
            pass  # UDP telemetry is best-effort, never a failure source

    def close(self):
        self._sock.close()


class DogstatsdSink(StatsdSink):
    """dogstatsd: the statsd line protocol plus a ``|#key:value,...`` tag
    block on every line (the go-metrics datadog sink role, ref
    command/agent/config.go datadog_address/datadog_tags). Tags come from
    the telemetry stanza and ride every metric, so one receiver can split
    series by node/region without name-mangling."""

    def __init__(self, address: str, prefix: str = "nomad", tags=None):
        super().__init__(address, prefix=prefix)
        if isinstance(tags, dict):
            tags = [f"{k}:{v}" for k, v in sorted(tags.items())]
        self.tags = [str(t) for t in (tags or [])]

    def _suffix(self) -> str:
        if not self.tags:
            return ""
        # tag values must not smuggle protocol delimiters — ',' splits
        # tags, '|' splits fields, newline splits lines
        clean = [
            t.replace("|", "_").replace("\n", "_").replace(",", "_")
            for t in self.tags
        ]
        return "|#" + ",".join(clean)


class StatsiteSink(StatsdSink):
    """statsite line protocol over TCP (the go-metrics statsite sink
    role): the same ``name:value|type`` lines, newline-terminated on one
    persistent connection. TCP gives ordering + no datagram size limit;
    a broken pipe drops the connection and the next flush redials —
    telemetry stays best-effort, never a failure source."""

    def __init__(self, address: str, prefix: str = "nomad"):
        # reuse the statsd formatting/delta machinery; replace transport
        super().__init__(address, prefix=prefix)
        self._sock.close()
        self._sock = None
        self._conn = None

    def _connect(self):
        import socket

        if self._conn is None:
            self._conn = socket.create_connection(self.addr, timeout=2.0)
        return self._conn

    def emit(self, counters: dict, timers: dict):
        # _lines consumes the counter deltas; keep the pre-flush marks so
        # a fully-failed send re-carries the counts next interval instead
        # of undercounting the receiver after every transient outage.
        # Deliberately at-least-once: sendall can't report partial
        # progress, so a connection dying mid-send may double-count the
        # flushed prefix on retry — the rarer and more benign failure
        # than silently losing every delta across an outage.
        marks = dict(self._last_counters)
        lines = self._lines(counters, timers)
        if not lines:
            return
        payload = ("\n".join(lines) + "\n").encode()
        for _ in range(2):  # one redial after a stale-connection failure
            try:
                self._connect().sendall(payload)
                return
            except OSError:
                self._drop()
        self._last_counters = marks

    def _drop(self):
        if self._conn is not None:
            try:
                self._conn.close()
            except OSError:
                pass
            self._conn = None

    def close(self):
        self._drop()


class SinkFlusher:
    """Periodically snapshots the registry into every configured sink
    (the collection_interval loop of the reference's telemetry setup)."""

    def __init__(self, sinks, interval: float = 10.0):
        self.sinks = list(sinks)
        self.interval = interval
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None

    def start(self):
        self._thread = threading.Thread(
            target=self._run, daemon=True, name="metrics-sink-flusher"
        )
        self._thread.start()
        return self

    def _run(self):
        while not self._stop.wait(self.interval):
            self.flush()

    def flush(self):
        snap = snapshot()
        for sink in self.sinks:
            try:
                sink.emit(snap["counters"], snap["timers"])
            except Exception:
                pass

    def stop(self):
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=2.0)
        for sink in self.sinks:
            try:
                sink.close()
            except Exception:
                pass


def configure_telemetry(config: dict):
    """Build + start the sink fan-out from an agent config's telemetry
    stanza (ref command/agent/config.go:500-577: statsd_address,
    statsite_address, datadog_address + datadog_tags,
    collection_interval). Returns a running SinkFlusher or None."""
    stanza = (config or {}).get("telemetry") or {}
    sinks = []
    addr = stanza.get("statsd_address")
    if addr:
        sinks.append(StatsdSink(str(addr)))
    addr = stanza.get("statsite_address")
    if addr:
        sinks.append(StatsiteSink(str(addr)))
    addr = stanza.get("datadog_address")
    if addr:
        sinks.append(
            DogstatsdSink(str(addr), tags=stanza.get("datadog_tags"))
        )
    if not sinks:
        return None
    interval = stanza.get("collection_interval", 10.0)
    if isinstance(interval, str):
        from .jobspec.hcl import parse_duration

        interval = parse_duration(interval) / 1e9
    return SinkFlusher(sinks, interval=float(interval)).start()
