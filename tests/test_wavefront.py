"""The wavefront placement plane (nomad_tpu/tpu/wavefront.py): parity,
contention binning, degradation and accounting.

The contract under test is exactness-by-construction: the wavefront
planner commits a PREFIX of each predicted window — cut at the first
lane whose candidate nodes or ring cursor could couple it to an earlier
lane — so its placements AND final state are bit-identical to the
sequential fill loop (kernel.plan_batch), which stays THE oracle. Every
test here therefore compares against plan_batch on the SAME (args,
init), unsharded and across the 8-device virtual mesh with an uneven
node axis, under the deterministic compile flavor where bit-equality is
guaranteed rather than merely expected.

The suite also pins the operational edges: the sole-shared-node
contention case must serialize (never share a wavefront), a faulted
kernel must degrade to the exact-np host path, disabling the plane must
reproduce the old exact-scan dispatch, and the devprof round accounting
must show commit rounds ≪ placements on multi-tenant shapes (the number
the MULTICHIP crpp criterion reads).
"""

import random

import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax.sharding import Mesh

from nomad_tpu.tpu import shard, wavefront
from nomad_tpu.tpu.kernel import (
    BatchArgs,
    BatchState,
    deterministic_scope,
    plan_batch,
)
from nomad_tpu.tpu.multichip import (
    build_cluster,
    exact_problem,
    pad_cluster,
    wavefront_problem,
)
from nomad_tpu.tpu.wavefront import plan_batch_wavefront

N_DEV = 8

#: real node count whose rows end MID-shard after bucketing (the
#: test_multichip.py property-suite constant): 2059 buckets to 3072
N_UNEVEN = 2059


@pytest.fixture(scope="module")
def mesh():
    devices = jax.devices()
    if len(devices) < N_DEV:
        pytest.skip(f"need {N_DEV} virtual devices, have {len(devices)}")
    return Mesh(np.array(devices[:N_DEV]), ("nodes",))


@pytest.fixture(autouse=True)
def _wavefront_reset():
    yield
    wavefront.reset()


def _jx(args, init):
    return (
        BatchArgs(*[jnp.asarray(a) for a in args]),
        BatchState(*[jnp.asarray(s) for s in init]),
    )


def _assert_state_equal(want, got):
    for name, w, g in zip(BatchState._fields, want, got):
        np.testing.assert_array_equal(
            np.asarray(w), np.asarray(g), err_msg=f"state.{name} diverged"
        )


# ---------------------------------------------------------------------------
# parity: the sequential fill loop is THE oracle
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("seed", [0, 3, 7])
def test_unsharded_parity_multi_group(seed):
    """Placements AND final state bit-equal to plan_batch on the
    multi-tenant problem, with real commit batching (rounds < allocs)."""
    n_nodes, n_allocs = 1024, 256
    c = build_cluster(n_nodes, n_allocs, seed=seed)
    args, init = wavefront_problem(c)
    jargs, jinit = _jx(args, init)

    s_want, want = plan_batch(jargs, jinit, n_nodes)
    f_state, got, rounds = plan_batch_wavefront(jargs, jinit, n_nodes)

    want, got = np.asarray(want), np.asarray(got)
    assert (want >= 0).sum() == n_allocs
    np.testing.assert_array_equal(want, got)
    _assert_state_equal(s_want, f_state)
    assert int(rounds) < n_allocs, (
        f"no commit batching: {int(rounds)} rounds for {n_allocs} lanes"
    )


@pytest.mark.parametrize("seed", [0, 7])
def test_unsharded_parity_single_group_serializes(seed):
    """The designed worst case: one group means every pair of lanes
    shares the feasible set, so exactness forces one commit per round —
    parity holds AND the round count equals the lane count."""
    n_nodes, n_allocs = 512, 64
    c = build_cluster(n_nodes, n_allocs, seed=seed)
    args, init = exact_problem(c)
    jargs, jinit = _jx(args, init)

    _, want = plan_batch(jargs, jinit, n_nodes)
    _, got, rounds = plan_batch_wavefront(jargs, jinit, n_nodes)

    np.testing.assert_array_equal(np.asarray(want), np.asarray(got))
    assert int(rounds) == n_allocs


@pytest.mark.parametrize("seed", [11, 29, 47])
def test_sharded_parity_uneven_axis_deterministic(mesh, seed, monkeypatch):
    """The acceptance pin: sharded wavefront == UNSHARDED sequential,
    bit-for-bit, across an uneven node axis (real rows end mid-shard)
    under the deterministic compile flavor."""
    monkeypatch.setenv("NOMAD_TPU_DETERMINISTIC", "1")
    n_allocs = 256
    c = pad_cluster(
        build_cluster(N_UNEVEN, n_allocs, seed=seed),
        shard.node_bucket(N_UNEVEN, mesh),
    )
    args, init = wavefront_problem(c)
    jargs, jinit = _jx(args, init)

    _, want = plan_batch(jargs, jinit, N_UNEVEN)
    want = np.asarray(want)

    aspec, sspec = shard.wavefront_specs()
    d_args = shard.put(args, aspec, mesh)
    d_init = shard.put(init, sspec, mesh)
    _, got, rounds = plan_batch_wavefront(
        d_args, d_init, N_UNEVEN, n_shards=shard.mesh_size(mesh)
    )

    assert (want >= 0).sum() == n_allocs
    np.testing.assert_array_equal(want, np.asarray(got))
    assert int(rounds) < n_allocs


# ---------------------------------------------------------------------------
# contention binning: shared feasibility must serialize
# ---------------------------------------------------------------------------


def test_sole_shared_node_never_shares_a_wavefront():
    """Two allocs in different groups whose ONLY feasible node is the
    same node: the conflict matrix must split them into two rounds (the
    second lane's selection depends on the first's usage write), and the
    sequential outcome — second lane unplaced once the node fills — must
    reproduce exactly."""
    n_nodes, V = 64, 4
    c = build_cluster(n_nodes, 2, seed=1)
    args, init = wavefront_problem(c, n_groups=2, overlap=0)
    sole = np.zeros((2, n_nodes), dtype=bool)
    sole[:, 5] = True  # both groups: node 5 only
    # demand sized so the node holds exactly one of the two allocs
    cap5 = np.asarray(c["capacity"])[5] - np.asarray(c["reserved"])[5]
    demands = np.tile((cap5 * 0.6).astype(np.int32), (2, 1))
    args = args._replace(
        feasible=sole,
        demands=demands,
        spread_active=np.zeros(2, dtype=bool),
        spread_desired=np.full((2, V), -1.0, dtype=np.float32),
    )
    jargs, jinit = _jx(args, init)

    _, want = plan_batch(jargs, jinit, n_nodes)
    _, got, rounds = plan_batch_wavefront(jargs, jinit, n_nodes)

    want, got = np.asarray(want), np.asarray(got)
    np.testing.assert_array_equal(want, got)
    assert want[0] == 5 and want[1] == -1, want
    assert int(rounds) == 2, (
        f"sole-shared-node lanes committed in {int(rounds)} round(s)"
    )


def test_disjoint_feasibility_commits_in_one_round():
    """The inverse control: fully disjoint feasible sets (no overlap,
    one alloc per group, no cursor coupling) commit in a single round
    per window."""
    n_nodes, n_allocs = 512, 16
    c = build_cluster(n_nodes, n_allocs, seed=2)
    args, init = wavefront_problem(c, n_groups=16, overlap=0)
    jargs, jinit = _jx(args, init)

    _, want = plan_batch(jargs, jinit, n_nodes)
    _, got, rounds = plan_batch_wavefront(jargs, jinit, n_nodes)

    np.testing.assert_array_equal(np.asarray(want), np.asarray(got))
    assert int(rounds) == 1, int(rounds)


# ---------------------------------------------------------------------------
# operational edges: fault degrade, disable, accounting
# ---------------------------------------------------------------------------


def test_kernel_fault_degrades_to_exact_np(monkeypatch):
    """With the device tier faulted and the wavefront ENABLED, a
    scheduler eval must degrade to the exact-np host path — the
    wavefront honors the same tpu.kernel fault point as the sequential
    dispatch, so the fallback ladder is unchanged."""
    from nomad_tpu import mock
    from nomad_tpu.state import StateStore
    from nomad_tpu.structs import compute_class
    from nomad_tpu.structs.model import Evaluation, PlanResult, generate_uuid
    from nomad_tpu.testing import faults
    from nomad_tpu.tpu import batch_sched
    from nomad_tpu.tpu.batch_sched import TPUBatchScheduler

    wavefront.configure(enabled=True)
    state = StateStore()
    rng = random.Random(5)
    nodes = []
    for i in range(96):
        n = mock.node()
        n.id = f"node-{i:04d}"
        n.node_resources.cpu.cpu_shares = rng.choice([8000, 16000])
        n.node_resources.memory.memory_mb = rng.choice([16384, 32768])
        n.node_resources.networks = []
        n.reserved_resources.networks.reserved_host_ports = ""
        compute_class(n)
        nodes.append(n)
    state.upsert_nodes(1, nodes)
    job = mock.job()
    job.id = "job-wavefront-fault"
    tg = job.task_groups[0]
    tg.count = 16
    tg.tasks[0].resources.networks = []
    state.upsert_job(2, job)

    class Planner:
        def __init__(self):
            self.plans = []

        def submit_plan(self, plan):
            self.plans.append(plan)
            return PlanResult(
                node_update=plan.node_update,
                node_allocation=plan.node_allocation,
                node_preemptions=plan.node_preemptions,
                alloc_index=1,
            ), None

        def update_eval(self, ev):
            pass

        def create_eval(self, ev):
            pass

    plane = faults.install(faults.FaultPlane(seed=3))
    plane.rule("point", "error", method="tpu.kernel", count=100)
    try:
        planner = Planner()
        sched = TPUBatchScheduler(
            state.snapshot(), planner, rng=random.Random(17)
        )
        ev = Evaluation(
            id=generate_uuid(), namespace=job.namespace,
            priority=job.priority, type=job.type,
            triggered_by="job-register", job_id=job.id,
            status="pending",
        )
        sched.process(ev)
    finally:
        faults.uninstall()
    assert batch_sched.LAST_KERNEL_STATS.get("mode") == "exact-np-degraded"
    placed = {
        a.name: a.node_id
        for allocs in planner.plans[0].node_allocation.values()
        for a in allocs
    }
    assert placed, "degraded eval placed nothing"


def test_disabled_equals_sequential_dispatch():
    """wavefront.enabled() False must leave the old exact-scan dispatch
    byte-for-byte in charge: same mode string, same placements."""
    from nomad_tpu import mock
    from nomad_tpu.state import StateStore
    from nomad_tpu.structs import compute_class
    from nomad_tpu.structs.model import (
        Evaluation,
        PlanResult,
        Spread,
        SpreadTarget,
        generate_uuid,
    )
    from nomad_tpu.tpu import batch_sched
    from nomad_tpu.tpu.batch_sched import TPUBatchScheduler

    def build_state():
        state = StateStore()
        rng = random.Random(9)
        nodes = []
        for i in range(96):
            n = mock.node()
            n.id = f"node-{i:04d}"
            n.datacenter = f"dc{i % 4 + 1}"
            n.node_resources.cpu.cpu_shares = rng.choice([8000, 16000])
            n.node_resources.memory.memory_mb = rng.choice([16384, 32768])
            n.node_resources.networks = []
            n.reserved_resources.networks.reserved_host_ports = ""
            compute_class(n)
            nodes.append(n)
        state.upsert_nodes(1, nodes)
        job = mock.job()
        job.id = "job-wavefront-ab"
        job.datacenters = ["dc1", "dc2", "dc3", "dc4"]
        tg = job.task_groups[0]
        tg.count = 16
        tg.tasks[0].resources.networks = []
        # a spread with a small count routes past the runs/windowed fast
        # paths to the exact-scan dispatch — the path the wavefront gates
        job.spreads = [
            Spread(
                attribute="${node.datacenter}",
                weight=100,
                spread_target=[
                    SpreadTarget(value=f"dc{i}", percent=25)
                    for i in (1, 2, 3, 4)
                ],
            )
        ]
        state.upsert_job(2, job)
        return state, job

    class Planner:
        def __init__(self):
            self.plans = []

        def submit_plan(self, plan):
            self.plans.append(plan)
            return PlanResult(
                node_update=plan.node_update,
                node_allocation=plan.node_allocation,
                node_preemptions=plan.node_preemptions,
                alloc_index=1,
            ), None

        def update_eval(self, ev):
            pass

        def create_eval(self, ev):
            pass

    def run(enable: bool):
        wavefront.configure(enabled=enable)
        state, job = build_state()
        planner = Planner()
        sched = TPUBatchScheduler(
            state.snapshot(), planner, rng=random.Random(17)
        )
        ev = Evaluation(
            id=generate_uuid(), namespace=job.namespace,
            priority=job.priority, type=job.type,
            triggered_by="job-register", job_id=job.id,
            status="pending",
        )
        sched.process(ev)
        mode = batch_sched.LAST_KERNEL_STATS.get("mode")
        placed = {
            a.name: a.node_id
            for allocs in planner.plans[0].node_allocation.values()
            for a in allocs
        }
        return mode, placed

    mode_off, placed_off = run(enable=False)
    mode_on, placed_on = run(enable=True)
    assert mode_off == "exact-scan", mode_off
    assert mode_on == "wavefront", mode_on
    assert placed_off == placed_on


def test_devprof_round_accounting():
    """count_rounds('wavefront', ...) must surface measured commit
    rounds ≪ placements on the multi-tenant shape — the crpp column the
    MULTICHIP acceptance reads."""
    from nomad_tpu.debug import devprof

    n_nodes, n_allocs = 1024, 256
    c = build_cluster(n_nodes, n_allocs, seed=4)
    args, init = wavefront_problem(c)
    jargs, jinit = _jx(args, init)

    before = devprof.rounds_snapshot().get("wavefront", {})
    _, placements, _ = plan_batch_wavefront(jargs, jinit, n_nodes)
    np.asarray(placements)  # sync so lazy round scalars resolve
    after = devprof.rounds_snapshot().get("wavefront", {})

    d_rounds = after.get("rounds", 0) - before.get("rounds", 0)
    d_place = after.get("placements", 0) - before.get("placements", 0)
    assert d_place == n_allocs
    assert 0 < d_rounds < 0.2 * d_place, (
        f"crpp {d_rounds}/{d_place} not under the 0.2 acceptance line"
    )


def test_config_knobs_resolve():
    """configure() beats env; reset() restores env/default resolution;
    window/shard derivations stay static-safe."""
    assert wavefront.enabled() is False  # default off
    wavefront.configure(enabled=True, max_round=8, contention_top_m=2)
    assert wavefront.enabled() is True
    assert wavefront.max_round() == 8
    assert wavefront.contention_top_m() == 2
    assert wavefront.window_for(4) == 4  # clamped to the lane count
    assert wavefront.window_for(512) == 8
    assert wavefront.shards_for(3072, 8) == 8
    assert wavefront.shards_for(3070, 8) == 1  # non-divisible → flat
    wavefront.reset()
    assert wavefront.enabled() is False
    assert wavefront.max_round() == wavefront.DEFAULT_MAX_ROUND


def test_contention_top_m_parity():
    """M>1 widens the conflict binning (more conservative) — parity and
    full placement must be unaffected."""
    n_nodes, n_allocs = 512, 128
    c = build_cluster(n_nodes, n_allocs, seed=6)
    args, init = wavefront_problem(c)
    jargs, jinit = _jx(args, init)

    _, want = plan_batch(jargs, jinit, n_nodes)
    wavefront.configure(contention_top_m=3)
    _, got, rounds_m3 = plan_batch_wavefront(jargs, jinit, n_nodes)
    wavefront.reset()
    _, got_m1, rounds_m1 = plan_batch_wavefront(jargs, jinit, n_nodes)

    np.testing.assert_array_equal(np.asarray(want), np.asarray(got))
    np.testing.assert_array_equal(np.asarray(want), np.asarray(got_m1))
    assert int(rounds_m3) >= int(rounds_m1)


def test_sharded_deterministic_scope_matches_fast(mesh):
    """deterministic_scope() routes the wavefront through the det AOT
    executables — same placements as the fast flavor on the same args
    (the bench parity machinery end to end)."""
    n_allocs = 128
    c = pad_cluster(
        build_cluster(N_UNEVEN, n_allocs, seed=23),
        shard.node_bucket(N_UNEVEN, mesh),
    )
    args, init = wavefront_problem(c)
    aspec, sspec = shard.wavefront_specs()
    d_args = shard.put(args, aspec, mesh)
    d_init = shard.put(init, sspec, mesh)
    s = shard.mesh_size(mesh)

    _, fast, _ = plan_batch_wavefront(d_args, d_init, N_UNEVEN, n_shards=s)
    fast = np.asarray(fast)
    with deterministic_scope():
        _, det, _ = plan_batch_wavefront(
            d_args, d_init, N_UNEVEN, n_shards=s
        )
    np.testing.assert_array_equal(fast, np.asarray(det))


def test_prewarm_ladder_covers_wavefront_zero_recompiles():
    """The warmup ladder must compile the wavefront program when the
    plane is enabled (one extra executable per rung), and a warmed
    dispatch must add nothing to the planner compile cache — the rc0
    column of the MULTICHIP acceptance."""
    from nomad_tpu.tpu import warmup
    from nomad_tpu.tpu.kernel import compile_cache_size

    n_nodes, batch = 512, 16
    base = warmup.prewarm_drain(n_nodes, batch)
    wavefront.configure(enabled=True)
    assert warmup.prewarm_drain(n_nodes, batch) == base + 1

    # steady state: a warm call pins the trace; same-shaped fresh args
    # must reuse it (0 recompiles), so timed loops never pay XLA.
    n_allocs = 256
    args, init = wavefront_problem(build_cluster(1024, n_allocs, seed=9))
    jargs, jinit = _jx(args, init)
    _, warm, _ = plan_batch_wavefront(jargs, jinit, 1024)
    np.asarray(warm)
    before = compile_cache_size()
    args2, init2 = wavefront_problem(build_cluster(1024, n_allocs, seed=10))
    jargs2, jinit2 = _jx(args2, init2)
    _, again, _ = plan_batch_wavefront(jargs2, jinit2, 1024)
    np.asarray(again)
    assert compile_cache_size() - before == 0
