"""Wires a core.Server's endpoint surface onto an RpcServer
(ref nomad/server.go:1019-1073 endpoint registry + nomad/*_endpoint.go).

Handlers decode plain msgpack payloads into model objects, call the
server method (which may raise NotLeaderError — answered with a leader
hint for client-side forwarding), and encode plain results.
"""

from __future__ import annotations

from ..structs.model import Allocation, Job, Node


def register_endpoints(server, rpc) -> None:
    """server: core.Server; rpc: RpcServer"""

    # ------------------------------------------------------------- Job
    def job_register(p):
        return server.job_register(Job.from_dict(p["job"]))

    def job_deregister(p):
        return server.job_deregister(
            p["namespace"], p["job_id"], purge=p.get("purge", False)
        )

    rpc.register("Job.Register", job_register)
    rpc.register("Job.Deregister", job_deregister)

    # ------------------------------------------------------------ Node
    def node_register(p):
        return server.node_register(Node.from_dict(p["node"]))

    def node_update_status(p):
        if p.get("heartbeat"):
            return server.node_heartbeat(p["node_id"])
        return server.node_update_status(p["node_id"], p["status"])

    def node_drain(p):
        server.node_drain(
            p["node_id"],
            p["drain"],
            deadline_ns=p.get("deadline_ns", 0),
            ignore_system_jobs=p.get("ignore_system_jobs", False),
            mark_eligible=p.get("mark_eligible"),
        )
        return {}

    def node_eligibility(p):
        server.node_update_eligibility(p["node_id"], p["eligibility"])
        return {}

    def node_deregister(p):
        server.node_deregister(p["node_id"])
        return {}

    def node_get_client_allocs(p):
        allocs, index = server.get_client_allocs(
            p["node_id"],
            min_index=p.get("min_index", 0),
            timeout=min(p.get("timeout", 30.0), 300.0),
        )
        return {"allocs": [a.to_dict() for a in allocs], "index": index}

    def node_update_alloc(p):
        server.update_allocs([Allocation.from_dict(d) for d in p["allocs"]])
        return {}

    rpc.register("Node.Register", node_register)

    def node_derive_vault_token(payload):
        return server.derive_vault_token(payload["alloc_id"], payload["task"])

    rpc.register("Node.DeriveVaultToken", node_derive_vault_token)
    rpc.register("Node.UpdateStatus", node_update_status)
    rpc.register("Node.Drain", node_drain)
    rpc.register("Node.Eligibility", node_eligibility)
    rpc.register("Node.Deregister", node_deregister)
    rpc.register("Node.GetClientAllocs", node_get_client_allocs)
    rpc.register("Node.UpdateAlloc", node_update_alloc)

    # ------------------------------------------------------------ Eval
    def eval_dequeue(p):
        ev, token = server.eval_dequeue(
            p["schedulers"], timeout=min(p.get("timeout", 1.0), 10.0)
        )
        return {"eval": ev.to_dict() if ev is not None else None, "token": token}

    rpc.register("Eval.Dequeue", eval_dequeue)
    rpc.register("Eval.Ack", lambda p: server.eval_ack(p["eval_id"], p["token"]) or {})
    rpc.register("Eval.Nack", lambda p: server.eval_nack(p["eval_id"], p["token"]) or {})

    # ---------------------------------------------------------- Status
    rpc.register(
        "Alloc.GetAlloc", lambda p: {"alloc": server.alloc_get(p["alloc_id"])}
    )
    rpc.register(
        "Catalog.Service",
        lambda p: {"entries": server.catalog_service(p["name"])},
    )
    rpc.register(
        "ClientFS.Forward",
        lambda p: server.forward_client_fs(
            p["alloc_id"], p["method"], p.get("params") or {}
        ),
    )

    def exec_forward(payload, stream):
        """Server hop of the interactive exec path: open the duplex stream
        to the hosting node's client and pump frames both ways until
        either side ends (the agent→server→client forwarding of the
        reference's alloc exec)."""
        import threading as _threading

        from .mux import StreamClosed, StreamError

        client_stream = server.open_client_exec(
            payload["alloc_id"],
            {
                "task": payload.get("task", ""),
                "cmd": payload.get("cmd", []),
                "tty": payload.get("tty", False),
            },
        )

        def pump(src, dst):
            try:
                for frame in src:
                    dst.send(frame)
                dst.close()
            except StreamError as e:
                # the node ended with a typed error (task not found, ...):
                # relay it verbatim instead of degrading to "internal"
                src.close()
                dst.close(e.error)
            except (StreamClosed, TimeoutError, OSError):
                # either side dropped mid-bridge (peer disconnect or pool
                # teardown) — close both directions and stop quietly
                src.close()
                dst.close()

        up = _threading.Thread(
            target=pump, args=(stream, client_stream), daemon=True,
            name="rpc-stream-bridge",
        )
        up.start()
        pump(client_stream, stream)
        up.join(timeout=5.0)

    rpc.register_duplex("ClientAllocations.ExecForward", exec_forward)

    rpc.register("Status.Ping", lambda p: {"ok": True})
    rpc.register(
        "Status.Leader",
        lambda p: {
            "leader_id": server.raft.leader_id,
            "leader_rpc_addr": rpc.server_rpc_addrs.get(server.raft.leader_id),
            "is_leader": server.is_leader(),
        },
    )
    rpc.register(
        "Status.Peers",
        lambda p: {"peers": dict(server.raft.voters)},
    )
    rpc.register("Status.RaftStats", lambda p: server.raft.stats())
    # the peer-HTTP-address lookup behind follower→leader forwarding
    # (ref nomad/rpc.go:280-340 forward(): the reference forwards over the
    # server RPC tier; our HTTP proxy layer resolves the leader's HTTP
    # address over that same tier so forwarding needs no gossip/config)
    rpc.register(
        "Status.HTTPAddr",
        lambda p: {"http_addr": server.http_advertise_addr},
    )
