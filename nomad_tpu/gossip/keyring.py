"""Gossip encryption keyring (ref serf's keyring + `nomad operator keygen`
/ `agent keyring` surface): AES-GCM seals every UDP gossip frame. The
keyring holds multiple keys so rotation is zero-downtime — the primary
encrypts, every installed key is tried for decryption, and packets that
authenticate under none are dropped (an unencrypted or wrong-key peer
simply never merges)."""

from __future__ import annotations

import base64
import os
import threading

from cryptography.hazmat.primitives.ciphers.aead import AESGCM

NONCE_LEN = 12
KEY_LEN = 32


def generate_key() -> str:
    """Base64 of a fresh 256-bit key (ref `nomad operator keygen`)."""
    return base64.b64encode(os.urandom(KEY_LEN)).decode()


def _decode(key: str) -> bytes:
    raw = base64.b64decode(key)
    if len(raw) not in (16, 24, 32):
        raise ValueError("gossip key must be 16/24/32 bytes of base64")
    return raw


class Keyring:
    """Primary + installed keys with serf's use/install/remove semantics.
    With ``path`` the ring persists as JSON (serf's keyring file role), so
    keys installed at runtime survive agent restarts."""

    def __init__(self, primary: str, path: str = ""):
        raw = _decode(primary)
        self._lock = threading.Lock()
        self._keys: dict[str, bytes] = {primary: raw}
        self._primary = primary
        self._path = path
        if path:
            self._load()

    def _load(self):
        import json

        try:
            with open(self._path) as f:
                doc = json.load(f)
        except (OSError, ValueError):
            return
        with self._lock:
            for key in doc.get("keys", []):
                try:
                    self._keys[key] = _decode(key)
                except Exception:
                    continue
            primary = doc.get("primary")
            if primary in self._keys:
                self._primary = primary

    def _persist_locked(self):
        if not self._path:
            return
        import json
        import tempfile

        doc = {"primary": self._primary, "keys": list(self._keys)}
        d = os.path.dirname(self._path) or "."
        try:
            os.makedirs(d, exist_ok=True)
            fd, tmp = tempfile.mkstemp(dir=d, prefix=".keyring-")
            with os.fdopen(fd, "w") as f:
                json.dump(doc, f)
            os.chmod(tmp, 0o600)
            os.replace(tmp, self._path)
        except OSError:
            pass

    # -- management (ref serf keyring InstallKey/UseKey/RemoveKey/List) --
    def install(self, key: str):
        raw = _decode(key)
        with self._lock:
            self._keys[key] = raw
            self._persist_locked()

    def use(self, key: str):
        with self._lock:
            if key not in self._keys:
                raise KeyError("key is not installed")
            self._primary = key
            self._persist_locked()

    def remove(self, key: str):
        with self._lock:
            if key == self._primary:
                raise ValueError("cannot remove the primary key")
            self._keys.pop(key, None)
            self._persist_locked()

    def list_keys(self) -> dict:
        with self._lock:
            return {"PrimaryKey": self._primary, "Keys": list(self._keys)}

    # -- framing ---------------------------------------------------------
    def seal(self, plaintext: bytes) -> bytes:
        with self._lock:
            raw = self._keys[self._primary]
        nonce = os.urandom(NONCE_LEN)
        return nonce + AESGCM(raw).encrypt(nonce, plaintext, b"")

    def open(self, frame: bytes) -> bytes | None:
        """Plaintext, or None when no installed key authenticates it."""
        if len(frame) <= NONCE_LEN:
            return None
        nonce, ct = frame[:NONCE_LEN], frame[NONCE_LEN:]
        with self._lock:
            candidates = list(self._keys.values())
        for raw in candidates:
            try:
                return AESGCM(raw).decrypt(nonce, ct, b"")
            except Exception:
                continue
        return None
