"""Retry-amplification hygiene checker.

The overload plane (core/overload.py) exists because retries multiply:
one user request that fans through a leader-chase ladder, a rotation
ladder, and an HTTP forward loop can hit a struggling cluster dozens of
times — each layer individually "bounded", the product a storm. The
process-wide ``RetryBudget`` is the damper: every retry loop consults it
before sleeping and re-firing, so past saturation retries stop instead
of compounding. That contract only holds if every NEW retry loop also
consults it — which is exactly the kind of invariant a reviewer misses
and a grep can keep.

Rule:

- ``retry-without-budget`` — a ``for``/``while`` loop that both catches
  an exception (``try`` in the loop body) and backs off with
  ``time.sleep(...)`` — the sleep-and-retry shape — inside a function
  with no budget/deadline evidence. Evidence (function granularity): any
  identifier, attribute, or string containing ``budget`` or ``deadline``
  (``retry_budget().try_acquire()``, ``deadline_remaining_s(...)``, a
  ``_deadline`` read, ...). Periodic tickers that pace on
  ``Event.wait()`` are deliberately out of scope — they re-run on a
  cadence, they don't amplify per-request.

Suppress deliberate exceptions with ``# nta: ignore[retry-without-budget]``
plus a WHY — e.g. a boot-time ramp that retries a fixed small number of
times before any user traffic exists.
"""

from __future__ import annotations

import ast

from .framework import Finding, Project, dotted, register

#: the module that IMPLEMENTS the budget/deadline plane: its internals
#: legitimately sleep in refill/accounting paths
_EXEMPT = ("nomad_tpu/core/overload.py",)

_EVIDENCE_SUBSTRINGS = ("budget", "deadline")


def _is_sleep_call(node: ast.AST) -> bool:
    """``time.sleep(...)`` (or any ``<mod>.sleep(...)``) — the backoff
    shape. ``Event.wait()`` pacing is out of scope (periodic tickers)."""
    if not isinstance(node, ast.Call):
        return False
    fn = node.func
    return isinstance(fn, ast.Attribute) and fn.attr == "sleep"


def _has_evidence(fn_node: ast.AST) -> bool:
    for node in ast.walk(fn_node):
        if isinstance(node, (ast.Name, ast.Attribute)):
            name = node.id if isinstance(node, ast.Name) else node.attr
            low = name.lower()
            if any(s in low for s in _EVIDENCE_SUBSTRINGS):
                return True
        elif isinstance(node, ast.Constant) and isinstance(node.value, str):
            low = node.value.lower()
            if any(s in low for s in _EVIDENCE_SUBSTRINGS):
                return True
    return False


def _retryish(loop: ast.AST) -> bool:
    """Loop body contains BOTH an exception catch and a backoff sleep —
    the sleep-and-retry ladder shape."""
    has_try = has_sleep = False
    for node in ast.walk(loop):
        if isinstance(node, ast.Try):
            has_try = True
        elif _is_sleep_call(node):
            has_sleep = True
        if has_try and has_sleep:
            return True
    return False


@register(
    "retry-without-budget",
    "sleep-and-retry loop that never consults the process retry budget "
    "or a deadline (the retry-amplification class)",
)
def check_retry_budget(project: Project) -> list[Finding]:
    findings = []
    for mod in project.modules:
        if mod.relpath in _EXEMPT:
            continue
        for fn in ast.walk(mod.tree):
            if not isinstance(
                fn, (ast.FunctionDef, ast.AsyncFunctionDef)
            ):
                continue
            loops = [
                n
                for n in ast.walk(fn)
                if isinstance(n, (ast.For, ast.While)) and _retryish(n)
            ]
            if not loops:
                continue
            if _has_evidence(fn):
                continue
            # report the INNERMOST matching loop(s) only: an outer loop
            # that merely contains a flagged retry ladder is not itself
            # a second ladder
            inner = [
                lp
                for lp in loops
                if not any(
                    lp2 is not lp and lp2 in ast.walk(lp) for lp2 in loops
                )
            ]
            for lp in inner:
                kind = "for" if isinstance(lp, ast.For) else "while"
                findings.append(
                    Finding(
                        "retry-without-budget", mod.relpath, lp.lineno,
                        f"{kind}-loop in {fn.name}() sleeps and retries "
                        "without consulting retry_budget() or a "
                        "deadline; past saturation this amplifies load "
                        "instead of shedding it",
                    )
                )
    return findings


__all__ = ["check_retry_budget", "dotted"]
