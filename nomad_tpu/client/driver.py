"""Task drivers (ref plugins/drivers/ + drivers/{mock,rawexec}).

The driver interface mirrors the reference's gRPC Driver service surface
(plugins/drivers/proto/driver.proto:13-84) in-process: fingerprint,
start/wait/stop/destroy/inspect/signal. The mock driver reproduces the
reference's scriptable test driver (drivers/mock): configurable run duration,
exit codes, and start errors. RawExecDriver runs real subprocesses with no
isolation (drivers/rawexec); the isolated exec driver arrives with the C++
executor.
"""

from __future__ import annotations

import subprocess
import threading
import time
from dataclasses import dataclass, field
from typing import Optional

from ..structs.model import Task


def parse_duration(v) -> float:
    """Seconds from a number or a Go-style duration string ("250ms",
    "1m30s" — the format the reference's mock driver configs use,
    drivers/mock/driver.go run_for). Delegates to the jobspec parser so
    compound durations behave identically everywhere."""
    if isinstance(v, (int, float)):
        return float(v)
    from ..jobspec.hcl import parse_duration as _hcl_duration

    return _hcl_duration(str(v)) / 1e9


@dataclass
class TaskHandle:
    task_name: str = ""
    driver: str = ""
    proc: Optional[object] = None
    exit_code: Optional[int] = None
    error: str = ""
    started_at: int = 0
    finished_at: int = 0
    _done: threading.Event = field(default_factory=threading.Event)

    def wait(self, timeout: Optional[float] = None) -> bool:
        return self._done.wait(timeout)

    def finish(self, exit_code: int, error: str = ""):
        self.exit_code = exit_code
        self.error = error
        self.finished_at = time.time_ns()
        self._done.set()


class Driver:
    """Driver plugin interface (ref plugins/drivers/driver.go)."""

    name = "driver"

    def fingerprint(self) -> dict:
        """Returns {detected, healthy, attributes}."""
        return {"detected": True, "healthy": True, "attributes": {}}

    def start_task(self, task: Task, task_dir: str) -> TaskHandle:
        raise NotImplementedError

    def stop_task(self, handle: TaskHandle, timeout: float = 5.0):
        raise NotImplementedError

    def destroy_task(self, handle: TaskHandle):
        pass

    def inspect_task(self, handle: TaskHandle) -> dict:
        return {
            "exit_code": handle.exit_code,
            "error": handle.error,
            "running": not handle._done.is_set(),
        }


class MockDriver(Driver):
    """Scriptable driver for tests (ref drivers/mock/driver.go).

    Task config keys:
      run_for          seconds to run before exiting (default 0: exit now)
      exit_code        exit code to report (default 0)
      start_error      error string raised at start
      start_block_for  seconds to block in start
    """

    name = "mock_driver"

    def __init__(self):
        self._timers: dict[int, threading.Timer] = {}

    def start_task(self, task: Task, task_dir: str) -> TaskHandle:
        cfg = task.config or {}
        if cfg.get("start_error"):
            raise RuntimeError(str(cfg["start_error"]))
        if cfg.get("start_block_for"):
            time.sleep(parse_duration(cfg["start_block_for"]))

        handle = TaskHandle(
            task_name=task.name, driver=self.name, started_at=time.time_ns()
        )
        run_for = parse_duration(cfg.get("run_for", 0))
        exit_code = int(cfg.get("exit_code", 0))
        if run_for <= 0:
            handle.finish(exit_code)
        else:
            key = id(handle)

            def _finish():
                self._timers.pop(key, None)
                handle.finish(exit_code)

            t = threading.Timer(run_for, _finish)
            t.daemon = True
            self._timers[key] = t
            t.start()
        return handle

    def stop_task(self, handle: TaskHandle, timeout: float = 5.0):
        t = self._timers.pop(id(handle), None)
        if t is not None:
            t.cancel()
        if not handle._done.is_set():
            handle.finish(130, "killed")


class RawExecDriver(Driver):
    """Run a real subprocess with no isolation (ref drivers/rawexec)."""

    name = "raw_exec"

    def start_task(self, task: Task, task_dir: str) -> TaskHandle:
        cfg = task.config or {}
        command = cfg.get("command")
        if not command:
            raise RuntimeError("raw_exec requires a command")
        args = [command] + list(cfg.get("args", []))
        proc = subprocess.Popen(
            args,
            cwd=task_dir or None,
            stdout=subprocess.DEVNULL,
            stderr=subprocess.DEVNULL,
            env={"PATH": "/usr/bin:/bin:/usr/local/bin", **task.env},
        )
        handle = TaskHandle(
            task_name=task.name,
            driver=self.name,
            proc=proc,
            started_at=time.time_ns(),
        )

        def waiter():
            code = proc.wait()
            handle.finish(code)

        threading.Thread(target=waiter, daemon=True).start()
        return handle

    def stop_task(self, handle: TaskHandle, timeout: float = 5.0):
        proc = handle.proc
        if proc is None or proc.poll() is not None:
            return
        proc.terminate()
        try:
            proc.wait(timeout)
        except subprocess.TimeoutExpired:
            proc.kill()


BUILTIN_DRIVERS = {
    MockDriver.name: MockDriver,
    RawExecDriver.name: RawExecDriver,
}
