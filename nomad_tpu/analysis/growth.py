"""Unbounded-cache checker: the static encoding of the ``_bad_http_addrs``
leak class (r5) and its churn-soak relatives (BlockedEvals'
``_node_unblock_indexes``, PeriodicDispatch's ``_gen``).

The shape: a long-lived dict/list/set — an instance attribute created in
``__init__`` or a module-level global — that some steady-state code path
*grows* (keyed insert, ``append``, ``add``, ``setdefault``) while **no**
path ever shrinks it (``pop``/``del``/``clear``/``remove``/rebind). On a
server that lives for months, every such container is a leak whose key
cardinality is only bounded by traffic: per-address maps, per-node-id
maps, per-job generation counters.

Rule ``unbounded-cache`` flags the *container*, at its creation site,
listing where it grows. Bounded-by-construction registries (one entry
per checker module, per RPC method, per scheduler factory — populated at
import/startup and never from request traffic) are the expected
suppression class: mark them ``# nta: ignore[unbounded-cache]`` with a
WHY.

Heuristics (kept conservative on the shrink side — ANY shrink/rebind
anywhere in the owning scope clears the container, since this checker
cannot prove the path is reachable):

- growth must happen inside a function/method other than the creating
  ``__init__`` (top-level one-shot registration isn't steady-state);
- instance attrs are tracked per class; ``self.X`` rebinds anywhere in
  the class count as shrink. Module globals are tracked per module;
- aliasing (``y = self.X`` then mutations through ``y``) is resolved one
  hop inside the same function body.
"""

from __future__ import annotations

import ast
import re
from typing import Optional

from .framework import Finding, Project, register

#: planes whose objects are scoped to one evaluation/run by construction
#: (scheduler iterator stacks, struct scratch builders, the one-shot
#: analysis CLI, the loadgen client whose accumulators ARE the run's
#: measurement): a container there dies with its short-lived owner
_EXEMPT_PREFIXES = (
    "nomad_tpu/scheduler/",
    "nomad_tpu/structs/",
    "nomad_tpu/analysis/",
    "nomad_tpu/loadgen/",
)

#: functions whose growth is startup/import-time registration, not
#: steady-state traffic (route tables, endpoint registries, thread
#: launch lists): growth seen ONLY here doesn't flag
_STARTUP_FN_RE = re.compile(
    r"^(start|setup|_setup\w*|register\w*|route|deco|install\w*)$"
)

#: call attrs that grow a container
_GROW_METHODS = {
    "append", "add", "setdefault", "extend", "insert", "update",
    "appendleft", "push",
}
#: call attrs that shrink (or can shrink) a container
_SHRINK_METHODS = {
    "pop", "popitem", "clear", "remove", "discard", "popleft",
}
#: constructor calls that create an empty growable container
_CONTAINER_CALLS = {"dict", "set", "list", "defaultdict", "OrderedDict", "deque"}


def _is_container_ctor(node: ast.AST) -> bool:
    if isinstance(node, (ast.Dict, ast.List, ast.Set)):
        # literal {} / [] — non-empty literals are config tables, not caches
        return not getattr(node, "keys", None) and not getattr(node, "elts", None)
    if isinstance(node, ast.Call) and isinstance(node.func, ast.Name):
        if node.func.id == "deque":
            # deque with a REAL maxlen is bounded by construction — the
            # ring idiom this checker must not cry wolf on. An explicit
            # maxlen=None is a bare unbounded deque and still flags.
            def _bound(arg):
                return not (
                    isinstance(arg, ast.Constant) and arg.value is None
                )

            for kw in node.keywords:
                if kw.arg == "maxlen":
                    return not _bound(kw.value)
            if len(node.args) == 2:  # deque(iterable, maxlen)
                return not _bound(node.args[1])
        return node.func.id in _CONTAINER_CALLS
    return False


class _Access:
    """One observed use of a tracked container: grow, shrink, or rebind."""

    __slots__ = ("kind", "line", "how")

    def __init__(self, kind: str, line: int, how: str):
        self.kind = kind
        self.line = line
        self.how = how


def _attr_of_self(node: ast.AST) -> Optional[str]:
    """'x' for a ``self.x`` expression."""
    if (
        isinstance(node, ast.Attribute)
        and isinstance(node.value, ast.Name)
        and node.value.id == "self"
    ):
        return node.attr
    return None


def _scan_function(fn: ast.AST, names: set, is_attr: bool, out: dict):
    """Collect accesses to tracked containers inside one function body.

    ``names`` are attr names (for ``self.X``) or global names; accesses
    land in ``out[name] -> list[_Access]``. One level of aliasing inside
    the function (``alias = self.X``) is followed.
    """
    aliases: dict[str, str] = {}

    # module-global mode: a plain ``NAME = ...`` without a ``global NAME``
    # declaration makes NAME function-LOCAL for the whole scope (Python
    # scoping), so every access to it in this function touches the local
    # shadow, not the tracked global — misreading the shadow as a
    # rebind/shrink of the global silences the rule for exactly the leak
    # class it exists to catch
    shadowed: set = set()
    if not is_attr:
        declared_global: set = set()
        for node in ast.walk(fn):
            if isinstance(node, ast.Global):
                declared_global.update(node.names)
        for node in ast.walk(fn):
            if isinstance(node, (ast.Assign, ast.AnnAssign, ast.AugAssign)):
                tgts = (
                    node.targets
                    if isinstance(node, ast.Assign)
                    else [node.target]
                )
                for t in tgts:
                    if (
                        isinstance(t, ast.Name)
                        and t.id in names
                        and t.id not in declared_global
                    ):
                        shadowed.add(t.id)

    def target_name(expr: ast.AST) -> Optional[str]:
        if is_attr:
            name = _attr_of_self(expr)
            if name in names:
                return name
            if isinstance(expr, ast.Name) and expr.id in aliases:
                return aliases[expr.id]
            return None
        if (
            isinstance(expr, ast.Name)
            and expr.id in names
            and expr.id not in shadowed
        ):
            return expr.id
        return None

    fname = getattr(fn, "name", "<fn>")
    in_init = fname == "__init__"
    # pre-pass: register aliases (``m = self.X``) before the access walk,
    # so walk order can't matter and the alias assignment itself isn't
    # misread as a rebind of the container
    alias_nodes: set = set()
    for node in ast.walk(fn):
        if isinstance(node, ast.Assign) and len(node.targets) == 1:
            tgt, val = node.targets[0], node.value
            src = None
            if is_attr:
                src = _attr_of_self(val)
            elif isinstance(val, ast.Name) and val.id in names:
                src = val.id
            if (
                src in names
                and isinstance(tgt, ast.Name)
                and not isinstance(val, ast.Call)
            ):
                aliases[tgt.id] = src
                alias_nodes.add(id(node))
    for node in ast.walk(fn):
        if id(node) in alias_nodes:
            continue
        # rebind: self.X = <anything> outside the creating __init__
        if isinstance(node, (ast.Assign, ast.AugAssign, ast.AnnAssign)):
            if isinstance(node, ast.Assign):
                targets = node.targets
            elif isinstance(node, ast.AnnAssign):
                targets = [node.target] if node.value is not None else []
            else:
                targets = [node.target]
            for tgt in targets:
                name = target_name(tgt)
                if name is not None and not isinstance(tgt, ast.Subscript):
                    if isinstance(node, ast.AugAssign):
                        # ``x += [e]`` / ``m |= d`` accumulate INTO the
                        # container — growth, not a rebind. Only the
                        # subtractive ops shrink (``s -= other``,
                        # ``s &= other``); anything else counts as grow
                        # so a leak can't hide behind an odd operator
                        if isinstance(node.op, (ast.Sub, ast.BitAnd)):
                            out.setdefault(name, []).append(
                                _Access("shrink", node.lineno, "augassign")
                            )
                        elif not in_init:
                            out.setdefault(name, []).append(
                                _Access(
                                    "grow", node.lineno, f"{fname}: augassign"
                                )
                            )
                    elif not in_init:
                        out.setdefault(name, []).append(
                            _Access("shrink", node.lineno, "rebind")
                        )
                    continue
                # keyed insert: self.X[k] = v  (AugAssign on a key is
                # accumulation into an existing slot, not new growth)
                if (
                    isinstance(node, ast.Assign)
                    and isinstance(tgt, ast.Subscript)
                ):
                    name = target_name(tgt.value)
                    if name is not None and not in_init:
                        out.setdefault(name, []).append(
                            _Access("grow", node.lineno, f"{fname}: [k] =")
                        )
        elif isinstance(node, ast.Delete):
            for tgt in node.targets:
                base = tgt.value if isinstance(tgt, ast.Subscript) else tgt
                name = target_name(base)
                if name is not None:
                    out.setdefault(name, []).append(
                        _Access("shrink", node.lineno, "del")
                    )
        elif isinstance(node, ast.Call) and isinstance(
            node.func, ast.Attribute
        ):
            name = target_name(node.func.value)
            if name is None:
                continue
            meth = node.func.attr
            if meth in _GROW_METHODS and not in_init:
                out.setdefault(name, []).append(
                    _Access("grow", node.lineno, f"{fname}: .{meth}()")
                )
            elif meth in _SHRINK_METHODS:
                out.setdefault(name, []).append(
                    _Access("shrink", node.lineno, f".{meth}()")
                )


def _check_class(mod, cls: ast.ClassDef) -> list[Finding]:
    # containers created in __init__ as self.X = {} / [] / set() / ...
    created: dict[str, int] = {}
    for stmt in cls.body:
        if not (
            isinstance(stmt, ast.FunctionDef) and stmt.name == "__init__"
        ):
            continue
        for node in ast.walk(stmt):
            tgt = val = None
            if isinstance(node, ast.Assign) and len(node.targets) == 1:
                tgt, val = node.targets[0], node.value
            elif isinstance(node, ast.AnnAssign) and node.value is not None:
                tgt, val = node.target, node.value
            if tgt is not None:
                name = _attr_of_self(tgt)
                if name is not None and _is_container_ctor(val):
                    created[name] = node.lineno
    if not created:
        return []
    accesses: dict[str, list[_Access]] = {}
    for stmt in ast.walk(cls):
        if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
            _scan_function(stmt, set(created), True, accesses)
    return _emit(mod, cls.name, created, accesses)


def _check_module_globals(mod) -> list[Finding]:
    created: dict[str, int] = {}
    for stmt in mod.tree.body:
        tgt = val = None
        if isinstance(stmt, ast.Assign) and len(stmt.targets) == 1:
            tgt, val = stmt.targets[0], stmt.value
        elif isinstance(stmt, ast.AnnAssign) and stmt.value is not None:
            tgt, val = stmt.target, stmt.value
        if (
            tgt is not None
            and isinstance(tgt, ast.Name)
            and _is_container_ctor(val)
        ):
            created[tgt.id] = stmt.lineno
    if not created:
        return []
    accesses: dict[str, list[_Access]] = {}
    for stmt in ast.walk(mod.tree):
        if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
            _scan_function(stmt, set(created), False, accesses)
    return _emit(mod, None, created, accesses)


def _emit(mod, cls_name, created, accesses) -> list[Finding]:
    findings = []
    for name, line in sorted(created.items()):
        acc = accesses.get(name, [])
        grows = [
            a
            for a in acc
            if a.kind == "grow"
            and not _STARTUP_FN_RE.match(a.how.split(":", 1)[0])
        ]
        shrinks = [a for a in acc if a.kind == "shrink"]
        if not grows or shrinks:
            continue
        owner = f"{cls_name}.{name}" if cls_name else name
        hows = sorted({a.how for a in grows})
        findings.append(
            Finding(
                "unbounded-cache", mod.relpath, line,
                f"{owner} only ever grows ({'; '.join(hows[:4])}) — no "
                "eviction/pop/clear/rebind on any path; bound it or "
                "suppress with a WHY if key cardinality is fixed",
            )
        )
    return findings


@register(
    "unbounded-cache",
    "long-lived dict/list/set grown on steady-state paths with no "
    "eviction anywhere (the _bad_http_addrs leak class)",
)
def check_unbounded_cache(project: Project) -> list[Finding]:
    findings: list[Finding] = []
    for mod in project.modules:
        if any(mod.relpath.startswith(p) for p in _EXEMPT_PREFIXES):
            continue
        for node in mod.tree.body:
            if isinstance(node, ast.ClassDef):
                findings.extend(_check_class(mod, node))
        findings.extend(_check_module_globals(mod))
    return findings


# ---------------------------------------------------------------------------
# subscriber-eviction: the event plane's stronger contract
# ---------------------------------------------------------------------------

#: the broker plane: containers here hold PER-SUBSCRIBER state (queues,
#: filters, pending frames, adopted sockets) whose cardinality is set by
#: external watchers — traffic, not code
_BROKER_PREFIX = "nomad_tpu/events/"

#: method names that ARE eviction paths (the slow-consumer close family)
_EVICT_NAME_RE = re.compile(
    r"(close|evict|unsubscribe|drop|reap|teardown|shutdown|reset)", re.I
)


def _fn_calls_and_guards(fn: ast.AST, names: set) -> tuple[set, set]:
    """(self-methods called, tracked containers len()-guarded inside a
    comparison) within ``fn``. Only SELF-methods count toward eviction
    reachability — ``sock.close()`` or ``f.close()`` must not launder a
    grow site — and only a ``len(self.X)`` that feeds a comparison is a
    cap check (``log(len(self.X))`` is observability, not a bound)."""
    called: set[str] = set()
    guarded: set[str] = set()
    for node in ast.walk(fn):
        if isinstance(node, ast.Call):
            func = node.func
            if (
                isinstance(func, ast.Attribute)
                and isinstance(func.value, ast.Name)
                and func.value.id == "self"
            ):
                called.add(func.attr)
        elif isinstance(node, ast.Compare):
            for expr in [node.left, *node.comparators]:
                if (
                    isinstance(expr, ast.Call)
                    and isinstance(expr.func, ast.Name)
                    and expr.func.id == "len"
                    and expr.args
                ):
                    attr = _attr_of_self(expr.args[0])
                    if attr in names:
                        guarded.add(attr)
    return called, guarded


@register(
    "subscriber-eviction",
    "broker-owned per-subscriber state grown at an append site with no "
    "reachable eviction: every grow site in nomad_tpu/events/ must "
    "shrink the container, be cap-guarded (len() comparison), or call "
    "an eviction path (close/evict/unsubscribe/drop)",
)
def check_subscriber_eviction(project: Project) -> list[Finding]:
    """The event plane holds per-subscriber state (queues, filters,
    pending frames, adopted sockets) in broker-owned containers whose
    cardinality external watchers control. ``unbounded-cache`` accepts a
    shrink ANYWHERE in the class; at production fan-out that is not
    enough — a grow site whose flow can't reach the slow-consumer close
    is a queue that fills while the eviction path idles elsewhere. So
    inside ``nomad_tpu/events/`` every grow site must itself (a) shrink
    the container, (b) guard on ``len(container)`` (explicit cap — the
    overflow return feeds the caller's close), or (c) call an
    eviction-named path (close/evict/unsubscribe/drop/…), directly or
    one self-method hop away. Deliberate exceptions carry
    ``# nta: ignore[subscriber-eviction]`` with a WHY."""
    findings: list[Finding] = []
    for mod in project.modules:
        if not mod.relpath.startswith(_BROKER_PREFIX):
            continue
        for cls in mod.tree.body:
            if not isinstance(cls, ast.ClassDef):
                continue
            created: dict[str, int] = {}
            for stmt in cls.body:
                if not (
                    isinstance(stmt, ast.FunctionDef)
                    and stmt.name == "__init__"
                ):
                    continue
                for node in ast.walk(stmt):
                    tgt = val = None
                    if isinstance(node, ast.Assign) and len(node.targets) == 1:
                        tgt, val = node.targets[0], node.value
                    elif isinstance(node, ast.AnnAssign) and node.value is not None:
                        tgt, val = node.target, node.value
                    if tgt is not None:
                        name = _attr_of_self(tgt)
                        if name is not None and _is_container_ctor(val):
                            created[name] = node.lineno
            if not created:
                continue
            names = set(created)
            # per-method accesses: shrink locality is the whole point
            methods = [
                stmt
                for stmt in ast.walk(cls)
                if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef))
            ]
            shrinks_by_method: dict[str, set] = {}
            for fn in methods:
                acc: dict[str, list[_Access]] = {}
                _scan_function(fn, names, True, acc)
                shrinks_by_method[fn.name] = {
                    n
                    for n, a in acc.items()
                    if any(x.kind == "shrink" for x in a)
                }
            for fn in methods:
                if fn.name == "__init__":
                    continue
                acc: dict[str, list[_Access]] = {}
                _scan_function(fn, names, True, acc)
                grows = {
                    n: [x for x in a if x.kind == "grow"]
                    for n, a in acc.items()
                }
                called, guarded = _fn_calls_and_guards(fn, names)
                for name, sites in grows.items():
                    if not sites:
                        continue
                    ok = (
                        name in shrinks_by_method.get(fn.name, ())
                        or name in guarded
                        or _EVICT_NAME_RE.search(fn.name) is not None
                        or any(
                            name in shrinks_by_method.get(m, ())
                            or _EVICT_NAME_RE.search(m)
                            for m in called
                        )
                    )
                    if ok:
                        continue
                    for site in sites:
                        findings.append(
                            Finding(
                                "subscriber-eviction",
                                mod.relpath,
                                site.line,
                                f"{cls.name}.{name} grows in {fn.name} "
                                "with no reachable eviction: shrink it "
                                "here, cap it with a len() guard, or "
                                "route through a close/evict path",
                            )
                        )
    return findings
