"""Preemption: choose lower-priority allocations to evict when a placement
doesn't fit (ref scheduler/preemption.go).

Semantics reproduced: candidates must be ≥10 priority below the placing job,
grouped by priority (lowest first), greedily picked by resource-distance with
a max_parallel penalty (cap 50/excess), then trimmed by filter_superset.
"""

from __future__ import annotations

import math
from typing import Optional

from ..structs.model import (
    AllocatedResources,
    AllocatedTaskResources,
    Allocation,
    ComparableResources,
    NetworkResource,
    Node,
    RequestedDevice,
)
from ..structs.network import NetworkIndex
from .context import EvalContext

MAX_PARALLEL_PENALTY = 50.0


def basic_resource_distance(
    ask: ComparableResources, used: ComparableResources
) -> float:
    """Euclidean distance in normalized (mem, cpu, disk) space
    (ref preemption.go:608-624)."""
    memory_coord = cpu_coord = disk_coord = 0.0
    if ask.flattened.memory.memory_mb > 0:
        memory_coord = (
            float(ask.flattened.memory.memory_mb)
            - float(used.flattened.memory.memory_mb)
        ) / float(ask.flattened.memory.memory_mb)
    if ask.flattened.cpu.cpu_shares > 0:
        cpu_coord = (
            float(ask.flattened.cpu.cpu_shares) - float(used.flattened.cpu.cpu_shares)
        ) / float(ask.flattened.cpu.cpu_shares)
    if ask.shared.disk_mb > 0:
        disk_coord = (
            float(ask.shared.disk_mb) - float(used.shared.disk_mb)
        ) / float(ask.shared.disk_mb)
    return math.sqrt(memory_coord**2 + cpu_coord**2 + disk_coord**2)


def network_resource_distance(
    used: Optional[NetworkResource], needed: Optional[NetworkResource]
) -> float:
    """ref preemption.go:627-635"""
    if used is None or needed is None:
        return math.inf
    return abs(float(needed.mbits - used.mbits) / float(needed.mbits))


def score_for_task_group(
    ask: ComparableResources,
    used: ComparableResources,
    max_parallel: int,
    num_preempted: int,
) -> float:
    """ref preemption.go:640-646"""
    penalty = 0.0
    if max_parallel > 0 and num_preempted >= max_parallel:
        penalty = float((num_preempted + 1) - max_parallel) * MAX_PARALLEL_PENALTY
    return basic_resource_distance(ask, used) + penalty


def score_for_network(
    used: Optional[NetworkResource],
    needed: Optional[NetworkResource],
    max_parallel: int,
    num_preempted: int,
) -> float:
    """ref preemption.go:650-659"""
    if used is None or needed is None:
        return math.inf
    penalty = 0.0
    if max_parallel > 0 and num_preempted >= max_parallel:
        penalty = float((num_preempted + 1) - max_parallel) * MAX_PARALLEL_PENALTY
    return network_resource_distance(used, needed) + penalty


def filter_and_group_preemptible_allocs(
    job_priority: int, current: list[Allocation]
) -> list[tuple[int, list[Allocation]]]:
    """Group by priority (ascending) after filtering allocs within a priority
    delta of 10 (ref preemption.go:663-697)."""
    by_priority: dict[int, list[Allocation]] = {}
    for alloc in current:
        if alloc.job is None:
            continue
        if job_priority - alloc.job.priority < 10:
            continue
        by_priority.setdefault(alloc.job.priority, []).append(alloc)
    return sorted(by_priority.items())


class Preemptor:
    """ref preemption.go:96-454"""

    def __init__(
        self, job_priority: int, ctx: EvalContext, job_id: Optional[tuple[str, str]]
    ):
        self.current_preemptions: dict[tuple[str, str], dict[str, int]] = {}
        self.alloc_details: dict[str, dict] = {}
        self.job_priority = job_priority
        self.job_id = job_id
        self.node_remaining_resources: Optional[ComparableResources] = None
        self.current_allocs: list[Allocation] = []
        self.ctx = ctx

    def set_node(self, node: Node):
        remaining = node.comparable_resources()
        reserved = node.comparable_reserved_resources()
        if reserved is not None:
            remaining.subtract(reserved)
        self.node_remaining_resources = remaining

    def set_candidates(self, allocs: list[Allocation]):
        self.current_allocs = []
        for alloc in allocs:
            if (
                self.job_id is not None
                and alloc.job_id == self.job_id[1]
                and alloc.namespace == self.job_id[0]
            ):
                continue
            max_parallel = 0
            if alloc.job is not None:
                tg = alloc.job.lookup_task_group(alloc.task_group)
                if tg is not None and tg.migrate is not None:
                    max_parallel = tg.migrate.max_parallel
            self.alloc_details[alloc.id] = {
                "max_parallel": max_parallel,
                "resources": alloc.comparable_resources(),
            }
            self.current_allocs.append(alloc)

    def set_preemptions(self, allocs: list[Allocation]):
        self.current_preemptions = {}
        for alloc in allocs:
            key = (alloc.namespace, alloc.job_id)
            self.current_preemptions.setdefault(key, {})
            self.current_preemptions[key][alloc.task_group] = (
                self.current_preemptions[key].get(alloc.task_group, 0) + 1
            )

    def _num_preemptions(self, alloc: Allocation) -> int:
        return self.current_preemptions.get((alloc.namespace, alloc.job_id), {}).get(
            alloc.task_group, 0
        )

    # ------------------------------------------------------------------
    def preempt_for_task_group(
        self, resource_ask: AllocatedResources
    ) -> list[Allocation]:
        """ref preemption.go:198-265"""
        resources_needed = resource_ask.comparable()

        for alloc in self.current_allocs:
            self.node_remaining_resources.subtract(
                self.alloc_details[alloc.id]["resources"]
            )

        allocs_by_priority = filter_and_group_preemptible_allocs(
            self.job_priority, self.current_allocs
        )

        best_allocs: list[Allocation] = []
        all_requirements_met = False
        available = self.node_remaining_resources.copy()
        resources_asked = resource_ask.comparable()

        for _, grp_allocs in allocs_by_priority:
            grp = list(grp_allocs)
            while grp and not all_requirements_met:
                closest_index = -1
                best_distance = math.inf
                for index, alloc in enumerate(grp):
                    count = self._num_preemptions(alloc)
                    details = self.alloc_details[alloc.id]
                    distance = score_for_task_group(
                        resources_needed,
                        details["resources"],
                        details["max_parallel"],
                        count,
                    )
                    if distance < best_distance:
                        best_distance = distance
                        closest_index = index
                closest = grp[closest_index]
                closest_resources = self.alloc_details[closest.id]["resources"]
                available.add(closest_resources)
                all_requirements_met, _ = available.superset(resources_asked)
                best_allocs.append(closest)
                grp[closest_index] = grp[-1]
                grp.pop()
                resources_needed.subtract(closest_resources)
            if all_requirements_met:
                break

        if not all_requirements_met:
            return []

        resources_needed = resource_ask.comparable()
        return self._filter_superset_base(
            best_allocs, self.node_remaining_resources, resources_needed
        )

    # ------------------------------------------------------------------
    def preempt_for_network(
        self, ask: NetworkResource, net_idx: NetworkIndex
    ) -> Optional[list[Allocation]]:
        """ref preemption.go:270-454. Returns None when preemption can't
        satisfy the ask (so the caller can skip this node)."""
        if not self.current_allocs:
            return None

        mbits_needed = ask.mbits
        reserved_ports_needed = ask.reserved_ports

        filtered_reserved_ports: dict[str, set[int]] = {}
        device_to_allocs: dict[str, list[Allocation]] = {}

        for alloc in self.current_allocs:
            if alloc.job is None:
                continue
            networks = self.alloc_details[alloc.id]["resources"].flattened.networks
            if not networks:
                continue
            net = networks[0]
            if self.job_priority - alloc.job.priority < 10:
                for port in net.reserved_ports:
                    filtered_reserved_ports.setdefault(net.device, set()).add(
                        port.value
                    )
                continue
            device_to_allocs.setdefault(net.device, []).append(alloc)

        if not device_to_allocs:
            return None

        allocs_to_preempt: list[Allocation] = []
        met = False
        free_bandwidth = 0
        preempted_device = ""

        for device, current_allocs in device_to_allocs.items():
            preempted_device = device
            total_bandwidth = net_idx.avail_bandwidth.get(device, 0)
            if total_bandwidth < mbits_needed:
                continue
            free_bandwidth = total_bandwidth - net_idx.used_bandwidth.get(device, 0)
            preempted_bandwidth = 0
            allocs_to_preempt = []
            skip_device = False

            if reserved_ports_needed:
                used_port_to_alloc: dict[int, Allocation] = {}
                for alloc in current_allocs:
                    for n in self.alloc_details[alloc.id][
                        "resources"
                    ].flattened.networks:
                        for p in n.reserved_ports:
                            used_port_to_alloc[p.value] = alloc
                for port in reserved_ports_needed:
                    alloc = used_port_to_alloc.get(port.value)
                    if alloc is not None:
                        preempted_bandwidth += self.alloc_details[alloc.id][
                            "resources"
                        ].flattened.networks[0].mbits
                        allocs_to_preempt.append(alloc)
                    elif port.value in filtered_reserved_ports.get(device, set()):
                        skip_device = True
                        break
                if skip_device:
                    continue
                preempt_ids = {a.id for a in allocs_to_preempt}
                current_allocs = [
                    a for a in current_allocs if a.id not in preempt_ids
                ]

            if preempted_bandwidth + free_bandwidth >= mbits_needed:
                met = True
                break

            allocs_by_priority = filter_and_group_preemptible_allocs(
                self.job_priority, current_allocs
            )
            for _, grp_allocs in allocs_by_priority:
                allocs = sorted(
                    grp_allocs, key=lambda a: self._network_distance_key(a, ask)
                )
                for alloc in allocs:
                    preempted_bandwidth += self.alloc_details[alloc.id][
                        "resources"
                    ].flattened.networks[0].mbits
                    allocs_to_preempt.append(alloc)
                    if preempted_bandwidth + free_bandwidth >= mbits_needed:
                        met = True
                        break
                if met:
                    break
            if met:
                break

        if not met:
            return None

        node_remaining = ComparableResources(
            flattened=AllocatedTaskResources(
                networks=[
                    NetworkResource(device=preempted_device, mbits=free_bandwidth)
                ]
            )
        )
        resources_needed = ComparableResources(
            flattened=AllocatedTaskResources(networks=[ask])
        )
        return self._filter_superset_network(
            allocs_to_preempt, node_remaining, resources_needed
        )

    def _network_distance_key(self, alloc: Allocation, ask: NetworkResource) -> float:
        """ref preemption.go:738-776"""
        count = self._num_preemptions(alloc)
        max_parallel = 0
        if alloc.job is not None:
            tg = alloc.job.lookup_task_group(alloc.task_group)
            if tg is not None and tg.migrate is not None:
                max_parallel = tg.migrate.max_parallel
        networks = self.alloc_details[alloc.id]["resources"].flattened.networks
        used = networks[0] if networks else None
        return score_for_network(used, ask, max_parallel, count)

    # ------------------------------------------------------------------
    def preempt_for_device(
        self, ask: RequestedDevice, dev_alloc
    ) -> Optional[list[Allocation]]:
        """ref preemption.go:472-555"""
        from .feasible import node_device_matches

        device_to_allocs: dict = {}
        for alloc in self.current_allocs:
            if alloc.allocated_resources is None:
                continue
            for tr in alloc.allocated_resources.tasks.values():
                for device in tr.devices:
                    device_id = device.device_id()
                    dev_inst = dev_alloc.devices.get(device_id)
                    if dev_inst is None:
                        continue
                    if not node_device_matches(self.ctx, dev_inst.device, ask):
                        continue
                    grp = device_to_allocs.setdefault(
                        device_id, {"allocs": [], "device_instances": {}}
                    )
                    grp["allocs"].append(alloc)
                    grp["device_instances"][alloc.id] = grp["device_instances"].get(
                        alloc.id, 0
                    ) + len(device.device_ids)

        needed_count = ask.count
        preemption_options = []

        for device_id, grp in device_to_allocs.items():
            allocs_by_priority = filter_and_group_preemptible_allocs(
                self.job_priority, grp["allocs"]
            )
            preempted_count = 0
            preempted_allocs: list[Allocation] = []
            satisfied = False
            for _, grp_allocs in allocs_by_priority:
                for alloc in grp_allocs:
                    dev_inst = dev_alloc.devices[device_id]
                    preempted_count += grp["device_instances"][alloc.id]
                    preempted_allocs.append(alloc)
                    if preempted_count + dev_inst.free_count() >= needed_count:
                        preemption_options.append(
                            {
                                "allocs": preempted_allocs,
                                "device_instances": grp["device_instances"],
                            }
                        )
                        satisfied = True
                        break
                if satisfied:
                    break

        if preemption_options:
            return _select_best_allocs(preemption_options, needed_count)
        return None

    # ------------------------------------------------------------------
    def _filter_superset_base(
        self,
        best_allocs: list[Allocation],
        node_remaining: ComparableResources,
        resource_ask: ComparableResources,
    ) -> list[Allocation]:
        """ref preemption.go:702-733 with base-resource distance."""
        best_allocs = sorted(
            best_allocs,
            key=lambda a: basic_resource_distance(
                resource_ask, self.alloc_details[a.id]["resources"]
            ),
            reverse=True,
        )
        available = node_remaining.copy()
        filtered: list[Allocation] = []
        for alloc in best_allocs:
            filtered.append(alloc)
            available.add(self.alloc_details[alloc.id]["resources"])
            met, _ = available.superset(resource_ask)
            if met:
                break
        return filtered

    def _filter_superset_network(
        self,
        best_allocs: list[Allocation],
        node_remaining: ComparableResources,
        resource_ask: ComparableResources,
    ) -> list[Allocation]:
        """ref preemption.go:702-733 with network distance."""
        needed = resource_ask.flattened.networks[0]

        def distance(a: Allocation) -> float:
            networks = self.alloc_details[a.id]["resources"].flattened.networks
            used = networks[0] if networks else None
            return network_resource_distance(used, needed)

        best_allocs = sorted(best_allocs, key=distance, reverse=True)
        available_mbits = node_remaining.flattened.networks[0].mbits
        filtered: list[Allocation] = []
        for alloc in best_allocs:
            filtered.append(alloc)
            networks = self.alloc_details[alloc.id]["resources"].flattened.networks
            if networks:
                available_mbits += networks[0].mbits
            if available_mbits != 0 and needed.mbits != 0 and available_mbits >= needed.mbits:
                break
        return filtered


def _select_best_allocs(preemption_options: list[dict], needed_count: int):
    """Choose the option with the lowest net (unique-priority-sum) priority
    (ref preemption.go:559-604)."""
    best_priority = math.inf
    best_allocs: list[Allocation] = []
    for grp in preemption_options:
        dev_inst = grp["device_instances"]
        allocs = sorted(grp["allocs"], key=lambda a: dev_inst[a.id], reverse=True)
        priorities: set[int] = set()
        net_priority = 0
        filtered: list[Allocation] = []
        preempted_instance_count = 0
        for alloc in allocs:
            if preempted_instance_count >= needed_count:
                break
            preempted_instance_count += dev_inst[alloc.id]
            filtered.append(alloc)
            if alloc.job.priority not in priorities:
                priorities.add(alloc.job.priority)
                net_priority += alloc.job.priority
        if net_priority < best_priority:
            best_priority = net_priority
            best_allocs = filtered
    return best_allocs
