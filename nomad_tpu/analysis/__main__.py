"""CLI: ``python -m nomad_tpu.analysis``.

Exit codes: 0 = clean (modulo baseline), 1 = new findings, 2 = usage or
internal error. ``--write-baseline`` accepts the current findings as the
new baseline (use after deliberately burning findings down, never to
bury a regression).
"""

from __future__ import annotations

import argparse
import json
import os
import sys

from . import (
    BASELINE_NAME,
    CHECKER_DOCS,
    CHECKERS,
    Project,
    load_baseline,
    partition,
    repo_root,
    run,
    write_baseline,
)


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m nomad_tpu.analysis",
        description="lock-order + JAX hot-path + raft-index static analyzer",
    )
    parser.add_argument(
        "--format", choices=("text", "json"), default="text"
    )
    parser.add_argument(
        "--root", default=None, help="repo root (default: auto-detect)"
    )
    parser.add_argument(
        "--baseline", default=None,
        help=f"baseline path (default: <root>/{BASELINE_NAME})",
    )
    parser.add_argument(
        "--no-baseline", action="store_true",
        help="report every finding, including baselined ones",
    )
    parser.add_argument(
        "--write-baseline", action="store_true",
        help="accept current findings as the new baseline",
    )
    parser.add_argument(
        "--rules", default=None,
        help="comma-separated checker subset (default: all)",
    )
    parser.add_argument(
        "--list-rules", action="store_true",
        help="print the checker catalog and exit",
    )
    parser.add_argument(
        "paths", nargs="*",
        help="limit findings to these repo-relative path prefixes",
    )
    args = parser.parse_args(argv)

    if args.list_rules:
        for name in sorted(CHECKERS):
            print(f"{name}: {CHECKER_DOCS.get(name, '')}")
        return 0

    rules = None
    if args.rules:
        rules = [r.strip() for r in args.rules.split(",") if r.strip()]
        unknown = [r for r in rules if r not in CHECKERS]
        if unknown:
            print(f"unknown rules: {', '.join(unknown)}", file=sys.stderr)
            return 2

    root = args.root or repo_root()
    baseline_path = args.baseline or os.path.join(root, BASELINE_NAME)
    try:
        project = Project.load(root)
        findings = run(project, rules)
    except Exception as e:
        print(f"analysis failed: {e}", file=sys.stderr)
        return 2

    if args.paths:
        findings = [
            f
            for f in findings
            if any(f.path.startswith(p) for p in args.paths)
        ]

    if args.write_baseline:
        write_baseline(findings, baseline_path)
        print(
            f"wrote {len(findings)} finding(s) to {baseline_path}",
            file=sys.stderr,
        )
        return 0

    baseline = {} if args.no_baseline else load_baseline(baseline_path)
    new, known = partition(findings, baseline)

    if args.format == "json":
        by_rule: dict[str, int] = {}
        for f in new:
            by_rule[f.rule] = by_rule.get(f.rule, 0) + 1
        print(
            json.dumps(
                {
                    "new": [f.to_dict() for f in new],
                    "new_count": len(new),
                    "baselined_count": len(known),
                    "by_rule": by_rule,
                },
                indent=2,
            )
        )
    else:
        for f in new:
            print(f.format())
        print(
            f"{len(new)} new finding(s), {len(known)} baselined",
            file=sys.stderr,
        )
    return 1 if new else 0


if __name__ == "__main__":
    sys.exit(main())
