#!/usr/bin/env python
"""Headline benchmark (BASELINE.md north star, config #4): plan 50K pending
allocations against a 10K-node simulated cluster — spread over the datacenter
attribute, preemption enabled — with the tpu-batch scheduler in <1s
end-to-end wall-clock on one TPU chip, at >=99% placement parity with the
scalar oracle (the Go BinPackIterator semantics).

Also runs the remaining BASELINE configs:
  #2 — 1K synthetic service jobs (cpu/mem only) vs 100 mock nodes, scoring
       parity per placement plus evals/sec and p99 plan latency,
  #3 — 10K batch allocs with constraint{} + affinity{} vs 1K nodes,
  #5 — mixed service+system jobs with device{} asks and NetworkIndex port
       collisions at 10K nodes (the exact-semantics oracle fallback path).

Parity at bench scale is measured three ways:
  * parity_exact  — the fast-path (runs/windowed) placements vs the exact
    one-step-per-placement scan kernel over ALL 50K placements (the exact
    scan is itself oracle-validated by tests/test_tpu_parity.py),
  * parity_oracle — oracle engines re-run position-by-position over windows
    of the very same eval (empty-state prefix + mid-sequence windows
    restarted from the fast path's own intermediate state at 20/50/80%,
    valid because placement i depends only on its predecessors): the
    vectorized float64 oracle (tpu/exact_np.py) carries >10% coverage and
    the scalar iterator chain adds spot windows, and
  * parity_np_scalar_pin — scalar-chain vs vectorized-oracle agreement at
    the SAME positions inside this run, keeping the trust chain rooted in
    the per-node Go-semantics walk (plus tests/test_tpu_parity.py's
    TestVectorOracleParity shape coverage).

Prints exactly one JSON line:
  {"metric": ..., "value": ..., "unit": ..., "vs_baseline": ..., "detail": ...}
value = end-to-end seconds for the headline eval (lower is better);
vs_baseline = 1s-target / value (higher is better).

Env knobs: BENCH_NODES, BENCH_ALLOCS, BENCH_SPREAD=0 (disable spread),
BENCH_PARITY_K (oracle prefix sample), BENCH_FAST=1 (headline only),
BENCH_WAVEFRONT_{NODES,ALLOCS,TENANTS,PARITY_ALLOCS} (multi-tenant
wavefront arm of the sharded section).
"""

import json
import os
import random
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

N_NODES = int(os.environ.get("BENCH_NODES", "10000"))
N_ALLOCS = int(os.environ.get("BENCH_ALLOCS", "50000"))
#: scalar-chain oracle placements checked PER WINDOW (2 spot windows that
#: pin the vectorized oracle; ~0.3s/placement at 10K nodes)
PARITY_K = int(os.environ.get("BENCH_PARITY_K", "128"))
#: vectorized-oracle (oracle-np) placements checked PER WINDOW (4 windows:
#: empty prefix + mid-sequence at 20/50/80% — >10% of the 50K placements
#: oracle-checked in total at ~1.7ms/placement)
PARITY_NP_K = int(os.environ.get("BENCH_PARITY_NP_K", "1536"))
TARGET_S = 1.0


def build_nodes(n, networks=False, devices_every=0):
    """Heterogeneous cluster: 4 hardware classes x 4 datacenters. Node IDs
    are deterministic (seeded) so parity workers in other processes can
    rebuild the byte-identical cluster instead of pickling 10K nodes."""
    from nomad_tpu import mock
    from nomad_tpu.structs import compute_class

    rng = random.Random(7)
    idrng = random.Random(7001)

    def det_uuid():
        return "%08x-%04x-%04x-%04x-%012x" % (
            idrng.getrandbits(32),
            idrng.getrandbits(16),
            idrng.getrandbits(16),
            idrng.getrandbits(16),
            idrng.getrandbits(48),
        )
    # build one template per class, then stamp copies (compute_class is
    # identical within a class, so hash once)
    templates = []
    for cpu, mem in ((4000, 8192), (8000, 16384), (16000, 32768), (32000, 65536)):
        for dc in ("dc1", "dc2", "dc3", "dc4"):
            t = mock.node()
            t.node_resources.cpu.cpu_shares = cpu
            t.node_resources.memory.memory_mb = mem
            t.datacenter = dc
            if not networks:
                t.node_resources.networks = []
                t.reserved_resources.networks.reserved_host_ports = ""
            compute_class(t)
            templates.append(t)
    tpu_template = None
    if devices_every:
        tpu_template = mock.tpu_node()
        tpu_template.datacenter = "dc1"
        tpu_template.attributes["tpu.count"] = "2"
        if not networks:
            tpu_template.node_resources.networks = []
            tpu_template.reserved_resources.networks.reserved_host_ports = ""
        compute_class(tpu_template)
    nodes = []
    for i in range(n):
        if devices_every and i % devices_every == 0:
            t = tpu_template
        else:
            t = templates[rng.randrange(len(templates))]
        node = t.copy()
        node.id = det_uuid()
        nodes.append(node)
    return nodes


def build_job(count, spread=True):
    from nomad_tpu import mock
    from nomad_tpu.structs.model import Constraint, Spread, SpreadTarget

    job = mock.job()
    job.datacenters = ["dc1", "dc2", "dc3", "dc4"]
    tg = job.task_groups[0]
    tg.count = count
    tg.tasks[0].resources.cpu = 100
    tg.tasks[0].resources.memory_mb = 128
    tg.tasks[0].resources.networks = []
    tg.ephemeral_disk.size_mb = 10
    job.constraints = [
        Constraint(l_target="${attr.kernel.name}", r_target="linux", operand="=")
    ]
    if spread:
        job.spreads = [
            Spread(
                attribute="${node.datacenter}",
                weight=100,
                spread_target=[
                    SpreadTarget(value=f"dc{i}", percent=25) for i in (1, 2, 3, 4)
                ],
            )
        ]
    return job


class NullPlanner:
    """Records the plan without applying it (plan-apply is benchmarked
    separately; this isolates scheduling latency)."""

    def __init__(self):
        self.plans = []
        self.evals = []

    def submit_plan(self, plan):
        from nomad_tpu.structs.model import PlanResult

        self.plans.append(plan)
        result = PlanResult(
            node_update=plan.node_update,
            node_allocation=plan.node_allocation,
            node_preemptions=plan.node_preemptions,
            alloc_index=1,
        )
        return result, None

    def update_eval(self, eval):
        self.evals.append(eval)

    def create_eval(self, eval):
        self.evals.append(eval)

    def reblock_eval(self, eval):
        self.reblock_evals = getattr(self, "reblock_evals", [])
        self.reblock_evals.append(eval)


def make_eval(job):
    from nomad_tpu.structs.model import Evaluation, generate_uuid

    return Evaluation(
        id=generate_uuid(),
        namespace=job.namespace,
        priority=job.priority,
        type=job.type,
        triggered_by="job-register",
        job_id=job.id,
        status="pending",
    )


def placements_of(planner):
    return {
        a.name: a.node_id
        for allocs in planner.plans[0].node_allocation.values()
        for a in allocs
    }


def run_once(state, job, factory="tpu-batch", seed=11, prefix=None):
    """One scheduling pass against a snapshot; returns (elapsed, placements).

    prefix=K truncates the placement loop to the first K pending allocations
    — valid for parity sampling because placement i depends only on
    placements < i (the spread/anti-affinity planes and capacity are updated
    sequentially), so the truncated run's placements equal the full run's
    first K. Supported for the scalar oracle ("service") and the vectorized
    float64 oracle ("oracle-np", tpu/exact_np.py).
    """
    from nomad_tpu.scheduler.generic import GenericScheduler
    from nomad_tpu.scheduler.scheduler import new_scheduler

    planner = NullPlanner()
    rng = random.Random(seed)
    snap = state.snapshot()
    if prefix is None:
        sched = new_scheduler(factory, snap, planner, rng=rng)
    elif factory == "service":

        class PrefixOracle(GenericScheduler):
            def _compute_placements(self, destructive, place):
                return super()._compute_placements(destructive, place[:prefix])

        sched = PrefixOracle(snap, planner, batch=False, rng=rng)
    elif factory == "oracle-np":
        from nomad_tpu.tpu.batch_sched import TPUBatchScheduler

        class PrefixNpOracle(TPUBatchScheduler):
            def _compute_placements(self, destructive, place):
                return super()._compute_placements(destructive, place[:prefix])

        sched = PrefixNpOracle(snap, planner, batch=False, rng=rng)
        sched.exact_numpy = True
    else:
        raise ValueError("prefix sampling drives the oracle engines")
    ev = make_eval(job)
    t0 = time.monotonic()
    sched.process(ev)
    elapsed = time.monotonic() - t0
    return elapsed, placements_of(planner) if planner.plans else {}


def parity(a: dict, b: dict, keys=None) -> float:
    """Fraction of reference placements (a) matched by b. An empty reference
    means nothing was compared — report 0.0 rather than a vacuous pass."""
    keys = list(keys if keys is not None else a)
    if not keys:
        return 0.0
    return sum(1 for k in keys if a.get(k) == b.get(k)) / len(keys)


def _alloc_index(name: str) -> int:
    return int(name.rsplit("[", 1)[1][:-1])


def _oracle_window_worker(payload):
    """Run an oracle engine (scalar chain or the float64 numpy stepper) for
    placements [M, M+K) of the headline eval; return {name: node_id}.

    Valid mid-sequence because placement i depends only on its
    predecessors: the state after the fast path's first M placements is
    reconstructed exactly by inserting M live allocs matching them (same
    usage, job-anti-affinity collisions, and spread counts the scan carry
    held at step M; verified against the exact-scan kernel re-run from the
    same reconstruction). The allocs carry the STORE's job copy so the
    reconciler sees them as current — a job_modify_index mismatch would
    in-place-update them into the plan and double-count every spread/anti
    plane (propertyset.go combines existing + proposed)."""
    import pickle

    M, K, job_blob, placed_items, n_nodes, seed, engine = payload
    job = pickle.loads(job_blob)
    placed = dict(placed_items)
    names = sorted(placed, key=_alloc_index)

    from nomad_tpu.state import StateStore
    from nomad_tpu.structs.model import (
        ALLOC_CLIENT_STATUS_RUNNING,
        ALLOC_DESIRED_STATUS_RUN,
        AllocatedCpuResources,
        AllocatedMemoryResources,
        AllocatedResources,
        AllocatedSharedResources,
        AllocatedTaskResources,
        Allocation,
        generate_uuid,
    )

    state = StateStore()
    state.upsert_nodes(1, build_nodes(n_nodes))
    state.upsert_job(2, job)
    stored_job = state.job_by_id(job.namespace, job.id)
    tg = job.task_groups[0]
    task = tg.tasks[0]
    allocs = []
    for i in range(M):
        nm = names[i]
        a = Allocation(
            id=generate_uuid(),
            namespace=job.namespace,
            job_id=job.id,
            task_group=tg.name,
            name=nm,
            node_id=placed[nm],
            desired_status=ALLOC_DESIRED_STATUS_RUN,
            client_status=ALLOC_CLIENT_STATUS_RUNNING,
            allocated_resources=AllocatedResources(
                tasks={
                    task.name: AllocatedTaskResources(
                        cpu=AllocatedCpuResources(cpu_shares=task.resources.cpu),
                        memory=AllocatedMemoryResources(
                            memory_mb=task.resources.memory_mb
                        ),
                    )
                },
                shared=AllocatedSharedResources(
                    disk_mb=tg.ephemeral_disk.size_mb
                ),
            ),
        )
        a.job = stored_job
        allocs.append(a)
    if allocs:
        state.upsert_allocs(3, allocs)

    _, placed_oracle = run_once(state, job, factory=engine, prefix=K, seed=seed)
    return engine, M, {k: placed_oracle.get(k) for k in names[M : M + K]}


def oracle_parity_windows(job, placed_fast, window_specs, seed=11):
    """Oracle parity over windows of the full-scale eval, run in parallel
    worker processes (each window is independent). ``window_specs`` is a
    list of (engine, M, K): the scalar chain ("service", ~0.3s/placement at
    10K nodes) spot-pins the vectorized float64 oracle ("oracle-np",
    ~1.7ms/placement), which carries the wide coverage. Returns
    ({engine: (matched, checked, per_window)}, {engine: {name: node}})."""
    import pickle
    from concurrent.futures import ProcessPoolExecutor
    import multiprocessing as mp

    job_blob = pickle.dumps(job)
    items = list(placed_fast.items())
    payloads = [
        (M, K, job_blob, items, N_NODES, seed, engine)
        for engine, M, K in window_specs
    ]
    ctx = mp.get_context("spawn")
    stats = {}
    results = {}
    with ProcessPoolExecutor(
        max_workers=min(len(payloads), 4), mp_context=ctx
    ) as pool:
        for engine, M, got in pool.map(_oracle_window_worker, payloads):
            m = sum(1 for k, v in got.items() if v == placed_fast.get(k))
            matched, checked, per_window = stats.get(engine, (0, 0, {}))
            per_window[M] = round(m / max(len(got), 1), 5)
            stats[engine] = (matched + m, checked + len(got), per_window)
            results.setdefault(engine, {}).update(got)
    return stats, results


def _kernel_cache_size() -> int:
    """Total compiled-program cache entries across the jitted planners —
    the recompile detector: a sample whose delta is nonzero paid an XLA
    trace+compile inside its timed window (shape-ladder miss), which is
    exactly the outlier signature the samples_detail splits can't separate
    from chip contention on their own."""
    from nomad_tpu.tpu import kernel

    # one detector definition (kernel.compile_cache_size): the trace
    # plane's recompile-flagged spans and these bench splits must agree
    return kernel.compile_cache_size()


def bench_headline():
    from nomad_tpu.state import StateStore
    from nomad_tpu.tpu import batch_sched

    spread = os.environ.get("BENCH_SPREAD", "1") != "0"
    state = StateStore()
    state.upsert_nodes(1, build_nodes(N_NODES))
    job = build_job(N_ALLOCS, spread=spread)
    state.upsert_job(2, job)
    # config #4 runs with preemption enabled for all job types
    state.set_scheduler_config(
        3,
        {
            "preemption_config": {
                "system_scheduler_enabled": True,
                "service_scheduler_enabled": True,
                "batch_scheduler_enabled": True,
            }
        },
    )

    # backend init first (TPU client connect is seconds of one-off latency
    # and not compilation — keep it out of the compile_s measurement)
    import jax.numpy as jnp

    jnp.zeros(8).block_until_ready()

    # warmup: triggers XLA compilation for these shapes (or a persistent-
    # cache load when a previous process compiled them; tpu/__init__.py)
    run_once(state, job)
    warm = dict(batch_sched.LAST_KERNEL_STATS)

    # steady-state latency: best of 5, with EVERY sample's stage split
    # recorded (kernel / columnar prep / host-side materialization) so an
    # outlier sample is attributable — chip contention inflates kernel_s,
    # a recompile shows up as a kernel_s spike on one sample only, and a
    # GC/materialization tail inflates other_s with kernel_s flat
    samples = []
    samples_detail = []
    elapsed, placed_fast, stats = None, None, None
    import gc

    for _ in range(5):
        # collect BETWEEN samples so a generational GC pause triggered by
        # the previous run's garbage doesn't land inside a timed window
        # (a suspect for the r4 1.09s outlier sample)
        gc.collect()
        cache0 = _kernel_cache_size()
        t, placed = run_once(state, job)
        s = dict(batch_sched.LAST_KERNEL_STATS)
        samples.append(round(t, 4))
        k = s.get("kernel_s", 0.0)
        c = s.get("columnar_s", 0.0)
        cache1 = _kernel_cache_size()
        samples_detail.append({
            "total_s": round(t, 4),
            "kernel_s": round(k, 4),
            "columnar_s": round(c, 4),
            "other_s": round(max(t - k - c, 0.0), 4),
            "mode": s.get("mode"),
            # nonzero ⇒ this sample paid an XLA compile (shape-ladder
            # miss); None ⇒ the detector itself is unavailable (private
            # jax cache API changed) — never a silent 0
            "recompiles": (
                cache1 - cache0 if cache0 >= 0 and cache1 >= 0 else None
            ),
        })
        if elapsed is None or t < elapsed:
            elapsed, placed_fast, stats = t, placed, s

    # parity, full scale: fast path vs the exact sequential-scan kernel
    batch_sched.EXACT_ONLY = True
    try:
        exact_s, placed_exact = run_once(state, job)
    finally:
        batch_sched.EXACT_ONLY = False
    parity_exact = parity(placed_exact, placed_fast)

    # parity, oracle link: placements oracle-checked position-by-position.
    # The float64 numpy oracle (tpu/exact_np.py — scalar-chain semantics at
    # ~1.7ms/placement) carries the wide coverage (>10% of the headline
    # eval); the scalar iterator chain itself spot-pins the numpy oracle
    # inside this same run, so the chain of trust stays rooted in the
    # per-node Go-semantics walk. With spread (the default headline):
    # mid-sequence windows restart from the fast path's own intermediate
    # state at 20/50/80% (valid because placement i depends only on its
    # predecessors and limit=∞ keeps the candidate cursor stationary).
    # Without spread: one long empty-state prefix (mid-sequence restarts
    # can't reproduce the log₂-bounded candidate cursor).
    if PARITY_K > 0:
        if spread:
            specs = [("oracle-np", 0, PARITY_NP_K)] + [
                ("oracle-np", int(N_ALLOCS * f), PARITY_NP_K)
                for f in (0.2, 0.5, 0.8)
            ]
            specs += [
                ("service", 0, PARITY_K),
                ("service", int(N_ALLOCS * 0.5), PARITY_K),
            ]
        else:
            specs = [
                ("oracle-np", 0, PARITY_NP_K * 4),
                ("service", 0, PARITY_K * 2),
            ]
        t_or = time.monotonic()
        stats_by_engine, results = oracle_parity_windows(
            job, placed_fast, specs
        )
        oracle_s = time.monotonic() - t_or
        np_matched, np_checked, np_windows = stats_by_engine.get(
            "oracle-np", (0, 0, {})
        )
        sc_matched, sc_checked, sc_windows = stats_by_engine.get(
            "service", (0, 0, {})
        )
        # the pin: scalar-chain and numpy-oracle decisions at the SAME
        # positions must agree exactly (scalar windows ⊆ numpy windows)
        np_got = results.get("oracle-np", {})
        sc_got = results.get("service", {})
        pin_keys = [k for k in sc_got if k in np_got]
        pin_match = sum(1 for k in pin_keys if sc_got[k] == np_got[k])
        matched = np_matched + sc_matched
        checked = np_checked + sc_checked
        parity_oracle = matched / max(checked, 1)
    else:
        checked = np_checked = sc_checked = 0
        np_windows = sc_windows = {}
        pin_keys, pin_match = [], 0
        oracle_s, parity_oracle = 0.0, 0.0

    ordered = sorted(samples)
    return {
        "end_to_end_s": round(elapsed, 4),
        "samples_s": samples,
        "samples_detail": samples_detail,
        "median_s": round(ordered[len(ordered) // 2], 4),
        "worst_s": round(ordered[-1], 4),
        "placed": len(placed_fast),
        "kernel_s": round(stats.get("kernel_s", 0.0), 4),
        "columnar_s": round(stats.get("columnar_s", 0.0), 4),
        "mode": stats.get("mode"),
        "spread": spread,
        "compile_s": round(warm.get("kernel_s", 0.0), 4),
        "parity_exact_full": round(parity_exact, 5),
        "parity_oracle": round(parity_oracle, 5),
        "parity_oracle_checked": checked,
        "parity_oracle_np_checked": np_checked,
        "parity_oracle_np_windows": np_windows,
        "parity_oracle_scalar_checked": sc_checked,
        "parity_oracle_scalar_windows": sc_windows,
        "parity_np_scalar_pin": (
            round(pin_match / len(pin_keys), 5) if pin_keys else None
        ),
        "parity_np_scalar_pin_checked": len(pin_keys),
        "parity_oracle_coverage": (
            "prefix+mid-sequence" if spread else
            "prefix-only (bounded-window cursor not reconstructable; "
            "load-regime parity covered by parity_exact_full)"
        ),
        "parity_oracle_wall_s": round(oracle_s, 2),
        "exact_scan_s": round(exact_s, 4),
    }


def bench_config2(n_jobs=1000, n_nodes=100):
    """1K synthetic service jobs (cpu/mem only) vs 100 mock nodes: per-
    placement scoring parity oracle-vs-kernel, with plans applied so later
    jobs bin-pack against earlier placements; reports evals/sec + p99."""
    from nomad_tpu import mock
    from nomad_tpu.scheduler import Harness

    rng = random.Random(3)
    nodes = []
    for i in range(n_nodes):
        n = mock.node()
        n.node_resources.cpu.cpu_shares = rng.choice([4000, 8000, 16000])
        n.node_resources.memory.memory_mb = rng.choice([8192, 16384, 32768])
        n.node_resources.networks = []
        n.reserved_resources.networks.reserved_host_ports = ""
        from nomad_tpu.structs import compute_class

        compute_class(n)
        nodes.append(n)
    jobs = []
    for i in range(n_jobs):
        job = mock.job()
        tg = job.task_groups[0]
        tg.count = rng.randint(1, 3)
        tg.tasks[0].resources.cpu = rng.choice([100, 250, 500])
        tg.tasks[0].resources.memory_mb = rng.choice([128, 256, 512])
        tg.tasks[0].resources.networks = []
        jobs.append(job)

    results = {}
    latencies = []
    for factory in ("service", "tpu-batch"):
        h = Harness(seed=13)
        for n in nodes:
            h.state.upsert_node(h.next_index(), n)
        placed = {}
        t0 = time.monotonic()
        for job in jobs:
            h.state.upsert_job(h.next_index(), job)
            ev = make_eval(job)
            h.state.upsert_evals(h.next_index(), [ev])
            t1 = time.monotonic()
            h.process(factory, ev)
            if factory == "tpu-batch":
                latencies.append(time.monotonic() - t1)
        total = time.monotonic() - t0
        for a in h.state.allocs():
            placed[(a.job_id, a.name)] = a.node_id
        results[factory] = (placed, total)

    p_oracle, _ = results["service"]
    p_batch, batch_total = results["tpu-batch"]
    latencies.sort()
    p99 = latencies[int(len(latencies) * 0.99) - 1] if latencies else 0.0
    return {
        "jobs": n_jobs,
        "nodes": n_nodes,
        "allocs": len(p_oracle),
        "parity": round(parity(p_oracle, p_batch), 5),
        "evals_per_s": round(n_jobs / batch_total, 1),
        "p99_plan_latency_s": round(p99, 4),
    }


def bench_config3(n_allocs=10000, n_nodes=1000):
    """10K batch allocs with constraint{} + affinity{} vs 1K heterogeneous
    nodes (affinity forces the full-ring path; batch-type job)."""
    from nomad_tpu import mock
    from nomad_tpu.state import StateStore
    from nomad_tpu.structs.model import Affinity, Constraint
    from nomad_tpu.tpu import batch_sched

    state = StateStore()
    nodes = build_nodes(n_nodes)
    for i, n in enumerate(nodes):
        n.meta["ssd"] = "true" if i % 5 == 0 else "false"
    state.upsert_nodes(1, nodes)

    job = mock.batch_job()
    job.datacenters = ["dc1", "dc2", "dc3", "dc4"]
    tg = job.task_groups[0]
    tg.count = n_allocs
    tg.tasks[0].resources.cpu = 100
    tg.tasks[0].resources.memory_mb = 64
    tg.tasks[0].resources.networks = []
    tg.ephemeral_disk.size_mb = 10
    job.constraints = [
        Constraint(l_target="${attr.kernel.name}", r_target="linux", operand="=")
    ]
    job.affinities = [
        Affinity(l_target="${meta.ssd}", r_target="true", operand="=", weight=50)
    ]
    state.upsert_job(2, job)

    run_once(state, job)  # compile
    elapsed, placed_fast = run_once(state, job)
    stats = dict(batch_sched.LAST_KERNEL_STATS)
    k = min(PARITY_K, 32)
    _, placed_oracle = run_once(state, job, factory="service", prefix=k)
    return {
        "allocs": n_allocs,
        "nodes": n_nodes,
        "end_to_end_s": round(elapsed, 4),
        "mode": stats.get("mode"),
        "placed": len(placed_fast),
        "parity_oracle_prefix": round(
            parity(placed_oracle, placed_fast, keys=placed_oracle), 5
        ),
        "parity_oracle_k": k,
    }


def bench_drain(n_jobs=500, n_nodes=1000, drain=32, workers=2,
                profile=False, pipeline=None):
    """Evals/sec through the REAL server path: jobs registered against a
    running server with default_scheduler=tpu-batch and batch_drain workers,
    evals fused into multi-eval kernel batches by the broker drain
    (worker.go:105-276 / SURVEY §2.3 north-star bridge). Samples the plan
    queue depth while running so worker scaling is a measured curve, not
    an assertion (VERDICT r3 weak #6)."""
    import threading

    from nomad_tpu import mock
    from nomad_tpu.core.server import Server
    from nomad_tpu.raft import InmemTransport, RaftConfig
    from nomad_tpu.tpu import drain as drain_mod

    drain_mod.DRAIN_COUNTERS.update(batches=0, evals=0)
    from nomad_tpu import metrics as metrics_mod
    from nomad_tpu.trace import tracer

    metrics_mod.reset()  # per-run stage timers
    tracer.reset()  # per-run retained traces (critical-path attribution)
    cfg = {
        "seed": 42,
        "heartbeat_ttl": 600.0,
        "default_scheduler": "tpu-batch",
        "batch_drain": drain,
        # fold whole drain waves into one consensus round (the knob the
        # plan.apply_batch_size histogram in /v1/metrics is tuned against)
        "plan_apply_batch": drain,
        # applier pipeline + broker ready-queue sharding (the applier
        # ladder passes {"max_inflight", "ready_shards"} here; None =
        # server defaults, i.e. pipelined applier, unsharded broker)
        **({"plan_pipeline": pipeline} if pipeline else {}),
        "raft": {
            "node_id": "s0",
            "address": "raft0",
            "voters": {"s0": "raft0"},
            "transport": InmemTransport(),
            "config": RaftConfig(
                heartbeat_interval=0.05,
                election_timeout_min=0.1,
                election_timeout_max=0.2,
            ),
        },
    }
    server = Server(cfg)
    server.start(num_workers=workers, wait_for_leader=5.0)
    depth_samples: list[int] = []
    overlay_samples: list[int] = []
    stop_sampler = threading.Event()

    def sampler():
        while not stop_sampler.wait(0.05):
            depth_samples.append(server.planner.queue.depth())
            overlay_samples.append(server.planner.overlay_depth())

    profiler = None
    try:
        for node in build_nodes(n_nodes):
            server.node_register(node)
        # compile the fused drain-batch shapes before the timed window
        # (same methodology as the headline's untimed warmup pass; the
        # persistent .jax_cache makes this a load after the first run)
        from nomad_tpu.tpu.warmup import prewarm_drain

        prewarm_drain(n_nodes, drain)
        rng = random.Random(11)
        jobs = []
        for _ in range(n_jobs):
            job = mock.job()
            job.datacenters = ["dc1", "dc2", "dc3", "dc4"]
            tg = job.task_groups[0]
            tg.count = rng.randint(1, 4)
            tg.tasks[0].resources.cpu = rng.choice([100, 250])
            tg.tasks[0].resources.memory_mb = rng.choice([64, 128])
            tg.tasks[0].resources.networks = []
            jobs.append(job)

        threading.Thread(
            target=sampler, daemon=True, name="bench-depth-sampler"
        ).start()
        # profiled arm of the A/B: the sampling wall-clock profiler
        # (nomad_tpu/debug) rides the SAME timed window it perturbs, so
        # the overhead measurement and the blocked-site attribution come
        # from one run
        if profile:
            from nomad_tpu.debug.profiler import SamplingProfiler

            profiler = SamplingProfiler(hz=100).start()
        t0 = time.monotonic()
        eval_ids = [server.job_register(j) for j in jobs]
        pending = set(eval_ids)
        deadline = time.monotonic() + 600
        while pending and time.monotonic() < deadline:
            for eid in list(pending):
                ev = server.state.eval_by_id(eid)
                if ev is not None and ev.status in ("complete", "failed"):
                    pending.discard(eid)
            time.sleep(0.02)
        elapsed = time.monotonic() - t0
        profile_report = profiler.stop() if profiler is not None else None
        profiler = None  # stopped; the finally must not re-join it
        stop_sampler.set()
        placed = sum(
            len(server.state.allocs_by_job(j.namespace, j.id)) for j in jobs
        )
        # per-stage timers (plan.queue_wait / plan.evaluate /
        # plan.raft_apply / plan.submit / worker.invoke): the breakdown
        # that names the saturation stage instead of guessing at it
        from nomad_tpu import metrics as metrics_mod

        snap_metrics = metrics_mod.snapshot()
        stages = {
            k: v
            for k, v in snap_metrics["timers"].items()
            if k.startswith("plan.")
            or k.startswith("worker.")
            or k.startswith("mirror.")
            or k.startswith("drain.")
        }
        mirror_stats = (
            server.columnar_mirror.stats()
            if server.columnar_mirror is not None
            else {}
        )
        # snapshot→restore of the committed planes: the recovery-path
        # number the columnar-first refactor is accountable for. Restore
        # must install the persisted planes (never rebuild them) and the
        # installed planes must be byte-identical to a cold rebuild of
        # the restored MVCC tables at the same raft index.
        from nomad_tpu.state import StateStore
        from nomad_tpu.state.planes import CommittedPlanes

        blob = server.state.persist()
        t_restore = time.monotonic()
        restored = StateStore()
        restored.restore(blob)
        plane_restore_s = round(time.monotonic() - t_restore, 4)
        plane_identity = (
            blob["planes"] == CommittedPlanes.build_blob(restored._gen)
        )
        return {
            "jobs": n_jobs,
            "nodes": n_nodes,
            "workers": workers,
            "unfinished": len(pending),
            "placed": placed,
            "wall_s": round(elapsed, 3),
            "evals_per_s": round(n_jobs / elapsed, 1),
            "drain_batches": drain_mod.DRAIN_COUNTERS["batches"],
            "drain_evals": drain_mod.DRAIN_COUNTERS["evals"],
            "plan_queue_depth_max": max(depth_samples, default=0),
            "plan_queue_depth_mean": round(
                sum(depth_samples) / max(len(depth_samples), 1), 2
            ),
            # how deep the applier's commit pipeline actually ran
            # (verified-but-uncommitted batches; core/plan_apply.py)
            "overlay_depth_max": max(overlay_samples, default=0),
            "stages": stages,
            # incremental columnar mirror accounting (tpu/mirror.py): how
            # many drain batches were served by O(delta) patches vs full
            # rebuilds, plus the observed plan-fold histogram
            "mirror_hits": mirror_stats.get("hits", 0),
            "mirror_rebuilds": mirror_stats.get("rebuilds", 0),
            "mirror_rebuild_reasons": mirror_stats.get("rebuild_reasons", {}),
            # full-state snapshot restore wall time + the byte-identity
            # verdict of the installed planes vs a cold rebuild
            "plane_restore_s": plane_restore_s,
            "plane_identity": plane_identity,
            "plan_apply_batch_hist": snap_metrics.get("hists", {}).get(
                "plan.apply_batch_size", {}
            ),
            # per-stage attribution of the eval.e2e tail from RETAINED
            # TRACES (nomad_tpu/trace): the artifact carries the verdict
            # the stage timers above only let a reader infer
            "critical_path": _drain_critical_path(),
            # sampling-profiler verdict for the profiled A/B arm: the
            # lock/wait table (folded stacks dropped from the artifact —
            # they're the bundle's job) + the headline number
            "profile": (
                {
                    "samples": profile_report["samples"],
                    "hz_actual": profile_report["hz_actual"],
                    "threads": profile_report["threads"],
                    "applier_block_frac": profile_report[
                        "applier_block_frac"
                    ],
                    "blocked_sites": profile_report["blocked_sites"][:10],
                }
                if profile_report is not None
                else None
            ),
        }
    finally:
        stop_sampler.set()
        # an exception before the happy-path stop must not leave a
        # 100Hz sampler perturbing every later bench section
        if profiler is not None:
            profiler.stop()
        server.stop()


def _drain_critical_path() -> dict:
    from nomad_tpu.trace import attribute, tracer

    report = attribute(tracer.store.records())
    return {
        "traces": report["traces"],
        "bottleneck": report["bottleneck"],
        "verdict": report["verdict"],
        "tail_stages": {
            name: row["share"]
            for name, row in list(
                (report.get("tail") or {}).get("stages", {}).items()
            )[:8]
        },
    }


def bench_config5(n_nodes=10000):
    """Mixed service+system jobs with device{} asks + NetworkIndex port
    collisions at 10K nodes. Bandwidth and device counts ride the kernel as
    dense resource columns; exact port numbers and device instance IDs are
    host post-passes on the winners (SURVEY §7 step 4). One untimed warmup
    pass pays XLA compilation for these shapes (same methodology as the
    headline/config3 steady-state measurement); counts are from the timed
    pass."""
    from nomad_tpu import mock
    from nomad_tpu.scheduler import Harness
    from nomad_tpu.structs.model import Constraint, NetworkResource, Port, RequestedDevice

    nodes = build_nodes(n_nodes, networks=True, devices_every=10)

    def fresh_harness():
        h = Harness(seed=29)
        for n in nodes:
            h.state.upsert_node(h.next_index(), n)
        return h

    def make_jobs():
        # service job with dynamic ports (port numbers arbitrated host-side
        # per winner; two allocs can never double-book a port on a node)
        port_job = mock.job()
        port_job.datacenters = ["dc1", "dc2", "dc3", "dc4"]
        tg = port_job.task_groups[0]
        tg.count = 1000
        tg.tasks[0].resources.cpu = 100
        tg.tasks[0].resources.memory_mb = 64
        tg.tasks[0].resources.networks = [
            NetworkResource(
                mbits=10,
                dynamic_ports=[Port(label="http"), Port(label="admin")],
            )
        ]

        # service job asking for a TPU device
        dev_job = mock.job()
        dev_job.datacenters = ["dc1", "dc2", "dc3", "dc4"]
        dtg = dev_job.task_groups[0]
        dtg.count = 200
        dtg.tasks[0].resources.cpu = 100
        dtg.tasks[0].resources.memory_mb = 64
        dtg.tasks[0].resources.networks = []
        dtg.tasks[0].resources.devices = [RequestedDevice(name="tpu", count=1)]

        # system job constrained to the device nodes (one alloc per node)
        sys_job = mock.system_job()
        sys_job.datacenters = ["dc1", "dc2", "dc3", "dc4"]
        sys_job.constraints.append(
            Constraint(l_target="${attr.tpu.count}", r_target="0", operand=">")
        )
        stg = sys_job.task_groups[0]
        stg.tasks[0].resources.cpu = 50
        stg.tasks[0].resources.memory_mb = 32
        stg.tasks[0].resources.networks = []
        return (
            (port_job, "tpu-batch"),
            (dev_job, "tpu-batch"),
            (sys_job, "tpu-system"),
        )

    def run(jobs):
        # fresh cluster per sample: every run schedules identical work
        # against the identical empty state (the headline gets this for
        # free from its NullPlanner; the Harness applies plans, so reusing
        # one would load the cluster a little more each sample)
        h = fresh_harness()
        t0 = time.monotonic()
        placed = []
        for job, factory in jobs:
            h.state.upsert_job(h.next_index(), job)
            ev = make_eval(job)
            h.state.upsert_evals(h.next_index(), [ev])
            h.process(factory, ev)
            placed.append(
                sum(1 for a in h.state.allocs_by_job(job.namespace, job.id))
            )
        return time.monotonic() - t0, placed

    from nomad_tpu.tpu.batch_sched import counters_snapshot

    def reasons_delta(before, after):
        return {
            k: v - before.get(k, 0)
            for k, v in after.items()
            if v - before.get(k, 0)
        }

    compile_s, _ = run(make_jobs())  # warmup: XLA compiles for these shapes
    # steady-state: best of 3 (same chip-load-noise guard as the headline)
    before = counters_snapshot()["fallback_reasons"]
    samples = []
    elapsed, placed = None, None
    for _ in range(3):
        t, p = run(make_jobs())
        samples.append(round(t, 4))
        if elapsed is None or t < elapsed:
            elapsed, placed = t, p
    after = counters_snapshot()["fallback_reasons"]

    return {
        "nodes": n_nodes,
        "wall_s": round(elapsed, 4),
        "samples_s": samples,
        "first_run_s": round(compile_s, 4),
        "port_allocs": placed[0],
        "device_allocs": placed[1],
        "system_allocs": placed[2],
        "fallback_reasons": reasons_delta(before, after),
    }


#: pinned continuous-profiling overhead budget for the 4-worker drain
#: A/B: the ~100Hz wall-clock sampler (nomad_tpu/debug/profiler.py) must
#: cost ≤ this on the path it watches, or it is not an always-on tool
PROFILE_OVERHEAD_BUDGET_PCT = 3.0


def bench_profile_ab(base_run=None, n_jobs=200, n_nodes=500, workers=4):
    """Profiled vs unprofiled 4-worker drain (same config as the
    worker-scaling curve's top tier; ``base_run`` reuses that curve's
    4-worker result as the first unprofiled sample). Best-of per arm —
    the same chip-load-noise guard every drain section uses. The
    profiled arm's blocked-site table is the knee diagnosis WITHOUT the
    trace plane: the applier path must top the worker-class wait table
    (ROADMAP item 2 reproduced from whole-process sampling alone)."""
    base_runs = [base_run] if base_run is not None else []
    prof_runs = []
    prof_runs.append(
        bench_drain(n_jobs=n_jobs, n_nodes=n_nodes, workers=workers,
                    profile=True)
    )
    base_runs.append(
        bench_drain(n_jobs=n_jobs, n_nodes=n_nodes, workers=workers)
    )
    prof_runs.append(
        bench_drain(n_jobs=n_jobs, n_nodes=n_nodes, workers=workers,
                    profile=True)
    )
    if len(base_runs) < 2:
        # symmetric arms: best-of-2 profiled vs best-of-1 unprofiled
        # would bias overhead_pct low under chip-load noise
        base_runs.append(
            bench_drain(n_jobs=n_jobs, n_nodes=n_nodes, workers=workers)
        )
    base_best = min(r["wall_s"] for r in base_runs)
    prof_best = min(prof_runs, key=lambda r: r["wall_s"])
    overhead = (
        (prof_best["wall_s"] - base_best) / base_best * 100.0
        if base_best
        else 0.0
    )
    prof = prof_best["profile"] or {}
    worker_sites = [
        r for r in prof.get("blocked_sites", []) if r["class"] == "worker"
    ]
    return {
        "workers": workers,
        "jobs": n_jobs,
        "nodes": n_nodes,
        "base_wall_s": [round(r["wall_s"], 3) for r in base_runs],
        "profiled_wall_s": [round(r["wall_s"], 3) for r in prof_runs],
        "overhead_pct": round(overhead, 2),
        "budget_pct": PROFILE_OVERHEAD_BUDGET_PCT,
        "within_budget": overhead <= PROFILE_OVERHEAD_BUDGET_PCT,
        "profile": prof,
        "applier_block_frac": prof.get("applier_block_frac"),
        "top_worker_blocked_site": (
            worker_sites[0]["site"] if worker_sites else None
        ),
    }


#: the applier ladder's worker tiers (ROADMAP item 1 acceptance shape)
APPLIER_TIERS = (1, 2, 4, 8)


def bench_applier():
    """The applier-knee section (ROADMAP item 1): worker-scaling ladder
    over the drain config with the FULL pipeline on — overlapped commits
    (max_inflight=2), device dense verify, and 8-way sharded broker
    ready-queues — reporting evals/s, plan.queue_wait p99 and (top tier)
    applier_block_frac per tier. ``cpu_count`` rides the artifact: on a
    1-core box the ladder measures contention removal, not parallel
    speedup (PERF.md caveat), so absolute targets are only meaningful on
    a multi-core box."""
    pipeline = {"max_inflight": 2, "ready_shards": 8}
    tiers = []
    for w in APPLIER_TIERS:
        run = bench_drain(
            n_jobs=200, n_nodes=500, workers=w,
            profile=(w == APPLIER_TIERS[-1]), pipeline=pipeline,
        )
        stages = run.get("stages") or {}
        queue_wait = stages.get("plan.queue_wait", {})
        prof = run.get("profile") or {}
        tiers.append({
            "workers": w,
            "evals_per_s": run.get("evals_per_s"),
            "wall_s": run.get("wall_s"),
            "plan_queue_wait_p99_ms": queue_wait.get("p99_ms", 0.0),
            "plan_queue_depth_max": run.get("plan_queue_depth_max"),
            "overlay_depth_max": run.get("overlay_depth_max"),
            "applier_block_frac": prof.get("applier_block_frac"),
            "trace_bottleneck": (run.get("critical_path") or {}).get(
                "bottleneck"
            ),
        })
    top = tiers[-1]
    return {
        # the 1-core-box caveat, recorded IN the artifact (not just docs)
        "cpu_count": os.cpu_count(),
        "pipeline": pipeline,
        "tiers": tiers,
        "applier_evals_s": top["evals_per_s"],
        "applier_queue_wait_p99_ms": top["plan_queue_wait_p99_ms"],
        "applier_block_frac": top["applier_block_frac"],
        "applier_bottleneck": top["trace_bottleneck"],
        # ONE formatter for the per-tier summary token, derived from
        # APPLIER_TIERS — BENCH_SUMMARY and scripts/applier.sh both
        # print this verbatim so the label can never drift from the
        # ladder actually run
        "applier_workers_line": (
            "applier_workers="
            + "/".join(str(t.get("evals_per_s")) for t in tiers)
            + "evals/s@"
            + ",".join(str(w) for w in APPLIER_TIERS)
        ),
    }


#: pinned trace-overhead budget for the headline A/B (acceptance: traced
#: vs untraced on the SAME box — never compare to BENCH_r* numbers; the
#: tier-1 gate in tests/test_trace.py enforces the same pin at small
#: scale with a per-eval microbench so CI noise can't flake it)
TRACE_OVERHEAD_BUDGET_PCT = 3.0


def bench_trace_overhead(samples=3):
    """A/B the headline pass traced vs untraced (same state, arms
    interleaved so thermal/cache drift hits both): median ratio =
    the trace plane's cost on the path it instruments. The traced arm
    runs with an active root context so the eval.plan_kernel span (and
    every tracer hook on the pass) actually fires."""
    import gc

    from nomad_tpu.state import StateStore
    from nomad_tpu.trace import tracer

    state = StateStore()
    state.upsert_nodes(1, build_nodes(N_NODES))
    job = build_job(N_ALLOCS, spread=True)
    state.upsert_job(2, job)
    run_once(state, job)  # warm compile outside both arms
    tracer.reset()
    traced: list[float] = []
    untraced: list[float] = []
    spans_recorded = 0
    try:
        for _ in range(samples):
            gc.collect()
            tracer.enabled = True
            with tracer.root("bench.headline"):
                t, _ = run_once(state, job)
            traced.append(t)
            gc.collect()
            tracer.enabled = False
            t, _ = run_once(state, job)
            untraced.append(t)
        spans_recorded = tracer.store.stats()["open_spans"]
    finally:
        tracer.enabled = True
        tracer.reset()

    def med(xs):
        return sorted(xs)[len(xs) // 2]

    t_med, u_med = med(traced), med(untraced)
    overhead = ((t_med - u_med) / u_med * 100.0) if u_med else 0.0
    return {
        "samples": samples,
        "traced_median_s": round(t_med, 4),
        "untraced_median_s": round(u_med, 4),
        "overhead_pct": round(overhead, 2),
        "spans_recorded": spans_recorded,
        "budget_pct": TRACE_OVERHEAD_BUDGET_PCT,
        "within_budget": overhead <= TRACE_OVERHEAD_BUDGET_PCT,
    }


#: the device profiler must be as close to free as the trace plane and
#: the sampling profiler: same A/B shape, same pinned budget
DEVPROF_OVERHEAD_BUDGET_PCT = 3.0


def bench_devprof_overhead(samples=3):
    """A/B the headline pass with the device profiler (debug/devprof.py)
    enabled vs disabled — arms interleaved like the trace/profile A/Bs
    so thermal/cache drift hits both. The enabled arm pays the dispatch
    wrapper (shard signature + cache-delta probe + round recording);
    compile events are excluded by warming first, exactly like
    production steady state."""
    import gc

    from nomad_tpu.debug import devprof
    from nomad_tpu.state import StateStore

    state = StateStore()
    state.upsert_nodes(1, build_nodes(N_NODES))
    job = build_job(N_ALLOCS, spread=True)
    state.upsert_job(2, job)
    run_once(state, job)  # warm compile outside both arms
    on: list[float] = []
    off: list[float] = []
    prior = devprof.enabled()
    try:
        for _ in range(samples):
            gc.collect()
            devprof.enable(True)
            t, _ = run_once(state, job)
            on.append(t)
            gc.collect()
            devprof.enable(False)
            t, _ = run_once(state, job)
            off.append(t)
    finally:
        # restore the operator's state, never force-enable: a
        # NOMAD_TPU_DEVPROF=0 bench run must stay uninstrumented for
        # every section after this one
        devprof.enable(prior)

    def med(xs):
        return sorted(xs)[len(xs) // 2]

    on_med, off_med = med(on), med(off)
    overhead = ((on_med - off_med) / off_med * 100.0) if off_med else 0.0
    summ = devprof.summary()
    return {
        "samples": samples,
        "enabled_median_s": round(on_med, 4),
        "disabled_median_s": round(off_med, 4),
        "overhead_pct": round(overhead, 2),
        "budget_pct": DEVPROF_OVERHEAD_BUDGET_PCT,
        "within_budget": overhead <= DEVPROF_OVERHEAD_BUDGET_PCT,
        "compile_s_total": summ["compile_s_total"],
        "h2d_mb": summ["h2d_mb"],
        "rounds_per_placement": summ["rounds_per_placement"],
    }


#: the sharded headline config: 10× the single-chip north star, spread
#: over the node axis of an 8-device mesh (ROADMAP item 1)
SHARDED_NODES = int(os.environ.get("BENCH_SHARDED_NODES", "100000"))
SHARDED_ALLOCS = int(os.environ.get("BENCH_SHARDED_ALLOCS", "500000"))
SHARDED_DEVICES = int(os.environ.get("BENCH_SHARDED_DEVICES", "8"))
SHARDED_SAMPLES = int(os.environ.get("BENCH_SHARDED_SAMPLES", "3"))
WAVEFRONT_NODES = int(os.environ.get("BENCH_WAVEFRONT_NODES", "8192"))
WAVEFRONT_ALLOCS = int(os.environ.get("BENCH_WAVEFRONT_ALLOCS", "1024"))
WAVEFRONT_TENANTS = int(os.environ.get("BENCH_WAVEFRONT_TENANTS", "32"))
WAVEFRONT_PARITY_ALLOCS = int(
    os.environ.get("BENCH_WAVEFRONT_PARITY_ALLOCS", "256")
)


def build_tenant_job(count, tenants):
    """Multi-tenant job: `tenants` task groups, each pinned to its own
    ${node.class} partition. G>1 routes to the exact-scan dispatch (the
    runs/windowed fast paths require a single group) — the dispatch the
    wavefront plane gates — and the disjoint feasibility is the regime
    where conflict-free commit prefixes batch many placements per round."""
    from nomad_tpu.structs.model import Constraint

    job = build_job(count, spread=True)
    tg0 = job.task_groups[0]
    job.task_groups = []
    for g in range(tenants):
        tg = tg0.copy()
        tg.name = f"wf{g:03d}"
        tg.count = max(count // tenants, 1)
        tg.constraints = list(tg.constraints or []) + [
            Constraint(
                l_target="${node.class}", r_target=f"wf{g}", operand="="
            ),
        ]
        job.task_groups.append(tg)
    return job


def bench_sharded():
    """The mesh-sharded headline: plan SHARDED_ALLOCS pending allocations
    against a SHARDED_NODES-node cluster end-to-end through the real
    tpu-batch scheduler, with the planner's node axis sharded across
    SHARDED_DEVICES devices (tpu/shard.py; GSPMD inserts the cross-shard
    argmax/spread collectives). Methodology mirrors the single-chip
    headline: untimed warmup per arm, best-of-N timed samples with
    per-sample recompile deltas (must be 0 — the warmup compiled the
    sharded layouts), and the UNSHARDED run of the identical eval as the
    oracle — placements must be bit-identical (parity 1.0), because
    sharding is a layout choice, never a semantics change. A
    traced-vs-untraced A/B pins the trace plane's budget on the sharded
    path too (shard-tagged dispatch spans ride the same hooks)."""
    import gc

    from nomad_tpu.state import StateStore
    from nomad_tpu.tpu import batch_sched, shard
    from nomad_tpu.trace import tracer

    mesh = shard.configure(SHARDED_DEVICES)
    if mesh is None:
        import jax

        return {
            "skipped": True,
            "reason": (
                f"need {SHARDED_DEVICES} devices, have {len(jax.devices())}"
                " (CPU boxes: XLA_FLAGS=--xla_force_host_platform_"
                "device_count=8, see scripts/multichip.sh)"
            ),
        }
    try:
        state = StateStore()
        state.upsert_nodes(1, build_nodes(SHARDED_NODES))
        job = build_job(SHARDED_ALLOCS, spread=True)
        state.upsert_job(2, job)

        # unsharded oracle arm first: warm, then one timed pass (the
        # single-chip column of the PERF.md table)
        shard.configure(enabled=False)
        run_once(state, job)  # warmup: compiles the unsharded shapes
        gc.collect()
        unsharded_s, placed_unsharded = run_once(state, job)
        unsharded_mode = batch_sched.LAST_KERNEL_STATS.get("mode")

        # sharded arm: warm (compiles the mesh layouts), then best-of-N
        shard.configure(SHARDED_DEVICES)
        warm_t, _ = run_once(state, job)
        samples, details = [], []
        best, placed_sharded = None, None
        for _ in range(SHARDED_SAMPLES):
            gc.collect()
            cache0 = _kernel_cache_size()
            t, placed = run_once(state, job)
            cache1 = _kernel_cache_size()
            samples.append(round(t, 4))
            details.append({
                "total_s": round(t, 4),
                "kernel_s": round(
                    batch_sched.LAST_KERNEL_STATS.get("kernel_s", 0.0), 4
                ),
                "recompiles": (
                    cache1 - cache0 if cache0 >= 0 and cache1 >= 0 else None
                ),
            })
            if best is None or t < best:
                best, placed_sharded = t, placed
        stats = dict(batch_sched.LAST_KERNEL_STATS)

        # fast-pair agreement (informational): the production programs
        # are two different XLA compilations, and fusion-level 1-ulp
        # score noise can legally flip exact ties between near-identical
        # nodes at this scale — semantic quality is pinned by the
        # ≥99% host-oracle budget, not by this number
        fast_parity = parity(placed_unsharded, placed_sharded)

        # THE parity pin: both arms through the deterministic compile
        # flavor (kernel.DET_COMPILER_OPTIONS — optimization level 0,
        # every float materialized once), where sharded placements are
        # bit-identical to unsharded by construction; any mismatch here
        # is a real GSPMD semantics regression. The checked sample runs
        # against the SAME cluster at a reduced alloc count — the
        # unfused flavor trades speed for bit-stability, so the sample
        # size is the knob (it still crosses every shard)
        parity_allocs = int(os.environ.get(
            "BENCH_SHARDED_PARITY_ALLOCS",
            str(min(SHARDED_ALLOCS, 50000)),
        ))
        parity_job = build_job(parity_allocs, spread=True)
        state.upsert_job(4, parity_job)
        from nomad_tpu.tpu.kernel import deterministic_scope

        parity_mode = "deterministic (kernel.DET_COMPILER_OPTIONS)"
        try:
            with deterministic_scope():
                shard.configure(enabled=False)
                det_plain_s, det_plain = run_once(state, parity_job)
                shard.configure(SHARDED_DEVICES)
                det_shard_s, det_shard = run_once(state, parity_job)
        except Exception as e:  # backend without the det flavor: degrade,
            # and say so — a fast-pair number must never masquerade as
            # the bit-identity pin
            parity_mode = f"fast pair (deterministic flavor failed: {e})"
            det_plain_s = det_shard_s = 0.0
            det_plain, det_shard = placed_unsharded, placed_sharded
        finally:
            # re-arm the mesh — the trace A/B below must measure the
            # SHARDED path even when the det unsharded arm raised before
            # the mesh was reconfigured
            shard.configure(SHARDED_DEVICES)
        det_parity = parity(det_plain, det_shard)

        # trace A/B on the sharded path (same interleaved-arms + median
        # methodology as bench_trace_overhead, so thermal/cache drift
        # hits both arms; budget pinned like the headline)
        tracer.reset()
        traced, untraced = [], []
        ab_samples = int(os.environ.get("BENCH_SHARDED_TRACE_SAMPLES", "2"))
        try:
            for _ in range(ab_samples):
                gc.collect()
                tracer.enabled = True
                with tracer.root("bench.sharded"):
                    t, _ = run_once(state, job)
                traced.append(t)
                gc.collect()
                tracer.enabled = False
                t, _ = run_once(state, job)
                untraced.append(t)
        finally:
            tracer.enabled = True
            tracer.reset()
        t_med = sorted(traced)[len(traced) // 2]
        u_med = sorted(untraced)[len(untraced) // 2]
        trace_overhead = (t_med - u_med) / u_med * 100.0 if u_med else 0.0

        # wavefront arm (tpu/wavefront.py): the multi-tenant exact-scan
        # dispatch routed through conflict-free batched commits. The big
        # sharded job above routes to the runs planner (one group,
        # a_real > 64), which already batches its collectives — the
        # wavefront's regime is the shape the fast paths can't take:
        # many groups with distinct feasibility, where the sequential
        # exact scan pays one collective round per placement (the
        # crpp-1.0 convoy). Dedicated cluster on the SAME mesh: node
        # classes partition feasibility across the tenant groups, the
        # sequential exact-scan run is baseline AND oracle, and the
        # parity pin rides the deterministic flavor.
        from nomad_tpu.debug import devprof as _dp_mod
        from nomad_tpu.structs import compute_class
        from nomad_tpu.tpu import wavefront as _wavefront

        wf_seq_s = wf_speedup = wf_rounds = wf_parity = wf_best = None
        wf_mode = wf_seq_mode = wf_parity_mode = None
        try:
            wf_state = StateStore()
            wf_cluster = build_nodes(WAVEFRONT_NODES)
            for i, n in enumerate(wf_cluster):
                n.node_class = f"wf{i % WAVEFRONT_TENANTS}"
                compute_class(n)  # node_class feeds the class hash
            wf_state.upsert_nodes(1, wf_cluster)
            wf_job = build_tenant_job(WAVEFRONT_ALLOCS, WAVEFRONT_TENANTS)
            wf_state.upsert_job(2, wf_job)

            run_once(wf_state, wf_job)  # warm: compiles the exact shape
            gc.collect()
            wf_seq_s, placed_seq = run_once(wf_state, wf_job)
            wf_seq_mode = batch_sched.LAST_KERNEL_STATS.get("mode")

            _wavefront.configure(enabled=True)
            run_once(wf_state, wf_job)  # warm: compiles the wavefront
            r0 = _dp_mod.rounds_snapshot().get("wavefront", {})
            placed_wf = None
            for _ in range(SHARDED_SAMPLES):
                gc.collect()
                t, placed = run_once(wf_state, wf_job)
                if wf_best is None or t < wf_best:
                    wf_best, placed_wf = t, placed
            wf_mode = batch_sched.LAST_KERNEL_STATS.get("mode")
            r1 = _dp_mod.rounds_snapshot().get("wavefront", {})
            disp = (r1.get("sharded_dispatches", 0)
                    - r0.get("sharded_dispatches", 0))
            rnds = (r1.get("sharded_rounds", 0)
                    - r0.get("sharded_rounds", 0))
            # honesty gate: the speedup column only means something when
            # the baseline took the sequential exact scan AND the timed
            # arm took the wavefront — otherwise report the modes and
            # null the number rather than print a 1.0x that measured
            # the runs planner against itself
            routed = (wf_seq_mode == "exact-scan"
                      and wf_mode == "wavefront")
            wf_rounds = round(rnds / disp) if routed and disp else None
            wf_speedup = (round(wf_seq_s / wf_best, 3)
                          if routed and wf_best else None)
            wf_parity_mode = "deterministic (vs sequential det, same mesh)"
            wf_parity_job = build_tenant_job(
                WAVEFRONT_PARITY_ALLOCS, WAVEFRONT_TENANTS
            )
            wf_state.upsert_job(4, wf_parity_job)
            try:
                with deterministic_scope():
                    _, det_wf = run_once(wf_state, wf_parity_job)
                    _wavefront.configure(enabled=False)
                    _, det_seq = run_once(wf_state, wf_parity_job)
                wf_parity = round(parity(det_seq, det_wf), 6)
            except Exception as e:
                wf_parity_mode = f"fast pair (det flavor failed: {e})"
                wf_parity = round(parity(placed_seq, placed_wf), 6)
        finally:
            _wavefront.reset()

        recompiles = (
            None
            if any(d["recompiles"] is None for d in details)
            else sum(d["recompiles"] for d in details)
        )
        ordered = sorted(samples)
        return {
            "nodes": SHARDED_NODES,
            "allocs": SHARDED_ALLOCS,
            "devices": shard.mesh_size(mesh),
            "end_to_end_s": round(best, 4),
            "samples_s": samples,
            "samples_detail": details,
            "median_s": round(ordered[len(ordered) // 2], 4),
            "compile_s": round(warm_t, 4),
            "unsharded_s": round(unsharded_s, 4),
            "speedup_vs_unsharded": (
                round(unsharded_s / best, 3) if best else None
            ),
            "mode": stats.get("mode"),
            "shards": stats.get("shards"),
            "placed": len(placed_sharded),
            "parity_vs_unsharded": round(det_parity, 6),
            "parity_checked": len(det_plain),
            "parity_mode": parity_mode,
            "parity_det_plain_s": round(det_plain_s, 4),
            "parity_det_shard_s": round(det_shard_s, 4),
            "parity_fast_pair": round(fast_parity, 6),
            "parity_fast_pair_checked": len(placed_unsharded),
            "recompiles": recompiles,
            "unsharded_mode": unsharded_mode,
            "trace_overhead_pct": round(trace_overhead, 2),
            "trace_budget_pct": TRACE_OVERHEAD_BUDGET_PCT,
            "trace_within_budget": (
                trace_overhead <= TRACE_OVERHEAD_BUDGET_PCT
            ),
            "wavefront_nodes": WAVEFRONT_NODES,
            "wavefront_allocs": WAVEFRONT_ALLOCS,
            "wavefront_tenants": WAVEFRONT_TENANTS,
            "wavefront_seq_s": (
                round(wf_seq_s, 4) if wf_seq_s else None
            ),
            "wavefront_seq_mode": wf_seq_mode,
            "wavefront_s": round(wf_best, 4) if wf_best else None,
            "wavefront_speedup": wf_speedup,
            "wavefront_rounds": wf_rounds,
            "wavefront_parity": wf_parity,
            "wavefront_parity_mode": wf_parity_mode,
            "wavefront_mode": wf_mode,
            "skipped": False,
        }
    finally:
        # later sections measure the single-chip paths; never leak the
        # mesh into them
        shard.configure(enabled=False)


PAGED_NODES = int(os.environ.get("BENCH_PAGED_NODES", "1000000"))
PAGED_ALLOCS = int(os.environ.get("BENCH_PAGED_ALLOCS", "100000"))
PAGED_TILE_NODES = int(os.environ.get("BENCH_PAGED_TILE_NODES", "65536"))
PAGED_BUDGET_MB = int(os.environ.get("BENCH_PAGED_BUDGET_MB", "8"))
PAGED_PARITY_NODES = int(os.environ.get("BENCH_PAGED_PARITY_NODES", "8192"))
PAGED_PARITY_ALLOCS = int(os.environ.get("BENCH_PAGED_PARITY_ALLOCS", "1024"))


def _paged_case(seed, n, a, limit=8, c=4):
    """Synthetic planner inputs at node counts no mock cluster could
    materialize (1M Node structs would dwarf the planes being measured);
    same plane shapes batch_sched extracts from a real snapshot."""
    import numpy as np

    rng = np.random.default_rng(seed)
    capacity = rng.integers(8, 64, size=(n, c)).astype(np.int32)
    usable = np.maximum(capacity[:, :2].astype(np.float32), 1.0)
    feasible = rng.random(n) < 0.9
    demand = rng.integers(1, 4, size=c).astype(np.int32)
    used0 = rng.integers(0, 4, size=(n, c)).astype(np.int32)
    collisions0 = rng.integers(0, 2, size=n).astype(np.int32)
    perm = rng.permutation(n).astype(np.int32)
    return (capacity, usable, feasible, perm, demand, 1, int(limit),
            int(a), used0, collisions0, int(n), int(a))


def bench_paged():
    """The paged-planner headline (tpu/paging.py): plan PAGED_ALLOCS
    pending allocations against a PAGED_NODES-node axis whose dense
    planes DO NOT FIT the enforced device budget — the pager streams
    them through in PAGED_TILE_NODES-row tiles, two tournament sweeps
    per round, double-buffered H2D. Methodology mirrors the other
    sections: an untimed warmup at the same tile shape compiles both
    sweep programs (the timed run's recompile delta must read 0 — one
    tile bucket serves every tile), the budget-vs-plane arithmetic is
    recorded IN the artifact (budget_holds_full must read False or the
    section measured nothing), and a reduced-scale subsample is planned
    twice — paged and through the pure-numpy windowed oracle — where
    placements must match bit for bit (paging is a residency policy,
    never a semantics change)."""
    import gc

    from nomad_tpu.debug import devprof as _dp_mod
    from nomad_tpu.tpu import paging

    paging.configure(
        enabled=True,
        device_node_budget_mb=PAGED_BUDGET_MB,
        tile_nodes=PAGED_TILE_NODES,
    )
    try:
        tn = paging.tile_rows()
        plane_bytes = paging.plane_bytes(PAGED_NODES)
        budget_bytes = PAGED_BUDGET_MB * (1 << 20)

        # warmup: a 2-tile problem at the SAME tile shape compiles both
        # sweep programs; the 1M-node run below must hit that cache
        paging.plan_batch_paged(*_paged_case(1, 2 * tn, 256))

        case = _paged_case(20260807, PAGED_NODES, PAGED_ALLOCS)
        gc.collect()
        cache0 = _kernel_cache_size()
        dp0 = _dp_mod.paged_totals()
        t0 = time.perf_counter()
        placements, rounds, stats = paging.plan_batch_paged(*case)
        paged_s = time.perf_counter() - t0
        recompiles = _kernel_cache_size() - cache0
        dp1 = _dp_mod.paged_totals()
        placed = int((placements >= 0).sum())

        # parity subsample: same generator, a scale the host oracle can
        # check exhaustively; both arms get identical inputs
        pcase = _paged_case(7, PAGED_PARITY_NODES, PAGED_PARITY_ALLOCS,
                            limit=4)
        paged_p, paged_r, _ = paging.plan_batch_paged(*pcase)
        oracle_p, oracle_r = paging.plan_windowed_np(*pcase)
        paged_parity = parity(
            {i: int(v) for i, v in enumerate(paged_p)},
            {i: int(v) for i, v in enumerate(oracle_p)},
        )

        return {
            "nodes": PAGED_NODES,
            "allocs": PAGED_ALLOCS,
            "placed": placed,
            "paged_s": round(paged_s, 4),
            "rounds": int(rounds),
            "tile_nodes": tn,
            "tiles": stats.get("tiles"),
            # the acceptance arithmetic, in-artifact: the run only
            # counts if the budget could NOT hold the full planes
            "budget_mb": PAGED_BUDGET_MB,
            "plane_mb": round(plane_bytes / 1e6, 1),
            "budget_holds_full": budget_bytes >= plane_bytes,
            "budget_raised": stats.get("budget_raised"),
            "resident_peak_mb": round(
                stats.get("resident_peak_bytes", 0) / 1e6, 2
            ),
            "tile_uploads": dp1["tile_uploads"] - dp0["tile_uploads"],
            "tile_reuploads": (
                dp1["tile_reuploads"] - dp0["tile_reuploads"]
            ),
            "tile_upload_mb": round(
                (dp1["tile_upload_bytes"] - dp0["tile_upload_bytes"])
                / 1e6, 1,
            ),
            "recompiles": recompiles,
            "parity_vs_oracle": round(paged_parity, 6),
            "parity_checked": len(paged_p),
            "parity_nodes": PAGED_PARITY_NODES,
            "parity_rounds_equal": int(paged_r) == int(oracle_r),
        }
    finally:
        paging.reset()


def bench_soak_smoke(seed=20260803):
    """The tier-1 smoke storm from the churn-soak load plane
    (nomad_tpu/loadgen), run as a bench section so the soak's headline
    health signals ride the BENCH_SUMMARY trajectory: a ~30s seeded mixed
    storm (submit/scale/update/flap/drain/dispatch/GC) through the real
    RPC+HTTP surface, scored continuously. Zero invariant violations is
    the contract; rss_peak/slope are the leak-class canaries."""
    from nomad_tpu.loadgen import get_scenario
    from nomad_tpu.loadgen.runner import run_scenario

    report = run_scenario(get_scenario("smoke"), seed, driver_workers=6)
    return {
        "scenario": report["scenario"],
        "seed": seed,
        "ops_fired": report["driver"]["fired"],
        "ops_failed": report["driver"]["failed"],
        "invariant_violations": report["invariants"]["violations"],
        "invariant_sweeps": report["invariants"]["sweeps"],
        "rss_peak_mb": report["rss_peak_mb"],
        "rss_tail_slope_mb_per_min": report["rss_tail_slope_mb_per_min"],
        "eval_e2e_p99_ms_max": report["eval_e2e_p99_ms_max"],
        "subscriber_lag_max": report["subscriber_lag_max"],
        "quiesced": report["quiesced"],
        "slo_score": report["slo"]["score"],
        "stream_digest": report["stream_digest"][:12],
    }


def bench_fanout():
    """Event plane at production fan-out (loadgen/fanout.py): ramp
    FANOUT_SUBS (default 10K) concurrent /v1/event/stream watchers
    against a live server, run the smoke storm, score delivery. The
    headline numbers ride BENCH_SUMMARY as fanout_*; silent gaps are
    pinned 0 (a drop without a marker is the one unforgivable failure).
    The subscriber fleet runs as a subprocess — the per-process fd
    ceiling can't hold both sides of 10K connections."""
    from nomad_tpu.loadgen.fanout import run_fanout_from_env

    report = run_fanout_from_env(seed=20260804)
    report.pop("driver", None)  # the op-level detail isn't bench signal
    return report


def bench_federation_smoke(seed=20260805):
    """The tier-1 federated storm (loadgen/federation.py smoke profile):
    2 regions x 1 server, a short mixed storm with cross-region submits
    through the forwarding plane and one full WAN partition + heal. The
    contract numbers ride BENCH_SUMMARY as fed_*: invariant violations
    (per-region + cross-region oracle) pinned 0, worst partition heal
    time, and the forwarding error rate OUTSIDE declared chaos windows
    (failures inside a severed-link window are chaos-by-design)."""
    from nomad_tpu.loadgen.federation import federation_smoke, run_federation

    report = run_federation(federation_smoke(), seed=seed)
    return {
        "regions": len(report["region_names"]),
        "servers": report["servers_total"],
        "seed": seed,
        "ops_fired": report["driver"]["fired"],
        "ops_failed": report["driver"]["failed"],
        "fed_invariant_violations": report["fed_invariant_violations"],
        "fed_lost_placements": report["fed_lost_placements"],
        "fed_double_placements": report["fed_double_placements"],
        "fed_heal_s": report["fed_heal_s"],
        "fed_fwd_attempted": report["fed_fwd_attempted"],
        "fed_fwd_err_rate": report["fed_fwd_err_rate"],
        "fed_replication_lag_p99_s": report["fed_replication_lag_p99_s"],
        "oracle_submits": report["oracle_checked_submits"],
        "quiesced": report["quiesced"],
        "slo_score": report["slo"]["score"],
        "stream_digests": {
            r: report["regions"][r]["stream_digest"][:12]
            for r in report["region_names"]
        },
    }


def bench_overload(seed=20260807):
    """The overload storm (loadgen/overload.py): capacity stage, a burst
    at OVERLOAD_BURST_X times that offered rate, then a recovery probe —
    grading the overload control plane past saturation. The contract
    numbers ride BENCH_SUMMARY as overload_*: goodput at burst must hold
    against the capacity stage (the brownout + shedding dividend), every
    op is accounted (zero real failures), and recovery completes inside
    the SLO window."""
    from nomad_tpu.loadgen.overload import run_overload_from_env

    report = run_overload_from_env(seed=seed)
    return {
        "seed": seed,
        "overload_goodput_cap_eps": report["overload_goodput_cap_eps"],
        "overload_goodput_eps": report["overload_goodput_eps"],
        "overload_goodput_drop": report["overload_goodput_drop"],
        "overload_shed_frac": report["overload_shed_frac"],
        "overload_dl_exceeded": report["overload_dl_exceeded"],
        "overload_recovery_s": report["overload_recovery_s"],
        "overload_admitted_p99_ms": report["overload_admitted_p99_ms"],
        "overload_failed": report["overload_failed"],
        "overload_unaccounted": report["overload_unaccounted"],
        "brownout_max_level": report["brownout_max_level"],
        "invariant_violations": report["invariants"]["violations"],
        "quiesced": report["quiesced"],
        "slo_score": report["slo"]["score"],
    }


def main():
    # the single-chip headline stays single-chip by construction, even
    # under NOMAD_TPU_SHARD=1 — the sharded section measures the mesh
    from nomad_tpu.tpu import shard as _shard

    _shard.configure(enabled=False)
    headline = bench_headline()
    detail = dict(headline)
    if os.environ.get("BENCH_FAST") != "1":
        if os.environ.get("BENCH_SHARDED", "1") != "0":
            detail["sharded"] = bench_sharded()
        detail["config2"] = bench_config2()
        detail["config3"] = bench_config3()
        detail["config5"] = bench_config5()
        detail["trace_overhead"] = bench_trace_overhead()
        detail["devprof_overhead"] = bench_devprof_overhead()
        detail["drain"] = bench_drain()
        detail["soak_smoke"] = bench_soak_smoke()
        if os.environ.get("BENCH_FANOUT", "1") != "0":
            detail["fanout"] = bench_fanout()
        if os.environ.get("BENCH_FEDERATION", "1") != "0":
            detail["federation_smoke"] = bench_federation_smoke()
        if os.environ.get("BENCH_OVERLOAD", "1") != "0":
            detail["overload"] = bench_overload()
        if os.environ.get("BENCH_PAGED", "1") != "0":
            detail["paged"] = bench_paged()
        # worker-scaling curve over the same real-server drain path (the
        # 1-core bench box bounds speedup; the curve + queue depth shows
        # WHERE the control plane saturates)
        detail["worker_scaling"] = [
            bench_drain(n_jobs=200, n_nodes=500, workers=w)
            for w in (1, 2, 4)
        ]
        # continuous-profiling A/B on the 4-worker drain (the top
        # worker-scaling tier doubles as the first unprofiled arm)
        detail["profile_ab"] = bench_profile_ab(
            base_run=detail["worker_scaling"][-1]
        )
        # the applier-knee ladder (ROADMAP item 1): 1/2/4/8 workers with
        # the pipelined applier + sharded ready-queues
        if os.environ.get("BENCH_APPLIER", "1") != "0":
            detail["applier"] = bench_applier()
    e2e = headline["end_to_end_s"]
    parities = [headline["parity_exact_full"], headline["parity_oracle"]]
    detail["parity"] = round(min(parities), 5)
    suffix = "_spread" if headline["spread"] else ""
    result = {
        "metric": f"batch_plan_e2e_{N_ALLOCS}allocs_x_{N_NODES}nodes{suffix}",
        "value": e2e,
        "unit": "s",
        "vs_baseline": round(TARGET_S / e2e, 3) if e2e else 0.0,
        "detail": detail,
    }
    print(json.dumps(result))
    # ONE compact trailing line AFTER the JSON blob: log tails truncate,
    # and the round's headline numbers must survive a 2000-char tail
    # (VERDICT r5 weak #1 — BENCH_r05 lost its own headline)
    parts = [
        f"e2e_best={e2e}s",
        f"median={headline.get('median_s')}s",
        f"worst={headline.get('worst_s')}s",
        f"parity={detail['parity']}",
        "recompiles="
        + (
            "unknown"
            if any(
                d.get("recompiles") is None
                for d in headline.get("samples_detail", [])
            )
            else str(
                sum(
                    d.get("recompiles", 0)
                    for d in headline.get("samples_detail", [])
                )
            )
        ),
    ]
    # 0 on a clean tree, -1 if the analyzer itself broke: drift shows up
    # in the perf trajectory next to the numbers the analyzer protects
    # (the shape-bucket rules exist because of a bench regression; see
    # ANALYSIS.md)
    from nomad_tpu.analysis import count_new_findings, count_race_findings

    parts.append(f"analysis_findings={count_new_findings()}")
    # the race plane's burn-down gauge: new + baselined findings from
    # the three race rules (racegraph.py) — drops as races get fixed,
    # never silently (a WHY'd ignore removes it from the count only
    # with a committed justification next to the write site)
    parts.append(f"race_findings={count_race_findings()}")
    if "sharded" in detail:
        sh = detail["sharded"]
        if sh.get("skipped"):
            parts += [
                "sharded_s=skipped", "sharded_parity=skipped",
                "sharded_devices=0",
            ]
        else:
            parts += [
                f"sharded_s={sh['end_to_end_s']}",
                f"sharded_parity={sh['parity_vs_unsharded']}",
                f"sharded_devices={sh['devices']}",
                f"sharded_recompiles={sh['recompiles']}",
                f"sharded_speedup={sh['speedup_vs_unsharded']}",
            ]
            if sh.get("wavefront_speedup") is not None:
                parts += [
                    f"wavefront_speedup={sh['wavefront_speedup']}",
                    f"wavefront_rounds={sh['wavefront_rounds']}",
                    f"wavefront_parity={sh['wavefront_parity']}",
                ]
    if "config2" in detail:
        parts.append(f"cfg2={detail['config2'].get('evals_per_s')}evals/s")
        parts.append(f"cfg3={detail['config3'].get('end_to_end_s')}s")
        parts.append(f"cfg5={detail['config5'].get('wall_s')}s")
        drain_d = detail["drain"]
        parts.append(f"drain={drain_d.get('evals_per_s')}evals/s")
        parts.append(
            f"mirror={drain_d.get('mirror_hits')}hit/"
            f"{drain_d.get('mirror_rebuilds')}rebuild"
        )
        # the committed-planes acceptance keys: rebuilds must read 0 in
        # steady state, and restore must come up byte-identical fast
        parts.append(f"mirror_rebuilds={drain_d.get('mirror_rebuilds')}")
        parts.append(f"plane_restore_s={drain_d.get('plane_restore_s')}")
        parts.append(f"plane_identity={drain_d.get('plane_identity')}")
        ws = detail.get("worker_scaling", [])
        parts.append(
            "workers="
            + "/".join(str(w.get("evals_per_s")) for w in ws)
            + "evals/s@1,2,4"
        )
        invokes = [
            (w.get("stages") or {})
            .get("worker.invoke_scheduler.tpu-batch", {})
            .get("mean_ms")
            for w in ws
        ]
        parts.append(
            "invoke_mean="
            + "/".join(str(v) for v in invokes)
            + "ms@1,2,4"
        )
        soak = detail["soak_smoke"]
        parts.append(
            f"soak_invariant_violations={soak['invariant_violations']}"
        )
        parts.append(f"soak_rss_peak_mb={soak['rss_peak_mb']}")
        parts.append(f"soak_slo_score={soak['slo_score']}")
        if "fanout" in detail:
            fo = detail["fanout"]
            parts.append(f"fanout_subs={fo['fanout_connected']}")
            parts.append(f"fanout_pub_eps={fo['fanout_pub_eps']}")
            parts.append(f"fanout_lag_p99_ms={fo['fanout_lag_p99_ms']}")
            parts.append(f"fanout_gaps={fo['fanout_gaps']}")
            parts.append(
                f"fanout_silent_gaps={fo['fanout_silent_gaps']}"
            )
            parts.append(f"fanout_slo_score={fo['slo']['score']}")
        if "federation_smoke" in detail:
            fed = detail["federation_smoke"]
            parts.append(
                "fed_invariant_violations="
                f"{fed['fed_invariant_violations']}"
            )
            parts.append(f"fed_heal_s={fed['fed_heal_s']}")
            parts.append(f"fed_fwd_err_rate={fed['fed_fwd_err_rate']}")
            parts.append(f"fed_slo_score={fed['slo_score']}")
        if "overload" in detail:
            ovl = detail["overload"]
            parts.append(
                f"overload_goodput_eps={ovl['overload_goodput_eps']}"
            )
            parts.append(
                f"overload_shed_frac={ovl['overload_shed_frac']}"
            )
            parts.append(
                f"overload_dl_exceeded={ovl['overload_dl_exceeded']}"
            )
            parts.append(
                f"overload_recovery_s={ovl['overload_recovery_s']}"
            )
            parts.append(f"overload_slo_score={ovl['slo_score']}")
        if "paged" in detail:
            pg = detail["paged"]
            parts.append(f"paged_nodes={pg['nodes']}")
            parts.append(f"paged_s={pg['paged_s']}")
            parts.append(f"paged_parity={pg['parity_vs_oracle']}")
            parts.append(
                f"paged_tile_reuploads={pg['tile_reuploads']}"
            )
            parts.append(f"paged_recompiles={pg['recompiles']}")
            parts.append(
                f"paged_budget_holds_full={pg['budget_holds_full']}"
            )
        to = detail["trace_overhead"]
        parts.append(f"trace_overhead_pct={to['overhead_pct']}")
        dpo = detail["devprof_overhead"]
        parts.append(f"devprof_overhead_pct={dpo['overhead_pct']}")
        # whole-run device-plane totals (every section's compiles and
        # transfers), read at print time from the live counters
        from nomad_tpu.debug import devprof as _devprof_mod

        dps = _devprof_mod.summary()
        parts.append(f"compile_s_total={dps['compile_s_total']}")
        parts.append(f"h2d_mb={dps['h2d_mb']}")
        pab = detail["profile_ab"]
        parts.append(f"profile_overhead_pct={pab['overhead_pct']}")
        if "applier" not in detail:
            # the applier ladder's 8-worker tier owns this key when it
            # ran (one key, one source — no ambiguous duplicates)
            parts.append(f"applier_block_frac={pab['applier_block_frac']}")
        parts.append(
            f"profile_block_site={pab['top_worker_blocked_site']}"
        )
        if "applier" in detail:
            ap = detail["applier"]
            parts.append(f"applier_evals_s={ap['applier_evals_s']}")
            parts.append(
                "applier_queue_wait_p99_ms="
                f"{ap['applier_queue_wait_p99_ms']}"
            )
            parts.append(f"applier_block_frac={ap['applier_block_frac']}")
            parts.append(f"applier_bottleneck={ap['applier_bottleneck']}")
            parts.append(f"applier_cores={ap['cpu_count']}")
            parts.append(ap["applier_workers_line"])
        # retained by the LAST drain section (ws[-1] = the 4-worker run):
        # its critical path is the worker-scaling verdict from traces
        ws_cp = (ws[-1].get("critical_path") or {}) if ws else {}
        parts.append(
            f"trace_retained={ws_cp.get('traces', drain_d.get('critical_path', {}).get('traces', 0))}"
        )
        parts.append(f"trace_bottleneck={ws_cp.get('bottleneck')}")
    print("BENCH_SUMMARY " + " ".join(parts))


if __name__ == "__main__":
    main()
