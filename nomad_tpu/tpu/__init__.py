"""TPU-native batched scheduling backend.

The reference scores one allocation against one node at a time inside a Go
iterator chain (scheduler/rank.go:176). Here the same semantics are expressed
as dense array programs: a columnar mirror of cluster state (columnar.py)
feeds a jitted lax.scan kernel (kernel.py) that plans every pending
allocation against every feasible node in one XLA program, and the
``tpu-batch`` scheduler (batch_sched.py) wires it into the factory map with
the scalar oracle as fallback for paths the kernel does not cover.
"""

import os as _os


def enable_compile_cache(path: str | None = None) -> str:
    """Point JAX's persistent compilation cache at a repo-local directory so
    a fresh process skips recompiling the planner shapes it has seen before
    (cold compile was 13s at r02 as the shape ladder grew; VERDICT r2 #7).
    Safe to call repeatedly; returns the cache dir. Disable with
    NOMAD_TPU_COMPILE_CACHE=off."""
    import jax

    path = path or _os.environ.get("NOMAD_TPU_COMPILE_CACHE", "")
    if path == "off":
        return ""
    if not path:
        path = _os.path.join(
            _os.path.dirname(_os.path.dirname(_os.path.dirname(_os.path.abspath(__file__)))),
            ".jax_cache",
        )
    try:
        jax.config.update("jax_compilation_cache_dir", path)
        # cache everything: even sub-second host compiles add up across the
        # bucket ladder, and entry-size floors would skip the small planners
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.0)
        jax.config.update("jax_persistent_cache_min_entry_size_bytes", -1)
    except Exception:
        pass
    return path


# Lazy re-exports (PEP 562): importing this package must not pull jax —
# the vectorized-oracle workers (bench.py spawn processes, tpu/exact_np.py)
# route through batch_sched with numpy only, and jax's cold init is seconds
# per process. The compile cache is enabled from kernel.py's module import,
# which still precedes every jit compile.
_LAZY = {
    "TPUBatchScheduler": ("batch_sched", "TPUBatchScheduler"),
    "ColumnarCluster": ("columnar", "ColumnarCluster"),
    "plan_batch": ("kernel", "plan_batch"),
}


def __getattr__(name):
    entry = _LAZY.get(name)
    if entry is None:
        raise AttributeError(name)
    import importlib

    mod = importlib.import_module(f".{entry[0]}", __name__)
    return getattr(mod, entry[1])
