#!/usr/bin/env sh
# Federated storm entry point (nomad_tpu/loadgen/federation.py; README
# "Federated storm plane" + OBSERVABILITY.md federation section). Runs
# the full multi-region chaos storm by default — region partition +
# heal, leader failover mid-storm, asymmetric partial sever, rolling
# region restart — and writes the scored FED_rNN.json artifact; exit 0
# = every SLO passed (0 invariant violations, 0 lost/double-committed
# cross-region placements, bounded heal time / forwarding error rate /
# replication lag p99).
#
#   scripts/federation.sh                       # full storm -> FED_r01.json
#   FED_PROFILE=smoke scripts/federation.sh     # the tier-1 2-region smoke
#   FED_SERVERS=3 FED_CHURN_S=180 scripts/federation.sh   # longer storm
#   scripts/federation.sh --seed 7              # different storm, same SLOs
#
# Scale knobs (env): FED_PROFILE (smoke|storm), FED_REGIONS (2..3),
# FED_SERVERS (per region), FED_NODES (per region), FED_JOB_SLOTS,
# FED_CHURN_S, FED_CHURN_RATE, FED_CROSS_P (cross-region submit
# fraction), FED_QUIESCE_S, FED_RESTART_REGION.
# Determinism: the same --seed compiles byte-identical per-region op
# streams (stream_digest per region in the artifact).
set -eu

cd "$(dirname "$0")/.."

out=""
for arg in "$@"; do
  case "$arg" in
    --out|--out=*) out="explicit" ;;
  esac
done
if [ -z "$out" ]; then
  n=1
  while [ -e "$(printf 'FED_r%02d.json' "$n")" ]; do n=$((n + 1)); done
  set -- --out "$(printf 'FED_r%02d.json' "$n")" "$@"
fi

exec env JAX_PLATFORMS="${JAX_PLATFORMS:-cpu}" \
  python -m nomad_tpu.loadgen --federation "$@"
