"""Client durable state + task recovery
(ref client/state/state_database.go:107, client.go:979 restoreState,
plugins/drivers/proto/driver.proto:35 RecoverTask).

A client that dies mid-task must come back as the SAME node, restore its
alloc runners from the local DB, and reattach to still-running tasks via
the driver's RecoverTask — no orphaned work, no duplicate allocs."""

import tempfile
import time

import nomad_tpu.mock as mock
from nomad_tpu.client.client import Client
from nomad_tpu.client.state import ClientStateDB
from nomad_tpu.core.server import Server
from nomad_tpu.raft import InmemTransport, RaftConfig


def make_server():
    cfg = {
        "seed": 42,
        "heartbeat_ttl": 600.0,
        "raft": {
            "node_id": "s0",
            "address": "raft0",
            "voters": {"s0": "raft0"},
            "transport": InmemTransport(),
            "config": RaftConfig(
                heartbeat_interval=0.02,
                election_timeout_min=0.05,
                election_timeout_max=0.10,
            ),
        },
    }
    s = Server(cfg)
    s.start(num_workers=1, wait_for_leader=5.0)
    return s


def wait_until(fn, timeout=15.0, msg="condition"):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if fn():
            return
        time.sleep(0.05)
    raise AssertionError(f"timed out waiting for {msg}")


def mock_job(run_for="10s", count=1, extra_config=None):
    # batch type: completed allocs stay complete (a service job would
    # replace them to hold count, so restart tests would never converge)
    job = mock.batch_job()
    tg = job.task_groups[0]
    tg.count = count
    task = tg.tasks[0]
    task.driver = "mock_driver"
    task.config = {"run_for": run_for}
    task.config.update(extra_config or {})
    task.resources.networks = []
    return job


class TestClientStateDB:
    def test_roundtrip(self):
        with tempfile.TemporaryDirectory() as d:
            db = ClientStateDB(d)
            db.put_meta("node_id", "n-1")
            db.put_alloc({"id": "a1", "job_id": "j1"})
            db.put_task_state("a1", "web", {"state": "running"})
            db.put_driver_handle("a1", "web", {"pid": 42})
            db.close()

            db2 = ClientStateDB(d)
            assert db2.get_meta("node_id") == "n-1"
            assert db2.get_allocs() == [{"id": "a1", "job_id": "j1"}]
            assert db2.get_task_states("a1") == {"web": {"state": "running"}}
            assert db2.get_driver_handle("a1", "web") == {"pid": 42}
            db2.delete_alloc("a1")
            assert db2.get_allocs() == []
            assert db2.get_driver_handle("a1", "web") is None
            db2.close()


class TestClientRestart:
    def _start_client(self, server, data_dir):
        c = Client(server, data_dir=data_dir)
        c.start()
        return c

    def test_mock_task_survives_client_restart(self):
        """Crash the client mid-task: the restarted client is the same node,
        recovers the runner, the task keeps running and completes — and the
        server never sees a duplicate alloc."""
        server = make_server()
        data_dir = tempfile.mkdtemp(prefix="client_restart_")
        try:
            c1 = self._start_client(server, data_dir)
            node_id = c1.node.id
            job = mock_job(run_for="4s")
            server.job_register(job)
            wait_until(
                lambda: any(
                    a.client_status == "running"
                    for a in server.state.allocs_by_job(job.namespace, job.id)
                ),
                msg="alloc running",
            )

            # crash: no destroy — tasks keep their (timer-simulated) life
            c1.stop(destroy_allocs=False)

            c2 = self._start_client(server, data_dir)
            assert c2.node.id == node_id, "restarted client must keep its node id"
            assert len(c2.alloc_runners) == 1, "runner restored from state db"
            (runner,) = c2.alloc_runners.values()
            (tr,) = runner.task_runners.values()
            wait_until(lambda: tr.handle is not None, msg="handle attached")
            assert tr.handle.recovered, "task reattached, not restarted"

            wait_until(
                lambda: all(
                    a.client_status == "complete"
                    for a in server.state.allocs_by_job(job.namespace, job.id)
                ),
                timeout=20.0,
                msg="task completes after recovery",
            )
            allocs = server.state.allocs_by_job(job.namespace, job.id)
            assert len(allocs) == 1, "no duplicate alloc after restart"
            c2.stop()
        finally:
            server.stop()

    def test_raw_exec_pid_reattach(self):
        """raw_exec: the real process keeps running through the client crash
        and the restarted client reattaches to the same pid."""
        server = make_server()
        data_dir = tempfile.mkdtemp(prefix="client_rawexec_")
        try:
            c1 = self._start_client(server, data_dir)
            job = mock.batch_job()
            tg = job.task_groups[0]
            tg.count = 1
            task = tg.tasks[0]
            task.driver = "raw_exec"
            task.config = {"command": "/bin/sleep", "args": ["4"]}
            task.resources.networks = []
            server.job_register(job)
            wait_until(
                lambda: any(
                    a.client_status == "running"
                    for a in server.state.allocs_by_job(job.namespace, job.id)
                ),
                msg="alloc running",
            )
            (runner,) = c1.alloc_runners.values()
            (tr,) = runner.task_runners.values()
            pid = tr.handle.pid
            assert pid > 0

            c1.stop(destroy_allocs=False)

            import os

            os.kill(pid, 0)  # still alive through the crash

            c2 = self._start_client(server, data_dir)
            (runner2,) = c2.alloc_runners.values()
            (tr2,) = runner2.task_runners.values()
            wait_until(lambda: tr2.handle is not None, msg="handle attached")
            assert tr2.handle.recovered and tr2.handle.pid == pid

            wait_until(
                lambda: all(
                    a.client_status == "complete"
                    for a in server.state.allocs_by_job(job.namespace, job.id)
                ),
                timeout=20.0,
                msg="sleep completes after recovery",
            )
            c2.stop()
        finally:
            server.stop()

    def test_unrecoverable_task_restarts(self):
        """fail_recover: RecoverTask declines, so the restarted client
        restarts the task under the restart policy instead of orphaning."""
        server = make_server()
        data_dir = tempfile.mkdtemp(prefix="client_norecover_")
        try:
            c1 = self._start_client(server, data_dir)
            job = mock_job(run_for="2s", extra_config={"fail_recover": True})
            # fast restarts for the test
            job.task_groups[0].restart_policy.delay = int(0.1 * 1e9)
            server.job_register(job)
            wait_until(
                lambda: any(
                    a.client_status == "running"
                    for a in server.state.allocs_by_job(job.namespace, job.id)
                ),
                msg="alloc running",
            )
            c1.stop(destroy_allocs=False)

            c2 = self._start_client(server, data_dir)
            (runner2,) = c2.alloc_runners.values()
            (tr2,) = runner2.task_runners.values()
            wait_until(lambda: tr2.handle is not None, msg="task started again")
            assert not tr2.handle.recovered, "unrecoverable task restarted fresh"
            wait_until(
                lambda: all(
                    a.client_status == "complete"
                    for a in server.state.allocs_by_job(job.namespace, job.id)
                ),
                timeout=20.0,
                msg="restarted task completes",
            )
            c2.stop()
        finally:
            server.stop()

    def test_restart_budget_survives_restart(self):
        """Persisted restart timestamps seed the restored runner, so a
        crash-looping task doesn't get a fresh restart-policy budget from a
        client restart (ref restarts/restarts.go)."""
        import time as _time

        from nomad_tpu.client.client import AllocRunner, TaskRunner
        from nomad_tpu.client.driver import MockDriver

        server = make_server()
        data_dir = tempfile.mkdtemp(prefix="client_budget_")
        try:
            c1 = self._start_client(server, data_dir)
            job = mock_job(run_for="30s")
            server.job_register(job)
            wait_until(
                lambda: any(
                    a.client_status == "running"
                    for a in server.state.allocs_by_job(job.namespace, job.id)
                ),
                msg="alloc running",
            )
            (runner,) = c1.alloc_runners.values()
            (tr,) = runner.task_runners.values()
            # simulate two consumed restart attempts, then a crash
            tr._restarts_in_interval = [_time.time() - 1.0, _time.time()]
            tr.state.restarts = 2
            c1.alloc_state_updated(runner)
            c1.stop(destroy_allocs=False)

            c2 = self._start_client(server, data_dir)
            (runner2,) = c2.alloc_runners.values()
            (tr2,) = runner2.task_runners.values()
            assert tr2.state.restarts == 2
            assert len(tr2._restarts_in_interval) == 2
            c2.stop()
        finally:
            server.stop()

    def test_terminal_allocs_pruned_on_restore(self):
        """Allocs that finished before the crash don't resurrect runners."""
        server = make_server()
        data_dir = tempfile.mkdtemp(prefix="client_prune_")
        try:
            c1 = self._start_client(server, data_dir)
            job = mock_job(run_for="0s")
            server.job_register(job)
            wait_until(
                lambda: all(
                    a.client_status == "complete"
                    for a in server.state.allocs_by_job(job.namespace, job.id)
                )
                and len(server.state.allocs_by_job(job.namespace, job.id)) == 1,
                msg="task complete",
            )
            c1.stop(destroy_allocs=False)
            c2 = self._start_client(server, data_dir)
            assert c2.alloc_runners == {}, "terminal alloc must not restore"
            c2.stop()
        finally:
            server.stop()
