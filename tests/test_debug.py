"""Debug plane tests (nomad_tpu/debug/): sampling profiler attribution,
lock-contention accounting, flight recorder, watchdog rules + auto
bundle capture, bundle content/redaction, and the HTTP/CLI round-trips.

The deterministic attribution tests drive the profiler with synthetic
threads (a spinning hot function; a convoy parked on a PendingPlan
future) so the assertions are about the attribution machinery, not
about scheduler load on the test box.
"""

import io
import json
import os
import tarfile
import threading
import time
from types import SimpleNamespace

import pytest

from nomad_tpu import metrics
from nomad_tpu.debug import (
    FlightRecorder,
    SamplingProfiler,
    Watchdog,
    capture_bundle,
    classify_thread,
    make_tarball,
    redact_config,
    render_folded,
    thread_dump,
)
from nomad_tpu.debug.bundle import BUNDLE_FILES
from nomad_tpu.debug.flight import rss_slope, sample_process
from nomad_tpu.testing import lockdep


def make_server(**extra):
    from nomad_tpu.core.server import Server
    from nomad_tpu.raft import InmemTransport, RaftConfig

    cfg = {
        "seed": 7,
        "heartbeat_ttl": 600.0,
        "raft": {
            "node_id": "s0",
            "address": "raft-dbg",
            "voters": {"s0": "raft-dbg"},
            "transport": InmemTransport(),
            "config": RaftConfig(
                heartbeat_interval=0.05,
                election_timeout_min=0.1,
                election_timeout_max=0.2,
            ),
        },
    }
    cfg.update(extra)
    return Server(cfg)


# ---------------------------------------------------------------------------
# profiler
# ---------------------------------------------------------------------------


class TestProfiler:
    def test_hot_function_attributed_above_threshold(self):
        """A synthetic hot function on a worker-named thread must own
        the overwhelming majority of that thread's samples."""
        stop = threading.Event()

        def spin_hot():
            x = 0
            while not stop.is_set():
                for i in range(500):
                    x += i * i
            return x

        t = threading.Thread(
            target=spin_hot, daemon=True, name="worker-hot-synthetic"
        )
        t.start()
        try:
            prof = SamplingProfiler(hz=200).start()
            time.sleep(0.5)
            report = prof.stop()
        finally:
            stop.set()
            t.join(timeout=2.0)

        worker_samples = report["threads"].get("worker", 0)
        assert worker_samples >= 20, report["threads"]
        hot = sum(
            count
            for stack, count in report["folded"].items()
            if "worker-hot-synthetic" in stack and "spin_hot" in stack
        )
        # deterministic: the thread does nothing else — ≥90% of its
        # samples must land in spin_hot
        assert hot / worker_samples >= 0.9, (hot, worker_samples)
        assert report["hz_actual"] > 20
        # folded rendering round-trips the stacks
        folded = render_folded(report)
        assert "spin_hot" in folded

    def test_applier_convoy_names_plan_apply_wait(self):
        """Worker-class threads parked on PendingPlan.wait (the applier
        future every real worker blocks on, core/plan_apply.py) must
        dominate the worker-class blocked-site table and drive
        applier_block_frac — the ROADMAP item 2 knee signature,
        reproduced without the trace plane."""
        from nomad_tpu.core.plan_apply import PendingPlan

        pending = PendingPlan(SimpleNamespace(eval_id="dbg-eval"))
        threads = [
            threading.Thread(
                target=lambda: pending.wait(timeout=3.0),
                daemon=True,
                name=f"sched-worker-dbg-{i}",
            )
            for i in range(3)
        ]
        for t in threads:
            t.start()
        time.sleep(0.05)
        prof = SamplingProfiler(hz=200).start()
        time.sleep(0.4)
        report = prof.stop()
        pending.respond(None, RuntimeError("test done"))
        for t in threads:
            t.join(timeout=2.0)

        assert report["applier_block_frac"] >= 0.9, report[
            "applier_block_frac"
        ]
        worker_rows = [
            r for r in report["blocked_sites"] if r["class"] == "worker"
        ]
        assert worker_rows, report["blocked_sites"]
        assert worker_rows[0]["site"].endswith("core/plan_apply.py:wait"), (
            worker_rows[0]
        )

    def test_thread_classification_contract(self):
        assert classify_thread("sched-worker-3") == "worker"
        assert classify_thread("drain-eval-abcd1234") == "worker"
        assert classify_thread("plan-applier") == "applier"
        assert classify_thread("plan-commit") == "applier"
        assert classify_thread("raft-repl-s1") == "raft"
        assert classify_thread("debug-flight-recorder") == "debug"
        assert classify_thread("eval-failed-reaper") == "leader"
        assert classify_thread("Thread-17") == "other"

    def test_thread_dump_keeps_legacy_pprof_shape(self):
        dump = thread_dump()
        assert set(dump) == {"threads", "thread_count", "gc"}
        assert dump["thread_count"] == len(dump["threads"])
        me = threading.current_thread().name
        assert me in dump["threads"]
        assert isinstance(dump["threads"][me], list)

    def test_thread_dump_keeps_duplicate_names_distinct(self):
        """Shared static names (rpc-conn, connect-proxy-pump, ...) must
        not clobber each other's stacks in the dump."""
        stop = threading.Event()
        threads = [
            threading.Thread(
                target=stop.wait, daemon=True, name="dump-dup-name"
            )
            for _ in range(3)
        ]
        for t in threads:
            t.start()
        try:
            dump = thread_dump()
        finally:
            stop.set()
            for t in threads:
                t.join(timeout=2.0)
        dups = [n for n in dump["threads"] if n.startswith("dump-dup-name")]
        assert len(dups) == 3, dups


# ---------------------------------------------------------------------------
# lockdep contention
# ---------------------------------------------------------------------------


@pytest.mark.skipif(
    not lockdep.installed(), reason="lockdep disabled (NOMAD_TPU_LOCKDEP=0)"
)
class TestLockContention:
    def test_two_thread_convoy_attributed_to_site(self):
        """A provoked convoy — one thread holds, one blocks — must show
        up in the contention table at the lock's allocation site with
        the actual blocked duration, and be the top site by wait delta
        inside this window."""
        before = {
            site: entry["wait_s"]
            for site, entry in lockdep.contention().items()
        }
        lock = threading.Lock()  # wrapped by lockdep; site = this line
        entered = threading.Event()

        def holder():
            with lock:
                entered.set()
                time.sleep(0.35)

        def blocker():
            with lock:
                pass

        th = threading.Thread(
            target=holder, daemon=True, name="convoy-holder"
        )
        tb = threading.Thread(
            target=blocker, daemon=True, name="convoy-blocker"
        )
        th.start()
        assert entered.wait(2.0)
        tb.start()
        th.join(timeout=2.0)
        tb.join(timeout=2.0)

        deltas = {
            site: entry["wait_s"] - before.get(site, 0.0)
            for site, entry in lockdep.contention().items()
        }
        convoy = {
            site: d for site, d in deltas.items() if "test_debug" in site
        }
        assert convoy, deltas
        site, waited = max(convoy.items(), key=lambda e: e[1])
        assert waited >= 0.25, (site, waited)
        # the provoked convoy is the top contended site in this window
        assert waited == max(deltas.values()), deltas

    def test_uncontended_acquire_not_counted(self):
        before = {
            site: entry["count"]
            for site, entry in lockdep.contention().items()
        }
        lock = threading.Lock()
        for _ in range(50):
            with lock:
                pass
        after = lockdep.contention()
        grown = {
            site
            for site, entry in after.items()
            if "test_debug" in site
            and entry["count"] > before.get(site, 0)
        }
        assert not grown, grown


# ---------------------------------------------------------------------------
# flight recorder
# ---------------------------------------------------------------------------


class TestFlightRecorder:
    def test_passive_record_fields_and_ring_bound(self):
        server = make_server()
        recorder = FlightRecorder(server, interval=0.05, retain=8)
        for _ in range(12):
            recorder.record()
        samples = recorder.samples()
        assert len(samples) == 8  # deque(maxlen=retain)
        sample = samples[-1]
        for key in (
            "t", "rss_mb", "index", "allocs", "evals", "jobs", "nodes",
            "deployments", "eval_e2e_p99_ms", "eval_e2e_mean_ms",
            "plan_queue_wait_p99_ms", "plan_submit_p99_ms",
            "plan_queue_depth", "broker_ready", "broker_unacked",
            "evals_processed", "subscribers", "slow_consumers_closed",
            "threads", "thread_classes",
        ):
            assert key in sample, key
        assert sample["rss_mb"] > 0
        # the committed-plane audit rides every sample (rate-limited to
        # one cold rebuild per interval): exact by construction → 0 rows
        assert sample["plane_divergence_rows"] == 0
        assert sample["plane_divergence_recs"] == 0
        assert sample["plane_audit_version"] == server.state.latest_index()
        dump = recorder.dump()
        assert dump["recorded"] == 8
        assert dump["retain"] == 8
        assert dump["samples"] == samples

    def test_server_starts_and_stops_recorder(self):
        server = make_server(debug={"flight_interval": 0.05})
        server.start(num_workers=1, wait_for_leader=5.0)
        try:
            deadline = time.monotonic() + 5
            while (
                time.monotonic() < deadline
                and len(server.flight_recorder.samples()) < 2
            ):
                time.sleep(0.05)
            assert len(server.flight_recorder.samples()) >= 2
        finally:
            server.stop()
        assert server.flight_recorder._thread is None

    def test_scorekeeper_delegates_to_flight_recorder(self):
        """The soak Scorekeeper's process sampling is the recorder's
        (one sampler, one reader) and its sample keys — the
        SOAK_rNN.json field-name contract — are unchanged."""
        from nomad_tpu.loadgen.score import Scorekeeper

        server = make_server()
        sk = Scorekeeper(server, interval=0.05, probes=0)
        assert sk.recorder is server.flight_recorder
        before = len(server.flight_recorder.samples())
        sk._t0 = time.monotonic()
        sk._sample(1)
        assert len(server.flight_recorder.samples()) == before + 1
        sample = sk.samples[0]
        for key in (
            "t", "rss_mb", "index", "allocs", "evals", "jobs", "nodes",
            "deployments", "eval_e2e_p99_ms", "eval_e2e_mean_ms",
            "plan_queue_wait_p99_ms", "plan_submit_p99_ms",
            "plan_queue_depth", "broker_ready", "subscribers",
            "slow_consumers_closed", "probe_lag",
        ):
            assert key in sample, key

    def test_rss_slope_least_squares(self):
        flat = [{"t": i * 10.0, "rss_mb": 100.0} for i in range(10)]
        assert rss_slope(flat) == 0.0
        growing = [
            {"t": i * 60.0, "rss_mb": 100.0 + 50.0 * i} for i in range(10)
        ]
        assert abs(rss_slope(growing) - 50.0) < 1e-6


# ---------------------------------------------------------------------------
# watchdog
# ---------------------------------------------------------------------------


class _FakeRecorder:
    def __init__(self, samples):
        self._samples = samples

    def samples(self, last=None):
        return self._samples[-last:] if last else list(self._samples)


class TestWatchdog:
    def _watchdog(self, samples, server=None, **kw):
        return Watchdog(
            server or SimpleNamespace(config={}),
            _FakeRecorder(samples),
            **kw,
        )

    def test_plan_queue_wait_rule_needs_consecutive_breaches(self):
        samples = [
            {
                "t": float(i),
                "plan_queue_wait_p99_ms": 9000.0,
                "plan_queue_depth": 3,
            }
            for i in range(3)
        ]
        wd = self._watchdog(
            samples,
            config={"plan_queue_wait_p99": {
                "threshold_ms": 2000.0, "consecutive": 3,
            }},
        )
        wd.on_sample(samples[-1])
        assert wd.trip_count == 1
        assert wd.trip_log[0]["rule"] == "plan_queue_wait_p99"
        # one breached sample among healthy ones: no trip
        healthy = [
            {
                "t": float(i),
                "plan_queue_wait_p99_ms": v,
                "plan_queue_depth": 3,
            }
            for i, v in enumerate((10.0, 9000.0, 10.0))
        ]
        wd2 = self._watchdog(healthy)
        wd2.on_sample(healthy[-1])
        assert wd2.trip_count == 0

    def test_plan_queue_wait_rule_ignores_stale_idle_p99(self):
        """The timer window never decays while idle: a frozen breach
        p99 with no queued plans and a flat evals-processed counter is
        history, not an incident — no trip, no bundle every cooldown."""
        stale = [
            {
                "t": float(i),
                "plan_queue_wait_p99_ms": 9000.0,
                "plan_queue_depth": 0,
                "evals_processed": 100,
            }
            for i in range(4)
        ]
        wd = self._watchdog(stale)
        wd.on_sample(stale[-1])
        assert wd.trip_count == 0
        # same breach with evals completing across the window: live
        live = [
            {**s, "evals_processed": 100 + i} for i, s in enumerate(stale)
        ]
        wd2 = self._watchdog(live)
        wd2.on_sample(live[-1])
        assert wd2.trip_count == 1

    def test_cooldown_suppresses_repeat_trips(self):
        samples = [
            {
                "t": float(i),
                "plan_queue_wait_p99_ms": 9000.0,
                "plan_queue_depth": 2,
            }
            for i in range(6)
        ]
        wd = self._watchdog(samples, cooldown_s=3600.0)
        for s in samples[3:]:
            wd.on_sample(s)
        assert wd.trip_count == 1

    def test_plane_divergence_trips_immediately(self):
        """A nonzero plane-audit row count means a write path bypassed
        the store's commit protocol — one sample is enough to bundle,
        no consecutive-breach streak."""
        clean = [{"t": 0.0, "plane_divergence_rows": 0,
                  "plane_divergence_recs": 0}]
        wd = self._watchdog(clean)
        wd.on_sample(clean[-1])
        assert wd.trip_count == 0
        bad = [{"t": 1.0, "plane_divergence_rows": 2,
                "plane_divergence_recs": 0, "plane_audit_version": 17}]
        wd2 = self._watchdog(bad)
        wd2.on_sample(bad[-1])
        assert wd2.trip_count == 1
        assert wd2.trip_log[0]["rule"] == "plane_divergence"
        assert wd2.trip_log[0]["detail"]["rows"] == 2
        assert wd2.trip_log[0]["detail"]["planes_version"] == 17

    def test_h2d_thrash_rule(self):
        """Paged-planner thrash: tile re-upload bytes far outpacing
        committed placements means the device budget is churning tiles
        without buying decisions — bundle it."""

        def window(re_bytes, placed):
            return [
                {"t": 0.0, "paged_tile_reupload_bytes": 0,
                 "placements_total": 0},
                {"t": 15.0, "paged_tile_reupload_bytes": re_bytes,
                 "placements_total": placed,
                 "paged_tile_reuploads": 40},
            ]

        thrash = window(50_000_000, 10)
        wd = self._watchdog(thrash)
        wd.on_sample(thrash[-1])
        assert wd.trip_count == 1
        assert wd.trip_log[0]["rule"] == "h2d_thrash"
        assert wd.trip_log[0]["detail"]["reupload_bytes"] == 50_000_000
        assert wd.trip_log[0]["detail"]["placements"] == 10

        # same traffic amortized over real placement volume: healthy
        busy = window(50_000_000, 1_000_000)
        wd2 = self._watchdog(busy)
        wd2.on_sample(busy[-1])
        assert wd2.trip_count == 0

        # trickle below the absolute floor never trips, whatever the
        # ratio says (idle servers re-stamp tiles occasionally)
        trickle = window(1_000_000, 0)
        wd3 = self._watchdog(trickle)
        wd3.on_sample(trickle[-1])
        assert wd3.trip_count == 0

        # servers without the pager (no paged_* sample keys): inert
        plain = [{"t": 0.0}, {"t": 15.0}]
        wd4 = self._watchdog(plain)
        wd4.on_sample(plain[-1])
        assert wd4.trip_count == 0

    def test_bundle_dirs_pruned_to_keep(self, tmp_path):
        """On-disk retention: only the newest bundle_keep watchdog-*
        dirs survive; operator-captured dirs in the same parent are
        never reaped."""
        wd = self._watchdog(
            [], bundle_dir=str(tmp_path), config={"bundle_keep": 2}
        )
        for i in range(5):
            d = tmp_path / f"watchdog-{i:03d}-rss_slope"
            d.mkdir()
            # prune orders by mtime, not name — pin distinct times
            os.utime(d, (1000.0 + i, 1000.0 + i))
        (tmp_path / "operator-bundle").mkdir()
        wd._prune_bundles()
        left = sorted(p.name for p in tmp_path.iterdir())
        assert left == [
            "operator-bundle", "watchdog-003-rss_slope",
            "watchdog-004-rss_slope",
        ], left

    def test_stalled_worker_rule(self):
        stalled = [
            {
                "t": float(i),
                "broker_ready": 5,
                "broker_unacked": 0,
                "evals_processed": 100,
            }
            for i in range(8)
        ]
        wd = self._watchdog(stalled)
        wd.on_sample(stalled[-1])
        assert wd.trip_count == 1
        assert wd.trip_log[0]["rule"] == "stalled_worker"
        # progress (evals_processed advancing) means no stall
        moving = [
            {**s, "evals_processed": 100 + i} for i, s in enumerate(stalled)
        ]
        wd2 = self._watchdog(moving)
        wd2.on_sample(moving[-1])
        assert wd2.trip_count == 0

    def test_rss_slope_rule(self):
        leaking = [
            {"t": i * 10.0, "rss_mb": 100.0 + 200.0 * i} for i in range(12)
        ]
        wd = self._watchdog(
            leaking,
            config={"rss_slope": {
                "threshold_mb_per_min": 500.0, "window": 12,
                "min_span_s": 30.0,
            }},
        )
        wd.on_sample(leaking[-1])
        assert wd.trip_count == 1
        assert wd.trip_log[0]["rule"] == "rss_slope"

    def test_trip_captures_complete_bundle(self, tmp_path):
        """A trip on a REAL server with a bundle_dir captures a complete
        bundle (every BUNDLE_FILES member present, valid JSON)."""
        server = make_server(
            debug={
                "flight_interval": 0.05,
                "bundle_dir": str(tmp_path),
                "watchdog": {
                    "plan_queue_wait_p99": {
                        "threshold_ms": 1.0, "consecutive": 2,
                    },
                    "profile_seconds": 0.1,
                },
            }
        )
        server.start(num_workers=1, wait_for_leader=5.0)
        try:
            for _ in range(8):
                metrics.sample("plan.queue_wait", 5.0)
            deadline = time.monotonic() + 10
            while (
                time.monotonic() < deadline
                and not server.watchdog.stats()["bundles"]
            ):
                # keep the plan plane "live" for the activity gate: the
                # rule must see evals completing across its window
                metrics.incr("worker.evals_processed.service")
                time.sleep(0.05)
            assert server.watchdog.wait_idle(10.0)
            stats = server.watchdog.stats()
        finally:
            server.stop()
        assert stats["trips"] >= 1
        assert stats["bundles"], stats
        bundle_dir = stats["bundles"][0]
        present = set(os.listdir(bundle_dir))
        assert present == set(BUNDLE_FILES), present
        manifest = json.loads(
            (tmp_path / os.path.basename(bundle_dir) / "manifest.json")
            .read_text()
        )
        assert manifest["reason"].startswith("watchdog:")
        assert manifest["errors"] == {}, manifest["errors"]
        # the trip rode the metrics surface too
        assert metrics.snapshot()["counters"].get(
            "debug.watchdog_trips", 0
        ) >= 1


# ---------------------------------------------------------------------------
# bundle content + redaction
# ---------------------------------------------------------------------------


class TestBundle:
    SECRETS = ("gossip-ENCRYPT-secret", "hvs.VAULT-SECRET-TOKEN",
               "acl-bootstrap-SECRET")

    def test_redact_config_scrubs_sensitive_keys(self):
        cfg = {
            "region": "global",
            "encrypt": self.SECRETS[0],
            "vault": {"enabled": True, "token": self.SECRETS[1]},
            "acl": {"enabled": True, "bootstrap_secret": self.SECRETS[2]},
            "raft": {"transport": object()},
            "plan_apply_batch": 16,
        }
        red = redact_config(cfg)
        assert red["encrypt"] == "<redacted>"
        assert red["vault"]["token"] == "<redacted>"
        assert red["acl"]["bootstrap_secret"] == "<redacted>"
        assert red["raft"]["transport"] == "<object>"
        assert red["plan_apply_batch"] == 16  # non-sensitive survives
        assert red["region"] == "global"

    def test_bundle_complete_and_secret_free(self, tmp_path):
        server = make_server(
            encrypt=self.SECRETS[0],
            vault={"enabled": False, "token": self.SECRETS[1]},
        )
        dest = tmp_path / "bundle"
        manifest = capture_bundle(
            server, str(dest), profile_seconds=0.1, reason="test"
        )
        assert manifest["errors"] == {}, manifest["errors"]
        assert set(os.listdir(dest)) == set(BUNDLE_FILES)
        for fn in BUNDLE_FILES:
            raw = (dest / fn).read_text()
            for secret in self.SECRETS:
                assert secret not in raw, (fn, secret)
            if fn.endswith(".json"):
                json.loads(raw)  # every .json member parses
        config = json.loads((dest / "config.json").read_text())
        assert config["encrypt"] == "<redacted>"
        # tarball form round-trips
        tar_path = str(tmp_path / "bundle.tar.gz")
        make_tarball(str(dest), tar_path)
        with tarfile.open(tar_path) as tar:
            names = {os.path.basename(m.name) for m in tar.getmembers()}
        assert set(BUNDLE_FILES) <= names


# ---------------------------------------------------------------------------
# HTTP + CLI round-trips
# ---------------------------------------------------------------------------


@pytest.fixture
def debug_agent():
    from nomad_tpu.api.http import HTTPServer
    from nomad_tpu.api.client import ApiClient

    server = make_server(
        enable_debug=True, debug={"flight_interval": 0.1}
    )
    server.start(num_workers=1, wait_for_leader=5.0)
    http = HTTPServer(server, port=0)
    http.start()
    client = ApiClient(address=http.address)
    try:
        yield server, http, client
    finally:
        http.stop()
        server.stop()


class TestHttpSurface:
    def test_pprof_legacy_shape_unbroken(self, debug_agent):
        _, _, client = debug_agent
        out = client.debug_pprof()
        assert set(out) == {"threads", "thread_count", "gc"}
        assert out["thread_count"] >= 1
        assert "counts" in out["gc"] and "stats" in out["gc"]
        # worker threads visible under their profiler-contract names
        assert any("sched-worker" in name for name in out["threads"])

    def test_pprof_profile_seconds_round_trip(self, debug_agent):
        _, _, client = debug_agent
        t0 = time.monotonic()
        report = client.debug_pprof("profile", seconds=0.3)
        assert time.monotonic() - t0 >= 0.3
        assert report["samples"] > 0
        assert "folded" in report and "blocked_sites" in report
        assert "applier_block_frac" in report
        assert report["ticks"] >= 10

    def test_bundle_endpoint_tarball_and_json(self, debug_agent, tmp_path):
        _, _, client = debug_agent
        out = tmp_path / "bundle.tar.gz"
        data = client.debug_bundle(seconds=0.1, output=str(out))
        assert out.read_bytes() == data
        with tarfile.open(fileobj=io.BytesIO(data)) as tar:
            names = {os.path.basename(m.name) for m in tar.getmembers()}
        assert set(BUNDLE_FILES) <= names
        inline = client.debug_bundle_json(seconds=0.1)
        assert set(inline["manifest"]["files"]) == set(BUNDLE_FILES)
        assert inline["files"]["manifest.json"]["reason"] == "http"
        assert "applier_block_frac" in inline["files"]["findings.json"]

    def test_debug_routes_gated_without_enable_debug(self):
        from nomad_tpu.api.http import HTTPServer
        from nomad_tpu.api.client import ApiClient, APIError

        server = make_server()  # no enable_debug
        server.start(num_workers=0, wait_for_leader=5.0)
        http = HTTPServer(server, port=0)
        http.start()
        try:
            client = ApiClient(address=http.address)
            for call in (
                lambda: client.debug_pprof(),
                lambda: client.debug_pprof("profile", seconds=0.1),
                lambda: client.debug_bundle(seconds=0.1),
                lambda: client.debug_bundle_json(seconds=0.1),
            ):
                with pytest.raises(APIError) as err:
                    call()
                assert err.value.status == 403
        finally:
            http.stop()
            server.stop()

    def test_operator_debug_cli(self, debug_agent, tmp_path, capsys):
        from nomad_tpu.cli.main import main

        _, http, _ = debug_agent
        out = tmp_path / "cli-bundle.tar.gz"
        code = main([
            "-address", http.address, "operator", "debug",
            "-seconds", "0.1", "-output", str(out),
        ])
        assert code == 0
        printed = capsys.readouterr().out
        assert "Debug bundle written to" in printed
        with tarfile.open(str(out)) as tar:
            names = {os.path.basename(m.name) for m in tar.getmembers()}
        assert set(BUNDLE_FILES) <= names

    def test_metrics_carries_debug_plane_health(self, debug_agent):
        _, _, client = debug_agent
        payload = client.metrics()
        assert "debug" in payload
        assert "flight_recorded" in payload["debug"]
        assert "watchdog_trips" in payload["debug"]


# ---------------------------------------------------------------------------
# the tier-1 pin: watchdog auto-captures during the soak smoke storm
# ---------------------------------------------------------------------------


@pytest.mark.soak
class TestWatchdogStorm:
    def test_watchdog_trips_and_captures_during_smoke_storm(self, tmp_path):
        """A short smoke storm with an always-breaching rss_slope rule:
        the watchdog must trip mid-storm, auto-capture a complete
        bundle, and the trips must land in the scored report and
        SOAK_SUMMARY line."""
        from nomad_tpu.loadgen import get_scenario
        from nomad_tpu.loadgen.runner import run_scenario
        from nomad_tpu.loadgen.score import summary_line

        scenario = get_scenario("smoke", nodes=16, churn_s=4.0)
        scenario.server_config = {
            **scenario.server_config,
            "debug": {
                "flight_interval": 0.25,
                "bundle_dir": str(tmp_path),
                "watchdog": {
                    # guaranteed breach once the window spans ≥1s: any
                    # slope beats the sentinel threshold
                    "rss_slope": {
                        "threshold_mb_per_min": -1e9,
                        "window": 6,
                        "min_span_s": 1.0,
                    },
                    "profile_seconds": 0.2,
                    "cooldown_s": 3600.0,
                },
            },
        }
        report = run_scenario(scenario, 20260804, driver_workers=4)
        watchdog = report["watchdog"]
        assert watchdog is not None
        assert watchdog["trips"] >= 1, watchdog
        assert watchdog["bundles"], watchdog
        bundle_dir = watchdog["bundles"][0]
        assert set(os.listdir(bundle_dir)) == set(BUNDLE_FILES)
        line = summary_line(report)
        assert "watchdog_trips=" in line
        assert f"watchdog_trips={watchdog['trips']}" in line
        # the storm itself stayed healthy
        assert report["invariants"]["violations"] == 0
