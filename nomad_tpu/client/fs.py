"""Allocation filesystem operations (ref client fs/logs/exec surface:
command/agent/fs_endpoint.go serving, client_fs_endpoint.go forwarding).

Pure functions over an allocation directory, shared by the agent's local
HTTP handlers and the client's RPC service (the server→client forwarding
path for allocations living on remote nodes)."""

from __future__ import annotations

import os
import subprocess

from ..util import contained_path


def list_dir(alloc_dir: str, path: str) -> list[dict]:
    full = contained_path(alloc_dir, path)
    entries = []
    for name in sorted(os.listdir(full)):
        p = os.path.join(full, name)
        st = os.stat(p)
        entries.append(
            {
                "Name": name,
                "IsDir": os.path.isdir(p),
                "Size": st.st_size,
                "ModTime": int(st.st_mtime),
            }
        )
    return entries


def cat(alloc_dir: str, path: str, offset: int = 0, limit: int = 1 << 20) -> dict:
    full = contained_path(alloc_dir, path)
    size = os.path.getsize(full)
    with open(full, "rb") as f:
        f.seek(offset)
        data = f.read(limit)
    return {
        "Data": data.decode("utf-8", "replace"),
        "Offset": offset + len(data),
        "Size": size,
    }


def logs(
    alloc_dir: str,
    task: str,
    kind: str,
    offset: int = 0,
    origin: str = "start",
    limit: int = 1 << 20,
) -> dict:
    if kind not in ("stdout", "stderr"):
        raise ValueError("type must be stdout or stderr")
    # Rotation (logmon) writes <task>.<kind>.<n>; the surviving files are
    # served as ONE logical stream so a follower's offset cursor crosses
    # rotation boundaries without losing the old file's tail (the frames
    # model of the reference's fs_endpoint.go Logs). Data reaped by
    # max_files ages out of the logical stream from the front.
    from .logmon import rotated_indexes

    log_dir = contained_path(alloc_dir, f"{task}/logs")
    prefix = f"{task}.{kind}."
    indexes = (
        rotated_indexes(log_dir, prefix) if os.path.isdir(log_dir) else []
    )
    if not indexes:
        return {"Data": "", "Offset": 0}
    segments = []  # (path, size) oldest → newest
    total = 0
    for idx in indexes:
        path = os.path.join(log_dir, prefix + str(idx))
        try:
            size = os.path.getsize(path)
        except OSError:
            continue
        segments.append((path, size))
        total += size
    start = max(total - offset, 0) if origin == "end" else min(offset, total)
    chunks = []
    remaining = limit
    position = 0
    for path, size in segments:
        if remaining <= 0:
            break
        seg_start = max(start - position, 0)
        if seg_start < size:
            with open(path, "rb") as f:
                f.seek(seg_start)
                chunks.append(f.read(min(remaining, size - seg_start)))
            remaining -= len(chunks[-1])
        position += size
    data = b"".join(chunks)
    return {
        "Data": data.decode("utf-8", "replace"),
        "Offset": start + len(data),
        "Size": total,
    }


def exec_in(alloc_dir: str, task: str, cmd: list, timeout: float = 30.0) -> dict:
    task_dir = contained_path(alloc_dir, task)
    try:
        proc = subprocess.run(
            cmd, cwd=task_dir, capture_output=True, timeout=timeout
        )
    except subprocess.TimeoutExpired as e:
        return {
            "ExitCode": -1,
            "TimedOut": True,
            "Stdout": (e.stdout or b"").decode("utf-8", "replace"),
            "Stderr": (e.stderr or b"").decode("utf-8", "replace"),
        }
    except (FileNotFoundError, NotADirectoryError, PermissionError) as e:
        raise ValueError(f"exec failed: {e}") from e
    return {
        "ExitCode": proc.returncode,
        "Stdout": proc.stdout.decode("utf-8", "replace"),
        "Stderr": proc.stderr.decode("utf-8", "replace"),
    }


def register_alloc_rpc(rpc_server, client):
    """Alloc lifecycle RPCs on the client's listener — the server→client
    path behind /v1/client/allocation/:id/{restart,signal}
    (ref client_alloc_endpoint.go → client/rpc Allocations.Restart/Signal)."""

    def check(payload):
        secret = client.node.secret_id
        if secret and payload.get("secret") != secret:
            raise ValueError("client RPC requires the node secret")

    def restart(payload):
        check(payload)
        return {
            "tasks": client.alloc_restart(
                payload["alloc_id"], payload.get("task", "")
            )
        }

    def signal(payload):
        check(payload)
        return {
            "tasks": client.alloc_signal(
                payload["alloc_id"],
                payload.get("signal", "SIGINT"),
                payload.get("task", ""),
            )
        }

    def stats(payload):
        check(payload)
        return client.alloc_stats(payload["alloc_id"])

    def host_stats(payload):
        check(payload)
        return client.host_stats()

    def exec_stream(payload, stream):
        """Duplex streaming exec (ref ExecTaskStreaming framing,
        plugins/drivers/proto/driver.proto:72-76,295): stdin frames in,
        stdout/stderr/exit frames out, bridged onto the task's execution
        context by the driver."""
        from .execstream import bridge_exec

        check(payload)
        proc = client.exec_session(
            payload["alloc_id"],
            payload.get("task", ""),
            [str(c) for c in payload.get("cmd", [])],
            tty=bool(payload.get("tty")),
        )
        bridge_exec(proc, stream)

    rpc_server.register("ClientAllocations.Restart", restart)
    rpc_server.register("ClientAllocations.Signal", signal)
    rpc_server.register("ClientAllocations.Stats", stats)
    rpc_server.register("ClientStats.Stats", host_stats)
    rpc_server.register_duplex("ClientAllocations.Exec", exec_stream)


def register_fs_rpc(rpc_server, client):
    """Expose the client's alloc dirs over its RPC listener
    (the server→client reverse path, client_fs_endpoint.go's role)."""

    def alloc_dir(payload) -> str:
        # node-secret auth (the reference authenticates client RPCs with
        # the node's SecretID): the HTTP layer already enforced namespace
        # ACLs and proves it by presenting the secret only servers know
        secret = client.node.secret_id
        if secret and payload.get("secret") != secret:
            raise ValueError("client RPC requires the node secret")
        d = os.path.join(client.data_dir, "allocs", payload["alloc_id"])
        if not os.path.isdir(d):
            raise KeyError(f"alloc dir not found for {payload['alloc_id']}")
        return d

    rpc_server.register(
        "ClientFS.List",
        lambda p: list_dir(alloc_dir(p), p.get("path", "/")),
    )
    rpc_server.register(
        "ClientFS.Cat",
        lambda p: cat(
            alloc_dir(p),
            p.get("path", "/"),
            offset=int(p.get("offset", 0)),
            limit=int(p.get("limit", 1 << 20)),
        ),
    )
    rpc_server.register(
        "ClientFS.Logs",
        lambda p: logs(
            alloc_dir(p),
            p["task"],
            p.get("type", "stdout"),
            offset=int(p.get("offset", 0)),
            origin=p.get("origin", "start"),
            limit=int(p.get("limit", 1 << 20)),
        ),
    )
    def logs_follow(payload):
        """Streaming log follow (ref fs_endpoint.go Logs with follow=true
        over streaming RPC): pushes a frame whenever the logical stream
        grows, for up to ``duration`` seconds (default 60)."""
        import time as time_mod

        base = alloc_dir(payload)
        task = payload["task"]
        kind = payload.get("type", "stdout")
        offset = int(payload.get("offset", 0))
        deadline = time_mod.monotonic() + float(payload.get("duration", 60.0))
        while time_mod.monotonic() < deadline:
            window = logs(base, task, kind, offset=offset, origin="start")
            if window["Data"]:
                offset = window["Offset"]
                yield window
            else:
                time_mod.sleep(0.2)

    rpc_server.register_stream("ClientFS.LogsFollow", logs_follow)
    rpc_server.register(
        "ClientFS.Exec",
        lambda p: exec_in(
            alloc_dir(p),
            p["task"],
            list(p.get("cmd", [])),
            timeout=float(p.get("timeout", 30.0)),
        ),
    )
