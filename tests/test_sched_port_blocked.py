"""BlockedEvals corpus ported from the reference
(nomad/blocked_evals_test.go — cited per test): tracking gates, per-job
dedup with duplicate reaping, class-eligibility unblocks, the
missed-unblock race closures, escaped-class behavior, untrack, per-node
system unblocks, and the failed-eval cooldown requeue. (Quota tests are
not ported: namespace quotas are enterprise-gated in the reference OSS
tree and likewise absent here — PARITY.md divergences.)"""

import time

from nomad_tpu import mock
from nomad_tpu.core.blocked_evals import BlockedEvals
from nomad_tpu.core.broker import EvalBroker
from nomad_tpu.structs.model import EVAL_TRIGGER_MAX_PLANS


def make_pair():
    broker = EvalBroker(nack_timeout=5.0, initial_nack_delay=0.001,
                        subsequent_nack_delay=0.005)
    broker.set_enabled(True)
    blocked = BlockedEvals(broker)
    blocked.set_enabled(True)
    return blocked, broker


def blocked_eval(**kw):
    e = mock.evaluation()
    e.status = "blocked"
    for k, v in kw.items():
        setattr(e, k, v)
    return e


class TestBlockedEvalsPort:
    def test_block_disabled(self):
        # ref TestBlockedEvals_Block_Disabled (blocked_evals_test.go:24)
        blocked, _ = make_pair()
        blocked.set_enabled(False)
        blocked.block(blocked_eval(escaped_computed_class=True))
        stats = blocked.stats()
        assert stats["total_blocked"] == 0
        assert stats["total_escaped"] == 0

    def test_block_same_job_dedups(self):
        # ref TestBlockedEvals_Block_SameJob (:42)
        blocked, _ = make_pair()
        e = blocked_eval()
        e2 = blocked_eval(job_id=e.job_id, namespace=e.namespace)
        blocked.block(e)
        blocked.block(e2)
        stats = blocked.stats()
        assert stats["total_blocked"] == 1
        assert stats["total_escaped"] == 0

    def test_block_prior_unblocks_requeue_immediately(self):
        # ref TestBlockedEvals_Block_PriorUnblocks (:76): an unblock for a
        # class the eval did NOT mark ineligible, landing after its
        # snapshot, means capacity may already exist — requeue, don't block
        blocked, broker = make_pair()
        blocked.unblock("v1:123", 1000)
        blocked.unblock("v1:123", 1001)
        e = blocked_eval(
            class_eligibility={"v1:123": False, "v1:456": False},
            snapshot_index=999,
        )
        blocked.block(e)
        # every seen class is ineligible: the unblocks are irrelevant and
        # the eval stays tracked
        assert blocked.stats()["total_blocked"] == 1
        assert broker.stats()["total_ready"] == 0

    def test_duplicates_reaped_newest_wins(self):
        # ref TestBlockedEvals_GetDuplicates (:98)
        blocked, _ = make_pair()
        e = blocked_eval(create_index=100)
        e2 = blocked_eval(
            job_id=e.job_id, namespace=e.namespace, create_index=101
        )
        e3 = blocked_eval(
            job_id=e.job_id, namespace=e.namespace, create_index=102
        )
        e4 = blocked_eval(
            job_id=e.job_id, namespace=e.namespace, create_index=100
        )
        blocked.block(e)
        blocked.block(e2)
        assert blocked.stats()["total_blocked"] == 1
        # the OLDER e lost to e2
        out = blocked.get_duplicates(0)
        assert [d.id for d in out] == [e.id]

        # a newer block raises a duplicate that a blocking wait observes
        import threading

        def later():
            time.sleep(0.05)
            blocked.block(e3)

        threading.Thread(target=later, daemon=True).start()
        out = blocked.get_duplicates(1.0)
        assert [d.id for d in out] == [e2.id]
        assert blocked.stats()["total_blocked"] == 1

        # an OLDER eval arriving after is itself the duplicate
        blocked.block(e4)
        out = blocked.get_duplicates(0)
        assert [d.id for d in out] == [e4.id]
        assert blocked.stats()["total_blocked"] == 1

    def test_unblock_escaped(self):
        # ref TestBlockedEvals_UnblockEscaped (:161)
        blocked, broker = make_pair()
        blocked.block(blocked_eval(escaped_computed_class=True))
        stats = blocked.stats()
        assert stats["total_blocked"] == 1
        assert stats["total_escaped"] == 1
        blocked.unblock("v1:123", 1000)
        assert broker.stats()["total_ready"] == 1
        stats = blocked.stats()
        assert stats["total_blocked"] == 0
        assert stats["total_escaped"] == 0

    def test_unblock_eligible_class(self):
        # ref TestBlockedEvals_UnblockEligible (:200)
        blocked, broker = make_pair()
        blocked.block(blocked_eval(class_eligibility={"v1:123": True}))
        assert blocked.stats()["total_blocked"] == 1
        blocked.unblock("v1:123", 1000)
        assert broker.stats()["total_ready"] == 1
        assert blocked.stats()["total_blocked"] == 0

    def test_unblock_ineligible_class_stays_blocked(self):
        # ref TestBlockedEvals_UnblockIneligible (:221)
        blocked, broker = make_pair()
        blocked.block(blocked_eval(class_eligibility={"v1:123": False}))
        blocked.unblock("v1:123", 1000)
        assert broker.stats()["total_ready"] == 0
        assert blocked.stats()["total_blocked"] == 1

    def test_unblock_unknown_class_unblocks(self):
        # ref TestBlockedEvals_UnblockUnknown (:258): a class the eval
        # never evaluated could fit it — unblock
        blocked, broker = make_pair()
        blocked.block(
            blocked_eval(
                class_eligibility={"v1:123": True, "v1:456": False}
            )
        )
        blocked.unblock("v1:789", 1000)
        assert broker.stats()["total_ready"] == 1
        assert blocked.stats()["total_blocked"] == 0

    def test_immediate_unblock_escaped(self):
        # ref TestBlockedEvals_Block_ImmediateUnblock_Escaped (:380)
        blocked, broker = make_pair()
        blocked.unblock("v1:123", 1000)
        blocked.block(
            blocked_eval(escaped_computed_class=True, snapshot_index=900)
        )
        assert blocked.stats()["total_blocked"] == 0
        assert broker.stats()["total_ready"] == 1

    def test_immediate_unblock_unseen_class_after_snapshot(self):
        # ref ..._ImmediateUnblock_UnseenClass_After (:407): the unblocked
        # class is absent from the eval's eligibility map (never checked)
        # and landed after its snapshot — requeue immediately
        blocked, broker = make_pair()
        blocked.unblock("v1:123", 1000)
        blocked.block(
            blocked_eval(
                class_eligibility={"v1:456": False}, snapshot_index=900
            )
        )
        assert blocked.stats()["total_blocked"] == 0
        assert broker.stats()["total_ready"] == 1

    def test_immediate_unblock_unseen_class_before_snapshot(self):
        # ref ..._ImmediateUnblock_UnseenClass_Before (:434): the unblock
        # predates the snapshot, so the scheduler already saw that world
        blocked, broker = make_pair()
        blocked.unblock("v1:123", 500)
        blocked.block(
            blocked_eval(
                class_eligibility={"v1:456": False}, snapshot_index=900
            )
        )
        assert blocked.stats()["total_blocked"] == 1
        assert broker.stats()["total_ready"] == 0

    def test_immediate_unblock_seen_ineligible_class(self):
        # ref ..._ImmediateUnblock_SeenClass (:458): the unblocked class
        # was explicitly marked ineligible — stay blocked
        blocked, broker = make_pair()
        blocked.unblock("v1:123", 1000)
        blocked.block(
            blocked_eval(
                class_eligibility={"v1:123": False}, snapshot_index=900
            )
        )
        assert blocked.stats()["total_blocked"] == 1
        assert broker.stats()["total_ready"] == 0

    def test_unblock_failed_cooldown(self):
        # ref TestBlockedEvals_UnblockFailed (:508)
        blocked, broker = make_pair()
        e = blocked_eval(triggered_by=EVAL_TRIGGER_MAX_PLANS)
        blocked.block(e)
        assert blocked.stats()["total_blocked"] == 1
        blocked.unblock_failed()
        assert broker.stats()["total_ready"] == 1
        assert blocked.stats()["total_blocked"] == 0

    def test_untrack(self):
        # ref TestBlockedEvals_Untrack (:550)
        blocked, broker = make_pair()
        e = blocked_eval()
        blocked.block(e)
        assert blocked.stats()["total_blocked"] == 1
        blocked.untrack(e.namespace, e.job_id)
        assert blocked.stats()["total_blocked"] == 0
        assert broker.stats()["total_ready"] == 0

    def test_system_untrack_and_node_unblock(self):
        # ref TestBlockedEvals_SystemUntrack (:624) + UnblockNode (:600)
        blocked, broker = make_pair()
        e = blocked_eval(node_id="node-1")
        blocked.block(e)
        stats = blocked.stats()
        assert stats["total_blocked"] == 1
        assert stats["total_system_blocked"] == 1

        blocked.untrack(e.namespace, e.job_id)
        assert blocked.stats()["total_blocked"] == 0

        e2 = blocked_eval(node_id="node-2")
        blocked.block(e2)
        blocked.unblock_node("node-2", 1000)
        assert blocked.stats()["total_blocked"] == 0
        assert broker.stats()["total_ready"] == 1

    def test_system_disable_flush(self):
        # ref TestBlockedEvals_SystemDisableFlush (:648)
        blocked, broker = make_pair()
        blocked.block(blocked_eval(node_id="node-1"))
        assert blocked.stats()["total_blocked"] == 1
        blocked.set_enabled(False)
        stats = blocked.stats()
        assert stats["total_blocked"] == 0
        assert stats["total_system_blocked"] == 0


class TestDuplicateReapLeaderDuty:
    def test_leader_cancels_superseded_blocked_evals(self):
        """The duplicate loser's raft record is marked cancelled by the
        leader reap loop (ref leader.go:524 reapDupBlockedEvaluations)."""
        from nomad_tpu.agent import DevAgent

        agent = DevAgent(num_clients=0, server_config={"seed": 7})
        agent.start()
        try:
            server = agent.server
            e = blocked_eval(create_index=100)
            e2 = blocked_eval(
                job_id=e.job_id, namespace=e.namespace, create_index=101
            )
            # replicating blocked evals routes them into BlockedEvals
            # via the FSM; the second supersedes the first
            server.update_evals([e])
            server.update_evals([e2])

            deadline = time.monotonic() + 10
            while time.monotonic() < deadline:
                got = server.state.eval_by_id(e.id)
                if got is not None and got.status == "canceled":
                    break
                time.sleep(0.05)
            got = server.state.eval_by_id(e.id)
            assert got.status == "canceled", got.status
            assert "existing blocked" in got.status_description
            # the winner stays blocked
            assert server.state.eval_by_id(e2.id).status == "blocked"
        finally:
            agent.stop()
