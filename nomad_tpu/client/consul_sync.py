"""External-Consul sync adapter (ref command/agent/consul/client.go:212
ServiceClient: the reference registers workload services and checks into
a Consul agent and keeps them in sync on a commit interval).

The framework's PRIMARY service catalog is nomad-native (`/v1/services`,
served straight from cluster state — see client/connect.py and the
PARITY.md divergence note). This adapter is the optional interop bridge:
it extracts the same service entries from state snapshots, diffs them
against what it last wrote, and pushes the delta to an external Consul
agent over its HTTP API —
``PUT /v1/agent/service/register`` with a TTL check,
``PUT /v1/agent/check/update/:id`` for health transitions, and
``PUT /v1/agent/service/deregister/:id`` when a service goes away.
Enabled by a ``consul { address = "http://..." }`` stanza on agents that
host cluster state (dev/server modes)."""

from __future__ import annotations

import json
import logging
import threading
import urllib.error
import urllib.request
from typing import Callable, Optional

logger = logging.getLogger("nomad_tpu.consul")

#: service-ID prefix, mirroring the reference's "_nomad-task-..." ids so
#: an operator can tell nomad-managed registrations apart (ref
#: command/agent/consul/client.go makeAgentServiceID)
ID_PREFIX = "_nomad-task"


def service_entries(snap) -> dict[str, dict]:
    """Extract {service_id: registration} for every service of every
    non-terminal alloc in the snapshot — the same data the native catalog
    serves, keyed for idempotent external sync."""
    out: dict[str, dict] = {}
    for alloc in snap.allocs():
        if alloc.terminal_status():
            continue
        job = alloc.job
        tg = job.lookup_task_group(alloc.task_group) if job else None
        if tg is None:
            continue
        for task in tg.tasks:
            state = alloc.task_states.get(task.name)
            healthy = state is not None and state.state == "running"
            checks = dict(state.check_status) if state is not None else {}
            if healthy and any(v != "passing" for v in checks.values()):
                healthy = False
            for svc in task.services:
                address, port = "", 0
                resources = alloc.allocated_resources
                tr = (
                    resources.tasks.get(task.name)
                    if resources is not None
                    else None
                )
                if tr is not None and svc.port_label:
                    for net in tr.networks:
                        for p in list(net.reserved_ports) + list(
                            net.dynamic_ports
                        ):
                            if p.label == svc.port_label:
                                address, port = net.ip, p.value
                sid = (
                    f"{ID_PREFIX}-{alloc.id}-{task.name}-{svc.name}"
                )
                out[sid] = {
                    "ID": sid,
                    "Name": svc.name,
                    "Tags": list(svc.tags),
                    "Address": address,
                    "Port": int(port),
                    "status": "passing" if healthy else "critical",
                }
    return out


class ConsulSyncer:
    """Periodic diff-sync of the native catalog into an external Consul
    agent. Registrations and deregistrations are only issued for CHANGES
    (the reference's operation batching per commit interval); health
    rides a TTL check per service updated on transitions."""

    def __init__(
        self,
        snapshot_fn: Callable,
        address: str,
        token: str = "",
        interval: float = 5.0,
        timeout: float = 5.0,
    ):
        self.snapshot_fn = snapshot_fn
        self.address = address.rstrip("/")
        self.token = token
        self.interval = interval
        self.timeout = timeout
        #: sid -> last registration payload (incl. health) written
        self._registered: dict[str, dict] = {}
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    # -- consul agent HTTP API ------------------------------------------
    def _req(self, method: str, path: str, body: Optional[dict] = None):
        data = json.dumps(body).encode() if body is not None else None
        req = urllib.request.Request(
            f"{self.address}{path}", data=data, method=method,
            headers={"Content-Type": "application/json"},
        )
        if self.token:
            req.add_header("X-Consul-Token", self.token)
        with urllib.request.urlopen(req, timeout=self.timeout) as resp:
            return resp.read()

    def _register(self, entry: dict):
        payload = {
            "ID": entry["ID"],
            "Name": entry["Name"],
            "Tags": entry["Tags"],
            "Address": entry["Address"],
            "Port": entry["Port"],
            # health rides a TTL check the syncer itself keeps fresh
            # (ref client.go: nomad pushes check state, consul stores it)
            "Check": {
                "CheckID": f"{entry['ID']}-ttl",
                "Name": f"{entry['Name']} liveness (nomad-synced)",
                "TTL": f"{max(int(self.interval * 6), 30)}s",
                "Status": entry["status"],
            },
        }
        self._req("PUT", "/v1/agent/service/register", payload)

    def _update_check(self, sid: str, status: str):
        self._req(
            "PUT",
            f"/v1/agent/check/update/{sid}-ttl",
            {"Status": status},
        )

    def _deregister(self, sid: str):
        self._req("PUT", f"/v1/agent/service/deregister/{sid}")

    # -- sync loop -------------------------------------------------------
    def sync_once(self) -> dict:
        """One diff-sync pass; returns op counts (observability + tests).
        Consul being down is retried next interval — already-registered
        state is kept so recovery converges instead of re-registering
        everything blindly."""
        desired = service_entries(self.snapshot_fn())
        ops = {"register": 0, "update": 0, "deregister": 0}
        try:
            for sid, entry in desired.items():
                prev = self._registered.get(sid)
                if prev is None or any(
                    prev[k] != entry[k]
                    for k in ("Name", "Tags", "Address", "Port")
                ):
                    self._register(entry)
                    ops["register"] += 1
                    self._registered[sid] = dict(entry)
                elif prev["status"] != entry["status"]:
                    self._update_check(sid, entry["status"])
                    ops["update"] += 1
                    self._registered[sid]["status"] = entry["status"]
                else:
                    # refresh the TTL so healthy services don't lapse
                    self._update_check(sid, entry["status"])
            for sid in list(self._registered):
                if sid not in desired:
                    self._deregister(sid)
                    ops["deregister"] += 1
                    del self._registered[sid]
        except (urllib.error.URLError, OSError) as e:
            logger.warning("consul sync failed (will retry): %s", e)
        return ops

    def start(self):
        def loop():
            while not self._stop.wait(self.interval):
                self.sync_once()

        self._thread = threading.Thread(
            target=loop, daemon=True, name="consul-sync"
        )
        self._thread.start()
        return self

    def stop(self):
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=2.0)
        # a clean shutdown removes this agent's registrations, like the
        # reference's Shutdown dereg pass
        for sid in list(self._registered):
            try:
                self._deregister(sid)
            except Exception:
                pass
        self._registered.clear()


def syncer_from_config(config: dict, snapshot_fn) -> Optional[ConsulSyncer]:
    """consul{address, token, sync_interval_s} → a started ConsulSyncer,
    or None when the stanza is absent (the native catalog needs none)."""
    ccfg = (config or {}).get("consul") or {}
    if not ccfg.get("address"):
        return None
    return ConsulSyncer(
        snapshot_fn,
        str(ccfg["address"]),
        token=str(ccfg.get("token", "")),
        interval=float(ccfg.get("sync_interval_s", 5.0)),
    ).start()
