"""Host volumes and ephemeral-disk migration (ref taskrunner/
volume_hook.go, client/allocwatcher/ local+remote migrators)."""

import os
import time

import pytest

import nomad_tpu.mock as mock
from nomad_tpu.agent import ClientAgent, DevAgent, ServerAgent
from nomad_tpu.structs.model import VolumeMount, VolumeRequest


def wait_until(fn, timeout=30.0, msg="condition"):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if fn():
            return
        time.sleep(0.05)
    raise AssertionError(f"timed out waiting for {msg}")


class TestHostVolumes:
    def test_mount_reaches_host_path(self, tmp_path):
        host_dir = tmp_path / "shared-data"
        host_dir.mkdir()
        agent = DevAgent(num_clients=1, server_config={"seed": 71})
        # declare the host volume on the node before registration
        client = agent.clients[0]
        from nomad_tpu.structs.model import ClientHostVolumeConfig

        client.node.host_volumes["data"] = ClientHostVolumeConfig(
            name="data", path=str(host_dir)
        )
        agent.start()
        try:
            job = mock.job()
            tg = job.task_groups[0]
            tg.count = 1
            tg.volumes["vol0"] = VolumeRequest(
                name="vol0", type="host", source="data"
            )
            task = tg.tasks[0]
            task.driver = "raw_exec"
            task.config = {
                "command": "/bin/sh",
                "args": ["-c", "echo from-task > mnt/out.txt"],
            }
            task.volume_mounts = [
                VolumeMount(volume="vol0", destination="mnt")
            ]
            task.resources.networks = []
            agent.server.job_register(job)
            wait_until(
                lambda: (host_dir / "out.txt").exists(),
                msg="task wrote through the volume mount",
            )
            assert (host_dir / "out.txt").read_text().strip() == "from-task"
        finally:
            agent.stop()

    def test_missing_volume_fails_task(self, tmp_path):
        agent = DevAgent(num_clients=1, server_config={"seed": 73})
        agent.start()
        try:
            job = mock.job()
            tg = job.task_groups[0]
            tg.count = 1
            # no tg.volumes declared: the mount must fail the task
            task = tg.tasks[0]
            task.driver = "raw_exec"
            task.config = {"command": "/bin/true"}
            task.volume_mounts = [
                VolumeMount(volume="ghost", destination="mnt")
            ]
            task.resources.networks = []
            # restart policy off so the failure is terminal quickly
            tg.restart_policy.attempts = 0
            tg.restart_policy.mode = "fail"
            agent.server.job_register(job)
            wait_until(
                lambda: any(
                    a.client_status == "failed"
                    for a in agent.server.state.allocs_by_job(
                        job.namespace, job.id
                    )
                ),
                msg="task failed on unknown volume",
            )
        finally:
            agent.stop()


class TestLocalDiskMigration:
    def test_alloc_stop_carries_sticky_data(self):
        """alloc stop → replacement on the same node inherits alloc/ data
        via the local migrator."""
        agent = DevAgent(num_clients=1, server_config={"seed": 79})
        agent.start()
        try:
            job = mock.job()
            tg = job.task_groups[0]
            tg.count = 1
            tg.ephemeral_disk.sticky = True
            tg.ephemeral_disk.migrate = True
            task = tg.tasks[0]
            task.driver = "raw_exec"
            task.config = {
                "command": "/bin/sh",
                "args": [
                    "-c",
                    'if [ ! -f "$NOMAD_ALLOC_DIR/marker" ]; then '
                    'echo generation-one > "$NOMAD_ALLOC_DIR/marker"; fi; '
                    "sleep 60",
                ],
            }
            task.resources.networks = []
            agent.server.job_register(job)
            wait_until(
                lambda: any(
                    a.client_status == "running"
                    for a in agent.server.state.allocs_by_job(
                        job.namespace, job.id
                    )
                ),
                msg="first alloc running",
            )
            (first,) = agent.server.state.allocs_by_job(job.namespace, job.id)
            marker = os.path.join(
                agent.clients[0].data_dir, "allocs", first.id, "alloc", "marker"
            )
            wait_until(lambda: os.path.exists(marker), msg="marker written")

            agent.server.alloc_stop(first.id)
            wait_until(
                lambda: any(
                    a.id != first.id
                    and a.previous_allocation == first.id
                    and a.client_status == "running"
                    for a in agent.server.state.allocs_by_job(
                        job.namespace, job.id
                    )
                ),
                msg="replacement running",
            )
            replacement = next(
                a
                for a in agent.server.state.allocs_by_job(job.namespace, job.id)
                if a.previous_allocation == first.id
            )
            inherited = os.path.join(
                agent.clients[0].data_dir,
                "allocs",
                replacement.id,
                "alloc",
                "marker",
            )
            wait_until(
                lambda: os.path.exists(inherited), msg="data migrated"
            )
            with open(inherited) as f:
                assert f.read().strip() == "generation-one"
        finally:
            agent.stop()


class TestRemoteDiskMigration:
    def test_drain_migrates_disk_across_nodes(self):
        """Two remote nodes; draining the one running the task moves the
        alloc AND its ephemeral disk through the server's ClientFS hop."""
        server = ServerAgent("mig0", config={"seed": 83, "heartbeat_ttl": 5.0})
        server.start(num_workers=2)
        agents = []
        try:
            for _ in range(2):
                a = ClientAgent([server.address])
                a.start()
                agents.append(a)
            wait_until(
                lambda: all(
                    server.server.state.node_by_id(a.node.id) is not None
                    for a in agents
                ),
                msg="both nodes registered",
            )
            job = mock.job()
            tg = job.task_groups[0]
            tg.count = 1
            tg.ephemeral_disk.migrate = True
            task = tg.tasks[0]
            task.driver = "raw_exec"
            task.config = {
                "command": "/bin/sh",
                "args": [
                    "-c",
                    'if [ ! -f "$NOMAD_ALLOC_DIR/marker" ]; then '
                    'echo first-node > "$NOMAD_ALLOC_DIR/marker"; fi; '
                    "sleep 120",
                ],
            }
            task.resources.networks = []
            server.server.job_register(job)
            wait_until(
                lambda: any(
                    a.client_status == "running"
                    for a in server.server.state.allocs_by_job(
                        job.namespace, job.id
                    )
                ),
                msg="first alloc running",
            )
            (first,) = server.server.state.allocs_by_job(job.namespace, job.id)
            origin = next(
                a for a in agents if first.node_id == a.node.id
            )
            dest = next(a for a in agents if a is not origin)
            marker = os.path.join(
                origin.client.data_dir, "allocs", first.id, "alloc", "marker"
            )
            wait_until(lambda: os.path.exists(marker), msg="marker written")

            server.server.node_drain(first.node_id, drain=True)
            wait_until(
                lambda: any(
                    a.id != first.id and a.client_status == "running"
                    for a in server.server.state.allocs_by_job(
                        job.namespace, job.id
                    )
                ),
                timeout=60,
                msg="replacement running on the other node",
            )
            replacement = next(
                a
                for a in server.server.state.allocs_by_job(
                    job.namespace, job.id
                )
                if a.id != first.id and a.client_status == "running"
            )
            assert replacement.node_id == dest.node.id
            inherited = os.path.join(
                dest.client.data_dir,
                "allocs",
                replacement.id,
                "alloc",
                "marker",
            )
            wait_until(
                lambda: os.path.exists(inherited),
                timeout=30,
                msg="disk migrated across nodes",
            )
            with open(inherited) as f:
                assert f.read().strip() == "first-node"
        finally:
            for a in agents:
                a.stop()
            server.stop()
