"""The batched placement kernel: one jitted lax.scan that plans every pending
allocation against every candidate node.

Replicates the oracle's per-placement semantics (stack.go:104-162) as dense
array ops per scan step:

- rotating candidate window: the reference's StaticIterator keeps a global
  offset that round-robins across Selects (feasible.go:59-86); here the node
  axis is pre-permuted by the seeded shuffle and the window is a roll+cumsum.
- limit iterator: first ``limit`` feasible+fitting nodes are candidates,
  deferring up to 3 options scoring ≤ 0 while better options remain
  (select.go:35-67).
- scoring: binpack = clamp(20 − 10^freeCpu − 10^freeMem, 0, 18)/18
  (funcs.go:154-188), job anti-affinity −(collisions+1)/count (rank.go:509),
  static node-affinity plane (rank.go:619-646), spread boost
  (spread.go:110-227); final score averages only the planes that fired
  (rank.go:678-692).
- sequential coupling: placements subtract capacity and bump collision and
  spread counts inside the scan carry, preserving the reference's
  one-at-a-time ProposedAllocs semantics.

Everything is static-shaped; N and A are padded by the caller.
"""

from __future__ import annotations

import functools
import time
from typing import NamedTuple

import jax
import jax.numpy as jnp

from . import enable_compile_cache
from ..debug import devprof as _devprof
from ..testing import faults as _faults

# must precede every jit compile; this module is the jax entry point for
# the whole scheduler tier (batch_sched/drain/system_sched import it)
enable_compile_cache()

MAX_SKIP = 3  # ref stack.go:17
NEG_INF = -1e30


class BatchArgs(NamedTuple):
    """Static per-batch planes (see columnar.py for construction).

    The batch may span several evaluations (the eval-broker drain,
    worker.go:105-276 / SURVEY §2.3): each group belongs to one eval via
    ``group_eval``, and every eval has its own shuffled node ring (``perm``
    row), ring size (``ring`` — the count of its datacenter-eligible nodes,
    which occupy the front of its perm row) and rotating cursor
    (``BatchState.offset`` entry). Capacity/usage are shared, so placements
    sequence across evals exactly like the serialized plan applier would."""

    capacity: jax.Array  # i32[N,3]
    usable: jax.Array  # f32[N,2]
    feasible: jax.Array  # bool[G,N]
    affinity: jax.Array  # f32[G,N]
    affinity_present: jax.Array  # bool[G,N]
    group_count: jax.Array  # i32[G]
    group_eval: jax.Array  # i32[G] owning eval per group
    # spread planes
    node_value: jax.Array  # i32[G,N] (-1 = missing)
    spread_desired: jax.Array  # f32[G,V] (-1 = absent)
    spread_implicit: jax.Array  # f32[G] (-1 = none)
    spread_weight_frac: jax.Array  # f32[G] (0 = no spread)
    spread_even: jax.Array  # bool[G]
    spread_active: jax.Array  # bool[G]
    perm: jax.Array  # i32[E,N] node id at shuffled ring position p, per eval
    ring: jax.Array  # i32[E] ring size (eligible-node count) per eval
    # per-alloc
    demands: jax.Array  # i32[A,3]
    groups: jax.Array  # i32[A]
    limits: jax.Array  # i32[A]
    valid: jax.Array  # bool[A]


class BatchState(NamedTuple):
    used: jax.Array  # i32[N,3]
    collisions: jax.Array  # i32[G,N]
    spread_counts: jax.Array  # i32[G,V]
    spread_present: jax.Array  # bool[G,V]
    offset: jax.Array  # i32[E] ring cursor per eval


#: log2(10) and its Veltkamp split: _HI carries the top 12 mantissa bits
#: (so products with 12-bit x-halves are exact in float32), _LO the rest
_LOG2_10 = 3.3219280948873623
_LOG2_10_HI = 3.322265625  # log2(10) rounded to 12 mantissa bits
_LOG2_10_LO = _LOG2_10 - _LOG2_10_HI


def _pow10(x):
    """Bit-stable 10^x in float32: 2^(x·log2 10) with the exponent split
    into an integer part (applied by exact exponent-field bit assembly)
    and a fractional part evaluated by a FIXED Horner polynomial
    (Cephes exp2f coefficients on [-0.5, 0.5]).

    Why not ``jnp.power``: XLA lowers transcendentals differently per
    compilation context (a fusion cluster that vectorizes gets the
    packet polynomial, one that doesn't gets scalar libm), and those
    approximations differ in final ulps. The sharded and unsharded
    planner programs are DIFFERENT compilations of the same math, so a
    transcendental in the score path makes "sharded placements are
    bit-identical to unsharded" unenforceable — observed as parity 0.63
    at 8K nodes × 40K allocs when thousands of near-identical nodes sit
    within 1 ulp of each other. Everything here is +,·,comparisons and
    integer/bit ops — all correctly rounded or exact under IEEE-754, so
    every compilation (any sharding, any fusion, any vector width)
    produces the same bits. Requires --xla_allow_excess_precision=false
    (tpu/__init__) so FMA contraction cannot reassociate the Horner
    chain differently per program."""
    # range reduction y = x·log2(10) in double-float: a single rounded
    # product loses ~|y|·eps which lands straight in the fractional part
    # (observed 4e-6 relative vs pow's 6e-8). Veltkamp-split the product
    # instead — 12-bit halves multiply EXACTLY in float32 — and carry
    # the low word into f. Every op below is IEEE-exact/correctly
    # rounded, so the reduction is bit-stable like the rest.
    x = jnp.clip(x, -45.2, 45.2)  # 10^±45 spans all float32 normals
    c = jnp.float32(4097.0) * x  # 2^12 + 1: Veltkamp split constant
    x_hi = c - (c - x)  # top ~12 mantissa bits of x
    x_lo = x - x_hi  # exact residual
    y_hi = x_hi * jnp.float32(_LOG2_10_HI)  # 12b × 12b: exact product
    y_lo = x_hi * jnp.float32(_LOG2_10_LO) + x_lo * jnp.float32(_LOG2_10)
    n = jnp.round(y_hi + y_lo)
    # y_hi - n is exact (Sterbenz: same binade once |y_hi - n| ≤ 0.5)
    f = (y_hi - n) + y_lo
    # 2^f on [-0.5, 0.5]: Cephes exp2f minimax polynomial
    p = jnp.float32(1.535336188319500e-4)
    p = p * f + jnp.float32(1.339887440266574e-3)
    p = p * f + jnp.float32(9.618437357674640e-3)
    p = p * f + jnp.float32(5.550332471162809e-2)
    p = p * f + jnp.float32(2.402264791363012e-1)
    p = p * f + jnp.float32(6.931472028550421e-1)
    p = p * f + jnp.float32(1.0)
    # 2^n via exponent-field assembly (exact); n is clamped into the
    # normal range and the residual scale applied in two exact steps so
    # deep underflow flushes to 0 instead of wrapping the bit field
    n_i = n.astype(jnp.int32)
    n1 = jnp.clip(n_i, -126, 127)
    n2 = jnp.clip(n_i - n1, -126, 127)
    def two_pow(e):
        return jax.lax.bitcast_convert_type(
            ((e + 127) << 23).astype(jnp.int32), jnp.float32
        )
    return p * two_pow(n1) * two_pow(n2)


def _binpack(free_cpu, free_mem):
    """Normalized ScoreFit: clip(20 − 10^fcpu − 10^fmem, [0,18]) / 18
    (ref funcs.go:154-191, rank.go:13). Single definition — the run/sweep
    planners' closed-form trajectories must match the step formula
    exactly, and ``_pow10`` keeps the only transcendental in the score
    path bit-stable across compilations (the mesh parity contract)."""
    total = _pow10(free_cpu) + _pow10(free_mem)
    return jnp.clip(20.0 - total, 0.0, 18.0) / 18.0


def _class_boosts(counts, present, desired, implicit, weight_frac, even_flag, active_flag):
    """Spread boost per value class, plus the missing-value pseudo-class at
    index V (spread.go:110-227: target mode boosts (desired−used)/desired
    weighted; even mode boosts below-min classes). Single definition — the
    per-placement scorer indexes it per node and the run planner consumes it
    per class, and both must agree exactly."""
    used_count = counts.astype(jnp.float32) + 1.0
    desired_eff = jnp.where(desired >= 0.0, desired, implicit)
    target = jnp.where(
        desired_eff >= 0.0,
        (desired_eff - used_count) / jnp.maximum(desired_eff, 1e-9) * weight_frac,
        -1.0,
    )

    counts_f = counts.astype(jnp.float32)
    big = jnp.float32(2**30)
    any_present = jnp.any(present)
    min_count = jnp.where(any_present, jnp.min(jnp.where(present, counts_f, big)), 0.0)
    max_count = jnp.where(any_present, jnp.max(jnp.where(present, counts_f, -big)), 0.0)
    delta_boost = jnp.where(
        min_count == 0.0, -1.0, (min_count - counts_f) / jnp.maximum(min_count, 1e-9)
    )
    even = jnp.where(
        counts_f != min_count,
        delta_boost,
        jnp.where(
            min_count == max_count,
            -1.0,
            jnp.where(
                min_count == 0.0,
                1.0,
                (max_count - min_count) / jnp.maximum(min_count, 1e-9),
            ),
        ),
    )
    even = jnp.where(any_present, even, 0.0)

    per_class = jnp.where(even_flag, even, target)
    boosts = jnp.concatenate([per_class, jnp.array([-1.0], dtype=jnp.float32)])
    return jnp.where(active_flag, boosts, jnp.zeros_like(boosts))


def _scores(args: BatchArgs, state: BatchState, g, demand):
    """Final score per node for one placement (mean over fired planes)."""
    used = state.used
    util = used + demand[None, :]

    free_cpu = 1.0 - util[:, 0].astype(jnp.float32) / args.usable[:, 0]
    free_mem = 1.0 - util[:, 1].astype(jnp.float32) / args.usable[:, 1]
    binpack = _binpack(free_cpu, free_mem)

    coll = state.collisions[g]
    anti_present = coll > 0
    anti = jnp.where(
        anti_present,
        -(coll.astype(jnp.float32) + 1.0) / args.group_count[g].astype(jnp.float32),
        0.0,
    )

    aff = args.affinity[g]
    aff_present = args.affinity_present[g]

    # spread plane (spread.go:110-227): per-class boosts indexed per node
    v = args.node_value[g]
    boosts = _class_boosts(
        state.spread_counts[g],
        state.spread_present[g],
        args.spread_desired[g],
        args.spread_implicit[g],
        args.spread_weight_frac[g],
        args.spread_even[g],
        args.spread_active[g],
    )
    V = args.spread_desired.shape[1]
    cls = jnp.where(v >= 0, v, V)
    spread_score = boosts[cls]
    spread_fired = args.spread_active[g] & (spread_score != 0.0)
    spread_score = jnp.where(spread_fired, spread_score, 0.0)

    num = (
        1.0
        + anti_present.astype(jnp.float32)
        + aff_present.astype(jnp.float32)
        + spread_fired.astype(jnp.float32)
    )
    final = (
        binpack
        + jnp.where(anti_present, anti, 0.0)
        + jnp.where(aff_present, aff, 0.0)
        + spread_score
    ) / num
    return final


def _rot_incl(x: jax.Array, offset, total, positions):
    """Inclusive count of ``x`` along rotation order up to each position:
    the ring starts at ``offset`` (two-segment prefix-sum trick; avoids a
    dynamic roll and keeps the ring size at the real node count)."""
    xc = jnp.cumsum(x.astype(jnp.int32))
    xex = xc - x.astype(jnp.int32)
    x_off = xex[offset]
    return jnp.where(positions >= offset, xc - x_off, total - x_off + xc)


def _step(n_real: int, args: BatchArgs, state: BatchState, alloc):
    demand, g, limit, valid = alloc
    n_pad = args.capacity.shape[0]
    positions = jnp.arange(n_pad)
    e = args.group_eval[g]
    ring_size = args.ring[e]
    perm = args.perm[e]
    in_ring = positions < ring_size

    fit_nodes = args.feasible[g] & jnp.all(
        state.used + demand[None, :] <= args.capacity, axis=1
    )
    final = _scores(args, state, g, demand)

    # permuted (shuffled) coordinates; ring positions are [0, ring_size)
    fit_p = fit_nodes[perm] & in_ring
    score_p = final[perm]
    offset = state.offset[e]

    fit_total = jnp.sum(fit_p.astype(jnp.int32))

    # limit-iterator window (select.go:35-67): defer up to 3 options ≤ 0
    nonpos = fit_p & (score_p <= 0.0)
    nonpos_total = jnp.sum(nonpos.astype(jnp.int32))
    nonpos_incl = _rot_incl(nonpos, offset, nonpos_total, positions)
    skipped = nonpos & (nonpos_incl <= MAX_SKIP)

    kept = fit_p & ~skipped
    kept_total = jnp.sum(kept.astype(jnp.int32))
    ret_incl = _rot_incl(kept, offset, kept_total, positions)
    returned = kept & (ret_incl <= limit)
    n_returned = jnp.sum(returned.astype(jnp.int32))

    # replay deferred options only when the ring exhausted before limit
    need = jnp.maximum(limit - n_returned, 0)
    skip_total = jnp.sum(skipped.astype(jnp.int32))
    skip_incl = _rot_incl(skipped, offset, skip_total, positions)
    replay = skipped & (skip_incl <= need)
    candidates = returned | replay

    # rotation rank of every ring position (0 = the iterator's cursor)
    rot_rank = jnp.where(positions >= offset, positions - offset, ring_size - offset + positions)

    found = jnp.any(candidates)
    max_score = jnp.max(jnp.where(candidates, score_p, NEG_INF))
    # first-strict-max in the order MaxScoreIterator sees options: returned
    # options in rotation order, then any replayed (deferred) options
    # (select.go:59-66 replays skipped nodes only after the source exhausts)
    tie = candidates & (score_p == max_score)
    visit_order = rot_rank + jnp.where(replay, n_pad, 0)
    best_p = jnp.argmin(jnp.where(tie, visit_order, 2**30))
    best_node = perm[best_p]

    # source positions consumed (StaticIterator.seen accounting): all ring
    # positions up to and including the limit-th returned option
    last_ret_rank = jnp.max(jnp.where(returned, rot_rank, -1))
    consumed = jnp.where(n_returned >= limit, last_ret_rank + 1, ring_size)

    place = found & valid
    best_node = jnp.where(place, best_node, -1)

    # carry updates
    used = jnp.where(
        place,
        state.used.at[best_node].add(demand),
        state.used,
    )
    collisions = jnp.where(
        place,
        state.collisions.at[g, best_node].add(1),
        state.collisions,
    )
    v = args.node_value[g][jnp.maximum(best_node, 0)]
    do_spread = place & args.spread_active[g] & (v >= 0)
    safe_v = jnp.maximum(v, 0)
    spread_counts = jnp.where(
        do_spread,
        state.spread_counts.at[g, safe_v].add(1),
        state.spread_counts,
    )
    spread_present = jnp.where(
        do_spread,
        state.spread_present.at[g, safe_v].set(True),
        state.spread_present,
    )
    new_offset = jnp.where(
        valid,
        state.offset.at[e].set((offset + consumed) % jnp.maximum(ring_size, 1)),
        state.offset,
    )

    new_state = BatchState(used, collisions, spread_counts, spread_present, new_offset)
    return new_state, best_node


@functools.partial(jax.jit, static_argnums=(2,))
def _plan_batch_jit(args: BatchArgs, init: BatchState, n_real: int):
    def step(state, alloc):
        return _step(n_real, args, state, alloc)

    final_state, placements = jax.lax.scan(
        step,
        init,
        (args.demands, args.groups, args.limits, args.valid),
    )
    return final_state, placements


def plan_batch(args: BatchArgs, init: BatchState, n_real: int,
               n_valid: int = None):
    """Run the placement scan; returns (final_state, node index per alloc
    or -1). The ``tpu.kernel`` fault point models device errors / NaN
    trips (jax debug-nans raises at dispatch) — the scheduler degrades to
    the exact-np host oracle when this raises.

    ``n_valid`` (optional) is the host-known count of REAL alloc lanes:
    the devprof round counter then records rounds-per-placement against
    the placements actually asked for instead of the padded scan length
    (callers that pad — drain/batch_sched — pass it; a caller whose
    lanes are all valid can omit it)."""
    _faults.fault_point("tpu.kernel")
    A = int(args.demands.shape[0])
    key = (
        f"E{args.perm.shape[0]}G{args.feasible.shape[0]}"
        f"A{A}N{args.capacity.shape[0]}"
    )
    out, sharded = _dispatch(
        "exact", _plan_batch_jit, (args, init, n_real), key
    )
    # the exact scan IS the sequential fill loop: one scan step per
    # alloc lane, each step a full-ring score + argmax — under a mesh,
    # one cross-shard collective round per lane (the ROADMAP item 2
    # hypothesis, measured instead of asserted)
    _devprof.count_rounds(
        "exact", A, A if n_valid is None else int(n_valid), sharded
    )
    return out


# ---------------------------------------------------------------------------
# deterministic compile flavor (the mesh bit-parity contract)
# ---------------------------------------------------------------------------
#
# The sharded==unsharded placement-equality contract compares two DIFFERENT
# XLA compilations of the same jaxpr. XLA's fusion pass rematerializes
# float subexpressions per consumer with context-dependent codegen, so the
# two programs (and even two differently-fused unsharded programs) can
# disagree on ``score`` by 1 ulp at a handful of lanes — and in a kernel
# whose tie-breaks hinge on exact score equality among hundreds of
# identical nodes, one flipped lane cascades into diverging fill runs
# (observed: parity 0.63 at 8K nodes × 40K allocs with byte-identical
# kernel inputs; neither --xla_cpu_enable_fast_math=false,
# --xla_allow_excess_precision=false, nor lax.optimization_barrier closes
# it — the remat happens inside the fusion pass). With fusion disabled,
# every HLO op is materialized exactly once and both compilations produce
# identical bits (verified at the failing scale).
#
# Production dispatch keeps the FUSED fast programs — placement quality
# there is pinned by the ≥99% host-oracle parity budget, which 1-ulp
# score noise cannot dent. The deterministic flavor exists for contracts
# that assert bitwise equality: the multichip parity suite, the scored
# multichip bench, and bench.py's sharded-vs-unsharded oracle check all
# dispatch through it (env NOMAD_TPU_DETERMINISTIC=1).

#: compiler options for the deterministic flavor: backend optimization
#: level 0 skips the fusion/remat machinery, so every float is
#: materialized exactly once and both compilations produce the same bits
#: (verified at the failing scale). Chosen over xla_disable_hlo_passes
#: ("fusion") because per-compile env_option_overrides only accept
#: SINGULAR proto fields, and that one is repeated.
DET_COMPILER_OPTIONS = {"xla_backend_optimization_level": 0}

# nta: ignore[unbounded-cache] WHY: keyed by (planner, static args, input
# aval+sharding signature) — the same bucketed shape ladder that bounds
# the jit caches bounds this one
_DET_EXECUTABLES: dict = {}


def deterministic_mode() -> bool:
    """Whether planner dispatch routes through the fusion-free
    deterministic executables (env NOMAD_TPU_DETERMINISTIC=1)."""
    import os

    return os.environ.get("NOMAD_TPU_DETERMINISTIC", "0") == "1"


def deterministic_scope():
    """Context manager: enable deterministic dispatch for the body and
    restore the operator's prior flag verbatim on exit (a bare pop would
    flip a NOMAD_TPU_DETERMINISTIC=1 bench run back to the fast flavor
    mid-artifact). The ONE definition of the env dance — bench.py's
    sharded parity pin and the multichip scored bench both enter here."""
    import contextlib
    import os

    @contextlib.contextmanager
    def scope():
        prior = os.environ.get("NOMAD_TPU_DETERMINISTIC")
        os.environ["NOMAD_TPU_DETERMINISTIC"] = "1"
        try:
            yield
        finally:
            if prior is None:
                os.environ.pop("NOMAD_TPU_DETERMINISTIC", None)
            else:
                os.environ["NOMAD_TPU_DETERMINISTIC"] = prior

    return scope()


def _det_key(name, call_args):
    """The deterministic-executable cache key for a call signature —
    shapes, dtypes AND shardings, so a sharded call never reuses an
    unsharded executable. Shared by ``_det_call`` and the devprof
    compile ledger (which fetches the freshly-minted executable by the
    same key to census it)."""

    def leaf_key(x):
        sharding = getattr(x, "sharding", None)
        shape = getattr(x, "shape", ())
        dtype = getattr(x, "dtype", type(x).__name__)
        return (tuple(shape), str(dtype), repr(sharding))

    statics = tuple(a for a in call_args if isinstance(a, (int, bool)))
    dynamic = tuple(a for a in call_args if not isinstance(a, (int, bool)))
    return (
        name,
        statics,
        tuple(
            leaf_key(x)
            for x in jax.tree_util.tree_leaves(dynamic)
        ),
    ), dynamic


def _det_call(jitfn, name, *call_args):
    """Dispatch ``jitfn(*call_args)`` through an AOT executable compiled
    with :data:`DET_COMPILER_OPTIONS`, cached per input signature (see
    :func:`_det_key`). Python ints/bools in ``call_args`` are the jits'
    static arguments: they select the lowering and are NOT passed to
    the compiled executable."""
    key, dynamic = _det_key(name, call_args)
    exe = _DET_EXECUTABLES.get(key)
    if exe is None:
        exe = jitfn.lower(*call_args).compile(
            compiler_options=DET_COMPILER_OPTIONS
        )
        _DET_EXECUTABLES[key] = exe
    return exe(*dynamic)


def _jit_cache_size(jitfn) -> int:
    try:
        return jitfn._cache_size()
    except Exception:
        return -1  # detector degrades (no compile events), never breaks


def _dispatch(planner: str, jitfn, call_args: tuple, shape_key: str,
              allow_det: bool = True):
    """One planner dispatch through the devprof compile ledger: route to
    the deterministic or fast flavor, detect a compile via the per-fn
    cache delta, and hand the executable to devprof for cost analysis +
    the HLO collective census. For the fast flavor the analysis hook is
    ``jitfn.lower(args).compile()`` — AFTER the triggering call that is
    a C++ dispatch-cache hit returning the SAME executable, never a
    second XLA compile. Returns ``(result, sharded)``; with devprof
    disabled this is exactly the old two-branch dispatch.
    ``allow_det=False`` pins the fast flavor (verify_rows: its boolean
    verdicts are not part of the bit-parity contract, and a det AOT
    compile inside a parity window would be pure waste)."""
    det = allow_det and deterministic_mode()
    if not _devprof.enabled():
        if det:
            return _det_call(jitfn, planner, *call_args), False
        return jitfn(*call_args), False
    flavor = "det" if det else "fast"
    sharded = _devprof.tree_sharded(call_args)
    if det:
        # detect via THIS dispatch's own key, not the global cache
        # length — a concurrent det dispatch of another planner growing
        # the dict must not mint a phantom compile entry here
        dkey = _det_key(planner, call_args)[0]
        was_missing = dkey not in _DET_EXECUTABLES
        t0 = time.monotonic()
        out = _det_call(jitfn, planner, *call_args)
        if was_missing and dkey in _DET_EXECUTABLES:
            _devprof.record_compile(
                planner, shape_key, sharded, flavor,
                time.monotonic() - t0,
                compiled=_DET_EXECUTABLES.get(dkey),
            )
    else:
        before = _jit_cache_size(jitfn)
        t0 = time.monotonic()
        out = jitfn(*call_args)
        after = _jit_cache_size(jitfn)
        if before >= 0 and after > before:
            _devprof.record_compile(
                planner, shape_key, sharded, flavor,
                time.monotonic() - t0,
                compile_fn=lambda: jitfn.lower(*call_args).compile(),
            )
    _devprof.record_dispatch(planner, shape_key, sharded, flavor)
    return out, sharded


def compile_cache_size() -> int:
    """Total compiled-program cache entries across the jitted planners —
    the recompile detector shared by bench.py outlier splits and the
    trace plane's flagged-span hook (a drain dispatch whose delta is
    nonzero paid an XLA trace+compile inside its window: the
    51200-vs-50176 off-bucket class, made visible). Sharded programs
    live in the SAME caches (a sharded input layout is just another
    entry), so the detector covers mesh dispatches for free. -1 when
    the internals move (detector degrades, never breaks dispatch)."""
    try:
        # the wavefront and paged planners (tpu/wavefront.py,
        # tpu/paging.py) register themselves into PLANNER_JITS on
        # import; pull them in lazily so this census stays complete
        # without a kernel->satellite top-level import cycle
        from . import paging, wavefront  # noqa: F401

        return sum(fn._cache_size() for fn in PLANNER_JITS.values())
    except Exception:
        return -1


# ---------------------------------------------------------------------------
# Rotation-parallel windowed planner
# ---------------------------------------------------------------------------
#
# When the candidate limit L is smaller than the ring (no affinities/spreads;
# stack.go:74-87), consecutive Selects consume *disjoint* windows of the
# rotating node ring, so every full ring pass places ~⌈feasible/L⌉ allocations
# whose decisions cannot interact (each node appears in at most one window).
# One "mega-step" therefore scores the ring once and resolves all of that
# pass's placements with a segmented argmax — turning 50K sequential Selects
# into ~A·L/N ring passes. Semantics match the sequential oracle except when
# a placement flips a node to infeasible mid-pass (window boundaries shift);
# with allocs far smaller than nodes this is rare, which is what the ≥99%
# (not 100%) parity budget is for.


# ---------------------------------------------------------------------------
# Run-based full-ring planner (spread/affinity fast path, limit=∞)
# ---------------------------------------------------------------------------
#
# With affinities or spreads the reference sets the candidate limit to ∞
# (stack.go:148-150): every Select is a global argmax over the full ring, and
# a naive scan needs one sequential step per placement. But the score
# dynamics collapse the sequence into *runs* that one step can resolve:
#
# - FILL runs: ScoreFit rewards utilization (funcs.go:154-188 — a fuller
#   node scores higher), so once a node wins and keeps rising it wins again
#   and again until it no longer fits. Its whole score trajectory under j
#   further self-placements is a closed-form function of j (binpack walks
#   the utilization curve, anti-affinity adds −1/count per hit, its class's
#   target-spread boost drops wf/desired per hit), while every OTHER node's
#   score is frozen (same-class nodes only fall). So: compute the
#   trajectory, compare against the frozen runner-up (an upper bound on the
#   competition — conservative, so a run can only end early, never late),
#   and place the whole run in one step.
#
# - SWEEP tie-runs: real clusters have tiers of identical nodes; fresh
#   identical nodes tie exactly and the sequential process consumes them in
#   rotation order, with each placement dropping that node far below the tie
#   (the plane-count denominator flips at the first collision). A placement
#   in class v also lowers every *tied* class-v key by wf/desired_v, so the
#   exact merged order of the whole tied set is given by keys
#   k_i = score − t_i·δ_v/num_i (t_i = rotation rank among same-class ties).
#   All accepted ties are placed in one step, in exactly that order; a
#   guard (post-placement score must stay ≤ the smallest accepted key)
#   rejects lanes that would be re-picked mid-sweep and defers them to the
#   next step's fill run.
#
# Both mechanisms are conservative: each step places a prefix of the true
# sequential order, and the next step re-scores the full ring, so splitting
# a run never changes the result — only even-mode spread (whose boost
# couples classes through min/max counts) disables runs and pays one step
# per placement. Divergence from the oracle is confined to the fired-flip
# corner (spread score crossing exactly 0 changes the denominator) and the
# candidate-local deferral tie-break (select.go:35-67), both covered by the
# ≥99% parity budget.


class RunArgs(NamedTuple):
    """Node-axis arrays are in ROTATION (shuffled) order; ``perm`` maps a
    position back to the node id the caller knows."""

    capacity: jax.Array  # i32[N,3]
    usable: jax.Array  # f32[N,2]
    feasible: jax.Array  # bool[N]
    affinity: jax.Array  # f32[N]
    affinity_present: jax.Array  # bool[N]
    group_count: jax.Array  # i32 scalar
    node_value: jax.Array  # i32[N] (-1 = missing)
    spread_desired: jax.Array  # f32[V] (-1 = absent)
    spread_implicit: jax.Array  # f32 scalar (-1 = none)
    spread_weight_frac: jax.Array  # f32 scalar
    spread_even: jax.Array  # bool scalar
    spread_active: jax.Array  # bool scalar
    perm: jax.Array  # i32[N]
    demand: jax.Array  # i32[3]
    n_allocs: jax.Array  # i32 scalar


def _run_class_boosts(args: RunArgs, counts, present):
    """Run-planner view of the shared spread-boost formula."""
    return _class_boosts(
        counts,
        present,
        args.spread_desired,
        args.spread_implicit,
        args.spread_weight_frac,
        args.spread_even,
        args.spread_active,
    )


RUNCAP = 512  # max placements resolved by a single fill run


def plan_batch_runs(
    args: RunArgs,
    init,
    a_pad: int,
    even_mode: bool = False,
):
    """Place ``n_allocs`` identical asks under full-ring (limit=∞) selection;
    returns node index per alloc slot (length ``a_pad``, -1 = unplaced).

    The jit additionally returns its while-loop trip count — the number
    of sequential device rounds (each one full-ring score + reduction;
    under a mesh, one cross-shard collective round). The wrapper feeds
    it to the devprof round counter as a LAZY device scalar (recording
    never syncs) and hands callers only the placements, unchanged."""
    _faults.fault_point("tpu.kernel")
    key = f"N{args.capacity.shape[0]}A{a_pad}"
    out, sharded = _dispatch(
        "runs", _plan_batch_runs_jit, (args, init, a_pad, even_mode), key
    )
    placements, rounds = out
    if _devprof.enabled():
        _devprof.count_rounds(
            "runs", rounds, int(args.n_allocs), sharded
        )
    return placements


@functools.partial(jax.jit, static_argnums=(2, 3))
def _plan_batch_runs_jit(
    args: RunArgs,
    init,
    a_pad: int,
    even_mode: bool = False,
):
    n_pad = args.capacity.shape[0]
    used0, coll0, counts0, present0 = init
    V = counts0.shape[0]
    count_f = args.group_count.astype(jnp.float32)
    pos = jnp.arange(n_pad)
    cls = jnp.where(args.node_value >= 0, args.node_value, V)
    onehot_cls = jax.nn.one_hot(cls, V + 1, dtype=jnp.float32)  # [N, V+1]
    aff_term = jnp.where(args.affinity_present, args.affinity, 0.0)
    aff_f = args.affinity_present.astype(jnp.float32)
    # per-placement key decay of a node's class under target spread
    desired_eff = jnp.where(
        args.spread_desired >= 0.0, args.spread_desired, args.spread_implicit
    )
    delta_v = jnp.where(
        desired_eff >= 0.0,
        args.spread_weight_frac / jnp.maximum(desired_eff, 1e-9),
        0.0,
    )
    delta_v = jnp.where(args.spread_active & ~args.spread_even, delta_v, 0.0)
    delta_node = jnp.concatenate([delta_v, jnp.zeros(1, dtype=jnp.float32)])[cls]
    demand_f2 = args.demand[:2].astype(jnp.float32)

    def _score_at(used, coll, boosts, extra_d, extra_c, extra_k):
        """Score vector with ``extra_d`` demands / ``extra_c`` collisions on
        every node and ``extra_k`` additional own-class placements."""
        util = (used + (1 + extra_d) * args.demand[None, :])[:, :2].astype(jnp.float32)
        free = 1.0 - util / args.usable
        binpack = _binpack(free[:, 0], free[:, 1])
        coll_e = coll + extra_c
        ap = coll_e > 0
        an = jnp.where(ap, -(coll_e.astype(jnp.float32) + 1.0) / count_f, 0.0)
        sp = (onehot_cls @ boosts) - extra_k * delta_node
        fired = args.spread_active & (sp != 0.0)
        num = 1.0 + ap.astype(jnp.float32) + aff_f + fired.astype(jnp.float32)
        score = (binpack + an + aff_term + jnp.where(fired, sp, 0.0)) / num
        return score, num

    def body(state):
        used, coll, counts, present, placed, placements, _, rounds = state

        fit = args.feasible & jnp.all(
            used + args.demand[None, :] <= args.capacity, axis=1
        )
        boosts = _run_class_boosts(args, counts, present)
        score, num = _score_at(used, coll, boosts, 0, 0, 0)
        avail = fit
        any_avail = jnp.any(avail)
        max_score = jnp.max(jnp.where(avail, score, NEG_INF))

        # deferral of the first 3 nonpositive options in rotation order
        # (select.go:35-67); only affects tie-breaks when everything is ≤ 0
        posf = pos.astype(jnp.float32)
        nonpos = avail & (score <= 0.0)
        m1 = jnp.min(jnp.where(nonpos, posf, jnp.inf))
        m2 = jnp.min(jnp.where(nonpos & (posf > m1), posf, jnp.inf))
        m3 = jnp.min(jnp.where(nonpos & (posf > m2), posf, jnp.inf))
        deferred = nonpos & (posf <= m3)
        visit = pos + jnp.where(deferred, n_pad, 0)

        tied = avail & (score == max_score)
        best = jnp.argmin(jnp.where(tied, visit, 2**30))
        score_not_best = jnp.where(pos == best, NEG_INF, score)
        runner_other = jnp.max(jnp.where(avail, score_not_best, NEG_INF))
        runner_nontied = jnp.max(jnp.where(avail & ~tied, score, NEG_INF))
        remaining = args.n_allocs - placed

        if not even_mode:
            # ---- sweep tie-run: keys of the tied set in merged order ----
            t_mat = jnp.cumsum(onehot_cls * tied[:, None].astype(jnp.float32), axis=0)
            t_own = jnp.sum(t_mat * onehot_cls, axis=1) - 1.0  # rank among class ties
            key = score - t_own * delta_node / num
            accept0 = tied & (key > runner_nontied)
            key_min0 = jnp.min(jnp.where(accept0, key, jnp.inf))
            score2, _ = _score_at(used, coll, boosts, 1, 1, 1)
            guard = score2 <= key_min0
            bad_key = jnp.max(jnp.where(accept0 & ~guard, key, NEG_INF))
            accept = accept0 & (key > bad_key)
            n_acc = jnp.sum(accept.astype(jnp.int32))
            sweep_ok = n_acc > 1
        else:
            accept = jnp.zeros(n_pad, dtype=bool)
            key = score
            sweep_ok = jnp.bool_(False)

        def sweep_branch(used, coll, counts, present, placed, placements):
            sort_key = jnp.where(accept, key, NEG_INF)
            order = jnp.lexsort((visit, -sort_key))
            rank = jnp.zeros(n_pad, dtype=jnp.int32).at[order].set(
                jnp.arange(n_pad, dtype=jnp.int32)
            )
            take = jnp.minimum(remaining, jnp.sum(accept.astype(jnp.int32)))
            acc = accept & (rank < take)
            slots = jnp.where(acc, placed + rank, a_pad)
            placements = placements.at[slots].set(jnp.where(acc, args.perm, -1))
            used = used + jnp.where(acc[:, None], args.demand[None, :], 0)
            coll = coll + acc.astype(jnp.int32)
            m_v = jnp.sum(onehot_cls * acc[:, None].astype(jnp.float32), axis=0)
            m_v = m_v[:V].astype(jnp.int32)
            hit = jnp.where(args.spread_active, m_v, 0)
            counts = counts + hit
            present = present | (hit > 0)
            placed = placed + take
            return used, coll, counts, present, placed, placements

        def fill_branch(used, coll, counts, present, placed, placements):
            # trajectory of the winning node under j self-placements
            used_b = used[best]
            coll_b = coll[best].astype(jnp.float32)
            cls_b = cls[best]
            boost_b = boosts[cls_b]
            delta_b = delta_node[best]
            aff_b = aff_term[best]
            aff_fb = aff_f[best]
            cap_b = args.capacity[best]
            usable_b = args.usable[best]
            jj = jnp.arange(RUNCAP)
            jf = jj.astype(jnp.float32)
            util_j = (
                used_b[:2].astype(jnp.float32)[None, :]
                + (jf[:, None] + 1.0) * demand_f2[None, :]
            )
            free_j = 1.0 - util_j / usable_b[None, :]
            bp_j = _binpack(free_j[:, 0], free_j[:, 1])
            coll_j = coll_b + jf
            ap_j = coll_j > 0.0
            an_j = jnp.where(ap_j, -(coll_j + 1.0) / count_f, 0.0)
            sp_j = boost_b - jf * delta_b
            fired_j = args.spread_active & (sp_j != 0.0)
            num_j = 1.0 + ap_j.astype(jnp.float32) + aff_fb + fired_j.astype(jnp.float32)
            traj = (bp_j + an_j + aff_b + jnp.where(fired_j, sp_j, 0.0)) / num_j
            fit_j = jnp.all(
                used_b[None, :] + (jj[:, None] + 1) * args.demand[None, :]
                <= cap_b[None, :],
                axis=1,
            )
            if even_mode:
                ok = jnp.zeros(RUNCAP, dtype=bool)
            else:
                ok = fit_j & (traj > runner_other) & (jj.astype(jnp.int32) < remaining)
            # ok[j] ⇒ the (j+1)-th consecutive placement happens; the first
            # is granted (best already won this step)
            ok = ok & (jj > 0)
            run = 1 + jnp.sum(jnp.cumprod(ok[1:].astype(jnp.int32)))
            run = jnp.minimum(run, remaining)

            idx = placed + jj
            mask = jj < run
            placements = placements.at[jnp.where(mask, idx, a_pad)].set(
                jnp.where(mask, args.perm[best], -1)
            )
            used = used.at[best].add(run * args.demand)
            coll = coll.at[best].add(run)
            do_spread = args.spread_active & (cls_b < V)
            safe_b = jnp.minimum(cls_b, V - 1)
            hit = jnp.where(do_spread, run, 0)
            counts = counts.at[safe_b].add(hit)
            present = present.at[safe_b].set(present[safe_b] | (hit > 0))
            placed = placed + run
            return used, coll, counts, present, placed, placements

        used, coll, counts, present, placed, placements = jax.lax.cond(
            sweep_ok & any_avail,
            sweep_branch,
            lambda *a: jax.lax.cond(any_avail, fill_branch, lambda *b: b, *a),
            used,
            coll,
            counts,
            present,
            placed,
            placements,
        )
        # rounds = while-loop trips: the device-loop round count the
        # devprof collective counter reads (one cross-shard reduction
        # set per round when sharded)
        return (used, coll, counts, present, placed, placements,
                any_avail, rounds + 1)

    def cond(state):
        _, _, _, _, placed, _, progress, _ = state
        return (placed < args.n_allocs) & progress

    placements0 = jnp.full(a_pad + 1, -1, dtype=jnp.int32)
    init_state = (
        used0,
        coll0,
        counts0,
        present0,
        jnp.int32(0),
        placements0,
        jnp.bool_(True),
        jnp.int32(0),
    )
    *_, placements, _, rounds = jax.lax.while_loop(cond, body, init_state)
    return placements[:a_pad], rounds


class WindowArgs(NamedTuple):
    capacity: jax.Array  # i32[N,3]
    usable: jax.Array  # f32[N,2]
    feasible: jax.Array  # bool[N]
    perm: jax.Array  # i32[N]
    demand: jax.Array  # i32[3]
    group_count: jax.Array  # i32 scalar
    limit: jax.Array  # i32 scalar
    n_allocs: jax.Array  # i32 scalar


def plan_batch_windowed(
    args: WindowArgs, used0: jax.Array, collisions0: jax.Array,
    n_real: int, a_pad: int
):
    """Place ``n_allocs`` identical asks; returns node index per alloc slot
    (length ``a_pad``, -1 = unplaced). Like :func:`plan_batch_runs`, the
    jit also returns its while-loop trip count, recorded to the devprof
    round counter (the windowed planner already resolves one WINDOW of
    placements per round — its rounds-per-placement is the existing
    counter-example to the one-collective-per-placement ceiling)."""
    _faults.fault_point("tpu.kernel")
    key = f"N{args.capacity.shape[0]}A{a_pad}"
    out, sharded = _dispatch(
        "windowed", _plan_batch_windowed_jit,
        (args, used0, collisions0, n_real, a_pad), key,
    )
    placements, rounds = out
    if _devprof.enabled():
        _devprof.count_rounds(
            "windowed", rounds, int(args.n_allocs), sharded
        )
    return placements


@functools.partial(jax.jit, static_argnums=(3, 4))
def _plan_batch_windowed_jit(
    args: WindowArgs, used0: jax.Array, collisions0: jax.Array,
    n_real: int, a_pad: int
):
    n_pad = args.capacity.shape[0]
    positions = jnp.arange(n_pad)
    in_ring = positions < n_real
    nseg = n_real + 1
    L = args.limit

    def cond(state):
        _, _, _, placed, _, progress, _ = state
        return (placed < args.n_allocs) & progress

    def body(state):
        used, collisions, offset, placed, placements, _, rounds = state

        fit_nodes = args.feasible & jnp.all(
            used + args.demand[None, :] <= args.capacity, axis=1
        )
        # scores (binpack + anti-affinity, averaged over fired planes)
        util = used + args.demand[None, :]
        free_cpu = 1.0 - util[:, 0].astype(jnp.float32) / args.usable[:, 0]
        free_mem = 1.0 - util[:, 1].astype(jnp.float32) / args.usable[:, 1]
        binpack = _binpack(free_cpu, free_mem)
        anti_present = collisions > 0
        anti = jnp.where(
            anti_present,
            -(collisions.astype(jnp.float32) + 1.0)
            / args.group_count.astype(jnp.float32),
            0.0,
        )
        final = (binpack + anti) / (1.0 + anti_present.astype(jnp.float32))

        fit_p = fit_nodes[args.perm] & in_ring
        score_p = final[args.perm]

        total_feas = jnp.sum(fit_p.astype(jnp.int32))
        feas_incl = _rot_incl(fit_p, offset, total_feas, positions)
        feas_rank = feas_incl - fit_p.astype(jnp.int32)  # 0-based among feasible

        remaining = args.n_allocs - placed
        full_windows = total_feas // jnp.maximum(L, 1)
        w_avail = jnp.where(total_feas > 0, jnp.maximum(full_windows, 1), 0)
        w_use = jnp.minimum(w_avail, remaining)

        window = feas_rank // jnp.maximum(L, 1)
        active = fit_p & (window < w_use)
        seg = jnp.where(active, window, nseg - 1)

        seg_max = jax.ops.segment_max(
            jnp.where(active, score_p, NEG_INF), seg, num_segments=nseg
        )
        is_best = active & (score_p == seg_max[seg])
        # first-in-rotation tie break within each window
        seg_min_rank = jax.ops.segment_min(
            jnp.where(is_best, feas_rank, 2**30), seg, num_segments=nseg
        )
        chosen = is_best & (feas_rank == seg_min_rank[seg])

        # apply: each chosen permuted position p places alloc (placed + window)
        nodes = args.perm  # node id per permuted position
        add = jnp.where(chosen[:, None], args.demand[None, :], 0)
        used = used.at[nodes].add(add)
        collisions = collisions.at[nodes].add(chosen.astype(jnp.int32))

        # scatter via max: unplaced slots hold -1, non-chosen lanes contribute
        # -1 (no-op), every chosen lane has a unique slot
        alloc_slot = jnp.where(chosen, placed + window, a_pad - 1)
        placements = placements.at[alloc_slot].max(jnp.where(chosen, nodes, -1))

        # consumed ring positions: through the (w_use·L)-th feasible node
        # (or the whole ring when the pass exhausted it)
        rot_rank = jnp.where(
            positions >= offset, positions - offset, n_real - offset + positions
        )
        consumed_window = fit_p & (feas_rank < w_use * L)
        last = jnp.max(jnp.where(consumed_window, rot_rank, -1))
        ring_exhausted = total_feas < (w_use * L)
        consumed = jnp.where(ring_exhausted, n_real, last + 1)
        offset = (offset + jnp.maximum(consumed, 0)) % n_real

        placed = placed + w_use
        progress = w_use > 0
        return (used, collisions, offset, placed, placements, progress,
                rounds + 1)

    placements0 = jnp.full(a_pad, -1, dtype=jnp.int32)
    init = (
        used0,
        collisions0,
        jnp.int32(0),
        jnp.int32(0),
        placements0,
        jnp.bool_(True),
        jnp.int32(0),
    )
    *_, placements, _, rounds = jax.lax.while_loop(cond, body, init)
    return placements, rounds


# ---------------------------------------------------------------------------
# dense plan verify (the applier's commit-time fit check, core/plan_apply.py)
# ---------------------------------------------------------------------------


@jax.jit
def _verify_rows_jit(capacity, used, rows, deltas):
    """Node-axis fit check for a plan's touched rows against the mirror's
    device-resident planes: scatter-add the plan's per-row usage deltas
    into ``used`` and test every resource column against ``capacity``.
    Shaped exactly like the planner kernel's feasibility mask (used +
    demand <= capacity over R_COLS) and like DeviceState's dirty-row
    scatter: ``rows``/``deltas`` are bucketed, with pad lanes repeating
    row 0 at delta 0 (`.add` of zero is idempotent, so repeats are safe —
    REAL rows must be pre-aggregated host-side, one lane per row).
    Returns the per-lane fit verdict ``fits[rows]`` (pad lanes echo row
    0's verdict; the caller reads only the real lanes)."""
    stacked = used.at[rows].add(deltas)
    fits = jnp.all(stacked <= capacity, axis=1)
    return fits[rows]


def verify_rows(capacity, used, rows, deltas):
    """Dispatch the dense verify; the ``tpu.kernel`` fault point models
    device errors exactly as it does for the planner kernels — the
    applier degrades the whole plan to the host oracle when this
    raises. Rides the devprof compile ledger like the planners (an
    applier verify shape that escapes the warmup prewarm is a compile
    event the ledger names), but records no rounds: the verify is one
    scatter+compare, not a fill loop."""
    _faults.fault_point("tpu.kernel")
    key = f"N{capacity.shape[0]}R{rows.shape[0]}"
    out, _ = _dispatch(
        "verify_rows", _verify_rows_jit, (capacity, used, rows, deltas),
        key, allow_det=False,
    )
    return out


#: the jitted planners, by mode name — the one enumeration shared by the
#: recompile detector above, the warmup prewarm ladder (single-chip AND
#: mesh-sharded layouts), and the multichip bench's per-planner timings.
#: verify_rows is deliberately NOT here: compile_cache_size() deltas are
#: diffed across DRAIN dispatch windows on other threads, and an applier
#: verify compile landing inside one would falsely flag the innocent
#: drain span [recompile] (warmup.prewarm_drain compiles the verify
#: shapes instead, so the applier hot path stays cold-compile-free)
PLANNER_JITS = {
    "exact": _plan_batch_jit,
    "runs": _plan_batch_runs_jit,
    "windowed": _plan_batch_windowed_jit,
}

# the wavefront planner lives in its own module (tpu/wavefront.py) and
# registers itself into PLANNER_JITS at import; every dispatcher imports
# it before calling plan_batch_wavefront, and compile_cache_size() pulls
# it in lazily, so the enumeration is complete wherever it is consumed
# without a kernel->wavefront top-level import cycle
