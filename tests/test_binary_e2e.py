"""Forked-binary e2e harness (ref testutil/server.go:1-28: the reference's
TestServer forks the real nomad binary; this spawns real
``python -m nomad_tpu agent`` processes). Catches packaging/CLI/signal
regressions the in-process harness (tests/test_e2e.py) can't: module
entrypoint, HCL boot path, real TCP raft between processes, and leader
failover across OS processes."""

import os
import signal
import socket
import subprocess
import sys
import tempfile
import time

import pytest

from nomad_tpu import mock
from nomad_tpu.api.client import ApiClient


def free_ports(n):
    socks = []
    try:
        for _ in range(n):
            s = socket.socket()
            s.bind(("127.0.0.1", 0))
            socks.append(s)
        return [s.getsockname()[1] for s in socks]
    finally:
        for s in socks:
            s.close()


def wait_until(fn, timeout=45.0, msg="condition"):
    deadline = time.monotonic() + timeout
    last = None
    while time.monotonic() < deadline:
        try:
            if fn():
                return
        except Exception as e:  # servers still booting
            last = e
        time.sleep(0.2)
    raise AssertionError(f"timed out waiting for {msg} (last: {last})")


@pytest.fixture()
def cluster(tmp_path):
    """Three server processes + one client process, torn down hard."""
    ports = free_ports(7)
    rpc = ports[:3]
    http = ports[3:6]
    names = ["s1", "s2", "s3"]
    voters = "\n".join(
        f'    {n} = "127.0.0.1:{p}"' for n, p in zip(names, rpc)
    )
    procs = []
    env = dict(os.environ, JAX_PLATFORMS="cpu", NOMAD_TPU_COMPILE_CACHE="off")
    try:
        for i, name in enumerate(names):
            cfg = tmp_path / f"{name}.hcl"
            cfg.write_text(f"""
name = "{name}"
ports {{ http = {http[i]} }}
server {{
  enabled = true
  rpc_port = {rpc[i]}
  num_schedulers = 1
  heartbeat_ttl = 3
  prewarm_kernels = false
  voters {{
{voters}
  }}
}}
""")
            procs.append(
                subprocess.Popen(
                    [sys.executable, "-m", "nomad_tpu", "agent",
                     "-config", str(cfg)],
                    stdout=open(tmp_path / f"{name}.log", "wb"),
                    stderr=subprocess.STDOUT,
                    env=env,
                )
            )
        apis = [ApiClient(address=f"http://127.0.0.1:{p}") for p in http]
        wait_until(
            lambda: any(_leader(api) for api in apis),
            msg="leader election across processes",
        )

        client_cfg = tmp_path / "client.hcl"
        servers = ", ".join(f'"127.0.0.1:{p}"' for p in rpc)
        client_cfg.write_text(f"""
name = "c1"
data_dir = "{tmp_path / 'client-data'}"
client {{
  enabled = true
  servers = [{servers}]
}}
""")
        procs.append(
            subprocess.Popen(
                [sys.executable, "-m", "nomad_tpu", "agent",
                 "-config", str(client_cfg)],
                stdout=open(tmp_path / "c1.log", "wb"),
                stderr=subprocess.STDOUT,
                env=env,
            )
        )
        wait_until(
            lambda: any(
                n.get("Status") == "ready"
                for api in apis
                if _alive(api)
                for n in api.nodes()
            ),
            msg="client node registers over RPC",
        )
        yield procs, apis
    finally:
        for p in procs:
            if p.poll() is None:
                p.terminate()
        deadline = time.monotonic() + 10
        for p in procs:
            try:
                p.wait(timeout=max(0.1, deadline - time.monotonic()))
            except subprocess.TimeoutExpired:
                p.kill()


def _alive(api) -> bool:
    try:
        api.get("/v1/status/leader")
        return True
    except Exception:
        return False


def _leader_addr(api):
    """The leader's rpc address per this server, or None (ApiClient.get
    returns a (payload, index) tuple — unpack the payload)."""
    try:
        return api.get("/v1/status/leader")[0] or None
    except Exception:
        return None


def _leader(api):
    return _leader_addr(api) is not None


def _run_job(apis):
    job = mock.batch_job()
    tg = job.task_groups[0]
    tg.count = 1
    tg.restart_policy.attempts = 0
    tg.restart_policy.mode = "fail"
    task = tg.tasks[0]
    task.driver = "raw_exec"
    task.config = {"command": "/bin/sh", "args": ["-c", "echo done"]}
    task.resources.networks = []
    api = next(a for a in apis if _alive(a))
    api.register_job(job.to_dict())

    def complete():
        for a in apis:
            if not _alive(a):
                continue
            allocs = a.job_allocations(job.id)
            return allocs and all(
                al.get("ClientStatus") == "complete" for al in allocs
            )
        return False

    wait_until(complete, msg=f"job {job.id[:8]} completes")
    return job


@pytest.mark.slow
def test_three_server_cluster_survives_leader_kill(cluster):
    procs, apis = cluster
    # a job runs through the forked cluster
    _run_job(apis)

    # find and SIGKILL the leader PROCESS (harsher than the in-process
    # leader-kill test: the OS process dies mid-heartbeat). Elections can
    # still be churning right after the job ran, so poll until some
    # process self-reports leadership rather than sampling once.
    found = {}

    def _find_leader():
        for i, api in enumerate(apis):
            try:
                if api.get("/v1/agent/self")[0]["member"]["is_leader"]:
                    found["idx"] = i
                    found["addr"] = _leader_addr(api)
                    return True
            except Exception:
                pass
        return False

    wait_until(_find_leader, msg="a server self-reports leadership")
    leader_idx, leader_addr = found["idx"], found["addr"]
    procs[leader_idx].send_signal(signal.SIGKILL)
    procs[leader_idx].wait(timeout=10)

    survivors = [api for i, api in enumerate(apis) if i != leader_idx]
    wait_until(
        lambda: any(
            _leader_addr(api) not in (None, leader_addr)
            for api in survivors
        ),
        msg="new leader elected after process kill",
    )
    # the cluster still schedules work
    _run_job(survivors)
