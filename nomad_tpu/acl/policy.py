"""ACL policy language (ref acl/policy.go: namespace blocks with
policy/capability grants plus node/agent/operator/quota blocks).

Policies are HCL:

    namespace "default" { policy = "write" }
    namespace "ops-*"   { capabilities = ["read-job", "submit-job"] }
    node     { policy = "read" }
    agent    { policy = "write" }
    operator { policy = "read" }

Coarse policies expand to capability sets exactly as the reference's
expandNamespacePolicy (policy.go:92-118)."""

from __future__ import annotations

from dataclasses import dataclass, field

POLICY_DENY = "deny"
POLICY_READ = "read"
POLICY_WRITE = "write"

# namespace capabilities (policy.go:40-66)
NS_CAP_DENY = "deny"
NS_CAP_LIST_JOBS = "list-jobs"
NS_CAP_READ_JOB = "read-job"
NS_CAP_SUBMIT_JOB = "submit-job"
NS_CAP_DISPATCH_JOB = "dispatch-job"
NS_CAP_READ_LOGS = "read-logs"
NS_CAP_READ_FS = "read-fs"
NS_CAP_ALLOC_EXEC = "alloc-exec"
NS_CAP_ALLOC_LIFECYCLE = "alloc-lifecycle"
NS_CAP_SENTINEL_OVERRIDE = "sentinel-override"

_READ_CAPS = [NS_CAP_LIST_JOBS, NS_CAP_READ_JOB]
_WRITE_CAPS = _READ_CAPS + [
    NS_CAP_SUBMIT_JOB,
    NS_CAP_DISPATCH_JOB,
    NS_CAP_READ_LOGS,
    NS_CAP_READ_FS,
    NS_CAP_ALLOC_EXEC,
    NS_CAP_ALLOC_LIFECYCLE,
]

VALID_COARSE = {POLICY_DENY, POLICY_READ, POLICY_WRITE}


class PolicyError(ValueError):
    pass


@dataclass
class NamespacePolicy:
    name: str  # may contain a glob suffix: "ops-*"
    capabilities: set[str] = field(default_factory=set)
    deny: bool = False


@dataclass
class ParsedPolicy:
    namespaces: list[NamespacePolicy] = field(default_factory=list)
    node: str = ""  # "", deny, read, write
    agent: str = ""
    operator: str = ""


def expand_namespace_policy(policy: str) -> list[str]:
    """ref policy.go:92-118 expandNamespacePolicy"""
    if policy == POLICY_DENY:
        return [NS_CAP_DENY]
    if policy == POLICY_READ:
        return list(_READ_CAPS)
    if policy == POLICY_WRITE:
        return list(_WRITE_CAPS)
    raise PolicyError(f"invalid namespace policy {policy!r}")


def parse_policy(rules: str) -> ParsedPolicy:
    """HCL rules → ParsedPolicy (ref policy.go:170-240 Parse)."""
    from ..jobspec import parse_hcl

    raw = parse_hcl(rules)
    parsed = ParsedPolicy()

    namespaces = raw.get("namespace", {})
    if isinstance(namespaces, dict):
        # {"default": {...}} or a single unlabeled block {"policy": ...}
        if "policy" in namespaces or "capabilities" in namespaces:
            namespaces = {"default": namespaces}
        for name, body in namespaces.items():
            if not isinstance(body, dict):
                raise PolicyError(f"namespace {name!r}: expected a block")
            caps: set[str] = set()
            deny = False
            coarse = body.get("policy")
            if coarse is not None:
                if coarse not in VALID_COARSE:
                    raise PolicyError(
                        f"namespace {name!r}: invalid policy {coarse!r}"
                    )
                expanded = expand_namespace_policy(coarse)
                if NS_CAP_DENY in expanded:
                    deny = True
                caps.update(c for c in expanded if c != NS_CAP_DENY)
            for cap in body.get("capabilities", []) or []:
                if cap == NS_CAP_DENY:
                    deny = True
                else:
                    caps.add(cap)
            parsed.namespaces.append(
                NamespacePolicy(name=name, capabilities=caps, deny=deny)
            )

    for block in ("node", "agent", "operator"):
        body = raw.get(block)
        if body is None:
            continue
        coarse = body.get("policy", "") if isinstance(body, dict) else ""
        if coarse and coarse not in VALID_COARSE:
            raise PolicyError(f"{block}: invalid policy {coarse!r}")
        setattr(parsed, block, coarse)
    return parsed
