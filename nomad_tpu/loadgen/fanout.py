"""Scored event-plane fan-out bench: N concurrent ``/v1/event/stream``
watchers riding a live server under the smoke storm.

The measurement contract (BENCH_SUMMARY ``fanout_*`` fields, PERF.md
methodology):

- **publish throughput** — broker events published per storm second;
- **subscriber lag** — publish→delivery latency in ms, joined from an
  in-process oracle subscription that stamps every published frame index
  with its publish wall time, against a receipt-time reservoir sampled
  across all client connections (p50/p99 over the join);
- **gap accounting** — explicit gaps are LostGap markers received;
  SILENT gaps are frames the oracle saw that a marker-free subscriber's
  contiguous [first, last] window never delivered — the one unforgivable
  number, SLO-pinned to zero;
- **per-subscriber memory** — server-process RSS delta across the
  connection ramp divided by subscribers (broker queues + mux conns +
  kernel buffers; the storm hasn't started yet so nothing else moves).

The subscriber client multiplexes every connection over a few selector
reader threads (no thread-per-stream — the client must scale past the
server or it measures itself) and parses frames with prefix regexes
instead of ``json.loads`` — frame lines are byte-identical across
subscribers (encode-once), so full JSON decode per connection would make
the CLIENT the bottleneck at 10K.

At 10K subscribers the client runs as a SUBPROCESS: the per-process fd
ceiling (20K on the bench box) can't hold both sides' sockets, and the
split also gives the client its own GIL. The tier-1 scaled-down smoke
(200 subscribers, tests/test_fanout.py) drives the same class in-proc.

Run via ``scripts/fanout.sh`` (env knobs FANOUT_SUBS / FANOUT_TOPICS /
STORM_S) or ``python -m nomad_tpu.loadgen --fanout``; bench.py embeds it
as the ``fanout`` section.
"""

from __future__ import annotations

import json
import os
import re
import selectors
import socket
import subprocess
import sys
import threading
import time
import urllib.parse
from collections import deque

from ..debug.flight import rss_mb

#: compact-JSON frame classifiers (the broker's encode-once wire shapes)
_RE_DELTA = re.compile(rb'^\{"Index":(\d+)')
_RE_SNAP = re.compile(rb'^\{"Snapshot":true,"Index":(\d+)')
_RE_SNAP_DONE = re.compile(rb'^\{"SnapshotDone":true,"Index":(\d+)')
_RE_GAP = re.compile(rb'^\{"LostGap":true,"Index":(\d+)')

#: every Nth delta frame per connection lands in the lag reservoir
LAG_SAMPLE_EVERY = 8


def raise_nofile():
    """Lift the soft fd limit to the hard limit (10K sockets a side)."""
    import resource

    soft, hard = resource.getrlimit(resource.RLIMIT_NOFILE)
    if soft < hard:
        resource.setrlimit(resource.RLIMIT_NOFILE, (hard, hard))
    return hard


class _FanConn:
    __slots__ = (
        "sock",
        "buf",
        "headers_done",
        "floor",
        "first",
        "last",
        "frames",
        "gaps",
        "snap_batches",
        "errors",
        "eof",
    )

    def __init__(self, sock):
        self.sock = sock
        self.buf = bytearray()
        self.headers_done = False
        #: completeness floor: SnapshotDone stamp or LostGap index —
        #: delivery is owed only for frames past it
        self.floor = 0
        self.first = 0  # first delta index received
        self.last = 0  # newest delta index received
        self.frames = 0  # delta frames received
        self.gaps = 0  # explicit LostGap markers
        self.snap_batches = 0
        self.errors = 0  # Error frames (broker-side close)
        self.eof = False


class FanoutClient:
    """N multiplexed event-stream subscribers against one HTTP address."""

    def __init__(
        self,
        address: str,
        subs: int,
        topics=None,
        heartbeat: float = 10.0,
        snapshot=None,
        readers: int = 4,
        connectors: int = 16,
    ):
        self.address = address
        self.subs = int(subs)
        self.topics = list(topics or [])
        self.heartbeat = float(heartbeat)
        self.snapshot = snapshot
        self.readers = max(1, int(readers))
        self.connectors = max(1, int(connectors))
        self.conns: list[_FanConn] = []
        #: (frame index, receipt wall time) samples for the lag join
        self.lag_samples: deque = deque(maxlen=500_000)
        self.connect_failures = 0
        self._stop = threading.Event()
        self._shards: list[deque] = [deque() for _ in range(self.readers)]
        self._threads: list[threading.Thread] = []
        self._lock = threading.Lock()

    # ------------------------------------------------------------------
    def _request_bytes(self) -> bytes:
        host = urllib.parse.urlparse(self.address)
        params: list = [("topic", t) for t in self.topics]
        params.append(("heartbeat", str(self.heartbeat)))
        if self.snapshot is not None:
            params.append(
                ("snapshot", "true" if self.snapshot else "false")
            )
        query = urllib.parse.urlencode(params)
        return (
            f"GET /v1/event/stream?{query} HTTP/1.1\r\n"
            f"Host: {host.netloc}\r\n"
            "Accept: application/json\r\n"
            "\r\n"
        ).encode()

    def connect(self, timeout: float = 300.0) -> int:
        """Ramp all subscribers (bounded connector parallelism), start the
        reader threads, return the connected count."""
        parsed = urllib.parse.urlparse(self.address)
        addr = (parsed.hostname, parsed.port)
        request = self._request_bytes()
        todo = deque(range(self.subs))
        deadline = time.monotonic() + timeout

        def connector(cid: int):
            while not self._stop.is_set():
                try:
                    i = todo.popleft()
                except IndexError:
                    return
                if time.monotonic() > deadline:
                    return
                for attempt in range(4):
                    try:
                        sock = socket.create_connection(addr, timeout=30)
                        sock.sendall(request)
                        sock.setblocking(False)
                        break
                    except OSError:
                        time.sleep(0.05 * (attempt + 1))
                else:
                    with self._lock:
                        self.connect_failures += 1
                    continue
                conn = _FanConn(sock)
                with self._lock:
                    self.conns.append(conn)
                self._shards[i % self.readers].append(conn)

        threads = [
            threading.Thread(
                target=connector, args=(c,), daemon=True,
                name=f"fanout-connect-{c}",
            )
            for c in range(self.connectors)
        ]
        for t in threads:
            t.start()
        for r in range(self.readers):
            t = threading.Thread(
                target=self._read_loop, args=(r,), daemon=True,
                name=f"fanout-reader-{r}",
            )
            t.start()
            self._threads.append(t)
        for t in threads:
            t.join(timeout=max(0.0, deadline - time.monotonic()))
        return len(self.conns)

    # ------------------------------------------------------------------
    def _read_loop(self, shard: int):
        sel = selectors.DefaultSelector()
        pending = self._shards[shard]
        while not self._stop.is_set():
            while pending:
                conn = pending.popleft()
                try:
                    sel.register(conn.sock, selectors.EVENT_READ, conn)
                except (ValueError, OSError):
                    conn.eof = True
            for key, _ in sel.select(0.2):
                conn = key.data
                try:
                    data = conn.sock.recv(262144)
                except (BlockingIOError, InterruptedError):
                    continue
                except OSError:
                    data = b""
                if not data:
                    conn.eof = True
                    try:
                        sel.unregister(conn.sock)
                        conn.sock.close()
                    except (KeyError, ValueError, OSError):
                        pass
                    continue
                conn.buf += data
                self._parse(conn)
        sel.close()

    def _parse(self, conn: _FanConn):
        buf = conn.buf
        if not conn.headers_done:
            end = buf.find(b"\r\n\r\n")
            if end < 0:
                return
            del buf[: end + 4]
            conn.headers_done = True
        # frames are whole NDJSON lines inside chunked framing; chunk
        # size/trailer lines never start with '{' so a line scan is a
        # complete parser (and frame bytes are shared across conns —
        # encode-once — so skipping json.loads costs nothing)
        while True:
            nl = buf.find(b"\n")
            if nl < 0:
                break
            line = bytes(buf[:nl])
            del buf[: nl + 1]
            if line.endswith(b"\r"):
                line = line[:-1]
            if not line.startswith(b"{") or line == b"{}":
                continue
            m = _RE_DELTA.match(line)
            if m:
                idx = int(m.group(1))
                if idx <= conn.floor:
                    # replayed ephemeral history at or below the
                    # snapshot/gap floor: real delivery, but outside the
                    # oracle-owed window the gap census counts
                    continue
                if not conn.first:
                    conn.first = idx
                if idx > conn.last:
                    conn.last = idx
                conn.frames += 1
                if conn.frames % LAG_SAMPLE_EVERY == 0:
                    self.lag_samples.append((idx, time.time()))
                continue
            m = _RE_SNAP_DONE.match(line)
            if m:
                conn.floor = max(conn.floor, int(m.group(1)))
                continue
            m = _RE_SNAP.match(line)
            if m:
                conn.snap_batches += 1
                continue
            m = _RE_GAP.match(line)
            if m:
                conn.gaps += 1
                conn.floor = max(conn.floor, int(m.group(1)))
                continue
            if line.startswith(b'{"Error"'):
                conn.errors += 1

    # ------------------------------------------------------------------
    def stop(self):
        self._stop.set()
        for t in self._threads:
            t.join(timeout=5.0)
        for conn in self.conns:
            try:
                conn.sock.close()
            except OSError:
                pass

    def report(self) -> dict:
        return {
            "requested": self.subs,
            "connected": len(self.conns),
            "connect_failures": self.connect_failures,
            "frames": sum(c.frames for c in self.conns),
            "gaps": sum(c.gaps for c in self.conns),
            "snapshot_batches": sum(c.snap_batches for c in self.conns),
            "errors": sum(c.errors for c in self.conns),
            "eof": sum(1 for c in self.conns if c.eof),
            "lag_samples": [
                [idx, t] for idx, t in self.lag_samples
            ],
            # per-conn delivery windows for the silent-gap join:
            # [floor, first, last, frames, gaps, errors]
            "conns": [
                [c.floor, c.first, c.last, c.frames, c.gaps, c.errors]
                for c in self.conns
            ],
        }


class _Oracle:
    """In-process all-seeing subscription: stamps every published frame
    index with its publish wall time — the ground truth the client-side
    receipt samples join against, and the per-frame census the silent-gap
    accounting compares every subscriber's window to."""

    def __init__(self, broker, topics=None):
        # parse "Topic" / "Topic:key" specs EXACTLY like the HTTP layer
        # does for the subscribers: an oracle scoped wider than the fleet
        # would count legitimately key-filtered frames as silent gaps
        norm = None
        if topics:
            norm = {}
            for spec in topics:
                name, _, key = spec.partition(":")
                norm.setdefault(name, set()).add(key or "*")
        self._sub = broker.subscribe(topics=norm, max_queued=10_000_000)
        self.times: dict[int, float] = {}
        self.indexes: list[int] = []
        self._stop = threading.Event()
        self._thread = threading.Thread(
            target=self._run, daemon=True, name="fanout-oracle"
        )
        self._thread.start()

    def _run(self):
        from ..events import SubscriptionClosedError

        while not self._stop.is_set():
            try:
                frame = self._sub.next(timeout=0.25)
            except SubscriptionClosedError:
                return
            if frame is None:
                continue
            index, events = frame
            if events is None:
                continue
            now = time.time()
            if index not in self.times:
                self.times[index] = now
                self.indexes.append(index)

    def stop(self):
        self._stop.set()
        self._thread.join(timeout=5.0)
        self._sub.close()


def _percentile(sorted_vals: list, q: float):
    if not sorted_vals:
        return 0.0
    return sorted_vals[min(len(sorted_vals) - 1, int(len(sorted_vals) * q))]


def _silent_gaps(oracle_indexes: list[int], conn_rows: list) -> dict:
    """Frames the oracle saw that a marker-free subscriber's contiguous
    delivery window never did. Subscribers that received an explicit
    LostGap marker are excluded here (their drop was DECLARED — counted
    under ``gaps``); duplicate delivery would surface as a negative
    deficit and is reported separately."""
    import bisect

    silent = 0
    dupes = 0
    checked = 0
    oracle_last = oracle_indexes[-1] if oracle_indexes else 0
    for floor, first, last, frames, gaps, errors in conn_rows:
        if gaps:
            continue
        # a conn with ZERO deltas is the worst silent gap, not an
        # exemption: it owes everything the oracle saw past its floor
        # (its join point — SnapshotDone stamp, or nothing at all for a
        # marker-free conn, which then owes the whole oracle window)
        start = floor if floor else (max(0, first - 1) if first else 0)
        end = last if last else oracle_last
        expected = bisect.bisect_right(
            oracle_indexes, end
        ) - bisect.bisect_right(oracle_indexes, start)
        deficit = expected - frames
        if deficit > 0:
            silent += deficit
        elif deficit < 0:
            dupes += -deficit
        checked += 1
    return {"silent": silent, "dupes": dupes, "checked_conns": checked}


def run_fanout(
    subs: int = 10000,
    topics=None,
    storm_s: float = 16.0,
    seed: int = 1,
    out: str | None = None,
    in_proc: bool = False,
    nodes: int = 48,
    settle_s: float = 60.0,
    heartbeat: float = 10.0,
    driver_workers: int = 6,
    connect_timeout: float = 600.0,
    slos: dict | None = None,
) -> dict:
    """Boot a live server, ramp ``subs`` stream watchers, run the smoke
    storm through the real RPC/HTTP surface, and score delivery."""
    from ..agent import ServerAgent
    from ..api.http import HTTPServer
    from .driver import StormDriver
    from .grammar import compile_stream
    from .score import grade
    from .scenarios import smoke

    raise_nofile()
    scenario = smoke(nodes=nodes, churn_s=storm_s)
    server_config = dict(scenario.server_config)
    # fan-out-tuned broker: deep ring + deep subscriber queues so lag is
    # MEASURED, not amputated by slow-consumer closes mid-storm; the cap
    # admits the fleet with headroom
    server_config["event_broker"] = {
        "event_buffer_size": 65536,
        "subscriber_buffer": 65536,
        "max_subscribers": subs + 64,
    }
    stream = compile_stream(scenario, seed)
    agent = ServerAgent("fanout", config=server_config)
    http = None
    oracle = None
    client = None
    proc = None
    try:
        agent.start(num_workers=scenario.n_workers, wait_for_leader=10.0)
        http = HTTPServer(agent.server, port=0)
        http.start()
        broker = agent.server.event_broker
        oracle = _Oracle(broker, topics)

        rss0 = rss_mb()
        t_ramp = time.monotonic()
        if in_proc:
            client = FanoutClient(
                http.address, subs, topics=topics, heartbeat=heartbeat
            )
            connected = client.connect(timeout=connect_timeout)
        else:
            proc = subprocess.Popen(
                [
                    sys.executable, "-m", "nomad_tpu.loadgen.fanout",
                    "--client", "--addr", http.address,
                    "--subs", str(subs),
                    "--heartbeat", str(heartbeat),
                    "--out", (out or "FANOUT") + ".client.json",
                ]
                + sum((["--topic", t] for t in (topics or [])), []),
                stdin=subprocess.PIPE,
                stdout=subprocess.PIPE,
            )
            connected = _await_ready(proc, connect_timeout)
        ramp_s = time.monotonic() - t_ramp
        rss_ramped = rss_mb()

        pub0 = broker.stats()["events_published"]
        t0 = time.monotonic()
        driver = StormDriver(
            stream,
            rpc_servers=[agent.address],
            http_address=http.address,
            workers=driver_workers,
        )
        driver_report = driver.run()
        storm_wall = time.monotonic() - t0
        pub1 = broker.stats()["events_published"]

        # settle: let the fleet drain to the head before the census
        deadline = time.monotonic() + settle_s
        while time.monotonic() < deadline:
            if broker.lag_stats()["max"] == 0:
                break
            time.sleep(0.5)
        lag_after_settle = broker.lag_stats(top=5)

        if in_proc:
            client.stop()
            client_report = client.report()
        else:
            client_report = _stop_client(proc, (out or "FANOUT") + ".client.json")
        oracle.stop()

        lag_ms = sorted(
            (t_recv - oracle.times[idx]) * 1000.0
            for idx, t_recv in client_report.get("lag_samples", ())
            if idx in oracle.times
        )
        gap_info = _silent_gaps(
            oracle.indexes, client_report.get("conns", ())
        )
        broker_stats = broker.stats()
        report = {
            "fanout_subs": subs,
            "fanout_connected": client_report.get("connected", 0),
            "connect_failures": client_report.get("connect_failures", 0),
            "ramp_s": round(ramp_s, 2),
            "storm_s": round(storm_wall, 2),
            "fanout_pub_eps": round((pub1 - pub0) / max(storm_wall, 1e-9), 1),
            "events_published": pub1 - pub0,
            "frames_delivered": client_report.get("frames", 0),
            "snapshot_batches": client_report.get("snapshot_batches", 0),
            "snapshots_served": broker_stats.get("snapshots_served", 0),
            "fanout_lag_p50_ms": round(_percentile(lag_ms, 0.50), 1),
            "fanout_lag_p99_ms": round(_percentile(lag_ms, 0.99), 1),
            "lag_samples_joined": len(lag_ms),
            "fanout_gaps": client_report.get("gaps", 0),
            "fanout_silent_gaps": gap_info["silent"],
            "fanout_dupes": gap_info["dupes"],
            "gap_checked_conns": gap_info["checked_conns"],
            "fanout_slow_closes": broker_stats.get(
                "slow_consumers_closed", 0
            ),
            "stream_errors": client_report.get("errors", 0),
            "per_sub_server_kb": round(
                max(0.0, rss_ramped - rss0) * 1024.0 / max(subs, 1), 1
            ),
            "lag_after_settle": lag_after_settle,
            "driver": driver_report.to_dict(),
            "broker": broker_stats,
            "scenario": scenario.name,
            "seed": seed,
            "in_proc_client": in_proc,
        }
        report["slo"] = grade(
            report,
            slos
            if slos is not None
            else {
                "max_fanout_silent_gaps": 0,
                "max_fanout_slow_closes": 0,
                "max_fanout_lag_p99_ms": float(
                    os.environ.get("FANOUT_LAG_SLO_MS", "60000")
                ),
            },
        )
        if out:
            with open(out, "w", encoding="utf-8") as f:
                json.dump(report, f, indent=1)
                f.write("\n")
        return report
    finally:
        if client is not None:
            client.stop()
        if proc is not None and proc.poll() is None:
            proc.kill()
        if oracle is not None:
            oracle.stop()
        if http is not None:
            http.stop()
        agent.stop()


def _await_ready(proc, timeout: float) -> int:
    deadline = time.monotonic() + timeout
    line = b""
    while time.monotonic() < deadline:
        line = proc.stdout.readline()
        if not line:
            raise RuntimeError("fanout client exited before READY")
        if line.startswith(b"READY"):
            return int(line.split()[1])
    raise RuntimeError(f"fanout client not ready in {timeout}s: {line!r}")


def _stop_client(proc, report_path: str) -> dict:
    try:
        proc.stdin.write(b"STOP\n")
        proc.stdin.flush()
        proc.stdin.close()
    except OSError:
        pass
    proc.wait(timeout=180)
    with open(report_path, encoding="utf-8") as f:
        report = json.load(f)
    os.unlink(report_path)
    return report


def run_fanout_from_env(seed: int, out: str | None = None,
                        driver_workers: int = 6) -> dict:
    """The one env-knob parser (FANOUT_SUBS / FANOUT_TOPICS / STORM_S)
    shared by every entry point — scripts/fanout.sh via
    ``python -m nomad_tpu.loadgen --fanout`` and bench.py's ``fanout``
    section must not each grow their own copy."""
    topics = [
        t for t in os.environ.get("FANOUT_TOPICS", "").split(",") if t
    ]
    return run_fanout(
        subs=int(os.environ.get("FANOUT_SUBS", "10000")),
        topics=topics,
        storm_s=float(os.environ.get("STORM_S", "16")),
        seed=seed,
        out=out,
        driver_workers=driver_workers,
    )


def summary_line(report: dict) -> str:
    """The trailing FANOUT_SUMMARY line (log-tail-survival contract)."""
    slo = report["slo"]
    parts = [
        f"fanout_subs={report['fanout_connected']}/{report['fanout_subs']}",
        f"fanout_pub_eps={report['fanout_pub_eps']}",
        f"fanout_lag_p50_ms={report['fanout_lag_p50_ms']}",
        f"fanout_lag_p99_ms={report['fanout_lag_p99_ms']}",
        f"fanout_gaps={report['fanout_gaps']}",
        f"fanout_silent_gaps={report['fanout_silent_gaps']}",
        f"fanout_slow_closes={report['fanout_slow_closes']}",
        f"snapshots={report['snapshots_served']}",
        f"per_sub_server_kb={report['per_sub_server_kb']}",
        f"slo={slo['passed']}/{slo['passed'] + slo['failed']}",
        f"score={slo['score']}",
    ]
    return "FANOUT_SUMMARY " + " ".join(parts)


# ---------------------------------------------------------------------------
# subprocess client entry: python -m nomad_tpu.loadgen.fanout --client ...
# ---------------------------------------------------------------------------


def main(argv=None) -> int:
    import argparse

    parser = argparse.ArgumentParser(prog="python -m nomad_tpu.loadgen.fanout")
    parser.add_argument("--client", action="store_true", required=True)
    parser.add_argument("--addr", required=True)
    parser.add_argument("--subs", type=int, required=True)
    parser.add_argument("--topic", action="append", default=[])
    parser.add_argument("--heartbeat", type=float, default=10.0)
    parser.add_argument("--out", required=True)
    args = parser.parse_args(argv)

    raise_nofile()
    client = FanoutClient(
        args.addr, args.subs, topics=args.topic, heartbeat=args.heartbeat
    )
    connected = client.connect()
    print(f"READY {connected}", flush=True)
    # the parent ends the run by writing STOP (or closing our stdin)
    sys.stdin.readline()
    client.stop()
    with open(args.out, "w", encoding="utf-8") as f:
        json.dump(client.report(), f)
    print("DONE", flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
