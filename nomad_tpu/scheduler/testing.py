"""Test harness: in-process fake Planner + real StateStore
(ref scheduler/testing.go:42-283). This is the oracle-parity fixture —
identical inputs through the scalar oracle and the TPU batch path are
compared on the plans captured here."""

from __future__ import annotations

import random
import threading
from typing import Optional

from ..state import StateStore
from ..structs.model import Evaluation, Plan, PlanResult


class RejectPlan:
    """Planner that rejects all plans (ref testing.go:17-39)."""

    def __init__(self, harness: "Harness"):
        self.harness = harness

    def submit_plan(self, plan: Plan):
        result = PlanResult(refresh_index=self.harness.next_index())
        return result, self.harness.state

    def update_eval(self, eval: Evaluation):
        pass

    def create_eval(self, eval: Evaluation):
        pass

    def reblock_eval(self, eval: Evaluation):
        pass


class Harness:
    """ref testing.go:42-283"""

    def __init__(self, state: Optional[StateStore] = None, seed: Optional[int] = None):
        self.state = state or StateStore()
        self.planner = None  # optional override
        self.plans: list[Plan] = []
        self.evals: list[Evaluation] = []
        self.create_evals: list[Evaluation] = []
        self.reblock_evals: list[Evaluation] = []
        self._next_index = 1
        self._lock = threading.Lock()
        self.seed = seed

    def next_index(self) -> int:
        with self._lock:
            idx = self._next_index
            self._next_index += 1
            return idx

    # -- Planner interface -------------------------------------------------
    def submit_plan(self, plan: Plan):
        """Apply the plan directly against the state store
        (ref testing.go:70-128)."""
        self.plans.append(plan)
        if self.planner is not None:
            return self.planner.submit_plan(plan)

        index = self.next_index()
        result = PlanResult(
            node_update=plan.node_update,
            node_allocation=plan.node_allocation,
            node_preemptions=plan.node_preemptions,
            deployment=plan.deployment,
            deployment_updates=plan.deployment_updates,
            alloc_index=index,
        )
        self.state.upsert_plan_results(index, plan, result)
        return result, None

    def update_eval(self, eval: Evaluation):
        self.evals.append(eval)
        if self.planner is not None:
            self.planner.update_eval(eval)

    def create_eval(self, eval: Evaluation):
        self.create_evals.append(eval)
        if self.planner is not None:
            self.planner.create_eval(eval)

    def reblock_eval(self, eval: Evaluation):
        self.reblock_evals.append(eval)
        if self.planner is not None:
            self.planner.reblock_eval(eval)

    # -- Driving -----------------------------------------------------------
    def snapshot(self):
        return self.state.snapshot()

    def process(self, factory_name: str, eval: Evaluation):
        """Create a scheduler against a snapshot and process the eval
        (ref testing.go:260-270)."""
        from .scheduler import new_scheduler

        rng = random.Random(self.seed) if self.seed is not None else None
        sched = new_scheduler(factory_name, self.snapshot(), self, rng=rng)
        sched.process(eval)
        return sched
