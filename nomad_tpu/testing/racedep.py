"""Runtime data-race witness: Eraser locksets over watched attributes.

The static pass (``nomad_tpu/analysis/racegraph.py``) derives which
shared attributes CAN race; this witness watches what threads ACTUALLY
do to a curated set of those attributes under tier-1 and applies the
Eraser lockset discipline (Savage et al., SOSP '97) per
``(instance, attribute)``:

- **virgin → exclusive** — the first accessing thread owns the value;
  no lockset refinement (initialization-before-publication is legal);
- **exclusive → shared** — a second thread touches it: the candidate
  lockset ``C(v)`` starts as the locks that thread holds *right now*
  (read from lockdep's per-thread held stack — the two witnesses share
  one ground truth, keyed by lock allocation site);
- **shared** — every access refines ``C(v) ∩= held``; when the
  attribute has been written in the shared state and ``C(v)`` goes
  empty, that is a race: no single lock protected every access.

Mechanics: :func:`install` patches each watched class's
``__setattr__`` (write witnessing on every assignment to a watched
attribute) and installs a property over each *declared hot read*
attribute (read witnessing at, e.g., a stats()/dump() site). Watched
classes must use instance ``__dict__`` storage — ``__slots__`` classes
(e.g. the mux's ``_Conn``) are not instrumentable this way and are
excluded by construction.

Scope decisions (documented, deliberate):

- races are RECORDED, never raised from the access path (raising inside
  arbitrary attribute writes can corrupt the code under test); the
  tier-1 conftest asserts ``races() == []`` after every test,
  mirroring the lockdep guard;
- both sides of a race are captured: the previous write's
  thread/location line (kept per attribute at every write — one frame
  walk, cheap) and the detecting access's full stack;
- one report per ``(class, attribute)`` — after the first race the
  record is parked so a hot racy counter cannot flood the report or
  tax the run;
- write-only watching (no read property) is for attributes whose
  unlocked reads are *deliberate* benign staleness (e.g. the broker's
  ``lag_stats`` sampling ``delivered_index``): the witness then checks
  that writes stay under a consistent lockset without indicting the
  sanctioned dirty reads.

Enable AFTER the watched modules import (the classes must exist) and
ideally with lockdep installed first — without lockdep every held
lockset reads empty and any second-thread write looks like a race.
``tests/conftest.py`` wires both; opt out with ``NOMAD_TPU_RACEDEP=0``.
"""

from __future__ import annotations

import _thread
import os
import sys
import threading
import traceback

from . import lockdep

#: raw lock guarding the shared-state transitions and the race report
#: list (never held across anything blocking)
_state_lock = _thread.allocate_lock()

#: human-readable race reports, in observation order
_races: list = []
#: (class_qual, attr) already reported — dedupe + parking
_reported: set = set()

#: Eraser states (virgin is "no record yet")
_EXCLUSIVE = 0
_SHARED_READ = 1
_SHARED_MOD = 2

_installed = False
#: cls -> (orig __setattr__, {attr: orig class attr or _MISSING}) for
#: uninstall
_patched: dict = {}

_MISSING = object()

#: the instance-state slot name (stored via object.__setattr__, so the
#: patched __setattr__ never recurses through it)
_STATE = "_racedep_state"


def _class_qual(cls) -> str:
    mod = cls.__module__ or ""
    if mod.startswith("nomad_tpu."):
        mod = mod[len("nomad_tpu.") :]
    return f"{mod}.{cls.__qualname__}"


def _where() -> str:
    f = sys._getframe(2)
    while f is not None and f.f_code.co_filename == __file__:
        f = f.f_back
    if f is None:
        return "<unknown>"
    fn = f.f_code.co_filename.replace(os.sep, "/").rsplit("/", 1)[-1]
    return (
        f"{threading.current_thread().name} at "
        f"{fn}:{f.f_lineno} ({f.f_code.co_name})"
    )


def _stack() -> str:
    out = []
    for line in traceback.format_stack(sys._getframe(2)):
        if __file__ in line:
            continue
        out.append(line.rstrip())
    return "\n".join(out[-12:])


def _note(obj, cls_qual: str, attr: str, is_write: bool):
    """One witnessed access. Fast paths (virgin, exclusive-owner) touch
    only the per-instance record; shared-state refinement and race
    recording serialize on ``_state_lock``."""
    state = obj.__dict__.get(_STATE)
    if state is None:
        state = {}
        object.__setattr__(obj, _STATE, state)
    ident = _thread.get_ident()
    rec = state.get(attr)
    if rec is None:
        # virgin → exclusive: first accessor owns it, no refinement
        state[attr] = [
            _EXCLUSIVE,
            ident,
            None,
            _where() if is_write else None,
        ]
        return
    if rec[0] == _EXCLUSIVE and rec[1] == ident:
        if is_write:
            rec[3] = _where()
        return
    if (cls_qual, attr) in _reported:
        return  # parked: one report per (class, attr)
    held = frozenset(lockdep.held_sites())
    with _state_lock:
        if rec[0] == _EXCLUSIVE:
            # second thread: C(v) starts as what it holds right now
            rec[0] = _SHARED_MOD if is_write else _SHARED_READ
            rec[2] = held
        else:
            rec[2] = rec[2] & held
            if is_write:
                rec[0] = _SHARED_MOD
        racy = rec[0] == _SHARED_MOD and not rec[2]
        if racy and (cls_qual, attr) not in _reported:
            _reported.add((cls_qual, attr))
            prev = rec[3] or "<no prior write witnessed>"
            _races.append(
                f"data race on {cls_qual}.{attr}: lockset empty at "
                f"{_where()} (previous write: {prev})\n"
                f"  access stack:\n{_stack()}"
            )
        if is_write:
            rec[3] = _where()


def _make_setattr(cls, watched: frozenset):
    orig = cls.__setattr__

    def __setattr__(self, name, value):
        if name in watched:
            _note(self, _class_qual(cls), name, True)
        orig(self, name, value)

    __setattr__._racedep = True
    return __setattr__


def _make_read_property(cls, attr: str):
    """Data descriptor witnessing reads of ``attr``; storage stays in
    the instance ``__dict__`` (the property outranks it for lookups,
    but writes go through the patched ``__setattr__`` → ``fset``)."""
    qual = _class_qual(cls)

    def fget(self):
        try:
            value = self.__dict__[attr]
        except KeyError:
            raise AttributeError(attr) from None
        _note(self, qual, attr, False)
        return value

    def fset(self, value):
        # the write was already noted by the patched __setattr__ (every
        # ``obj.attr = v`` routes through it before reaching fset)
        self.__dict__[attr] = value

    return property(fget, fset)


def watch_class(cls, write_attrs, read_attrs=()):
    """Instrument ``cls``: witness writes to ``write_attrs`` (plus
    ``read_attrs`` — every read attr is write-witnessed too) and reads
    of ``read_attrs``. Idempotent per class; used by :func:`install`
    for the default watchlist and directly by provocation tests."""
    if cls in _patched:
        return
    if getattr(cls, "__slots__", None) is not None:
        raise TypeError(
            f"{cls.__qualname__} uses __slots__ — racedep needs "
            "instance __dict__ storage"
        )
    watched = frozenset(write_attrs) | frozenset(read_attrs)
    saved: dict = {}
    for attr in read_attrs:
        saved[attr] = cls.__dict__.get(attr, _MISSING)
        setattr(cls, attr, _make_read_property(cls, attr))
    orig_setattr = cls.__dict__.get("__setattr__", _MISSING)
    cls.__setattr__ = _make_setattr(cls, watched)
    _patched[cls] = (orig_setattr, saved)


def unwatch_class(cls):
    """Remove instrumentation from one class (test cleanup for ad-hoc
    :func:`watch_class` targets). No-op when the class isn't watched."""
    if cls in _patched:
        _unwatch_class(cls)


def _unwatch_class(cls):
    orig_setattr, saved = _patched.pop(cls)
    if orig_setattr is _MISSING:
        del cls.__setattr__
    else:
        cls.__setattr__ = orig_setattr
    for attr, orig in saved.items():
        if orig is _MISSING:
            delattr(cls, attr)
        else:
            setattr(cls, attr, orig)


def _default_watchlist():
    """The curated tier-1 set: attributes the racegraph proved shared
    across thread classes, fixed in this plane, and cheap to witness.
    Imported lazily so racedep itself stays import-light."""
    from ..core.broker import EvalBroker
    from ..core.overload import AdmissionController
    from ..debug.flight import FlightRecorder
    from ..events.broker import Subscription
    from ..events.mux import StreamMux

    return [
        # admit() counters: handler threads write, stats()/flight read
        (AdmissionController, ("admitted",), ("admitted",)),
        # pump-thread counters vs stats() readers
        (StreamMux, ("dropped", "served"), ("dropped",)),
        # write-only: lag_stats() reads are sanctioned benign staleness
        (Subscription, ("delivered_index", "_closed"), ()),
        # write-only: enabled reads are deliberate dirty checks; the
        # set_enabled transition itself must stay under _enabled_lock
        (EvalBroker, ("enabled",), ()),
        # sampler-thread error count vs dump()
        (FlightRecorder, ("errors",), ("errors",)),
    ]


def install():
    """Instrument the default watchlist. Instances created before
    install still witness (state rides the instance lazily); attributes
    set before install simply start their Eraser life at the next
    access."""
    global _installed
    if _installed:
        return
    _installed = True
    for cls, w, r in _default_watchlist():
        watch_class(cls, w, r)


def uninstall():
    global _installed
    if not _installed and not _patched:
        return
    _installed = False
    for cls in list(_patched):
        _unwatch_class(cls)


def installed() -> bool:
    return _installed


def reset():
    """Drop recorded races and reporting state (test isolation). The
    per-instance Eraser records live on the instances and die with
    them."""
    with _state_lock:
        del _races[:]
        _reported.clear()


def races() -> list:
    with _state_lock:
        return list(_races)


def race_count() -> int:
    return len(_races)


def race_keys() -> list:
    """The ``(class_qual, attr)`` identity keys of every recorded race
    — what the static cross-validation joins on."""
    with _state_lock:
        return sorted(_reported)


def check():
    """Raise AssertionError when any race has been observed."""
    r = races()
    if r:
        raise AssertionError(
            "racedep observed data races:\n" + "\n\n".join(r)
        )
