"""Scored multichip bench: MULTICHIP graduates from dry-run to timings.

The driver's dryrun (``__graft_entry__.dryrun_multichip``) proves the
sharded program compiles and matches the unsharded placements once, at
toy scale, and its artifact carried only ``ok``/``rc`` plus a stderr
tail drowned in XLA CPU-AOT machine-feature warnings. This module is
the graduated harness:

- :func:`bench_multichip` runs ALL THREE planners (exact scan, runs,
  windowed) unsharded AND mesh-sharded at an env-scalable size
  (``MULTICHIP_NODES`` / ``MULTICHIP_ALLOCS`` / ``MULTICHIP_DEVICES``),
  timing each arm after an untimed warm pass, pinning sharded ==
  unsharded placements value-for-value, and counting recompiles in the
  timed window (must be 0 after warmup);
- :func:`write_artifact` emits ``MULTICHIP_rNN.json`` (next free round
  number) with the timings, parity counts and a **noise-filtered,
  capped** stderr tail — the known XLA CPU-AOT loader warnings are
  dropped so the field carries signal (the r05 artifact's tail was
  ~95% machine-feature spam);
- ``python -m nomad_tpu.tpu.multichip`` is the CLI
  (scripts/multichip.sh wraps it with the 8-virtual-device CPU env).

The synthetic cluster builders here are THE definition the sharded
tests (tests/test_multichip.py) import, so bench and test clusters can
never drift.
"""

from __future__ import annotations

import contextlib
import glob
import json
import os
import re
import time

import numpy as np

#: default bench scale — big enough that the node axis crosses every
#: shard (8 shards × 256 rows) yet friendly to a single-core CPU mesh
#: (collectives on virtual devices serialize; a few minutes end-to-end);
#: MULTICHIP_NODES/ALLOCS scale it up, and the real headline scale rides
#: bench.py's sharded section on real devices instead
DEFAULT_NODES = int(os.environ.get("MULTICHIP_NODES", "2048"))
DEFAULT_ALLOCS = int(os.environ.get("MULTICHIP_ALLOCS", "512"))
DEFAULT_DEVICES = int(os.environ.get("MULTICHIP_DEVICES", "8"))

#: stderr lines matching any of these are known environment noise, not
#: signal: XLA's CPU AOT loader warning (per cache entry!) that the
#: compile machine's feature flags differ from the host's, plus absl's
#: pre-init log banner. Kept specific — an unknown error line must
#: never be filtered into silence.
NOISE_PATTERNS = (
    r"cpu_aot_loader",
    r"Loading XLA:CPU AOT result",
    r"machine features?: \[",
    r"This could lead to execution errors such as SIGILL",
    r"WARNING: All log messages before absl::InitializeLog",
    r"external/org_tensorflow",
)

#: hard cap on the artifact's tail field (chars, post-filter)
TAIL_CAP = 2000

_NOISE_RE = re.compile("|".join(NOISE_PATTERNS))


def filter_noise_tail(text: str, cap: int = TAIL_CAP) -> str:
    """Drop known-noise stderr lines and cap the result to its LAST
    ``cap`` characters (the tail end is where a real failure prints)."""
    kept = [ln for ln in text.splitlines() if ln and not _NOISE_RE.search(ln)]
    out = "\n".join(kept)
    if len(out) > cap:
        out = out[-cap:]
        # never start mid-line after the cut
        nl = out.find("\n")
        if 0 <= nl < len(out) - 1:
            out = out[nl + 1:]
    return out


@contextlib.contextmanager
def capture_stderr_fd():
    """Capture fd-2 writes (XLA logs from C++ bypass sys.stderr) into a
    temp file; yields a callable returning what was captured so far."""
    import tempfile

    saved = os.dup(2)
    tmp = tempfile.TemporaryFile(mode="w+b")
    os.dup2(tmp.fileno(), 2)
    try:
        def read() -> str:
            os.fsync(2)
            tmp.seek(0)
            return tmp.read().decode("utf-8", "replace")

        yield read
    finally:
        os.dup2(saved, 2)
        os.close(saved)
        tmp.close()


# ---------------------------------------------------------------------------
# synthetic cluster + per-planner args (shared with tests/test_multichip.py)
# ---------------------------------------------------------------------------


def build_cluster(n_nodes: int, n_allocs: int, n_values: int = 4, seed: int = 0):
    """Heterogeneous capacities, ~10% infeasible nodes, spread classes —
    the seeded synthetic cluster every sharded test and bench arm plans
    against."""
    rng = np.random.default_rng(seed)
    capacity = np.stack(
        [
            rng.choice([4000, 8000, 16000, 32000], n_nodes),
            rng.choice([8192, 16384, 32768], n_nodes),
            # nta: ignore[shape-literal-unbucketed] WHY: resource VALUES
            # (disk MB / bandwidth), not tensor dims — the array shape is
            # (n_nodes,), which the callers bucket via shard.node_bucket
            np.full(n_nodes, 100 * 1024),
            np.full(n_nodes, 1000),
        ],
        axis=1,
    ).astype(np.int32)
    # nta: ignore[shape-literal-unbucketed] WHY: reserved-resource VALUES
    # per row, not a padded dimension
    reserved = np.tile(np.array([100, 256, 4096, 0], dtype=np.int32), (n_nodes, 1))
    usable = (capacity[:, :2] - reserved[:, :2]).astype(np.float32)
    feasible = rng.random(n_nodes) > 0.1
    node_value = (np.arange(n_nodes) % n_values).astype(np.int32)
    perm = rng.permutation(n_nodes).astype(np.int32)
    demand = np.array([100, 128, 10, 5], dtype=np.int32)
    return dict(
        capacity=capacity,
        reserved=reserved,
        usable=usable,
        feasible=feasible,
        node_value=node_value,
        perm=perm,
        demand=demand,
        n_allocs=n_allocs,
        n_values=n_values,
    )


def pad_cluster(c: dict, n_pad: int) -> dict:
    """Pad the node axis to ``n_pad`` rows (mesh-divisible sizes come
    from ``shard.node_bucket``): pad rows are infeasible, carry zero
    capacity and a poisoned ``reserved`` (2**30, the batch_sched pad
    convention) so no planner can ever place on one, and extend the
    rotation ring's tail ids. ``n_real`` records the true node count —
    the exact scan's ring size and the windowed planner's static bound
    keep using it, so the padding is invisible to the semantics (the
    contract the uneven-last-shard property test pins)."""
    n = c["capacity"].shape[0]
    if n_pad < n:
        raise ValueError(f"n_pad {n_pad} < real node count {n}")
    out = dict(c)
    out["n_real"] = n
    if n_pad == n:
        return out
    k = n_pad - n
    out["capacity"] = np.concatenate(
        [c["capacity"], np.zeros((k, c["capacity"].shape[1]), np.int32)]
    )
    out["reserved"] = np.concatenate(
        [c["reserved"], np.full((k, c["reserved"].shape[1]), 2**30, np.int32)]
    )
    out["usable"] = np.concatenate([c["usable"], np.ones((k, 2), np.float32)])
    out["feasible"] = np.concatenate([c["feasible"], np.zeros(k, bool)])
    out["node_value"] = np.concatenate(
        [c["node_value"], np.full(k, -1, np.int32)]
    )
    out["perm"] = np.concatenate(
        [c["perm"], np.arange(n, n_pad, dtype=np.int32)]
    )
    return out


def exact_problem(c, spread: bool = True):
    """(BatchArgs, BatchState) for the exact sequential-scan planner."""
    from .kernel import BatchArgs, BatchState

    n_nodes = c["capacity"].shape[0]
    n_real = c.get("n_real", n_nodes)
    n_allocs = c["n_allocs"]
    V = c["n_values"]
    args = BatchArgs(
        capacity=c["capacity"],
        usable=c["usable"],
        feasible=c["feasible"][None, :],
        affinity=np.zeros((1, n_nodes), dtype=np.float32),
        affinity_present=np.zeros((1, n_nodes), dtype=bool),
        group_count=np.full(1, n_allocs, dtype=np.int32),
        group_eval=np.zeros(1, dtype=np.int32),
        node_value=c["node_value"][None, :],
        spread_desired=np.full(
            (1, V), float(n_allocs) / V if spread else -1.0, dtype=np.float32
        ),
        spread_implicit=np.full(1, -1.0, dtype=np.float32),
        spread_weight_frac=np.ones(1, dtype=np.float32),
        spread_even=np.zeros(1, dtype=bool),
        spread_active=np.full(1, spread, dtype=bool),
        perm=c["perm"][None, :],
        ring=np.array([n_real], dtype=np.int32),
        demands=np.tile(c["demand"], (n_allocs, 1)),
        groups=np.zeros(n_allocs, dtype=np.int32),
        limits=np.full(n_allocs, n_nodes, dtype=np.int32),
        valid=np.ones(n_allocs, dtype=bool),
    )
    init = BatchState(
        used=c["reserved"].copy(),
        collisions=np.zeros((1, n_nodes), dtype=np.int32),
        spread_counts=np.zeros((1, V), dtype=np.int32),
        spread_present=np.zeros((1, V), dtype=bool),
        offset=np.zeros(1, dtype=np.int32),
    )
    return args, init


def wavefront_problem(c, n_groups: int = 32, spread: bool = True,
                      overlap: int = 16):
    """(BatchArgs, BatchState) for the wavefront planner's scored
    section: a multi-tenant batch of ``n_groups`` independent groups in
    interleaved submission order, each feasible on a mostly-disjoint
    slice of the cluster (every ``overlap``-th node is shared with the
    next group, so real conflicts exist without dominating), full-ring
    limits, per-group demands and spread. This is the drain-shaped
    workload the wavefront decomposition targets: the sequential scan
    pays one cross-shard collective round per placement here even though
    consecutive allocs cannot interact, while the wavefront commits ~W
    conflict-free placements per round. The SAME (args, init) drive the
    sequential oracle, so parity is pinned on this exact problem.

    NOTE the single-group ``exact_problem`` is the wavefront's designed
    worst case — every alloc shares one feasible set, so exactness
    forces one commit per round. That regime belongs to the runs
    planner's fill/sweep trajectories; the wavefront's win condition is
    multi-tenant independence, which is why this builder exists."""
    from .kernel import BatchArgs, BatchState

    n_nodes = c["capacity"].shape[0]
    n_real = c.get("n_real", n_nodes)
    n_allocs = c["n_allocs"]
    V = c["n_values"]
    G = int(n_groups)
    ids = np.arange(n_nodes)
    gid = np.arange(G)
    # contiguous 8-node blocks round-robin across groups; every
    # ``overlap``-th node is additionally feasible for the NEXT group
    slice_of = (ids // 8) % G
    base = slice_of[None, :] == gid[:, None]
    if overlap:
        shared = ids % max(int(overlap), 1) == 0
        base = base | (
            shared[None, :] & (((slice_of + 1) % G)[None, :] == gid[:, None])
        )
    feasible = base & c["feasible"][None, :]
    groups = (np.arange(n_allocs) % G).astype(np.int32)
    group_count = np.bincount(groups, minlength=G).astype(np.int32)
    # per-group demand tiers (1x/2x/3x the base ask)
    scale = (1 + groups % 3).astype(np.int32)
    demands = c["demand"][None, :] * scale[:, None]
    args = BatchArgs(
        capacity=c["capacity"],
        usable=c["usable"],
        feasible=feasible,
        affinity=np.zeros((G, n_nodes), dtype=np.float32),
        affinity_present=np.zeros((G, n_nodes), dtype=bool),
        group_count=np.maximum(group_count, 1),
        group_eval=np.zeros(G, dtype=np.int32),
        node_value=np.tile(c["node_value"], (G, 1)),
        spread_desired=np.tile(
            np.full(
                (1, V),
                float(max(int(group_count.max()), 1)) / V if spread else -1.0,
                dtype=np.float32,
            ),
            (G, 1),
        ),
        spread_implicit=np.full(G, -1.0, dtype=np.float32),
        spread_weight_frac=np.ones(G, dtype=np.float32),
        spread_even=np.zeros(G, dtype=bool),
        spread_active=np.full(G, spread, dtype=bool),
        perm=c["perm"][None, :],
        ring=np.array([n_real], dtype=np.int32),
        demands=demands.astype(np.int32),
        groups=groups,
        limits=np.full(n_allocs, n_nodes, dtype=np.int32),
        valid=np.ones(n_allocs, dtype=bool),
    )
    init = BatchState(
        used=c["reserved"].copy(),
        collisions=np.zeros((G, n_nodes), dtype=np.int32),
        spread_counts=np.zeros((G, V), dtype=np.int32),
        spread_present=np.zeros((G, V), dtype=bool),
        offset=np.zeros(1, dtype=np.int32),
    )
    return args, init


def runs_problem(c, affinity: bool = True, spread: bool = True):
    """(RunArgs, init tuple) for the run-based full-ring planner, in
    rotation order."""
    from .kernel import RunArgs

    n_nodes = c["capacity"].shape[0]
    V = c["n_values"]
    perm = c["perm"]
    aff = (
        np.where(np.arange(n_nodes) % 5 == 0, 0.5, 0.0).astype(np.float32)
        if affinity
        else np.zeros(n_nodes, dtype=np.float32)
    )
    args = RunArgs(
        capacity=c["capacity"][perm],
        usable=c["usable"][perm],
        feasible=c["feasible"][perm],
        affinity=aff[perm],
        affinity_present=(aff > 0)[perm],
        group_count=np.int32(c["n_allocs"]),
        node_value=c["node_value"][perm],
        spread_desired=np.full(
            V, float(c["n_allocs"]) / V if spread else -1.0, dtype=np.float32
        ),
        spread_implicit=np.float32(-1.0),
        spread_weight_frac=np.float32(1.0),
        spread_even=np.bool_(False),
        spread_active=np.bool_(spread),
        perm=perm,
        demand=c["demand"],
        n_allocs=np.int32(c["n_allocs"]),
    )
    init = (
        c["reserved"][perm].copy(),
        np.zeros(n_nodes, dtype=np.int32),
        np.zeros(V, dtype=np.int32),
        np.zeros(V, dtype=bool),
    )
    return args, init


def window_problem(c, limit: int = 10):
    """(WindowArgs, used0, collisions0) for the windowed planner."""
    from .kernel import WindowArgs

    n_nodes = c["capacity"].shape[0]
    args = WindowArgs(
        capacity=c["capacity"],
        usable=c["usable"],
        feasible=c["feasible"],
        perm=c["perm"],
        demand=c["demand"],
        group_count=np.int32(c["n_allocs"]),
        limit=np.int32(limit),
        n_allocs=np.int32(c["n_allocs"]),
    )
    return args, c["reserved"].copy(), np.zeros(n_nodes, dtype=np.int32)


# ---------------------------------------------------------------------------
# the scored bench
# ---------------------------------------------------------------------------


def _time_best(fn, samples: int = 2) -> float:
    best = None
    for _ in range(samples):
        t0 = time.monotonic()
        fn()
        dt = time.monotonic() - t0
        best = dt if best is None or dt < best else best
    return best


def bench_multichip(
    n_devices: int = DEFAULT_DEVICES,
    n_nodes: int = DEFAULT_NODES,
    n_allocs: int = DEFAULT_ALLOCS,
    seed: int = 0,
    samples: int = 2,
) -> dict:
    """Run all three planners unsharded and mesh-sharded; returns the
    scored report (no I/O — :func:`write_artifact` persists it)."""
    import jax.numpy as jnp

    from . import shard
    from .kernel import (
        compile_cache_size,
        plan_batch,
        plan_batch_runs,
        plan_batch_windowed,
    )

    mesh = shard.configure(n_devices)
    if mesh is None:
        return {
            "n_devices": n_devices,
            "nodes": n_nodes,
            "allocs": n_allocs,
            "ok": False,
            "skipped": True,
            "reason": f"need {n_devices} devices",
        }

    # pad to the mesh-divisible node bucket so ANY env scale shards
    # (uneven real counts leave the padding on the last shard)
    c = pad_cluster(
        build_cluster(n_nodes, n_allocs, seed=seed),
        shard.node_bucket(n_nodes, mesh),
    )
    A = n_allocs
    planners: dict[str, dict] = {}

    def score(name, run_plain, run_sharded):
        from ..debug import devprof

        # production arms: warm (compiles, or loads from the persistent
        # cache), then timed best-of-N with the recompile pin
        want = np.asarray(run_plain())
        got_warm = np.asarray(run_sharded())
        t_plain = _time_best(lambda: np.asarray(run_plain()), samples)
        cache0 = compile_cache_size()
        # per-planner comm breakdown: the devprof round counter diffed
        # around the sharded timed arm gives this planner's collective
        # rounds per dispatch — the number the wavefront rewrite (item
        # 2) must push from ~placements toward placements/K
        rounds0 = devprof.rounds_snapshot().get(name, {})
        t_shard = _time_best(lambda: np.asarray(run_sharded()), samples)
        rounds1 = devprof.rounds_snapshot().get(name, {})
        cache1 = compile_cache_size()

        def _delta(key):
            return rounds1.get(key, 0) - rounds0.get(key, 0)

        s_disp = _delta("sharded_dispatches")
        s_rounds = _delta("sharded_rounds")
        s_place = _delta("sharded_placements")
        census = {}
        for e in devprof.snapshot()["compile_ledger"]:
            if e["planner"] == name and e["sharded"] and e["collectives"]:
                census = e["collectives"]
                break
        got = np.asarray(run_sharded())
        placed = int((want >= 0).sum())
        # fast-pair agreement (informational): two different fused
        # compilations may legally disagree on sub-ulp score ties
        fast_agree = int((want == got).sum())
        # THE parity pin rides the deterministic compile flavor
        # (kernel.DET_COMPILER_OPTIONS): bit-identical by construction,
        # so any mismatch is a real GSPMD semantics regression
        from .kernel import deterministic_scope

        parity_mode = "deterministic"
        try:
            with deterministic_scope():
                det_want = np.asarray(run_plain())
                det_got = np.asarray(run_sharded())
        except Exception as e:  # backend without the det flavor:
            # degrade to the fast pair, visibly
            parity_mode = f"fast pair (det flavor failed: {e})"
            det_want, det_got = want, got
        matched = int((det_want == det_got).sum())
        planners[name] = {
            "unsharded_s": round(t_plain, 4),
            "sharded_s": round(t_shard, 4),
            "speedup": round(t_plain / t_shard, 3) if t_shard else None,
            "placed": placed,
            "parity": round(matched / max(len(det_want), 1), 6),
            "parity_checked": int(len(det_want)),
            "parity_mode": parity_mode,
            "fast_pair_agreement": round(
                fast_agree / max(len(want), 1), 6
            ),
            "recompiles": (
                cache1 - cache0 if cache0 >= 0 and cache1 >= 0 else None
            ),
            "warm_equal": bool(np.array_equal(want, got_warm)),
            # device-plane comm breakdown (debug/devprof.py):
            # mesh_comm_frac = the sharded wall clock in EXCESS of the
            # unsharded program — comm + partitioning overhead, exact
            # when per-shard compute is free and tight on a single-core
            # virtual mesh where compute can't parallelize at all
            "mesh_comm_frac": devprof.mesh_comm_frac(t_plain, t_shard),
            "collective_rounds": (
                round(s_rounds / s_disp) if s_disp else None
            ),
            "collective_rounds_per_placement": (
                round(s_rounds / s_place, 4) if s_place else None
            ),
            "collective_census": census,
        }

    n_real = c.get("n_real", n_nodes)

    # exact sequential scan
    bargs, binit = exact_problem(c)
    baspec, bsspec = shard.batch_specs()
    b_plain_args = tuple(jnp.asarray(a) for a in bargs)
    b_plain_init = tuple(jnp.asarray(s) for s in binit)
    b_shard_args = shard.put(bargs, baspec, mesh)
    b_shard_init = shard.put(binit, bsspec, mesh)
    score(
        "exact",
        lambda: plan_batch(
            type(bargs)(*b_plain_args), type(binit)(*b_plain_init), n_real
        )[1],
        lambda: plan_batch(b_shard_args, b_shard_init, n_real)[1],
    )

    # run-based full-ring planner (the spread/affinity headline path)
    rargs, rinit = runs_problem(c)
    raspec, rispec = shard.run_specs()
    r_plain_args = type(rargs)(*[jnp.asarray(a) for a in rargs])
    r_plain_init = tuple(jnp.asarray(x) for x in rinit)
    r_shard_args = shard.put(rargs, raspec, mesh)
    r_shard_init = shard.put(rinit, rispec, mesh)
    score(
        "runs",
        lambda: plan_batch_runs(r_plain_args, r_plain_init, A, False),
        lambda: plan_batch_runs(r_shard_args, r_shard_init, A, False),
    )

    # rotation-parallel windowed planner
    wargs, wused0, wcoll0 = window_problem(c)
    waspec, (wuspec, wcspec) = shard.window_specs()
    w_plain = (
        type(wargs)(*[jnp.asarray(a) for a in wargs]),
        jnp.asarray(wused0),
        jnp.asarray(wcoll0),
    )
    w_shard = (
        shard.put(wargs, waspec, mesh),
        shard.put(wused0, wuspec, mesh),
        shard.put(wcoll0, wcspec, mesh),
    )
    score(
        "windowed",
        lambda: plan_batch_windowed(w_plain[0], w_plain[1], w_plain[2],
                                    n_real, A),
        lambda: plan_batch_windowed(w_shard[0], w_shard[1], w_shard[2],
                                    n_real, A),
    )

    # wavefront conflict-free batched commits (tpu/wavefront.py): the
    # sequential fill loop stays THE oracle — run_plain is plan_batch on
    # the SAME (args, init), so score()'s deterministic parity pin
    # proves the wavefront reproduces the sequential placements
    # bit-for-bit while its crpp column shows the mesh cost dropping
    # from one collective round per placement toward per-ROUND.
    # MULTICHIP_WAVEFRONT=0 skips the section.
    if os.environ.get("MULTICHIP_WAVEFRONT", "1") not in ("0", ""):
        from . import wavefront as _wavefront

        fargs, finit = wavefront_problem(c)
        faspec, fsspec = shard.wavefront_specs()
        f_plain_args = type(fargs)(*[jnp.asarray(a) for a in fargs])
        f_plain_init = type(finit)(*[jnp.asarray(s) for s in finit])
        f_shard_args = shard.put(fargs, faspec, mesh)
        f_shard_init = shard.put(finit, fsspec, mesh)
        n_shards = shard.mesh_size(mesh)
        score(
            "wavefront",
            lambda: plan_batch(f_plain_args, f_plain_init, n_real)[1],
            lambda: _wavefront.plan_batch_wavefront(
                f_shard_args, f_shard_init, n_real, n_shards=n_shards
            )[1],
        )
        # the honest tentpole measure on a single-core virtual mesh
        # (where sharded-vs-unsharded can never win on wall clock):
        # sharded sequential vs sharded wavefront on the SAME args —
        # the dispatch/collective count is all that differs
        t_seq_sharded = _time_best(
            lambda: np.asarray(
                plan_batch(f_shard_args, f_shard_init, n_real)[1]
            ),
            samples,
        )
        wf = planners["wavefront"]
        wf["sequential_sharded_s"] = round(t_seq_sharded, 4)
        wf["wavefront_speedup"] = (
            round(t_seq_sharded / wf["sharded_s"], 3)
            if wf["sharded_s"] else None
        )

    # the contract: deterministic-pair parity 1.0 with real placements.
    # fast_pair_agreement/warm_equal stay informational — two fused
    # compilations may legally disagree on sub-ulp score ties.
    ok = all(
        p["parity"] == 1.0 and p["placed"] > 0 for p in planners.values()
    )
    # headline comm aggregates: overall mesh_comm_frac over the summed
    # arm pairs, total collective rounds per full planner sweep — the
    # MULTICHIP_SUMMARY keys ROADMAP item 2's PR will be judged against
    from ..debug import devprof as _devprof

    t_plain_total = sum(p["unsharded_s"] for p in planners.values())
    t_shard_total = sum(p["sharded_s"] for p in planners.values())
    comm_frac = _devprof.mesh_comm_frac(t_plain_total, t_shard_total)
    rounds_total = sum(
        p["collective_rounds"] or 0 for p in planners.values()
    )
    return {
        "n_devices": n_devices,
        "nodes": n_nodes,
        "allocs": n_allocs,
        "seed": seed,
        "samples": samples,
        "planners": planners,
        "mesh_comm_frac": comm_frac,
        "collective_rounds": rounds_total,
        "devprof": _devprof.summary(),
        "ok": ok,
        "skipped": False,
    }


def next_artifact_path(root: str = None) -> str:
    """The next free ``MULTICHIP_rNN.json`` round slot under ``root``."""
    if root is None:
        root = os.path.dirname(os.path.dirname(os.path.dirname(
            os.path.abspath(__file__))))
    taken = []
    for p in glob.glob(os.path.join(root, "MULTICHIP_r*.json")):
        m = re.search(r"MULTICHIP_r(\d+)\.json$", p)
        if m:
            taken.append(int(m.group(1)))
    nn = max(taken, default=0) + 1
    return os.path.join(root, f"MULTICHIP_r{nn:02d}.json")


def write_artifact(report: dict, tail: str = "", path: str = None) -> str:
    """Persist the scored report with a noise-filtered, capped tail."""
    path = path or next_artifact_path()
    report = dict(report)
    report["tail"] = filter_noise_tail(tail)
    with open(path, "w") as f:
        json.dump(report, f, indent=1)
        f.write("\n")
    return path


def summary_line(report: dict) -> str:
    """One greppable line (the artifact's headline must survive a
    truncated log tail — same contract as BENCH_SUMMARY)."""
    if report.get("skipped"):
        return f"MULTICHIP_SUMMARY skipped=1 reason={report.get('reason')}"
    parts = [
        f"devices={report['n_devices']}",
        f"nodes={report['nodes']}",
        f"allocs={report['allocs']}",
        f"ok={int(report['ok'])}",
    ]
    if "mesh_comm_frac" in report:
        parts.append(f"mesh_comm_frac={report['mesh_comm_frac']}")
        parts.append(f"collective_rounds={report['collective_rounds']}")
    for name, p in report.get("planners", {}).items():
        line = (
            f"{name}={p['sharded_s']}s/x{p['speedup']}"
            f"/parity{p['parity']}/rc{p['recompiles']}"
        )
        if p.get("collective_rounds_per_placement") is not None:
            line += f"/crpp{p['collective_rounds_per_placement']}"
        if p.get("wavefront_speedup") is not None:
            line += f"/wfx{p['wavefront_speedup']}"
        parts.append(line)
    return "MULTICHIP_SUMMARY " + " ".join(parts)


def main(argv=None) -> int:
    import argparse

    ap = argparse.ArgumentParser(
        description="scored multichip bench (writes MULTICHIP_rNN.json)"
    )
    ap.add_argument("--devices", type=int, default=DEFAULT_DEVICES)
    ap.add_argument("--nodes", type=int, default=DEFAULT_NODES)
    ap.add_argument("--allocs", type=int, default=DEFAULT_ALLOCS)
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--out", default=None, help="artifact path override")
    ap.add_argument(
        "--no-artifact", action="store_true",
        help="print the report, write nothing",
    )
    args = ap.parse_args(argv)

    with capture_stderr_fd() as read_tail:
        report = bench_multichip(
            n_devices=args.devices, n_nodes=args.nodes,
            n_allocs=args.allocs, seed=args.seed,
        )
        tail = read_tail()
    if not args.no_artifact:
        path = write_artifact(report, tail=tail, path=args.out)
        print(f"wrote {path}")
    else:
        print(json.dumps(report, indent=1))
    print(summary_line(report))
    return 0 if report.get("ok") or report.get("skipped") else 1


if __name__ == "__main__":
    raise SystemExit(main())
