"""Scheduler-util corpus ported from the reference
(scheduler/util_test.go — cited per test): the diff engines that decide
place/update/migrate/stop/ignore/lost, the taint/ready node sets, the
tasks_updated destructive-vs-inplace matrix, evict_and_place limits,
set_status, the in-place update path, and the queued-alloc bookkeeping.
"""

import random

import pytest

from nomad_tpu import mock
from nomad_tpu.scheduler.context import EvalContext
from nomad_tpu.scheduler.feasible import shuffle_nodes
from nomad_tpu.scheduler.stack import GenericStack, task_group_constraints
from nomad_tpu.scheduler.testing import Harness
from nomad_tpu.scheduler.util import (
    AllocTuple,
    DiffResult,
    adjust_queued_allocations,
    desired_updates,
    diff_allocs,
    diff_system_allocs,
    evict_and_place,
    generic_alloc_update_fn,
    materialize_task_groups,
    progress_made,
    retry_max,
    set_status,
    tainted_nodes,
    tasks_updated,
    update_non_terminal_allocs_to_lost,
)
from nomad_tpu.structs.model import (
    ALLOC_CLIENT_STATUS_COMPLETE,
    ALLOC_CLIENT_STATUS_FAILED,
    ALLOC_CLIENT_STATUS_RUNNING,
    ALLOC_DESIRED_STATUS_STOP,
    AllocatedCpuResources,
    AllocatedMemoryResources,
    AllocatedResources,
    AllocatedTaskResources,
    Allocation,
    Constraint,
    Deployment,
    DeploymentStatusUpdate,
    EphemeralDisk,
    Plan,
    PlanResult,
    Port,
    Resources,
    Service,
    Task,
    TaskGroup,
    Vault,
    generate_uuid,
)


def named_alloc(name, node_id, job):
    return Allocation(
        id=generate_uuid(), node_id=node_id, name=name, job=job,
        job_id=job.id, namespace=job.namespace,
    )


class TestMaterializeTaskGroupsPort:
    def test_expands_counts_into_named_slots(self):
        # ref TestMaterializeTaskGroups (util_test.go:23)
        job = mock.job()
        index = materialize_task_groups(job)
        assert len(index) == 10
        for i in range(10):
            assert index[f"my-job.web[{i}]"] is job.task_groups[0]

    def test_stopped_and_purged_jobs_materialize_nothing(self):
        job = mock.job()
        job.stop = True
        assert materialize_task_groups(job) == {}
        assert materialize_task_groups(None) == {}


class TestDiffAllocsPort:
    def test_full_diff_matrix(self):
        # ref TestDiffAllocs (util_test.go:42)
        job = mock.job()
        required = materialize_task_groups(job)
        old_job = job.copy()
        old_job.job_modify_index -= 1

        drain_node = mock.node()
        drain_node.drain = True
        dead_node = mock.node()
        dead_node.status = "down"
        tainted = {"dead": dead_node, "drainNode": drain_node}

        update0 = named_alloc("my-job.web[0]", "zip", old_job)
        ignore1 = named_alloc("my-job.web[1]", "zip", job)
        stop10 = named_alloc("my-job.web[10]", "zip", old_job)
        migrate2 = named_alloc("my-job.web[2]", "drainNode", old_job)
        migrate2.desired_transition.migrate = True
        lost3 = named_alloc("my-job.web[3]", "dead", old_job)
        allocs = [update0, ignore1, stop10, migrate2, lost3]

        terminal = {
            f"my-job.web[{i}]": named_alloc(f"my-job.web[{i}]", "zip", job)
            for i in (4, 5, 6)
        }

        diff = diff_allocs(job, tainted, required, allocs, terminal)
        assert [t.alloc for t in diff.update] == [update0]
        assert [t.alloc for t in diff.ignore] == [ignore1]
        assert [t.alloc for t in diff.stop] == [stop10]
        assert [t.alloc for t in diff.migrate] == [migrate2]
        assert [t.alloc for t in diff.lost] == [lost3]
        assert len(diff.place) == 6
        # replacements of terminal allocs carry the terminal alloc
        for tup in diff.place:
            if tup.name in terminal:
                assert tup.alloc is terminal[tup.name]


class TestDiffSystemAllocsPort:
    def test_per_node_diff(self):
        # ref TestDiffSystemAllocs (util_test.go:179)
        job = mock.system_job()
        old_job = job.copy()
        old_job.job_modify_index -= 1

        drain_node = mock.node()
        drain_node.drain = True
        dead_node = mock.node()
        dead_node.status = "down"
        tainted = {dead_node.id: dead_node, drain_node.id: drain_node}

        from nomad_tpu.structs.model import Node

        nodes = [
            Node(id="foo"), Node(id="bar"), Node(id="baz"),
            Node(id="pipe"), Node(id=drain_node.id), Node(id=dead_node.id),
        ]

        update_baz = named_alloc("my-job.web[0]", "baz", old_job)
        ignore_bar = named_alloc("my-job.web[0]", "bar", job)
        migrate_drain = named_alloc("my-job.web[0]", drain_node.id, old_job)
        migrate_drain.desired_transition.migrate = True
        lost_dead = named_alloc("my-job.web[0]", dead_node.id, old_job)
        allocs = [update_baz, ignore_bar, migrate_drain, lost_dead]

        terminal = {
            "my-job.web[0]": named_alloc("my-job.web[0]", "pipe", job)
        }

        diff = diff_system_allocs(job, nodes, tainted, allocs, terminal)
        assert [t.alloc for t in diff.update] == [update_baz]
        assert [t.alloc for t in diff.ignore] == [ignore_bar]
        assert diff.stop == []
        assert [t.alloc for t in diff.migrate] == [migrate_drain]
        assert [t.alloc for t in diff.lost] == [lost_dead]
        # foo and pipe get placements (bar/baz have allocs; tainted nodes
        # never get system placements)
        assert len(diff.place) == 2
        for tup in diff.place:
            if tup.alloc is not None and tup.alloc.node_id == "pipe":
                assert tup.alloc is terminal["my-job.web[0]"]


class TestNodeSetsPort:
    def _state(self):
        h = Harness(seed=42)
        n1 = mock.node()
        n2 = mock.node()
        n2.datacenter = "dc2"
        n3 = mock.node()
        n3.datacenter = "dc2"
        n3.status = "down"
        n4 = mock.node()
        n4.drain = True
        for i, n in enumerate((n1, n2, n3, n4)):
            h.state.upsert_node(1000 + i, n)
        return h, (n1, n2, n3, n4)

    def test_ready_nodes_in_dcs(self):
        # ref TestReadyNodesInDCs (util_test.go:299)
        h, (n1, n2, n3, n4) = self._state()
        nodes, dc = h.state.snapshot().ready_nodes_in_dcs(["dc1", "dc2"])
        assert len(nodes) == 2
        assert all(n.id not in (n3.id, n4.id) for n in nodes)
        assert dc == {"dc1": 1, "dc2": 1}

    def test_tainted_nodes(self):
        # ref TestTaintedNodes (util_test.go:379)
        h, (n1, n2, n3, n4) = self._state()
        allocs = [
            Allocation(node_id=n1.id), Allocation(node_id=n2.id),
            Allocation(node_id=n3.id), Allocation(node_id=n4.id),
            Allocation(node_id="12345678-abcd-efab-cdef-123456789abc"),
        ]
        tainted = tainted_nodes(h.state.snapshot(), allocs)
        assert len(tainted) == 3
        assert n1.id not in tainted and n2.id not in tainted
        assert tainted[n3.id] is not None
        assert tainted[n4.id] is not None
        # unknown node: present with None (treated as gone)
        assert tainted["12345678-abcd-efab-cdef-123456789abc"] is None


class TestRetryMaxPort:
    def test_retry_exhaustion_reset_and_success(self):
        # ref TestRetryMax (util_test.go:334)
        calls = [0]

        def bad():
            calls[0] += 1
            return False

        with pytest.raises(Exception):
            retry_max(3, bad, None)
        assert calls[0] == 3

        calls[0] = 0
        first = [True]

        def reset():
            if calls[0] == 3 and first[0]:
                first[0] = False
                return True
            return False

        with pytest.raises(Exception):
            retry_max(3, bad, reset)
        assert calls[0] == 6

        calls[0] = 0

        def good():
            calls[0] += 1
            return True

        retry_max(3, good, None)
        assert calls[0] == 1


class TestShuffleNodesPort:
    def test_seeded_shuffle_changes_order(self):
        # ref TestShuffleNodes (util_test.go:430)
        nodes = [mock.node() for _ in range(10)]
        orig = list(nodes)
        ctx = EvalContext(None, Plan(), rng=random.Random(7))
        shuffle_nodes(ctx, nodes)
        assert nodes != orig
        assert sorted(n.id for n in nodes) == sorted(n.id for n in orig)


class TestTasksUpdatedPort:
    """ref TestTasksUpdated (util_test.go:453): every change that must
    force a destructive update, plus the no-change baseline."""

    def test_identical_jobs_not_updated(self):
        j1, j2 = mock.job(), mock.job()
        assert not tasks_updated(j1, j2, j1.task_groups[0].name)

    def _changed(self, mutate):
        j1 = mock.job()
        j2 = mock.job()
        mutate(j2)
        return tasks_updated(j1, j2, j1.task_groups[0].name)

    def test_changed_command(self):
        assert self._changed(
            lambda j: j.task_groups[0].tasks[0].config.__setitem__(
                "command", "/bin/other"
            )
        )

    def test_changed_task_name(self):
        assert self._changed(
            lambda j: setattr(j.task_groups[0].tasks[0], "name", "foo")
        )

    def test_changed_driver(self):
        assert self._changed(
            lambda j: setattr(j.task_groups[0].tasks[0], "driver", "foo")
        )

    def test_added_task(self):
        assert self._changed(
            lambda j: j.task_groups[0].tasks.append(j.task_groups[0].tasks[0])
        )

    def test_changed_dynamic_ports(self):
        def mutate(j):
            j.task_groups[0].tasks[0].resources.networks[0].dynamic_ports = [
                Port(label="http"), Port(label="https"), Port(label="admin"),
            ]
        assert self._changed(mutate)

    def test_changed_env(self):
        assert self._changed(
            lambda j: j.task_groups[0].tasks[0].env.__setitem__(
                "NEW_ENV", "NEW_VALUE"
            )
        )

    def test_changed_user(self):
        assert self._changed(
            lambda j: setattr(j.task_groups[0].tasks[0], "user", "foo")
        )

    def test_changed_artifacts(self):
        from nomad_tpu.structs.model import TaskArtifact

        def mutate(j):
            j.task_groups[0].tasks[0].artifacts = [
                TaskArtifact(getter_source="http://foo.com/bar")
            ]
        assert self._changed(mutate)

    def test_changed_task_meta(self):
        assert self._changed(
            lambda j: j.task_groups[0].tasks[0].meta.__setitem__(
                "baz", "boom"
            )
        )

    def test_changed_cpu(self):
        assert self._changed(
            lambda j: setattr(j.task_groups[0].tasks[0].resources, "cpu", 1337)
        )

    def test_changed_mbits(self):
        assert self._changed(
            lambda j: setattr(
                j.task_groups[0].tasks[0].resources.networks[0], "mbits", 100
            )
        )

    def test_changed_dynamic_port_label(self):
        def mutate(j):
            j.task_groups[0].tasks[0].resources.networks[0].dynamic_ports[
                0
            ].label = "foobar"
        assert self._changed(mutate)

    def test_changed_reserved_ports(self):
        def mutate(j):
            j.task_groups[0].tasks[0].resources.networks[0].reserved_ports = [
                Port(label="foo", value=1312)
            ]
        assert self._changed(mutate)

    def test_changed_vault(self):
        assert self._changed(
            lambda j: setattr(
                j.task_groups[0].tasks[0], "vault", Vault(policies=["foo"])
            )
        )

    def test_changed_sticky_disk(self):
        assert self._changed(
            lambda j: setattr(j.task_groups[0].ephemeral_disk, "sticky", True)
        )

    def test_changed_group_meta(self):
        assert self._changed(
            lambda j: j.task_groups[0].meta.__setitem__(
                "j17_test", "roll_baby_roll"
            )
        )

    def test_changed_job_meta(self):
        assert self._changed(
            lambda j: j.meta.__setitem__("j18_test", "roll_baby_roll")
        )


class TestEvictAndPlacePort:
    def _tuples(self, n=4):
        return [
            AllocTuple(alloc=Allocation(id=generate_uuid())) for _ in range(n)
        ]

    def _ctx(self):
        h = Harness(seed=42)
        return EvalContext(h.state.snapshot(), Plan(), rng=random.Random(1))

    def test_limit_less_than_allocs(self):
        # ref TestEvictAndPlace_LimitLessThanAllocs (util_test.go:575)
        ctx = self._ctx()
        diff = DiffResult()
        limit = [2]
        assert evict_and_place(ctx, diff, self._tuples(), "", limit)
        assert limit[0] == 0
        assert len(diff.place) == 2

    def test_limit_equal_to_allocs(self):
        # ref TestEvictAndPlace_LimitEqualToAllocs (util_test.go:599)
        ctx = self._ctx()
        diff = DiffResult()
        limit = [4]
        assert not evict_and_place(ctx, diff, self._tuples(), "", limit)
        assert limit[0] == 0
        assert len(diff.place) == 4

    def test_limit_greater_than_allocs(self):
        # ref TestEvictAndPlace_LimitGreaterThanAllocs (util_test.go:948)
        ctx = self._ctx()
        diff = DiffResult()
        limit = [6]
        assert not evict_and_place(ctx, diff, self._tuples(), "", limit)
        assert limit[0] == 2
        assert len(diff.place) == 4


class TestSetStatusPort:
    """ref TestSetStatus (util_test.go:623)."""

    def test_status_and_description(self):
        h = Harness(seed=42)
        ev = mock.evaluation()
        set_status(h, ev, None, None, {}, "a", "b", None, "")
        assert len(h.evals) == 1
        got = h.evals[0]
        assert got.id == ev.id and got.status == "a"
        assert got.status_description == "b"

    def test_next_eval_link(self):
        h = Harness(seed=42)
        ev, nxt = mock.evaluation(), mock.evaluation()
        set_status(h, ev, nxt, None, {}, "a", "b", None, "")
        assert h.evals[0].next_eval == nxt.id

    def test_blocked_eval_link(self):
        h = Harness(seed=42)
        ev, blocked = mock.evaluation(), mock.evaluation()
        set_status(h, ev, None, blocked, {}, "a", "b", None, "")
        assert h.evals[0].blocked_eval == blocked.id

    def test_failed_tg_metrics(self):
        h = Harness(seed=42)
        ev = mock.evaluation()
        metrics = {"foo": None}
        set_status(h, ev, None, None, metrics, "a", "b", None, "")
        assert h.evals[0].failed_tg_allocs == metrics

    def test_queued_allocations(self):
        h = Harness(seed=42)
        ev = mock.evaluation()
        set_status(h, ev, None, None, {}, "a", "b", {"web": 1}, "")
        assert h.evals[0].queued_allocations == {"web": 1}

    def test_deployment_id(self):
        h = Harness(seed=42)
        ev = mock.evaluation()
        did = generate_uuid()
        set_status(h, ev, None, None, {}, "a", "b", None, did)
        assert h.evals[0].deployment_id == did


def _inplace_fixture(new_tg, job_tg=None):
    """An existing alloc + the update_fn the reconciler uses for it
    (the repo's per-alloc analog of the reference's batch inplaceUpdate,
    util.go:759-856). ``job_tg`` is what the NEW JOB carries (drives the
    tasks_updated destructive check); ``new_tg`` is the group handed to
    the updater (drives the select ask). The Go Success test aliases the
    two through a shared Tasks slice — here they are explicit."""
    h = Harness(seed=42)
    node = mock.node()
    h.state.upsert_node(900, node)
    job = mock.job()
    h.state.upsert_job(901, job)
    stored = h.state.job_by_id(job.namespace, job.id)

    alloc = Allocation(
        namespace="default",
        id=generate_uuid(),
        eval_id=generate_uuid(),
        node_id=node.id,
        job_id=stored.id,
        job=stored,
        task_group="web",
        desired_status="run",
        allocated_resources=AllocatedResources(
            tasks={
                "web": AllocatedTaskResources(
                    cpu=AllocatedCpuResources(cpu_shares=2048),
                    memory=AllocatedMemoryResources(memory_mb=2048),
                )
            }
        ),
    )
    h.state.upsert_allocs(1001, [alloc])
    stored_alloc = h.state.alloc_by_id(alloc.id)

    new_job = stored.copy()
    new_job.job_modify_index += 1
    new_job.task_groups = [job_tg if job_tg is not None else new_tg]

    ctx = EvalContext(h.state.snapshot(), Plan(), rng=random.Random(3))
    stack = GenericStack(False, ctx)
    stack.set_job(new_job)
    fn = generic_alloc_update_fn(ctx, stack, generate_uuid())
    return fn, stored_alloc, new_job, ctx


class TestInplaceUpdatePort:
    def test_changed_task_group_is_destructive(self):
        # ref TestInplaceUpdate_ChangedTaskGroup (util_test.go:723)
        tg = TaskGroup(
            name="web", count=1, ephemeral_disk=EphemeralDisk(),
            tasks=[Task(name="FOO", resources=Resources())],
        )
        fn, alloc, new_job, ctx = _inplace_fixture(tg)
        ignore, destructive, new_alloc = fn(alloc, new_job, tg)
        assert (ignore, destructive) == (False, True)
        assert new_alloc is None
        assert not ctx.plan.node_allocation

    def test_no_fit_is_destructive(self):
        # ref TestInplaceUpdate_NoMatch (util_test.go:783)
        job = mock.job()
        tg = job.task_groups[0].copy()
        tg.tasks[0].resources = Resources(cpu=9999)
        fn, alloc, new_job, ctx = _inplace_fixture(tg)
        ignore, destructive, new_alloc = fn(alloc, new_job, tg)
        assert (ignore, destructive) == (False, True)
        assert new_alloc is None

    def test_success_updates_resources_in_place(self):
        # ref TestInplaceUpdate_Success (util_test.go:839)
        job = mock.job()
        tg = job.task_groups[0].copy()
        tg.tasks[0].resources = Resources(cpu=737, memory_mb=256)
        tg.tasks[0].services = [
            Service(name="dummy-service", port_label="http"),
            Service(name="dummy-service2", port_label="http"),
        ]
        # the Go test's shared-Tasks aliasing makes tasksUpdated compare
        # the job against itself; reproduce that by giving the new job an
        # UNCHANGED group while the updater receives the new ask
        fn, alloc, new_job, ctx = _inplace_fixture(
            tg, job_tg=job.task_groups[0]
        )
        ignore, destructive, new_alloc = fn(alloc, new_job, tg)
        assert (ignore, destructive) == (False, False)
        assert new_alloc is not None and new_alloc.id == alloc.id
        assert (
            new_alloc.allocated_resources.tasks["web"].cpu.cpu_shares == 737
        )


class TestTaskGroupConstraintsPort:
    def test_combined_constraints_and_drivers(self):
        # ref TestTaskGroupConstraints (util_test.go:972)
        constr = Constraint(r_target="bar")
        constr2 = Constraint(l_target="foo")
        constr3 = Constraint(operand="<")
        tg = TaskGroup(
            name="web", count=10, constraints=[constr],
            ephemeral_disk=EphemeralDisk(),
            tasks=[
                Task(
                    name="a", driver="exec",
                    resources=Resources(cpu=500, memory_mb=256),
                    constraints=[constr2],
                ),
                Task(
                    name="b", driver="docker",
                    resources=Resources(cpu=500, memory_mb=256),
                    constraints=[constr3],
                ),
            ],
        )
        constraints, drivers = task_group_constraints(tg)
        assert constraints == [constr, constr2, constr3]
        assert drivers == {"exec", "docker"}


class TestProgressMadePort:
    def test_progress_variants(self):
        # ref TestProgressMade (util_test.go:1015)
        assert not progress_made(None)
        assert not progress_made(PlanResult())
        m = {"foo": [mock.alloc()]}
        assert progress_made(PlanResult(node_allocation=m, node_update=m))
        assert progress_made(PlanResult(node_update=m))
        assert progress_made(PlanResult(node_allocation=m))
        assert progress_made(PlanResult(deployment=Deployment()))
        assert progress_made(
            PlanResult(
                deployment_updates=[
                    DeploymentStatusUpdate(deployment_id=generate_uuid())
                ]
            )
        )


class TestDesiredUpdatesPort:
    def test_per_group_rollup(self):
        # ref TestDesiredUpdates (util_test.go:1042)
        tg1 = TaskGroup(name="foo")
        tg2 = TaskGroup(name="bar")
        a2 = Allocation(task_group="bar")
        diff = DiffResult()
        diff.place = [
            AllocTuple(task_group=tg1), AllocTuple(task_group=tg1),
            AllocTuple(task_group=tg1), AllocTuple(task_group=tg2),
        ]
        diff.stop = [
            AllocTuple(task_group=tg2, alloc=a2),
            AllocTuple(task_group=tg2, alloc=a2),
        ]
        diff.ignore = [AllocTuple(task_group=tg1)]
        diff.migrate = [AllocTuple(task_group=tg2)]
        inplace = [AllocTuple(task_group=tg1), AllocTuple(task_group=tg1)]
        destructive = [
            AllocTuple(task_group=tg1),
            AllocTuple(task_group=tg2), AllocTuple(task_group=tg2),
        ]
        desired = desired_updates(diff, inplace, destructive)
        assert desired["foo"].place == 3
        assert desired["foo"].ignore == 1
        assert desired["foo"].in_place_update == 2
        assert desired["foo"].destructive_update == 1
        assert desired["bar"].place == 1
        assert desired["bar"].stop == 2
        assert desired["bar"].migrate == 1
        assert desired["bar"].destructive_update == 2


class TestQueuedAllocBookkeepingPort:
    def test_adjust_queued_allocations(self):
        # ref TestUtil_AdjustQueuedAllocations (util_test.go:1100)
        alloc1 = mock.alloc()
        alloc2 = mock.alloc()
        alloc2.create_index = 4
        alloc2.modify_index = 4
        alloc3 = mock.alloc()
        alloc3.create_index = 3
        alloc3.modify_index = 5
        alloc4 = mock.alloc()
        alloc4.create_index = 6
        alloc4.modify_index = 8

        result = PlanResult(
            node_update={"node-1": [alloc1]},
            node_allocation={
                "node-1": [alloc2],
                "node-2": [alloc3, alloc4],
            },
            refresh_index=3,
            alloc_index=16,  # must not be considered
        )
        queued = {"web": 2}
        adjust_queued_allocations(result, queued)
        assert queued["web"] == 1

    def test_update_non_terminal_allocs_to_lost(self):
        # ref TestUtil_UpdateNonTerminalAllocsToLost (util_test.go:1137)
        node = mock.node()
        node.status = "down"

        def stopped(client_status):
            a = mock.alloc()
            a.node_id = node.id
            a.desired_status = ALLOC_DESIRED_STATUS_STOP
            a.client_status = client_status
            return a

        alloc1 = stopped("pending")
        alloc2 = stopped(ALLOC_CLIENT_STATUS_RUNNING)
        alloc3 = stopped(ALLOC_CLIENT_STATUS_COMPLETE)
        alloc4 = stopped(ALLOC_CLIENT_STATUS_FAILED)
        allocs = [alloc1, alloc2, alloc3, alloc4]

        plan = Plan()
        update_non_terminal_allocs_to_lost(plan, {node.id: node}, allocs)
        assert [a.id for a in plan.node_update.get(node.id, [])] == [
            alloc1.id, alloc2.id,
        ]

        # a READY tainted node (drain) must not mark anything lost
        plan = Plan()
        node2 = node.copy()
        node2.status = "ready"
        update_non_terminal_allocs_to_lost(plan, {node2.id: node2}, allocs)
        assert plan.node_update.get(node2.id, []) == []
