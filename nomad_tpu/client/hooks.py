"""Task prestart hooks (ref client/allocrunner/taskrunner/
task_runner_hooks.go:48-118: validate → taskdir → logmon → dispatch
payload → artifacts → templates → env; logmon lives in the drivers'
_spawn log capture here).

Hooks run before every driver start, in order; a hook failure fails the
start attempt, which routes through the task's restart policy exactly like
a driver start failure."""

from __future__ import annotations

import base64
import logging
import os
import shutil
import urllib.request
from urllib.parse import urlparse

from . import taskenv

logger = logging.getLogger("nomad_tpu.client.hooks")


class HookError(RuntimeError):
    pass


def _contained(base: str, rel: str) -> str:
    from ..util import contained_path

    try:
        return contained_path(base, rel)
    except ValueError as e:
        raise HookError(str(e)) from e


def task_dir_hook(task_dir: str, alloc_dir: str):
    """allocdir layout (ref client/allocdir/): shared alloc dir plus the
    task's local/secrets/tmp tree."""
    for d in (
        alloc_dir,
        os.path.join(alloc_dir, "data"),
        os.path.join(alloc_dir, "tmp"),
        os.path.join(task_dir, "local"),
        os.path.join(task_dir, "secrets"),
        os.path.join(task_dir, "tmp"),
    ):
        os.makedirs(d, exist_ok=True)


def dispatch_payload_hook(alloc, task, task_dir: str):
    """Write the dispatch payload into local/ (ref dispatch_hook.go)."""
    if task.dispatch_payload is None or not task.dispatch_payload.file:
        return
    job = alloc.job
    payload = getattr(job, "payload", "") if job is not None else ""
    if not payload:
        return
    try:
        data = base64.b64decode(payload)
    except Exception:
        data = payload.encode()
    # user-controlled filename: must stay inside the task dir
    dest = _contained(task_dir, os.path.join("local", task.dispatch_payload.file))
    os.makedirs(os.path.dirname(dest), exist_ok=True)
    with open(dest, "wb") as f:
        f.write(data)


def artifacts_hook(task, task_dir: str, env: dict, node=None):
    """Fetch artifacts into local/ (ref artifact_hook.go + go-getter).
    Supported getters: file:// and bare paths (copy, dir or file) and
    http(s):// via urllib; failures raise and route through the restart
    policy like the reference's artifact failures."""
    for artifact in task.artifacts:
        source = taskenv.interpolate(artifact.getter_source, env, node)
        rel = taskenv.interpolate(artifact.relative_dest, env, node) or "local/"
        dest_base = _contained(task_dir, rel)
        os.makedirs(dest_base, exist_ok=True)
        parsed = urlparse(source)
        try:
            if parsed.scheme in ("", "file"):
                path = parsed.path if parsed.scheme == "file" else source
                if os.path.isdir(path):
                    shutil.copytree(
                        path,
                        os.path.join(dest_base, os.path.basename(path.rstrip("/"))),
                        dirs_exist_ok=True,
                    )
                else:
                    shutil.copy(path, dest_base)
            elif parsed.scheme in ("http", "https"):
                name = os.path.basename(parsed.path) or "artifact"
                local = os.path.join(dest_base, name)
                with urllib.request.urlopen(source, timeout=30) as resp:
                    with open(local, "wb") as f:
                        shutil.copyfileobj(resp, f)
                _maybe_unpack(local, dest_base)
            elif parsed.scheme == "git" or source.startswith("git::"):
                # go-getter's git mode: git::<url>[?ref=<ref>]
                url = source[len("git::"):] if source.startswith("git::") else source
                ref = ""
                if "?ref=" in url:
                    url, _, ref = url.partition("?ref=")
                import subprocess

                target = os.path.join(
                    dest_base,
                    os.path.basename(url.rstrip("/")).removesuffix(".git"),
                )
                cmd = ["git", "clone", "--depth", "1"]
                if ref:
                    cmd += ["--branch", ref]
                cmd += [url, target]
                out = subprocess.run(
                    cmd, capture_output=True, text=True, timeout=120
                )
                if out.returncode != 0:
                    raise HookError(
                        f"git clone failed: {out.stderr.strip()[:300]}"
                    )
            else:
                raise HookError(f"unsupported artifact getter: {source}")
        except HookError:
            raise
        except Exception as e:
            raise HookError(f"artifact fetch failed for {source}: {e}") from e


def _maybe_unpack(path: str, dest: str):
    """go-getter auto-unpacks recognized archives; same here. The archive
    file is removed after a successful extraction."""
    import tarfile
    import zipfile

    lowered = path.lower()
    try:
        if lowered.endswith((".tar.gz", ".tgz", ".tar.bz2", ".tar")):
            with tarfile.open(path) as tf:
                tf.extractall(dest, filter="data")
        elif lowered.endswith(".zip"):
            with zipfile.ZipFile(path) as zf:
                for info in zf.infolist():
                    target = os.path.join(dest, info.filename)
                    if not os.path.realpath(target).startswith(
                        os.path.realpath(dest)
                    ):
                        raise HookError(f"zip escapes dest: {info.filename}")
                zf.extractall(dest)
        else:
            return
    except (tarfile.TarError, zipfile.BadZipFile) as e:
        raise HookError(f"archive unpack failed for {path}: {e}") from e
    os.remove(path)


def templates_hook(task, task_dir: str, env: dict, node=None):
    """Render templates (ref template_hook.go; the reference runs
    consul-template — here embedded templates interpolate the task env and
    node attributes through the same ${...} syntax)."""
    for template in task.templates:
        content = template.embedded_tmpl
        if not content and template.source_path:
            source = _contained(task_dir, template.source_path)
            try:
                with open(source) as f:
                    content = f.read()
            except OSError as e:
                raise HookError(f"template source unreadable: {e}") from e
        rendered = taskenv.interpolate(content, env, node)
        dest = _contained(task_dir, template.dest_path)
        os.makedirs(os.path.dirname(dest), exist_ok=True)
        with open(dest, "w") as f:
            f.write(rendered)
        try:
            os.chmod(dest, int(template.perms or "0644", 8))
        except (ValueError, OSError):
            pass


def volumes_hook(alloc, task, node, task_dir: str):
    """Materialize host-volume mounts into the task dir as symlinks
    (ref taskrunner/volume_hook.go: the group's volume{} requests bound
    through the node's client host_volume config)."""
    import os

    job = alloc.job
    tg = job.lookup_task_group(alloc.task_group) if job else None
    requests = tg.volumes if tg is not None else {}
    for mount in task.volume_mounts:
        req = requests.get(mount.volume)
        if req is None:
            raise RuntimeError(f"task mounts unknown volume {mount.volume!r}")
        host = node.host_volumes.get(req.source)
        if host is None:
            raise RuntimeError(
                f"node is missing host volume {req.source!r}"
            )
        target = os.path.join(task_dir, mount.destination.lstrip("/"))
        os.makedirs(os.path.dirname(target) or task_dir, exist_ok=True)
        if os.path.islink(target):
            os.unlink(target)
        elif os.path.exists(target):
            continue  # restart of a recovered task: mount already present
        os.symlink(host.path, target)


def run_prestart(
    alloc, task, node, task_dir: str, alloc_dir: str, extra_env=None,
    skip_templates: bool = False,
):
    """The prestart pipeline; returns the prepared (interpolated) task copy
    and its full environment. ``skip_templates`` hands template rendering
    to the caller's TemplateManager (the live-template path renders once
    with dynamic sources instead of a static pass here)."""
    task_dir_hook(task_dir, alloc_dir)
    volumes_hook(alloc, task, node, task_dir)
    env = taskenv.build_env(alloc, task, node, task_dir, alloc_dir)
    env.update(extra_env or {})
    dispatch_payload_hook(alloc, task, task_dir)
    artifacts_hook(task, task_dir, env, node)
    if not skip_templates:
        templates_hook(task, task_dir, env, node)

    prepared = task.copy()
    prepared.env = {
        **{k: taskenv.interpolate(v, env, node) for k, v in task.env.items()},
        **env,
    }
    prepared.config = taskenv.interpolate(task.config, prepared.env, node)
    # drivers see the ALLOCATED networks (NetworkIndex's granted host
    # ports), not the jobspec ask whose dynamic ports are still 0 — the
    # reference builds the driver TaskConfig from the alloc's resources
    # (drivers/task_handle + driver.go createContainerConfig port binds)
    ar = getattr(alloc, "allocated_resources", None)
    if ar is not None:
        tr = ar.tasks.get(task.name)
        if tr is not None and tr.networks:
            prepared.resources.networks = [n.copy() for n in tr.networks]
    return prepared, env
