"""Service health-check runner (ref command/agent/consul/ ServiceClient +
script_checks: the reference registers check definitions with Consul and
runs script checks itself; this nomad-native analog runs script/http/tcp
checks in the client and publishes results through task state, which the
cluster's service catalog reads).

Each running task with service checks gets one runner thread that cycles
its checks on their configured intervals. Results transition between
"passing" and "critical"; transitions mark the task state dirty so the
client's update loop pushes them to the servers."""

from __future__ import annotations

import logging
import socket
import subprocess
import threading
import time
import urllib.request

logger = logging.getLogger("nomad_tpu.client.checks")

PASSING = "passing"
CRITICAL = "critical"

DEFAULT_INTERVAL_S = 10.0
MIN_INTERVAL_S = 0.05
DEFAULT_TIMEOUT_S = 5.0


def _service_address(alloc, task_name: str, port_label: str):
    """(ip, port) a check should probe, from the task's allocated network
    resources (the same resolution the service catalog performs)."""
    resources = alloc.allocated_resources
    tr = resources.tasks.get(task_name) if resources is not None else None
    if tr is None:
        return None
    for net in tr.networks:
        for p in list(net.reserved_ports) + list(net.dynamic_ports):
            if p.label == port_label:
                return net.ip or "127.0.0.1", p.value
    return None


def run_check(check, alloc, task_name: str, task_dir: str, env: dict) -> tuple[str, str]:
    """Execute one check; returns (status, output)."""
    timeout = (check.timeout / 1e9) if check.timeout else DEFAULT_TIMEOUT_S
    kind = (check.type or "").lower()
    try:
        if kind == "script":
            out = subprocess.run(
                [check.command, *[str(a) for a in check.args]],
                cwd=task_dir or None,
                env={"PATH": "/usr/bin:/bin:/usr/local/bin", **(env or {})},
                capture_output=True,
                text=True,
                timeout=timeout,
            )
            status = PASSING if out.returncode == 0 else CRITICAL
            return status, (out.stdout or out.stderr)[:512]
        addr = _service_address(alloc, task_name, check.port_label)
        if addr is None:
            return CRITICAL, f"no port labelled {check.port_label!r}"
        ip, port = addr
        if kind == "tcp":
            with socket.create_connection((ip, port), timeout=timeout):
                return PASSING, f"tcp connect {ip}:{port} ok"
        if kind == "http":
            proto = check.protocol or "http"
            url = f"{proto}://{ip}:{port}{check.path or '/'}"
            with urllib.request.urlopen(url, timeout=timeout) as resp:
                code = resp.status
            if 200 <= code < 400:
                return PASSING, f"HTTP {code}"
            return CRITICAL, f"HTTP {code}"
        return CRITICAL, f"unknown check type {check.type!r}"
    except subprocess.TimeoutExpired:
        return CRITICAL, "check timed out"
    except Exception as e:  # connection refused, DNS, non-2xx, ...
        return CRITICAL, str(e)[:512]


class CheckRunner:
    """Cycles a task's service checks while the task runs."""

    def __init__(self, task_runner):
        self.task_runner = task_runner
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None
        # (service, check) → next fire time
        # nta: ignore[unbounded-cache] WHY: keyed by the task's
        # (service, check) set; the runner dies with its task
        self._schedule: dict[tuple[str, str], float] = {}
        # check name → consecutive critical results (check_restart)
        # nta: ignore[unbounded-cache] WHY: keyed by the task's check
        # names; the runner dies with its task
        self._fail_streak: dict[str, int] = {}
        self._started_at = time.monotonic()

    def start(self):
        checks = [
            (svc, chk)
            for svc in self.task_runner.task.services
            for chk in svc.checks
        ]
        if not checks:
            return
        self._checks = checks
        self._thread = threading.Thread(
            target=self._run, daemon=True, name="client-check-watcher"
        )
        self._thread.start()

    def stop(self):
        self._stop.set()

    def _run(self):
        tr = self.task_runner
        alloc_runner = tr.alloc_runner
        task_dir = alloc_runner.task_dir(tr.task.name)
        now = time.monotonic()
        for svc, chk in self._checks:
            self._schedule[(svc.name, chk.name)] = now
        while not self._stop.is_set() and tr.state.state == "running":
            now = time.monotonic()
            next_fire = now + DEFAULT_INTERVAL_S
            for svc, chk in self._checks:
                key = (svc.name, chk.name)
                due = self._schedule[key]
                if now >= due:
                    status, output = run_check(
                        chk,
                        alloc_runner.alloc,
                        tr.task.name,
                        task_dir,
                        getattr(tr, "_env", None) or {},
                    )
                    self._publish(chk.name or svc.name, status, output)
                    if self._maybe_restart(chk, status):
                        return  # restart kills the process; this run ends
                    interval = max(
                        (chk.interval / 1e9) if chk.interval else DEFAULT_INTERVAL_S,
                        MIN_INTERVAL_S,
                    )
                    due = now + interval
                    self._schedule[key] = due
                next_fire = min(next_fire, due)
            self._stop.wait(max(next_fire - time.monotonic(), MIN_INTERVAL_S))

    def _maybe_restart(self, check, status: str) -> bool:
        """check_restart (ref structs.go CheckRestart + taskrunner's
        checkRestarter): ``limit`` consecutive critical results after the
        ``grace`` window restart the task through the normal user-restart
        path (outside the restart-policy budget, like the reference's
        Restart(force))."""
        cr = check.check_restart
        if cr is None or cr.limit <= 0:
            return False
        name = check.name
        if status == PASSING:
            self._fail_streak[name] = 0
            return False
        if time.monotonic() - self._started_at < (cr.grace / 1e9):
            return False
        self._fail_streak[name] = self._fail_streak.get(name, 0) + 1
        if self._fail_streak[name] < cr.limit:
            return False
        tr = self.task_runner
        logger.warning(
            "check %s failed %d times; restarting task %s",
            name, self._fail_streak[name], tr.task.name,
        )
        tr._event(
            "Restart Signaled",
            f"healthcheck: check {name!r} unhealthy",
        )
        try:
            tr.restart()
        except ValueError:
            pass  # already stopping/stopped
        return True

    def _publish(self, name: str, status: str, output: str):
        tr = self.task_runner
        prev = tr.state.check_status.get(name)
        if prev == status:
            return
        tr.state.check_status = dict(tr.state.check_status, **{name: status})
        if status == CRITICAL:
            tr._event("Check", f"check {name!r} {status}: {output}")
        logger.info("check %s for task %s: %s", name, tr.task.name, status)
        tr.alloc_runner.task_state_updated()
