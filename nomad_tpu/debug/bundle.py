"""Debug bundle: one directory (or tarball) holding everything an
operator needs to debug an agent after the fact — the ``nomad operator
debug`` role.

Contents (all JSON except the flamegraph-ready ``profile.folded``):

- ``manifest.json``  — capture reason/time, file list, agent identity;
- ``config.json``    — the server config, **secrets redacted**;
- ``metrics.json``   — full metrics registry snapshot;
- ``flight.json``    — the flight-recorder ring (the pre-incident tape);
- ``threads.json``   — one-shot thread stacks + gc (the pprof dump);
- ``profile.json``   — sampling-profiler report (``profile.folded`` is
  the same data as flamegraph input);
- ``traces.json``    — slowest-N + error traces from the trace store;
- ``lockdep.json``   — contention table + violations (when installed);
- ``device.json``    — the device plane (debug/devprof.py): compile
  ledger with per-executable cost + HLO collective census, transfer
  totals, per-planner round counters, last-dispatch table;
- ``findings.json``  — the analysis layer: applier_block_frac, top
  blocked sites, watchdog state, trace critical-path verdict, and the
  distilled devprof summary (collective_rounds_per_placement).

Captured by the watchdog on a rule trip, by ``nomad-tpu operator
debug`` / ``GET /v1/debug/bundle`` on demand, and by scripts/debug.sh.
"""

from __future__ import annotations

import json
import os
import tarfile
import time

#: config keys whose values never leave the process (substring match,
#: case-insensitive: encrypt, vault tokens, tls material, acl secrets)
_SENSITIVE = ("token", "secret", "password", "encrypt", "key", "cert", "ca")

REDACTED = "<redacted>"

#: every file a complete bundle carries (the watchdog test pins this)
BUNDLE_FILES = (
    "manifest.json",
    "config.json",
    "metrics.json",
    "flight.json",
    "threads.json",
    "profile.json",
    "profile.folded",
    "traces.json",
    "lockdep.json",
    "device.json",
    "findings.json",
)


def redact_config(value, key: str = ""):
    """Deep-copy ``value`` with sensitive leaves replaced and
    non-JSON-serializable objects (raft transports, sockets) rendered as
    type placeholders — the bundle must never require pickling live
    machinery or leak credentials."""
    lowered = key.lower()
    if isinstance(value, dict):
        return {
            str(k): redact_config(v, key=str(k)) for k, v in value.items()
        }
    if isinstance(value, (list, tuple)):
        return [redact_config(v, key=key) for v in value]
    if isinstance(value, (str, bytes)) and any(
        s in lowered for s in _SENSITIVE
    ):
        return REDACTED
    if isinstance(value, (str, int, float, bool)) or value is None:
        return value
    return f"<{type(value).__name__}>"


def _write_json(dest: str, name: str, payload):
    with open(os.path.join(dest, name), "w", encoding="utf-8") as f:
        json.dump(payload, f, indent=1, default=repr)
        f.write("\n")


def capture_bundle(
    server,
    dest: str,
    profile_seconds: float = 1.0,
    hz: float = 100.0,
    reason: str = "manual",
    slowest: int = 16,
) -> dict:
    """Write a full bundle into directory ``dest`` (created); returns
    the manifest (including ``path``). Every section is individually
    exception-guarded: a debug capture that dies on the one broken
    subsystem it exists to debug is worthless — missing sections are
    listed in the manifest's ``errors`` instead."""
    from .. import metrics
    from ..testing import lockdep
    from .profiler import profile, render_folded, thread_dump

    os.makedirs(dest, exist_ok=True)
    errors: dict[str, str] = {}
    t0 = time.time()

    def section(name, fn):
        try:
            return fn()
        except Exception as e:
            errors[name] = repr(e)
            return None

    _write_json(
        dest, "config.json",
        section("config", lambda: redact_config(server.config)) or {},
    )
    _write_json(
        dest, "metrics.json", section("metrics", metrics.snapshot) or {}
    )
    recorder = getattr(server, "flight_recorder", None)
    _write_json(
        dest, "flight.json",
        section("flight", recorder.dump) if recorder is not None else {},
    )
    _write_json(
        dest, "threads.json", section("threads", thread_dump) or {}
    )
    prof = section(
        "profile", lambda: profile(profile_seconds, hz=hz)
    ) or {}
    _write_json(dest, "profile.json", prof)
    with open(
        os.path.join(dest, "profile.folded"), "w", encoding="utf-8"
    ) as f:
        f.write(render_folded(prof) + "\n")

    def traces():
        from ..trace import tracer

        slow = tracer.store.list(limit=slowest, slowest=True)
        errs = tracer.store.list(limit=slowest, errors=True)
        return {
            "stats": tracer.stats(),
            "slowest": [
                r
                for r in (
                    tracer.store.get(row["trace_id"]) for row in slow
                )
                if r is not None
            ],
            "errors": errs,
        }

    _write_json(dest, "traces.json", section("traces", traces) or {})

    def lockdep_dump():
        if not lockdep.installed():
            return {"installed": False}
        table = sorted(
            (
                {"site": site, **entry}
                for site, entry in lockdep.contention().items()
            ),
            key=lambda e: -e["wait_s"],
        )
        return {
            "installed": True,
            "contention": table[:64],
            "violations": lockdep.violations(),
        }

    _write_json(
        dest, "lockdep.json", section("lockdep", lockdep_dump) or {}
    )

    def device():
        from . import devprof

        return devprof.snapshot()

    _write_json(dest, "device.json", section("device", device) or {})

    def findings():
        out = {
            "applier_block_frac": prof.get("applier_block_frac"),
            "top_blocked_sites": prof.get("blocked_sites", [])[:10],
        }
        try:
            from . import devprof

            out["device"] = devprof.summary()
        except Exception:
            out["device"] = None
        watchdog = getattr(server, "watchdog", None)
        if watchdog is not None:
            out["watchdog"] = watchdog.stats()
        broker = getattr(server, "event_broker", None)
        if broker is not None:
            # fan-out overload diagnosis without a live shell: who is
            # behind (per-subscriber lag top-N with queue depth and
            # topics) and what the ring looked like when the rule tripped
            out["event_broker"] = {
                "stats": broker.stats(),
                "subscriber_lag": broker.lag_stats(top=10),
            }
        try:
            from ..trace import attribute, tracer

            cp = attribute(tracer.store.records())
            out["critical_path"] = {
                "traces": cp["traces"],
                "bottleneck": cp["bottleneck"],
                "verdict": cp["verdict"],
            }
        except Exception:
            out["critical_path"] = None
        # federation diagnosis (the acl_replication_lag trip's payload):
        # which region this is, who it can reach, how replication and
        # cross-region forwarding are doing, and local raft health
        region = getattr(server, "region", None)
        if region is not None:
            try:
                from .. import metrics as _metrics

                counters = _metrics.snapshot()["counters"]
                fed = {
                    "region": region,
                    "known_regions": server.regions(),
                    "replication": dict(
                        getattr(server, "acl_replication_status", {}) or {}
                    ),
                    "raft": {
                        "leader_id": getattr(server.raft, "leader_id", None),
                        "is_leader": server.is_leader(),
                        "voters": sorted(server.raft.voters),
                    },
                    "forwarding": {
                        k: v
                        for k, v in counters.items()
                        if k.startswith(
                            ("http.region_forward", "http.leader_forward",
                             "rpc.not_leader_retry")
                        )
                    },
                }
                lag_fn = getattr(server, "acl_replication_lag_s", None)
                lag = lag_fn() if lag_fn is not None else None
                if lag is not None:
                    fed["replication"]["lag_s"] = round(lag, 3)
                out["federation"] = fed
            except Exception:
                out["federation"] = None
        return out

    _write_json(dest, "findings.json", section("findings", findings) or {})

    manifest = {
        "reason": reason,
        "created": round(t0, 3),
        "duration_s": round(time.time() - t0, 3),
        "profile_seconds": profile_seconds,
        "path": dest,
        "errors": errors,
        "files": sorted(
            fn for fn in os.listdir(dest) if fn != "manifest.json"
        ) + ["manifest.json"],
    }
    _write_json(dest, "manifest.json", manifest)
    return manifest


def make_tarball(bundle_dir: str, tar_path: str) -> str:
    """gzip tarball of a captured bundle directory (the HTTP/CLI wire
    form); members are rooted at the bundle dir's basename."""
    with tarfile.open(tar_path, "w:gz") as tar:
        tar.add(bundle_dir, arcname=os.path.basename(bundle_dir.rstrip("/")))
    return tar_path
