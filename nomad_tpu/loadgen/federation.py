"""Federated storm plane: multi-region chaos at cluster-of-clusters
scale (ROADMAP item 5; ref the reference's e2e framework + Jepsen-style
partition testing, PAPERS.md).

The single-region machinery composes into regions:

- **topology** — 2–3 regions, each its own raft domain of
  ``ServerAgent``s (real RPC listeners, real HTTP surfaces) federated
  over gossip; region 0 is the ACL-authoritative region, every other
  region replicates policies and global tokens from it
  (core/server.py replicate_acl_once);
- **storm** — one seeded op stream per region (the PR 6 grammar; the
  region name is part of every named-RNG path, so streams are
  independent AND byte-reproducible per region), driven open-loop by a
  per-region :class:`FederatedDriver`. A seeded fraction of
  ``job.submit`` ops is routed *cross-region*: fired at a foreign
  region's HTTP surface with ``?region=<home>`` so they exercise the
  forwarding plane under load — the routing decision lands in the op
  args, inside the stream's digest;
- **chaos** — region-scale fault phases over the PR 1 plane's region
  scope (testing/faults.py): full region partition + heal, leader kill
  mid-storm, asymmetric partial sever, rolling region restart
  ("upgrade": stop/rebuild each server in sequence on its data dir);
- **score** — per-region flight-recorder samples (the PR 9 debug plane
  drives the watchdog, acl_replication_lag rule included), per-region
  incremental invariant sweeps mid-storm, ACL replication-lag probes
  (a nonce policy written to the authoritative region, convergence
  timed per replica region), partition heal timing, and a final
  cross-region oracle (testing/invariants.py
  check_federation_invariants): no lost or double-committed
  cross-region submits, ACL state converged.

Artifacts: scored ``FED_rNN.json`` + one trailing ``FED_SUMMARY`` line
(the log-tail-survival contract, same as SOAK/FANOUT). Run via
``python -m nomad_tpu.loadgen --federation`` or ``scripts/federation.sh``;
scale knobs are FED_* env vars (see :func:`federation_config_from_env`).
"""

from __future__ import annotations

import json
import logging
import os
import shutil
import tempfile
import threading
import time
from dataclasses import dataclass, field

from ..testing import faults as _faults
from ..testing.invariants import (
    IncrementalInvariantChecker,
    check_federation_invariants,
)
from .driver import StormDriver
from .grammar import Op, OpStream, Phase, Scenario, compile_stream, named_rng
from .score import grade, write_report

logger = logging.getLogger("nomad_tpu.loadgen.federation")

#: region names in topology order; region 0 is ACL-authoritative
REGION_NAMES = ("east", "west", "north")


# ---------------------------------------------------------------------------
# configuration
# ---------------------------------------------------------------------------


@dataclass
class FederationConfig:
    """One federated storm: topology + per-region storm shape + chaos
    schedule + SLOs."""

    regions: int = 2
    servers_per_region: int = 3
    nodes_per_region: int = 100
    job_slots: int = 24
    churn_s: float = 60.0
    churn_rate: float = 6.0
    #: probability a job.submit routes through a foreign region's HTTP
    #: surface with ?region=<home> (the forwarding plane under load)
    cross_region_p: float = 0.25
    driver_workers: int = 4
    n_workers: int = 1
    sample_interval: float = 0.5
    invariants_every: int = 4
    #: ticks between ACL replication-lag probe writes
    repl_probe_every: int = 4
    quiesce_timeout: float = 90.0
    #: chaos events as (frac_of_churn, kind, args); fractions are offsets
    #: into the churn phase so one schedule scales with churn_s
    chaos: list = field(default_factory=list)
    slos: dict = field(
        default_factory=lambda: {
            "max_fed_invariant_violations": 0,
            "max_fed_lost_placements": 0,
            "max_fed_double_placements": 0,
            "max_fed_heal_s": 15.0,
            "max_fed_fwd_err_rate": 0.02,
            "max_fed_replication_lag_p99_s": 10.0,
            "max_op_failure_rate": 0.05,
            "max_shed_rate": 0.0,
        }
    )

    def region_names(self) -> list[str]:
        return list(REGION_NAMES[: self.regions])


def federation_smoke() -> FederationConfig:
    """The tier-1 shape: 2 regions x 1 server, a short mixed storm with
    one full partition + heal. Cheap enough for every suite; failover
    and rolling restart run in the full storm (and their own regression
    tests) — a 1-server region has no quorum to fail over."""
    return FederationConfig(
        regions=2,
        servers_per_region=1,
        nodes_per_region=24,
        job_slots=12,
        churn_s=12.0,
        churn_rate=6.0,
        cross_region_p=0.3,
        quiesce_timeout=60.0,
        chaos=[
            (0.2, "partition", {"a": "east", "b": "west"}),
            (0.55, "heal", {}),
        ],
    )


def federation_storm() -> FederationConfig:
    """The full storm: partition + heal, leader failover mid-storm,
    asymmetric partial sever, rolling region restart — the ISSUE's four
    region-scale chaos phases over a multi-server-per-region topology."""
    cfg = FederationConfig(
        regions=int(os.environ.get("FED_REGIONS", "2")),
        servers_per_region=int(os.environ.get("FED_SERVERS", "3")),
        nodes_per_region=int(os.environ.get("FED_NODES", "300")),
        job_slots=int(os.environ.get("FED_JOB_SLOTS", "32")),
        churn_s=float(os.environ.get("FED_CHURN_S", "90")),
        churn_rate=float(os.environ.get("FED_CHURN_RATE", "8")),
        cross_region_p=float(os.environ.get("FED_CROSS_P", "0.25")),
        quiesce_timeout=float(os.environ.get("FED_QUIESCE_S", "180")),
    )
    secondary = cfg.region_names()[1]
    restart_region = os.environ.get("FED_RESTART_REGION", secondary)
    cfg.chaos = [
        (0.10, "partition", {"a": "east", "b": secondary}),
        (0.28, "heal", {}),
        (0.40, "leader_kill", {"region": secondary}),
        (0.55, "partial_sever", {"a": "east", "b": secondary}),
        (0.70, "heal", {}),
        (0.80, "rolling_restart", {"region": restart_region}),
    ]
    return cfg


def federation_config_from_env() -> FederationConfig:
    """FED_PROFILE=smoke|storm (default storm for the CLI/script)."""
    profile = os.environ.get("FED_PROFILE", "storm")
    return federation_smoke() if profile == "smoke" else federation_storm()


# ---------------------------------------------------------------------------
# per-region storm grammar
# ---------------------------------------------------------------------------


def region_scenario(region: str, cfg: FederationConfig) -> Scenario:
    """The per-region storm: the smoke-storm op-class mass (submit /
    scale / update / stop / dispatch / flap / drain / GC) sized by the
    federation config. The scenario NAME embeds the region, so every
    named RNG stream — arrivals, mixes, args — is independent per
    region while staying byte-reproducible from (region, seed)."""
    nodes = cfg.nodes_per_region
    common = {
        "node_fleet": nodes,
        "job_slots": cfg.job_slots,
        "job_floor": 3,
        "ready_floor": max(4, nodes // 3),
        "count_range": (1, 4),
        "cpu_choices": (50, 100, 250),
        "memory_choices": (32, 64, 128),
        "job_categories": {"svc": 2.0, "bat": 1.0},
        "dispatch_slots": 2,
        "dispatch_fanout": (1, 3),
        "drain_deadline_s": (2.0, 8.0),
    }
    ramp_s = max(2.0, nodes / 40.0)
    return Scenario(
        name=f"fed-{region}",
        description=f"federated storm, region {region}",
        n_workers=cfg.n_workers,
        phases=[
            Phase(
                name="ramp_nodes",
                duration=ramp_s,
                rate=nodes / ramp_s,
                uniform=True,
                mix={"node.register": 1.0},
                params=common,
            ),
            Phase(
                name="ramp_jobs",
                duration=3.0,
                rate=max(2.0, cfg.job_slots / 2.0) / 3.0,
                uniform=True,
                mix={"job.submit": 1.0},
                params=common,
            ),
            Phase(
                name="ramp_dsp",
                duration=1.0,
                rate=2.0,
                uniform=True,
                mix={"job.dispatch_register": 1.0},
                params=common,
            ),
            Phase(
                name="churn",
                duration=cfg.churn_s,
                rate=cfg.churn_rate,
                mix={
                    "job.submit": 2.0,
                    "job.scale": 3.0,
                    "job.update": 2.0,
                    "job.stop": 1.0,
                    "job.dispatch": 1.0,
                    "job.evaluate": 0.5,
                    "node.down": 0.8,
                    "node.up": 1.0,
                    "node.drain": 0.6,
                    "node.drain_off": 0.8,
                    "system.gc": 0.3,
                },
                params=common,
            ),
            Phase(
                name="wind_down",
                duration=5.0,
                rate=4.0,
                mix={
                    "job.stop": 1.0,
                    "node.up": 2.0,
                    "node.drain_off": 2.0,
                },
                params=common,
            ),
        ],
        quiesce_timeout=cfg.quiesce_timeout,
        sample_interval=cfg.sample_interval,
        invariants_every=cfg.invariants_every,
        probes=0,
        slos={},
    )


def route_cross_region(
    stream: OpStream, region: str, others: list[str], seed: int, p: float
) -> OpStream:
    """Tag a seeded fraction of job.submit ops with ``via_region``: the
    op fires at that foreign region's HTTP surface with
    ``?region=<home>``, so it crosses the WAN through the forwarding
    plane. The tag lands in the op args — inside the encoded stream and
    its digest — so routing is part of the determinism contract."""
    if not others or p <= 0:
        return stream
    rng = named_rng(seed, stream.scenario_name, "cross-region-routing")
    ops = []
    for op in stream.ops:
        # every submit consumes exactly one draw, so adding/removing
        # other op kinds never perturbs the routing of existing submits
        if op.kind == "job.submit":
            roll = rng.random()
            pick = rng.randrange(len(others))
            if roll < p:
                op = Op(
                    t=op.t, seq=op.seq, kind=op.kind,
                    args={**op.args, "via_region": others[pick]},
                )
        ops.append(op)
    return OpStream(stream.scenario_name, stream.seed, ops)


# ---------------------------------------------------------------------------
# topology
# ---------------------------------------------------------------------------


@dataclass
class FedServer:
    region: str
    index: int
    name: str
    agent: object = None
    http: object = None
    data_dir: str = ""
    rpc_port: int = 0
    http_port: int = 0
    gossip_port: int = 0
    alive: bool = False


class FederatedCluster:
    """Builds and owns the multi-region topology. Every server is a
    real ``ServerAgent`` (TCP RPC listener + raft on the same port) with
    an ``HTTPServer``, a file-backed raft log (so restarts recover), and
    a fixed port set (so a restarted server is reachable at the same
    addresses — the rolling-upgrade shape, and what keeps driver
    address lists valid across chaos)."""

    #: federation-tuned gossip: fast failure detection so a partition is
    #: *observed* within ~2s, suspect long enough that a GIL-stalled
    #: member under storm load can refute before a false dead verdict,
    #: reap long enough that heal-time refutation has live records to
    #: refute
    GOSSIP = {
        "probe_interval": 0.25,
        "ack_timeout": 0.4,
        "suspect_timeout": 1.5,
        "reap_timeout": 8.0,
    }

    #: multi-server raft timing: the in-tree dev defaults (50ms
    #: heartbeat / 150-300ms election) assume an idle box; this topology
    #: runs regions x servers full Python servers in ONE process under
    #: storm load, where GIL stalls alone exceed 300ms — followers would
    #: fire elections against a healthy leader all storm long. WAN-ish
    #: timing keeps failover inside the heal SLO with stall headroom.
    RAFT = {
        "heartbeat_interval": 0.2,
        "election_timeout_min": 0.8,
        "election_timeout_max": 1.6,
    }

    def __init__(self, cfg: FederationConfig, seed: int = 42):
        self.cfg = cfg
        self.seed = seed
        self.regions = cfg.region_names()
        self.auth_region = self.regions[0]
        self.servers: list[FedServer] = []
        self.mgmt_token = ""
        self._tmpdir = tempfile.mkdtemp(prefix="nomad_tpu_fed_")
        self._lock = threading.Lock()

    # -- config assembly -------------------------------------------------
    def _server_config(self, region: str, index: int, seeds: list) -> dict:
        acl: dict = {"enabled": True}
        if region != self.auth_region:
            acl.update(
                authoritative_region=self.auth_region,
                replication_token=self.mgmt_token,
                replication_interval=0.5,
            )
        return {
            "seed": self.seed,
            "region": region,
            "heartbeat_ttl": 3600.0,
            "nack_timeout": 5.0,
            "initial_nack_delay": 0.1,
            "subsequent_nack_delay": 0.5,
            "acl": acl,
            "raft": dict(self.RAFT),
            # the federation scorekeeper drives each recorder's ring via
            # record() — one sampler per server, no second cadence
            "debug": {"flight_recorder": False},
            "gossip": {
                "bind": ("127.0.0.1", 0),
                "join": seeds,
                **self.GOSSIP,
            },
            # region 0's first server bootstraps the WHOLE region's raft
            # domain; everyone else joins voter-less through gossip
            "bootstrap": index == 0,
        }

    def _boot_server(self, fs: FedServer, seeds: list,
                     wait_leader: bool = False):
        from ..agent import ServerAgent
        from ..api.http import HTTPServer

        cfg = self._server_config(fs.region, fs.index, seeds)
        if fs.gossip_port:
            cfg["gossip"]["bind"] = ("127.0.0.1", fs.gossip_port)
        agent = ServerAgent(
            fs.name, port=fs.rpc_port, data_dir=fs.data_dir, config=cfg
        )
        # a region's first server is its own voter set; joiners pass an
        # EXPLICITLY empty map and wait for the leader's CONFIG entry
        # (restarts recover the real voter map from their log, so the
        # initial voters value is only the cold-boot seed either way)
        voters = None if fs.index == 0 else {}
        agent.start(
            voters=voters,
            num_workers=self.cfg.n_workers,
            wait_for_leader=10.0 if wait_leader else None,
        )
        http = HTTPServer(agent.server, port=fs.http_port)
        http.start()
        fs.agent = agent
        fs.http = http
        fs.rpc_port = int(agent.address.rsplit(":", 1)[1])
        fs.http_port = int(http.address.rsplit(":", 1)[1].rstrip("/"))
        fs.gossip_port = agent.server.gossip.addr[1]
        fs.alive = True

    def start(self):
        gossip_seeds: list = []
        for region in self.regions:
            for i in range(self.cfg.servers_per_region):
                name = f"{region}-{i}"
                fs = FedServer(
                    region=region, index=i, name=name,
                    data_dir=os.path.join(self._tmpdir, name),
                )
                self._boot_server(fs, list(gossip_seeds), wait_leader=i == 0)
                self.servers.append(fs)
                if not gossip_seeds:
                    gossip_seeds.append(list(fs.agent.server.gossip.addr))
                if region == self.auth_region and i == 0:
                    boot = fs.agent.server.acl_bootstrap()
                    # nta: ignore[unsynchronized-shared-write] WHY: set
                    # during cluster start, before the chaos executor
                    # (the only cross-thread reader) is spawned —
                    # pre-spawn publication
                    self.mgmt_token = boot.secret_id

    def wait_ready(self, timeout: float = 30.0):
        """Readiness barrier: every region elected a leader, every
        region sees every other region's HTTP servers in its forwarding
        table, and the bootstrap token replicated everywhere (so
        cross-region submits authenticate from the first op)."""
        deadline = time.monotonic() + timeout

        def ready() -> bool:
            for region in self.regions:
                leader = self.leader_of(region)
                if leader is None:
                    return False
                for other in self.regions:
                    if other != region and not (
                        leader.agent.server.region_http_servers(other)
                    ):
                        return False
                if region != self.auth_region:
                    srv = leader.agent.server
                    if not list(srv.state.acl_tokens()):
                        return False
            return True

        while time.monotonic() < deadline:
            if ready():
                return
            time.sleep(0.1)
        raise TimeoutError("federated topology never became ready")

    # -- lookups ---------------------------------------------------------
    def live_servers(self, region: str) -> list[FedServer]:
        with self._lock:
            return [
                s for s in self.servers if s.region == region and s.alive
            ]

    def leader_of(self, region: str):
        for s in self.live_servers(region):
            try:
                if s.agent.server.is_leader():
                    return s
            except Exception:
                continue
        return None

    def anchor(self, region: str):
        """The server a scorekeeper should read: the leader when there
        is one, else any live server (state is raft-replicated)."""
        leader = self.leader_of(region)
        if leader is not None:
            return leader
        live = self.live_servers(region)
        return live[0] if live else None

    def http_address(self, region: str) -> str | None:
        s = self.anchor(region)
        return s.http.address if s is not None else None

    def rpc_addresses(self, region: str) -> list[str]:
        """ALL the region's server RPC addresses, dead ones included —
        ports are fixed, so a restarted server is reachable again at the
        same entry and the ServerProxy's rotation handles the rest."""
        return [
            f"127.0.0.1:{s.rpc_port}"
            for s in self.servers
            if s.region == region
        ]

    # -- chaos actions ---------------------------------------------------
    def kill(self, fs: FedServer):
        """Simulated crash: no gossip leave, listener torn down."""
        with self._lock:
            fs.alive = False
        fs.agent.stop(hard=True)
        try:
            fs.http.stop()
        except Exception:
            pass

    def graceful_stop(self, fs: FedServer):
        with self._lock:
            fs.alive = False
        try:
            fs.http.stop()
        except Exception:
            pass
        fs.agent.stop()

    def restart(self, fs: FedServer):
        """Bring a stopped server back on the same ports and data dir
        (the rolling-upgrade step): raft state recovers from its log,
        gossip rejoins through any live peer."""
        seeds = []
        for s in self.servers:
            if s.alive and s.name != fs.name:
                seeds.append(["127.0.0.1", s.gossip_port])
                break
        self._boot_server(fs, seeds)

    def wait_region_leader(self, region: str, timeout: float = 20.0):
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            if self.leader_of(region) is not None:
                return True
            time.sleep(0.05)
        return False

    def probe_forward(self, src_region: str, dst_region: str) -> bool:
        """One end-to-end forwarding probe: a request entering
        ``src_region``'s HTTP surface naming ``dst_region`` must come
        back answered by the other raft domain."""
        from ..api.client import ApiClient

        addr = self.http_address(src_region)
        if addr is None:
            return False
        try:
            regions, _ = ApiClient(
                address=addr, token=self.mgmt_token
            ).get("/v1/regions", region=dst_region)
            return bool(regions)
        except Exception:
            return False

    def rejoin_gossip(self, a: str, b: str):
        sa, sb = self.anchor(a), self.anchor(b)
        if sa is None or sb is None:
            return
        try:
            sa.agent.server.gossip_join(
                [f"127.0.0.1:{sb.gossip_port}"]
            )
        except Exception:
            logger.exception("gossip rejoin %s->%s failed", a, b)

    def stop(self):
        for fs in self.servers:
            if fs.alive:
                try:
                    self.graceful_stop(fs)
                except Exception:
                    logger.exception("stopping %s failed", fs.name)
        shutil.rmtree(self._tmpdir, ignore_errors=True)


# ---------------------------------------------------------------------------
# driver: per-region storm with cross-region routing + oracle
# ---------------------------------------------------------------------------


class FederatedDriver(StormDriver):
    """One region's open-loop driver. ``via_region``-tagged submits fire
    at the foreign region's HTTP surface with ``?region=<home>`` (the
    forwarding plane); every acknowledged submit enters the shared
    cross-region ORACLE — job id, home region, and whether it crossed
    the WAN — and a later acknowledged stop retires its entry, so the
    final sweep checks exactly the jobs that must exist."""

    def __init__(self, *args, region: str, cluster: FederatedCluster,
                 oracle: dict, oracle_lock: threading.Lock, **kw):
        # region-scoped job ids: region A's slot 3 and region B's slot 3
        # must be DIFFERENT jobs, or the cross-region "present in exactly
        # its home region" oracle reads legitimate same-slot submits in
        # two raft domains as a double commit
        kw.setdefault("job_prefix", f"ldg-{region}")
        super().__init__(*args, **kw)
        self.region = region
        self.cluster = cluster
        self.oracle = oracle
        self._oracle_lock = oracle_lock

    def _fire(self, op, payload, proxy, http):
        from .grammar import build_job, job_id_for

        # re-anchor the HTTP surface per op: chaos kills/restarts the
        # server a worker's client was built against (the leader-kill
        # phase targets exactly it), and a fixed dead endpoint would
        # fail every later HTTP op in the region — an operator's LB
        # follows the live servers, so the driver does too
        addr = self.cluster.http_address(self.region)
        if addr and addr.rstrip("/") != http.address:
            from ..api.client import ApiClient

            http = ApiClient(address=addr, token=self.token)
        via = op.args.get("via_region")
        if op.kind == "job.submit" and via:
            from ..api.client import ApiClient

            addr = self.cluster.http_address(via)
            if addr is None:
                raise ConnectionError(f"no live server in region {via}")
            client = ApiClient(address=addr, token=self.token)
            job = build_job(op.args, self.datacenters, self.job_prefix)
            client.put(
                "/v1/jobs", body={"Job": job.to_dict()}, region=self.region
            )
            self._oracle_record(op, forwarded=True)
            return
        if op.kind == "job.stop" and payload is not None:
            # a stop ATTEMPT retires the oracle entry — before the call,
            # not after the ack: a stop that times out may still have
            # applied (the plan-commit indeterminacy class), and with
            # force-GC in the op mix the stopped job can then vanish —
            # the sweep must never demand presence of a job the storm
            # tried to remove. Retiring early only narrows lost-submit
            # coverage for that one job to its pre-stop lifetime.
            job_id = job_id_for(
                op.args["slot"], payload["category"], self.job_prefix
            )
            with self._oracle_lock:
                self.oracle.pop(("default", job_id), None)
        super()._fire(op, payload, proxy, http)
        if op.kind == "job.submit":
            self._oracle_record(op, forwarded=False)

    def _oracle_record(self, op, forwarded: bool):
        from .grammar import job_id_for

        job_id = job_id_for(
            op.args["slot"], op.args["category"], self.job_prefix
        )
        with self._oracle_lock:
            self.oracle[("default", job_id)] = {
                "namespace": "default",
                "job_id": job_id,
                "region": self.region,
                "forwarded": forwarded,
                "via": op.args.get("via_region"),
                "seq": op.seq,
                # dead batch jobs are legitimate force-GC prey
                # (core_sched job_gc: dead AND (stopped OR batch)), so
                # absence at sweep time is not evidence of loss for
                # them — the invariant checker skips their lost-check
                # (double-commit still applies: GC removes, never adds)
                "may_complete": op.args.get("type") == "batch",
            }


# ---------------------------------------------------------------------------
# chaos executor
# ---------------------------------------------------------------------------


class ChaosExecutor:
    """Fires the config's region-scale chaos events at their scheduled
    offsets into the churn phase, records a timeline (with measured heal
    times), and exposes the affected-link windows the scorer uses to
    classify forwarding failures."""

    def __init__(self, cluster: FederatedCluster, plane: _faults.FaultPlane,
                 cfg: FederationConfig, churn_start: float,
                 time_scale: float = 1.0):
        self.cluster = cluster
        self.plane = plane
        self.cfg = cfg
        self.time_scale = time_scale
        # absolute storm offsets: churn_start + frac * churn_s (key on
        # the offset alone — tuple fallthrough would compare the args
        # dicts when two same-kind events share an offset)
        self.events = sorted(
            [
                (
                    (churn_start + frac * cfg.churn_s) * time_scale,
                    kind,
                    dict(args),
                )
                for frac, kind, args in cfg.chaos
            ],
            key=lambda e: e[0],
        )
        self.timeline: list[dict] = []
        self.heal_times: list[float] = []
        #: (t_open, t_closed, frozenset({a,b})) per severed link window
        self.windows: list[tuple] = []
        #: currently-severed pairs: frozenset({a,b}) -> (t_open, rules).
        #: Keyed per pair so a schedule may sever several links before
        #: one heal — an overwrite would leak the first pair's rules
        #: (never expired, never window-recorded) past the heal
        self._open: dict = {}
        self._stop = threading.Event()
        self._t0: float | None = None
        self._thread = threading.Thread(
            target=self._run, name="fed-chaos", daemon=True
        )

    def start(self, t0: float):
        # nta: ignore[unsynchronized-shared-write] WHY: written before
        # the thread spawn on the next line — pre-spawn publication
        self._t0 = t0
        self._thread.start()

    def join(self, timeout: float = 120.0):
        self._thread.join(timeout=timeout)

    def abort(self):
        self._stop.set()

    def _now(self) -> float:
        return time.monotonic() - self._t0

    def _record(self, kind: str, detail: dict):
        entry = {"t": round(self._now(), 2), "kind": kind, **detail}
        self.timeline.append(entry)
        logger.info("chaos: %s %s", kind, detail)

    def _run(self):
        for at, kind, args in self.events:
            delay = at - self._now()
            if delay > 0 and self._stop.wait(delay):
                return
            if self._stop.is_set():
                return
            try:
                getattr(self, f"_do_{kind}")(args)
            except Exception:
                logger.exception("chaos event %s %s failed", kind, args)
        # a schedule must never end inside a partition: if the last heal
        # was omitted, heal now so quiescence and the final sweep run on
        # a connected federation
        if self._open:
            self._do_heal({})

    # -- events ----------------------------------------------------------
    def _sever(self, kind: str, args, symmetric: bool):
        a, b = args["a"], args["b"]
        pair = frozenset((a, b))
        prior = self._open.pop(pair, None)
        if prior is not None:
            # same link severed again (e.g. partition -> partial_sever
            # with no heal between): retire the superseded rules, keep
            # the ORIGINAL open time — the link has been dark throughout
            self.plane.expire_rules(prior[1])
        rules = self.plane.partition_regions(a, b, symmetric=symmetric)
        t_open = prior[0] if prior is not None else self._now()
        self._open[pair] = (t_open, rules)
        self._record(kind, {"a": a, "b": b})

    def _do_partition(self, args):
        self._sever("partition", args, symmetric=True)

    def _do_partial_sever(self, args):
        self._sever("partial_sever", args, symmetric=False)

    def _do_heal(self, args):
        if not self._open:
            return
        open_pairs, self._open = self._open, {}
        for _, rules in open_pairs.values():
            self.plane.expire_rules(rules)
        pairs = list(open_pairs)
        t_heal_start = self._now()
        self._record("heal_start", {"pairs": [sorted(p) for p in pairs]})
        # reconnect gossip both ways, then measure until forwarding
        # works end-to-end in both directions (the operator-visible
        # definition of "healed")
        for pair in pairs:
            a, b = sorted(pair)
            self.cluster.rejoin_gossip(a, b)
            self.cluster.rejoin_gossip(b, a)
        deadline = time.monotonic() + 30.0
        healed = False
        while time.monotonic() < deadline and not self._stop.is_set():
            if all(
                self.cluster.probe_forward(a, b)
                and self.cluster.probe_forward(b, a)
                for pair in pairs
                for a, b in [sorted(pair)]
            ):
                healed = True
                break
            time.sleep(0.05)
        heal_s = round(self._now() - t_heal_start, 2)
        if healed:
            self.heal_times.append(heal_s)
        t_closed = self._now()
        for pair, (t_open, _) in open_pairs.items():
            self.windows.append((t_open, t_closed, pair))
        self._record(
            "heal", {"heal_s": heal_s if healed else None, "ok": healed}
        )

    def disruption_windows(self, grace: float = 10.0) -> list[tuple]:
        """(t_lo, t_hi) storm-offset windows in which the cluster was
        being actively disrupted: severed-link windows plus a grace
        neighborhood around leader kills and rolling-restart steps. The
        scorer uses these to classify MID-STORM invariant violations: a
        failover can transiently double-run an alloc (the reconciler
        retires the extra — Nomad's replacement semantics), which is
        chaos-by-design as long as the final sweep comes back clean."""
        wins = [
            (t_open - grace, t_close + grace)
            for t_open, t_close, _ in self.windows
        ]
        for e in self.timeline:
            if e["kind"] in ("leader_kill", "rolling_restart_step"):
                # t stamps the END of the step; step_s covers its start
                lo = e["t"] - e.get("step_s", 0.0) - grace
                wins.append((lo, e["t"] + grace))
        return wins

    def _do_leader_kill(self, args):
        region = args["region"]
        leader = self.cluster.leader_of(region)
        if leader is None:
            self._record("leader_kill", {"region": region, "skipped": True})
            return
        self.cluster.kill(leader)
        elected = self.cluster.wait_region_leader(region)
        self._record(
            "leader_kill",
            {"region": region, "killed": leader.name,
             "reelected": elected},
        )

    def _do_rolling_restart(self, args):
        region = args["region"]
        for fs in list(self.cluster.live_servers(region)):
            if self._stop.is_set():
                return
            t_step = self._now()
            self.cluster.graceful_stop(fs)
            self.cluster.restart(fs)
            leader_ok = self.cluster.wait_region_leader(region)
            self._record(
                "rolling_restart_step",
                {
                    "region": region,
                    "server": fs.name,
                    "leader_after": leader_ok,
                    "step_s": round(self._now() - t_step, 2),
                },
            )


# ---------------------------------------------------------------------------
# scorekeeper
# ---------------------------------------------------------------------------


class FederationScorekeeper:
    """Samples every region on an interval: per-region flight-recorder
    snapshots (through each anchor server's own recorder, so the
    watchdog — acl_replication_lag rule included — rides the same
    samples), per-region incremental invariant sweeps (re-anchored when
    chaos replaces the server object), and ACL replication-lag probes —
    a nonce policy written to the authoritative region and timed until
    each replica region's state shows it."""

    def __init__(self, cluster: FederatedCluster, cfg: FederationConfig,
                 seed: int = 0):
        self.cluster = cluster
        self.cfg = cfg
        self.seed = seed
        self.samples: dict[str, list[dict]] = {
            r: [] for r in cluster.regions
        }
        self.violations: dict[str, list[dict]] = {
            r: [] for r in cluster.regions
        }
        #: measured replication convergence probes:
        #: {region, t_sent, t_obs (storm offsets), lag_s}. Kept per-probe
        #: so the report can classify partition-stalled probes (lag by
        #: design) apart from steady-state convergence lag
        self.repl_lags: list[dict] = []
        self._checkers: dict[str, tuple] = {}
        self._probe_nonce = 0
        #: region -> (nonce, t_sent) for the probe it hasn't seen yet
        self._pending_probe: dict[str, tuple] = {}
        self._stop = threading.Event()
        self._t0: float | None = None
        self._thread = threading.Thread(
            target=self._run, name="fed-scorekeeper", daemon=True
        )

    def start(self, t0: float):
        # nta: ignore[unsynchronized-shared-write] WHY: written before
        # the thread spawn on the next line — pre-spawn publication
        self._t0 = t0
        self._thread.start()

    def stop(self):
        self._stop.set()
        self._thread.join(timeout=10.0)

    def _run(self):
        ticks = 0
        while not self._stop.wait(self.cfg.sample_interval):
            ticks += 1
            try:
                self._tick(ticks)
            except Exception:
                logger.exception("federation scorekeeper tick failed")

    def _tick(self, ticks: int):
        t = round(time.monotonic() - self._t0, 2)
        for region in self.cluster.regions:
            fs = self.cluster.anchor(region)
            if fs is None:
                continue
            server = fs.agent.server
            try:
                sample = dict(server.flight_recorder.record())
            except Exception:
                continue
            sample["t"] = t
            sample["server"] = fs.name
            self.samples[region].append(sample)
            if ticks % self.cfg.invariants_every == 0:
                self._sweep(region, server, t)
        if ticks % self.cfg.repl_probe_every == 0:
            self._probe_replication(t)
        self._check_probe_arrival(t)

    def _sweep(self, region: str, server, t: float):
        checker_entry = self._checkers.get(region)
        if checker_entry is None or checker_entry[0] is not server.state:
            # chaos replaced the anchor (restart / failover): re-anchor a
            # fresh incremental checker on the new replica's store
            checker_entry = (
                server.state,
                IncrementalInvariantChecker(
                    server.state, max_fit_nodes=256, seed=self.seed
                ),
            )
            self._checkers[region] = checker_entry
        for v in checker_entry[1].check(quiesced=False):
            self.violations[region].append({"t": t, "violation": v})

    def _probe_replication(self, t: float):
        from ..structs.model import AclPolicy

        auth = self.cluster.leader_of(self.cluster.auth_region)
        if auth is None:
            return
        self._probe_nonce += 1
        nonce = self._probe_nonce
        try:
            auth.agent.server.acl_upsert_policies(
                [
                    AclPolicy(
                        name="fed-replication-probe",
                        description="loadgen federation lag probe",
                        rules=f"# probe nonce {nonce}",
                    )
                ]
            )
        except Exception:
            return  # auth region mid-election: probe next tick
        now = time.monotonic()
        for region in self.cluster.regions:
            if region != self.cluster.auth_region:
                # one in-flight probe per region; a newer nonce replaces
                # an unobserved older one (the lag keeps accruing from
                # the OLD send time — replication is behind both)
                old = self._pending_probe.get(region)
                self._pending_probe[region] = (
                    nonce, old[1] if old else now
                )

    def _check_probe_arrival(self, t: float):
        for region, (nonce, t_sent) in list(self._pending_probe.items()):
            fs = self.cluster.anchor(region)
            if fs is None:
                continue
            try:
                policy = fs.agent.server.state.acl_policy_by_name(
                    "fed-replication-probe"
                )
            except Exception:
                continue
            if policy is not None and f"nonce {nonce}" in policy.rules:
                now = time.monotonic()
                self.repl_lags.append(
                    {
                        "region": region,
                        "t_sent": round(t_sent - self._t0, 2),
                        "t_obs": round(now - self._t0, 2),
                        "lag_s": round(now - t_sent, 3),
                    }
                )
                del self._pending_probe[region]

    def checker_stats(self) -> dict:
        return {
            region: entry[1].stats()
            for region, entry in self._checkers.items()
        }


# ---------------------------------------------------------------------------
# the runner
# ---------------------------------------------------------------------------


def _percentile(xs: list[float], pct: float) -> float:
    if not xs:
        return 0.0
    ordered = sorted(xs)
    return ordered[min(len(ordered) - 1, int(len(ordered) * pct))]


def _chaos_event_windows(
    chaos: "ChaosExecutor", grace: float
) -> dict:
    """region -> [(lo, hi)] windows around leader kills and rolling-
    restart steps: chaos that disrupts a region's servers without a
    severed-link window to show for it."""
    event_windows: dict[str, list[tuple[float, float]]] = {}
    for e in chaos.timeline:
        if e["kind"] in ("leader_kill", "rolling_restart_step"):
            lo = e["t"] - e.get("step_s", 0.0) - grace
            event_windows.setdefault(e["region"], []).append(
                (lo, e["t"] + grace)
            )
    return event_windows


def _link_disrupted(
    t_lo: float, t_hi: float, a: str, b: str,
    chaos: "ChaosExecutor", event_windows: dict, grace: float,
) -> bool:
    """Was traffic between regions ``a`` and ``b`` over [t_lo, t_hi]
    subject to declared chaos — a severed-link window covering the pair,
    or a leader kill / restart step in EITHER endpoint region?"""
    if any(
        a in pair
        and b in pair
        and t_lo <= t_close + grace
        and t_hi >= t_open - grace
        for t_open, t_close, pair in chaos.windows
    ):
        return True
    return any(
        t_lo <= hi and t_hi >= lo
        for region in (a, b)
        for lo, hi in event_windows.get(region, ())
    )


def _replication_lag_split(
    probes: list[dict], chaos: "ChaosExecutor", auth: str,
    grace: float = 3.0,
) -> tuple[list[float], list[float]]:
    """→ (steady_lags, chaos_lags): a probe whose in-flight interval
    overlaps chaos that stalls replication was lagged by design — the
    SLO grades the steady-state tail, the chaos tail is reported
    separately. Replication-impacting chaos is (a) a severed-link
    window touching the (auth, region) WAN link, and (b) a leader kill
    or rolling-restart step in the REPLICA's region (its leader runs
    the replication loop; a kill stalls the pull until re-election) or
    in the authoritative region (its servers answer it)."""
    event_windows = _chaos_event_windows(chaos, grace)
    steady, chaotic = [], []
    for p in probes:
        in_window = _link_disrupted(
            p["t_sent"], p["t_obs"], p["region"], auth,
            chaos, event_windows, grace,
        )
        (chaotic if in_window else steady).append(p["lag_s"])
    return steady, chaotic


def _forward_failure_split(
    results, stream, chaos: "ChaosExecutor", home: str,
    grace: float = 3.0,
) -> tuple[int, int, int, list]:
    """→ (attempted, failed_outside_windows, failed_inside_windows,
    failure_details) for the cross-region submits of one region's
    driver (``home``). A failure whose firing interval overlaps
    declared chaos on its via→home hop — a severed-link window
    covering the pair, or a leader kill / rolling-restart step in
    either endpoint region (a restarting server resets in-flight
    forwards, which correctly surface as outcome-unknown) — is
    chaos-by-design; one outside every window is a forwarding bug.
    The details (timestamped, window-classified, error-tailed) land in
    the artifact per region."""
    ops_by_seq = {op.seq: op for op in stream.ops}
    event_windows = _chaos_event_windows(chaos, grace)
    attempted = failed_out = failed_in = 0
    details: list[dict] = []
    for r in results:
        op = ops_by_seq.get(r.seq)
        if op is None or op.kind != "job.submit":
            continue
        via = op.args.get("via_region")
        if not via:
            continue
        attempted += 1
        if r.ok or r.expected_miss or r.shed:
            continue
        # the WAN link exercised: via -> home (the forward direction)
        link_in_window = _link_disrupted(
            r.t_start, r.t_done, via, home, chaos, event_windows, grace,
        )
        if link_in_window:
            failed_in += 1
        else:
            failed_out += 1
        details.append(
            {
                "t_start": round(r.t_start, 2),
                "t_done": round(r.t_done, 2),
                "via": via,
                "home": home,
                "in_window": link_in_window,
                "error": r.error[:160],
            }
        )
    return attempted, failed_out, failed_in, details


def _wait_replication_settled(cluster, timeout: float = 15.0):
    """Settle barrier before the final cross-region sweep: the
    scorekeeper's LAST lag probe (and any late policy write) may still
    be mid-replication when it stops — the contract is convergence with
    *bounded* lag, so equality is asserted only after replication had
    one bounded window to drain. Times out silently: a genuinely stuck
    replica then fails the final sweep loudly, which is the point."""
    auth = cluster.anchor(cluster.auth_region)
    if auth is None:
        return
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        try:
            want = {
                p.name: p.rules
                for p in auth.agent.server.state.acl_policies()
            }
        except Exception:
            return
        settled = True
        for region in cluster.regions:
            if region == cluster.auth_region:
                continue
            fs = cluster.anchor(region)
            if fs is None:
                continue
            try:
                got = {
                    p.name: p.rules
                    for p in fs.agent.server.state.acl_policies()
                }
            except Exception:
                settled = False
                break
            if got != want:
                settled = False
                break
        if settled:
            return
        time.sleep(0.1)


def run_federation(
    cfg: FederationConfig | None = None,
    seed: int = 1,
    out: str | None = None,
    time_scale: float = 1.0,
) -> dict:
    """One federated storm end-to-end; returns the scored report (also
    written to ``out``). Grading is the caller's verdict, same contract
    as run_scenario."""
    from .runner import wait_quiescent

    cfg = cfg or federation_config_from_env()
    regions = cfg.region_names()

    # compile + route every region's stream FIRST: the determinism
    # contract (same seed -> same per-region digest) holds before any
    # cluster exists
    streams: dict[str, OpStream] = {}
    for region in regions:
        base = compile_stream(region_scenario(region, cfg), seed)
        streams[region] = route_cross_region(
            base, region, [r for r in regions if r != region], seed,
            cfg.cross_region_p,
        )
    for region, stream in streams.items():
        logger.info(
            "compiled %s: %d ops (digest %s)",
            stream.scenario_name, len(stream.ops), stream.digest()[:12],
        )

    churn_start = sum(
        p.duration for p in region_scenario(regions[0], cfg).phases[:3]
    )
    plane = _faults.install(_faults.FaultPlane(seed=seed))
    cluster = FederatedCluster(cfg, seed=42)
    scorekeeper = None
    chaos = None
    try:
        cluster.start()
        cluster.wait_ready()

        oracle: dict = {}
        oracle_lock = threading.Lock()
        drivers = {
            region: FederatedDriver(
                streams[region],
                cluster.rpc_addresses(region),
                cluster.http_address(region),
                workers=cfg.driver_workers,
                time_scale=time_scale,
                token=cluster.mgmt_token,
                region=region,
                cluster=cluster,
                oracle=oracle,
                oracle_lock=oracle_lock,
            )
            for region in regions
        }

        t0 = time.monotonic()
        scorekeeper = FederationScorekeeper(cluster, cfg, seed=seed)
        scorekeeper.start(t0)
        chaos = ChaosExecutor(
            cluster, plane, cfg, churn_start, time_scale=time_scale
        )
        chaos.start(t0)

        driver_reports: dict[str, object] = {}
        threads = []
        for region, driver in drivers.items():
            def _run(region=region, driver=driver):
                driver_reports[region] = driver.run()

            th = threading.Thread(
                target=_run, name=f"fed-driver-{region}", daemon=True
            )
            th.start()
            threads.append(th)
        for th in threads:
            th.join()
        chaos.join()

        # quiesce every region (on its current leader), then the final
        # cross-region oracle over every region's replicated state
        quiesced = {}
        for region in regions:
            fs = cluster.anchor(region)
            quiesced[region] = (
                wait_quiescent(fs.agent.server, cfg.quiesce_timeout)
                if fs is not None
                else False
            )
        scorekeeper.stop()
        _wait_replication_settled(cluster)

        region_states = {
            region: cluster.anchor(region).agent.server.state
            for region in regions
            if cluster.anchor(region) is not None
        }
        with oracle_lock:
            oracle_entries = list(oracle.values())
        final_violations = check_federation_invariants(
            region_states,
            oracle=oracle_entries,
            acl_authoritative=cluster.auth_region,
        )
        report = _assemble_report(
            cfg, seed, cluster, streams, drivers, driver_reports,
            scorekeeper, chaos, oracle_entries, final_violations, quiesced,
        )
        if out:
            write_report(report, out)
        return report
    finally:
        if scorekeeper is not None:
            scorekeeper.stop()
        if chaos is not None:
            chaos.abort()
        _faults.uninstall()
        cluster.stop()


def _assemble_report(
    cfg, seed, cluster, streams, drivers, driver_reports, scorekeeper,
    chaos, oracle_entries, final_violations, quiesced,
) -> dict:
    regions = cluster.regions
    lost = sum(
        1 for v in final_violations if "lost cross-region submit" in v
    )
    double = sum(
        1
        for v in final_violations
        if "double-committed cross-region submit" in v
    )
    # mid-storm violations inside an active disruption window are
    # transient-by-design IF the final sweep is clean (a failover window
    # can double-run an alloc until the reconciler retires the extra);
    # one outside every window — or any final violation — is a real bug
    disruption = chaos.disruption_windows()
    mid_storm = {}
    mid_storm_count = transient_count = 0
    for region in regions:
        entries = []
        for entry in scorekeeper.violations[region]:
            in_window = any(
                lo <= entry["t"] <= hi for lo, hi in disruption
            )
            entries.append({**entry, "in_disruption_window": in_window})
            if in_window:
                transient_count += 1
            else:
                mid_storm_count += 1
        mid_storm[region] = entries

    fwd_attempted = fwd_failed_out = fwd_failed_in = 0
    per_region = {}
    agg = {"fired": 0, "ok": 0, "failed": 0, "expected_miss": 0, "shed": 0}
    for region in regions:
        rep = driver_reports.get(region)
        drv = rep.to_dict() if rep is not None else {}
        for k in agg:
            agg[k] += drv.get(k, 0)
        # the window classification needs the raw per-op results (which
        # live on the driver, not its report): a forwarded submit that
        # failed INSIDE a severed-link window is chaos-by-design, one
        # outside every window is a forwarding bug
        att, out_w, in_w, fwd_details = _forward_failure_split(
            drivers[region].results, streams[region], chaos, region,
        )
        fwd_attempted += att
        fwd_failed_out += out_w
        fwd_failed_in += in_w
        samples = scorekeeper.samples[region]
        # per-failure timelines: cheap (failures only, capped) and the
        # difference between "debuggable artifact" and "rerun with logs"
        failed_ops = [
            {
                "t": round(r.t_start, 2),
                "kind": r.kind,
                "error": r.error[:160],
            }
            for r in drivers[region].results
            if not (r.ok or r.expected_miss or r.shed)
        ][:200]
        per_region[region] = {
            "servers": sum(1 for s in cluster.servers if s.region == region),
            "stream_digest": streams[region].digest(),
            "stream_ops": len(streams[region].ops),
            "driver": drv,
            "failed_ops": failed_ops,
            "fwd_failures": fwd_details,
            "quiesced": quiesced.get(region, False),
            "mid_storm_violations": mid_storm[region],
            "rss_peak_mb": max(
                (s.get("rss_mb", 0.0) for s in samples), default=0.0
            ),
            "acl_replication_lag_s_max": max(
                (
                    s["acl_replication_lag_s"]
                    for s in samples
                    if "acl_replication_lag_s" in s
                ),
                default=0.0,
            ),
            "watchdog": (
                cluster.anchor(region).agent.server.watchdog.stats()
                if cluster.anchor(region) is not None
                and cluster.anchor(region).agent.server.watchdog is not None
                else None
            ),
            "samples": samples,
        }

    total_violations = len(final_violations) + mid_storm_count
    unhealed = any(
        e["kind"] == "heal" and not e.get("ok") for e in chaos.timeline
    )
    # a partition that never measurably healed fails the heal SLO loudly
    # (finite sentinel: the artifact stays strict JSON)
    heal_s = (
        9999.0 if unhealed
        else (max(chaos.heal_times) if chaos.heal_times else 0.0)
    )
    steady_lags, chaos_lags = _replication_lag_split(
        scorekeeper.repl_lags, chaos, cluster.auth_region
    )
    repl_p99 = round(_percentile(steady_lags, 0.99), 3)
    report = {
        "scenario": "federation",
        "profile": "smoke" if cfg.servers_per_region == 1 else "storm",
        "seed": seed,
        "regions": per_region,
        "region_names": regions,
        "servers_total": len(cluster.servers),
        "driver": agg,
        "chaos": chaos.timeline,
        "oracle_checked_submits": len(oracle_entries),
        "oracle_forwarded_submits": sum(
            1 for e in oracle_entries if e.get("forwarded")
        ),
        "fed_fwd_attempted": fwd_attempted,
        "fed_fwd_failed": fwd_failed_out,
        "fed_fwd_failed_in_chaos": fwd_failed_in,
        "fed_fwd_err_rate": round(
            fwd_failed_out / max(fwd_attempted, 1), 4
        ),
        "fed_heal_s": heal_s,
        "fed_heal_times": chaos.heal_times,
        "fed_replication_lag_p99_s": repl_p99,
        "fed_replication_lag_chaos_max_s": round(max(chaos_lags, default=0.0), 3),
        "fed_replication_probes": len(scorekeeper.repl_lags),
        "fed_replication_probes_in_chaos": len(chaos_lags),
        "replication_probes": scorekeeper.repl_lags,
        "fed_lost_placements": lost,
        "fed_double_placements": double,
        "fed_invariant_violations": total_violations,
        "fed_transient_violations": transient_count,
        "disruption_windows": [
            [round(lo, 2), round(hi, 2)] for lo, hi in disruption
        ],
        "final_violations": final_violations,
        "invariant_checkers": scorekeeper.checker_stats(),
        "watchdog_trips": sum(
            (per_region[r]["watchdog"] or {}).get("trips", 0)
            for r in regions
        ),
        "quiesced": all(quiesced.get(r, False) for r in regions),
    }
    slo = grade(report, cfg.slos)
    # a federation that cannot quiesce failed no matter what the samples
    # say (same contract as the soak runner)
    ok = report["quiesced"]
    slo["checks"]["quiesced"] = {"target": True, "actual": ok, "pass": ok}
    slo["passed" if ok else "failed"] += 1
    slo["score"] = round(slo["passed"] / (slo["passed"] + slo["failed"]), 3)
    report["slo"] = slo
    return report


def summary_line(report: dict) -> str:
    """The trailing FED_SUMMARY line (log-tail-survival contract)."""
    slo = report["slo"]
    digests = ",".join(
        f"{r}:{report['regions'][r]['stream_digest'][:8]}"
        for r in report["region_names"]
    )
    parts = [
        f"regions={len(report['region_names'])}",
        f"servers={report['servers_total']}",
        f"seed={report['seed']}",
        f"ops={report['driver']['fired']}",
        f"ok={report['driver']['ok']}",
        f"failed={report['driver']['failed']}",
        f"fwd={report['fed_fwd_attempted']}",
        f"fwd_err_rate={report['fed_fwd_err_rate']}",
        f"fwd_chaos_failed={report['fed_fwd_failed_in_chaos']}",
        f"heal_s={report['fed_heal_s']}",
        f"repl_lag_p99_s={report['fed_replication_lag_p99_s']}",
        f"invariant_violations={report['fed_invariant_violations']}",
        f"transient_violations={report['fed_transient_violations']}",
        f"lost={report['fed_lost_placements']}",
        f"double={report['fed_double_placements']}",
        f"oracle_submits={report['oracle_checked_submits']}",
        f"watchdog_trips={report['watchdog_trips']}",
        f"slo={slo['passed']}/{slo['passed'] + slo['failed']}",
        f"score={slo['score']}",
        f"digests={digests}",
    ]
    return "FED_SUMMARY " + " ".join(parts)


def run_federation_from_env(
    seed: int, out: str | None = None, time_scale: float = 1.0
) -> dict:
    return run_federation(
        federation_config_from_env(), seed=seed, out=out,
        time_scale=time_scale,
    )
