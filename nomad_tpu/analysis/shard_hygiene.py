"""Shard-spec hygiene for the device mesh (nomad_tpu/tpu/shard.py).

The sharded planner's zero-recompile and bit-parity contracts rest on
one discipline: in a code path where a device mesh is active, every
array placement and every jit must state its sharding. A bare
``jax.device_put(x)`` next to sharded inputs hands XLA a layout choice
the warmup never compiled (a silent recompile plus a possible gather on
the hot path), and a ``jax.jit`` without ``out_shardings`` may return a
replicated buffer where the caller's next dispatch expects the
partitioned one (the exact class the mirror's scatter refresh pins with
an explicit out sharding).

Rule ``shard-spec-drift`` (scoped to ``nomad_tpu/tpu/``): inside a
function that references a mesh (a ``mesh``-named parameter/local, a
call to ``active_mesh``/``configure``, or a spec-tree fetch —
``batch_specs``/``run_specs``/``window_specs``/``wavefront_specs``),
flag

- ``device_put`` calls carrying no sharding (single argument, no
  ``device=``/``sharding=`` keyword), and
- ``jax.jit`` calls carrying neither ``out_shardings`` nor
  ``in_shardings``,

EXCEPT in statically-unsharded regions — the body of
``if <mesh> is None:`` and the else of ``if <mesh> is not None:`` —
where the single-chip defaults are exactly right. Deliberate
exceptions take a ``# nta: ignore[shard-spec-drift]`` with a WHY.
"""

from __future__ import annotations

import ast

from .framework import Finding, Project, dotted, register

_SCOPE = "nomad_tpu/tpu/"

#: calls that make a function a "sharded code path" even without a
#: mesh-named binding
_MESH_CALLS = {"active_mesh", "configure"}

#: spec-tree constructors (shard.py): a function fetching a
#: PartitionSpec tree is preparing sharded placements, so it is
#: mesh-active even when the mesh object itself never appears by name
#: (e.g. the specs are fetched for a put() further down the call chain)
_SPEC_CALLS = {
    "batch_specs", "run_specs", "window_specs", "wavefront_specs",
    "paged_specs",
}


def _mentions_mesh(node: ast.AST) -> bool:
    """The expression names a mesh: ``mesh``, ``self.mesh``,
    ``span_mesh``, ``all_mesh``, ..."""
    name = dotted(node)
    if not name:
        return False
    tail = name.rsplit(".", 1)[-1]
    return tail == "mesh" or tail.endswith("_mesh")


def _mesh_gate(test: ast.AST):
    """Classify an if-test over a mesh: returns 'is_none' / 'not_none' /
    None (not a mesh gate)."""
    if not isinstance(test, ast.Compare) or len(test.ops) != 1:
        return None
    op = test.ops[0]
    left, right = test.left, test.comparators[0]
    none_side = (
        right if isinstance(right, ast.Constant) and right.value is None
        else left if isinstance(left, ast.Constant) and left.value is None
        else None
    )
    mesh_side = right if none_side is left else left
    if none_side is None or not _mentions_mesh(mesh_side):
        return None
    if isinstance(op, ast.Is):
        return "is_none"
    if isinstance(op, ast.IsNot):
        return "not_none"
    return None


def _function_references_mesh(fn) -> bool:
    for arg in list(fn.args.args) + list(fn.args.kwonlyargs):
        if arg.arg == "mesh" or arg.arg.endswith("_mesh"):
            return True
    for node in ast.walk(fn):
        if isinstance(node, ast.Name) and (
            node.id == "mesh" or node.id.endswith("_mesh")
        ):
            return True
        if isinstance(node, ast.Attribute) and _mentions_mesh(node):
            return True
        if isinstance(node, ast.Call):
            name = dotted(node.func)
            tail = name.rsplit(".", 1)[-1]
            if tail in _MESH_CALLS and "shard" in name:
                return True
            if tail in _SPEC_CALLS and "shard" in name:
                return True
    return False


def _unsharded_lines(fn) -> set[int]:
    """Line numbers inside statically-unsharded regions (mesh-is-None
    branches), where bare placements are the correct single-chip path."""
    lines: set[int] = set()

    def mark(stmts):
        for s in stmts:
            for node in ast.walk(s):
                ln = getattr(node, "lineno", None)
                if ln is not None:
                    lines.add(ln)

    for node in ast.walk(fn):
        if not isinstance(node, ast.If):
            continue
        gate = _mesh_gate(node.test)
        if gate == "is_none":
            mark(node.body)
        elif gate == "not_none":
            mark(node.orelse)
    return lines


@register(
    "shard-spec-drift",
    "device_put/jax.jit in a mesh-active tpu/ code path without an "
    "explicit sharding/out_shardings (silent recompile + layout drift)",
)
def check_shard_spec_drift(project: Project) -> list[Finding]:
    findings = []
    for mod in project.modules:
        if not mod.relpath.startswith(_SCOPE):
            continue
        for fn in ast.walk(mod.tree):
            if not isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            if not _function_references_mesh(fn):
                continue
            exempt = _unsharded_lines(fn)
            for node in ast.walk(fn):
                if not isinstance(node, ast.Call):
                    continue
                if node.lineno in exempt:
                    continue
                name = dotted(node.func)
                tail = name.rsplit(".", 1)[-1]
                if tail == "device_put":
                    has_spec = len(node.args) >= 2 or any(
                        kw.arg in ("device", "sharding")
                        for kw in node.keywords
                    )
                    if not has_spec:
                        findings.append(
                            Finding(
                                "shard-spec-drift", mod.relpath,
                                node.lineno,
                                f"{name}() without a sharding in a "
                                "mesh-active path: pass the "
                                "NamedSharding (or shard.put) so the "
                                "layout matches what warmup compiled",
                            )
                        )
                elif tail == "jit" and name.startswith("jax"):
                    has_spec = any(
                        kw.arg in ("out_shardings", "in_shardings")
                        for kw in node.keywords
                    )
                    if not has_spec:
                        findings.append(
                            Finding(
                                "shard-spec-drift", mod.relpath,
                                node.lineno,
                                f"{name}() without out_shardings in a "
                                "mesh-active path: pin the output "
                                "partitioning or GSPMD may hand back a "
                                "replicated buffer",
                            )
                        )
    return findings
