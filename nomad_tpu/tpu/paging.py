"""Paged node axis: stream million-node dense planes through device
memory in fixed-size tiles.

Every planner before this one assumed the full node-axis planes
(capacity/usable/feasible/used/collisions) are device-resident, so the
problem size was capped by one device's memory, not by the algorithm.
This module removes that cap for the windowed regime — the 1M-node
workload ROADMAP item 1 names — by decomposing
``kernel._plan_batch_windowed_jit`` into per-tile sweeps whose
cross-tile finish is **bit-identical to the flat scan**:

**Tiling in ring coordinates.** The node axis is pre-gathered through
the eval's shuffled ``perm`` into rotation order and split into
``tile_rows()``-sized tiles (THE tile bucketing policy — one compiled
program per tile shape, the 51200-vs-50176 recompile class cannot
reappear on the tile axis). Every per-round reduction of the flat
windowed planner decomposes exactly over that split:

- the rotation prefix-sum (``kernel._rot_incl``) is the two-stage
  tournament of ``wavefront._tcumsum`` with tiles as the outer stage:
  each tile's local exclusive cumsum is rebased by the host-combined
  exclusive sum of the per-tile feasible counts (sweep 1), and the
  ring-offset correction is one scalar ``X0 = Σ count(fit & pos <
  offset)``. Integer sums are exact, so ranks are bit-identical.
- the per-window segmented argmax (score max, then min-feasible-rank
  tie-break) becomes per-tile partials — (max score, min rank among
  tile-local maxima, winner node) per window intersecting the tile
  (sweep 2) — combined across tiles on the host by the same
  lexicographic rule. Float max is order-insensitive and every
  comparison is exact, so the winner per window is the flat scan's
  winner, bit for bit.

**Double-buffered H2D stream.** Tiles upload through a budget-bounded
``TileCache``: before sweeping tile r the pager issues tile r+1's
uploads (JAX async dispatch overlaps the transfer with tile r's
compute), and device-resident bytes never exceed
``paging{device_node_budget_mb}`` (floored at two tiles so the double
buffer stays legal — the effective limit is recorded in the stats).
Static planes (capacity/usable/feasible/node ids) upload once per
residency; the dynamic planes (used/collisions) re-upload only when a
committed placement dirtied the tile — steady-state rounds re-upload
only touched tiles, counted in the devprof transfer ledger
(``paged_tile_reuploads``) and watched by the ``h2d_thrash`` rule.

**The host oracle is unchanged.** ``plan_windowed_np`` (below) is a
pure-numpy replica of the flat windowed planner — float32 op-for-op,
including the bit-stable ``_pow10`` exponent assembly — used by the
bench/tests as the parity pin for the paged path; the exact-np
sequential oracle that dispatch degrades to is untouched.

Config stanza ``paging{enabled, device_node_budget_mb, tile_nodes}``
(env: ``NOMAD_TPU_PAGING``, ``NOMAD_TPU_PAGING_BUDGET_MB``,
``NOMAD_TPU_PAGING_TILE_NODES``); off by default, and paging off is
byte-identical to the flat dispatch path (pinned by the A/B test).
``batch_sched`` routes the windowed regime through ``plan_batch_paged``
when ``should_page(N)`` says the planes exceed the resident budget;
``mirror.device_state`` refuses to build an over-budget full mirror for
the same reason (drain degrades to its host-plane path and counts why).
"""

from __future__ import annotations

import os
import threading

import jax
import jax.numpy as jnp
import numpy as np

from ..debug import devprof as _devprof
from ..testing import faults as _faults
from . import kernel as _kernel
from .kernel import (
    _LOG2_10,
    _LOG2_10_HI,
    _LOG2_10_LO,
    NEG_INF,
    _binpack,
)

_BIG = 2**30

# ---------------------------------------------------------------------------
# config stanza (mirrors wavefront.py's module state: explicit configure()
# wins, env is the library-code default, disabled until someone opts in)
# ---------------------------------------------------------------------------

DEFAULT_BUDGET_MB = 256
DEFAULT_TILE_NODES = 65536
#: floor for the tile policy — below this the per-tile dispatch overhead
#: dwarfs the compute and the window partial arrays stop amortizing
#: (tests configure down to it to exercise the multi-tile combine)
MIN_TILE_NODES = 64

_lock = threading.Lock()
_state = {"enabled": None, "budget_mb": None, "tile_nodes": None}


def configure(enabled=None, device_node_budget_mb=None, tile_nodes=None):
    """Set the paging knobs from config (server passthrough) or tests.
    ``None`` leaves a knob on its env/default resolution."""
    with _lock:
        if enabled is not None:
            _state["enabled"] = bool(enabled)
        if device_node_budget_mb is not None:
            _state["budget_mb"] = max(1, int(device_node_budget_mb))
        if tile_nodes is not None:
            _state["tile_nodes"] = max(1, int(tile_nodes))
    if tile_nodes is not None:
        # the committed planes stamp dirtiness at the same granularity
        # the H2D stream pages at (instances latch at axis rebuild)
        from ..state import planes as _planes

        _planes.TILE_ROWS = tile_rows()


def reset():
    """Back to env/default resolution (test isolation)."""
    with _lock:
        _state.update({"enabled": None, "budget_mb": None,
                       "tile_nodes": None})


def enabled() -> bool:
    """Whether dispatch may route over-budget node axes through the
    pager (config stanza, env ``NOMAD_TPU_PAGING=1``)."""
    with _lock:
        v = _state["enabled"]
    if v is not None:
        return v
    return os.environ.get("NOMAD_TPU_PAGING", "0") == "1"


def budget_mb() -> int:
    """Device-resident node-plane budget in MB."""
    with _lock:
        v = _state["budget_mb"]
    if v is not None:
        return v
    return max(1, int(os.environ.get(
        "NOMAD_TPU_PAGING_BUDGET_MB", str(DEFAULT_BUDGET_MB))))


def _tile_nodes_raw() -> int:
    with _lock:
        v = _state["tile_nodes"]
    if v is not None:
        return v
    return max(1, int(os.environ.get(
        "NOMAD_TPU_PAGING_TILE_NODES", str(DEFAULT_TILE_NODES))))


def tile_rows(mesh=None) -> int:
    """THE tile bucketing policy: the configured ``tile_nodes`` rounded
    up to a power of two (never below ``MIN_TILE_NODES``) and to a mesh
    multiple, independent of the cluster size — one compiled tile shape
    per configuration, single source for dispatch AND the warmup
    prewarm ladder (the 51200-vs-50176 drift class stays dead on the
    tile axis)."""
    t = max(MIN_TILE_NODES, _tile_nodes_raw())
    p = 1
    while p < t:
        p *= 2
    t = p
    if mesh is not None:
        from . import shard as _shard

        m = max(1, _shard.mesh_size(mesh))
        t = ((t + m - 1) // m) * m
    return t


#: bytes per node of device-resident plane state in the paged layout:
#: capacity i32[C] + used i32[C] + usable f32[2] + node id i32 +
#: collisions i32 + feasible bool
def plane_bytes_per_node(r_cols: int = 3) -> int:
    return 8 * r_cols + 13


def plane_bytes(n_pad: int, r_cols: int = 3) -> int:
    """Device bytes the FLAT windowed dispatch would pin resident for an
    ``n_pad``-row node axis — the number the budget gate compares."""
    return int(n_pad) * plane_bytes_per_node(r_cols)


def should_page(n_pad: int, r_cols: int = 3) -> bool:
    """True when paging is enabled and the flat planes for ``n_pad``
    nodes exceed the resident budget."""
    return enabled() and plane_bytes(n_pad, r_cols) > budget_mb() * (1 << 20)


# ---------------------------------------------------------------------------
# budget-bounded tile cache: static planes upload once per residency,
# dynamic planes (used/collisions) re-upload only when dirtied
# ---------------------------------------------------------------------------


def _tree_nbytes(tree) -> int:
    return sum(int(np.asarray(x).nbytes) for x in tree)


class TileCache:
    """LRU tile cache under a device byte budget. ``ensure(t)`` returns
    the tile's device arrays, issuing (async) uploads for absent or
    dirty tiles; eviction keeps resident bytes ≤ ``limit_bytes``, which
    is the configured budget floored at two tiles so the prefetch
    double buffer is always legal (``budget_raised`` records when the
    floor engaged)."""

    def __init__(self, budget_bytes: int, build_static, build_dynamic,
                 mesh=None):
        self.budget_bytes = int(budget_bytes)
        self._build_static = build_static
        self._build_dynamic = build_dynamic
        self.mesh = mesh
        self._resident: dict[int, dict] = {}
        self._dirty: set[int] = set()
        self._clock = 0
        self._tile_bytes = None  # learned from the first upload
        self.limit_bytes = int(budget_bytes)
        self.budget_raised = False
        self.uploads = 0
        self.reuploads = 0
        self.upload_bytes = 0
        self.reupload_bytes = 0
        self.evictions = 0
        self.hits = 0
        self.resident_peak_bytes = 0
        # nta: ignore[unbounded-cache] WHY: keyed by tile index — at
        # most n_tiles entries, and the cache lives for ONE
        # plan_batch_paged call
        self._ever: set[int] = set()

    def _put(self, tree):
        if self.mesh is not None:
            from . import shard as _shard

            specs = _shard.paged_specs()
            static_specs, dyn_specs = specs
            spec = static_specs if len(tree) == 4 else dyn_specs
            return _shard.put(tuple(tree), spec, self.mesh)
        _devprof.count_tree_h2d(tree)
        return tuple(jnp.asarray(x) for x in tree)

    def mark_dirty(self, tiles):
        for t in tiles:
            self._dirty.add(int(t))

    def _resident_bytes(self) -> int:
        if self._tile_bytes is None:
            return 0
        return len(self._resident) * self._tile_bytes

    def _evict_for(self, incoming: int):
        if self._tile_bytes is None:
            return
        while (self._resident
               and self._resident_bytes() + self._tile_bytes
               > self.limit_bytes):
            victim = min(self._resident, key=lambda t: self._resident[t]["stamp"])
            if victim == incoming:
                break
            del self._resident[victim]
            self.evictions += 1

    def ensure(self, t: int) -> dict:
        """Return tile ``t``'s device arrays, uploading what is absent
        or stale. Upload dispatch is asynchronous — call ``ensure(t+1)``
        before computing on tile ``t`` and the H2D stream overlaps the
        compute (the double buffer)."""
        self._clock += 1
        ent = self._resident.get(t)
        if ent is not None:
            ent["stamp"] = self._clock
            if t in self._dirty:
                dyn = self._build_dynamic(t)
                nbytes = _tree_nbytes(dyn)
                ent["dyn"] = self._put(dyn)
                self._dirty.discard(t)
                self.reuploads += 1
                self.reupload_bytes += nbytes
                self.upload_bytes += nbytes
                _devprof.count_tile_upload(nbytes, reupload=True)
            else:
                self.hits += 1
            return ent
        static = self._build_static(t)
        dyn = self._build_dynamic(t)
        s_bytes = _tree_nbytes(static)
        d_bytes = _tree_nbytes(dyn)
        if self._tile_bytes is None:
            self._tile_bytes = s_bytes + d_bytes
            # the double buffer needs two tiles resident; record when the
            # configured budget had to be raised to stay legal
            floor = 2 * self._tile_bytes
            if self.budget_bytes < floor:
                self.limit_bytes = floor
                self.budget_raised = True
        self._evict_for(t)
        revisit = t in self._ever
        ent = {
            "static": self._put(static),
            "dyn": self._put(dyn),
            "stamp": self._clock,
        }
        self._resident[t] = ent
        self._dirty.discard(t)
        self._ever.add(t)
        self.uploads += 1
        self.upload_bytes += s_bytes + d_bytes
        if revisit:
            self.reuploads += 1
            self.reupload_bytes += s_bytes + d_bytes
        _devprof.count_tile_upload(s_bytes + d_bytes, reupload=revisit)
        self.resident_peak_bytes = max(
            self.resident_peak_bytes, self._resident_bytes()
        )
        return ent

    def stats(self) -> dict:
        return {
            "budget_bytes": self.budget_bytes,
            "limit_bytes": self.limit_bytes,
            "budget_raised": self.budget_raised,
            "tile_bytes": self._tile_bytes or 0,
            "uploads": self.uploads,
            "reuploads": self.reuploads,
            "upload_bytes": self.upload_bytes,
            "reupload_bytes": self.reupload_bytes,
            "evictions": self.evictions,
            "hits": self.hits,
            "resident_peak_bytes": self.resident_peak_bytes,
        }


# ---------------------------------------------------------------------------
# the per-tile sweeps. Every argument is dynamic (scalars ride as 0-d
# arrays), so ONE compiled program covers every tile of a given shape —
# the same discipline that keeps the flat planners recompile-free.
# ---------------------------------------------------------------------------


@jax.jit
def _tile_count_jit(cap, feas, used, demand, t0, offset, n_real):
    """Sweep 1: per-tile feasible count and the count of feasible
    positions before the ring offset — the two integers the host needs
    to rebase every tile's rotation ranks exactly."""
    tn = cap.shape[0]
    pos = t0 + jnp.arange(tn, dtype=jnp.int32)
    in_ring = pos < n_real
    fit = feas & jnp.all(used + demand[None, :] <= cap, axis=1) & in_ring
    cnt = jnp.sum(fit.astype(jnp.int32))
    before = jnp.sum((fit & (pos < offset)).astype(jnp.int32))
    return cnt, before


@jax.jit
def _tile_window_jit(cap, usable, feas, used, coll, nodes, demand,
                     group_count, limit, t0, offset, n_real,
                     flat_base, x0, total, w_use):
    """Sweep 2: per-window partial winners within one tile — (max score,
    min feasible-rank among tile-local maxima, winner node id) for every
    window intersecting the tile, plus the consumed-ring watermark. The
    score math is the flat windowed planner's, op for op."""
    tn = cap.shape[0]
    # a tile's lanes carry up to TWO disjoint feasible-rank intervals —
    # positions ≥ offset rank low, wrapped positions (< offset) rank
    # high — so the window partials come in two groups, each with its
    # own base; within a group the window span is < tn, so a
    # (window - base) segment index never collides. Slot [2·tn] is the
    # dump segment for inactive lanes.
    s = 2 * tn + 1
    pos = t0 + jnp.arange(tn, dtype=jnp.int32)
    in_ring = pos < n_real
    fit = feas & jnp.all(used + demand[None, :] <= cap, axis=1) & in_ring

    util = used + demand[None, :]
    free_cpu = 1.0 - util[:, 0].astype(jnp.float32) / usable[:, 0]
    free_mem = 1.0 - util[:, 1].astype(jnp.float32) / usable[:, 1]
    binpack = _binpack(free_cpu, free_mem)
    anti_present = coll > 0
    anti = jnp.where(
        anti_present,
        -(coll.astype(jnp.float32) + 1.0) / group_count.astype(jnp.float32),
        0.0,
    )
    score = (binpack + anti) / (1.0 + anti_present.astype(jnp.float32))

    fit_i = fit.astype(jnp.int32)
    local_ex = jnp.cumsum(fit_i) - fit_i
    xex = flat_base + local_ex
    wrapped = pos < offset
    feas_rank = jnp.where(wrapped, total - x0 + xex, xex - x0)

    lm = jnp.maximum(limit, 1)
    window = feas_rank // lm
    active = fit & (window < w_use)
    base_lo = jnp.min(jnp.where(active & ~wrapped, window, _BIG))
    base_hi = jnp.min(jnp.where(active & wrapped, window, _BIG))
    seg_lo = jnp.clip(window - base_lo, 0, tn - 1)
    seg_hi = tn + jnp.clip(window - base_hi, 0, tn - 1)
    seg = jnp.where(active, jnp.where(wrapped, seg_hi, seg_lo), s - 1)
    seg_score = jax.ops.segment_max(
        jnp.where(active, score, NEG_INF), seg, num_segments=s
    )
    is_best = active & (score == seg_score[seg])
    seg_rank = jax.ops.segment_min(
        jnp.where(is_best, feas_rank, _BIG), seg, num_segments=s
    )
    winner = is_best & (feas_rank == seg_rank[seg])
    seg_node = jax.ops.segment_max(
        jnp.where(winner, nodes, -1), seg, num_segments=s
    )

    rot_rank = jnp.where(wrapped, n_real - offset + pos, pos - offset)
    consumed_window = fit & (feas_rank < w_use * limit)
    last = jnp.max(jnp.where(consumed_window, rot_rank, -1))
    bases = jnp.stack([base_lo, base_hi])
    return bases, seg_score, seg_rank, seg_node, last


# ---------------------------------------------------------------------------
# the paged windowed planner: host-orchestrated rounds over the tile
# stream; placements land directly in host memory (no full-axis D2H)
# ---------------------------------------------------------------------------


def plan_batch_paged(capacity, usable, feasible, perm, demand, group_count,
                     limit, n_allocs, used0, collisions0, n_real: int,
                     a_pad: int, mesh=None):
    """Windowed placement with the node axis streamed through device
    memory in tiles. Same inputs as the flat windowed planner (host
    numpy planes, node-id space + the ring permutation), same placements
    bit for bit; returns ``(placements i32[a_pad], rounds, stats)``.
    The ``tpu.kernel`` fault point degrades callers to the exact-np
    host oracle exactly as the flat dispatch does."""
    _faults.fault_point("tpu.kernel")
    capacity = np.asarray(capacity, dtype=np.int32)
    usable = np.asarray(usable, dtype=np.float32)
    feasible = np.asarray(feasible, dtype=bool)
    perm = np.asarray(perm, dtype=np.int32)
    used_nodes = np.asarray(used0, dtype=np.int32).copy()
    coll_nodes = np.asarray(collisions0, dtype=np.int32).copy()
    n0, c = capacity.shape

    tn = tile_rows(mesh)
    n_tiles = max(1, -(-int(n_real) // tn))
    n_pad = n_tiles * tn
    m = min(n0, n_pad)

    # ring-space planes: row q is ring position q's node (pad rows are
    # never in_ring, values only have to be type-safe)
    cap_r = np.zeros((n_pad, c), np.int32)
    cap_r[:m] = capacity[perm[:m]]
    usable_r = np.ones((n_pad, usable.shape[1]), np.float32)
    usable_r[:m] = usable[perm[:m]]
    feas_r = np.zeros(n_pad, bool)
    feas_r[:m] = feasible[perm[:m]]
    nodes_r = np.zeros(n_pad, np.int32)
    nodes_r[:m] = perm[:m]
    used_r = np.full((n_pad, c), _BIG, np.int32)
    used_r[:m] = used_nodes[perm[:m]]
    coll_r = np.zeros(n_pad, np.int32)
    coll_r[:m] = coll_nodes[perm[:m]]
    inv = np.zeros(n0, np.int64)
    inv[perm[:m]] = np.arange(m)

    def build_static(t):
        sl = slice(t * tn, (t + 1) * tn)
        return (cap_r[sl], usable_r[sl], feas_r[sl], nodes_r[sl])

    def build_dynamic(t):
        sl = slice(t * tn, (t + 1) * tn)
        return (used_r[sl], coll_r[sl])

    cache = TileCache(
        budget_mb() * (1 << 20), build_static, build_dynamic, mesh=mesh
    )
    sharded = mesh is not None
    n_shards = 1
    if sharded:
        from . import shard as _shard

        n_shards = _shard.mesh_size(mesh)
    ckey = f"T{tn}S{n_shards}c"
    wkey = f"T{tn}S{n_shards}w"

    demand_d = np.asarray(demand, dtype=np.int32)
    gcount_d = np.int32(group_count)
    limit_d = np.int32(limit)
    n_real_d = np.int32(n_real)
    a = int(n_allocs)
    lraw = int(limit)
    lm = max(lraw, 1)

    placements = np.full(a_pad, -1, np.int32)
    offset = 0
    placed = 0
    rounds = 0
    while placed < a:
        rounds += 1
        offset_d = np.int32(offset)

        # sweep 1: per-tile feasible counts (prefetch tile t+1's planes
        # while tile t computes — the H2D double buffer)
        cnts = np.zeros(n_tiles, np.int64)
        befs = np.zeros(n_tiles, np.int64)
        ent = cache.ensure(0)
        for t in range(n_tiles):
            cur = ent
            if t + 1 < n_tiles:
                ent = cache.ensure(t + 1)
            cap_t, _, feas_t, _ = cur["static"]
            used_t, _ = cur["dyn"]
            out, _ = _kernel._dispatch(
                "paged", _tile_count_jit,
                (cap_t, feas_t, used_t, demand_d,
                 np.int32(t * tn), offset_d, n_real_d),
                ckey,
            )
            cnts[t] = int(out[0])
            befs[t] = int(out[1])

        total = int(cnts.sum())
        x0 = int(befs.sum())
        remaining = a - placed
        w_use = min(max(total // lm, 1), remaining) if total > 0 else 0
        if w_use <= 0:
            break
        flat_base = np.zeros(n_tiles, np.int64)
        flat_base[1:] = np.cumsum(cnts)[:-1]

        # sweep 2: per-window partial winners, combined across tiles by
        # the flat planner's (max score, min rank) rule
        g_score = np.full(w_use, NEG_INF, np.float32)
        g_rank = np.full(w_use, _BIG, np.int64)
        g_node = np.full(w_use, -1, np.int64)
        last = -1
        ent = cache.ensure(0)
        for t in range(n_tiles):
            cur = ent
            if t + 1 < n_tiles:
                ent = cache.ensure(t + 1)
            cap_t, usable_t, feas_t, nodes_t = cur["static"]
            used_t, coll_t = cur["dyn"]
            out, _ = _kernel._dispatch(
                "paged", _tile_window_jit,
                (cap_t, usable_t, feas_t, used_t, coll_t, nodes_t,
                 demand_d, gcount_d, limit_d, np.int32(t * tn), offset_d,
                 n_real_d, np.int32(flat_base[t]), np.int32(x0),
                 np.int32(total), np.int32(w_use)),
                wkey,
            )
            bases = np.asarray(out[0])
            t_score = np.asarray(out[1])
            t_rank = np.asarray(out[2])
            t_node = np.asarray(out[3])
            last = max(last, int(out[4]))
            _devprof.count_d2h(
                t_score.nbytes + t_rank.nbytes + t_node.nbytes + 16
            )
            # two partial blocks per tile (the straddle groups); the
            # (max score, min rank) merge is associative, so folding
            # them in independently reproduces the flat argmax exactly
            for blk in (0, 1):
                w_base = int(bases[blk])
                if w_base >= _BIG:
                    continue
                lo = blk * tn
                w_ids = w_base + np.arange(tn, dtype=np.int64)
                b_node = t_node[lo:lo + tn]
                sel = (b_node != -1) & (w_ids < w_use)
                if not sel.any():
                    continue
                wi = w_ids[sel]
                sc = t_score[lo:lo + tn][sel]
                rk = t_rank[lo:lo + tn][sel]
                nd = b_node[sel]
                better = (sc > g_score[wi]) | (
                    (sc == g_score[wi]) & (rk < g_rank[wi])
                )
                wi = wi[better]
                g_score[wi] = sc[better]
                g_rank[wi] = rk[better]
                g_node[wi] = nd[better]

        # apply: window w's winner takes alloc slot (placed + w); each
        # winner is a distinct ring position (windows partition the
        # feasible rank space), so the vectorized update is race-free
        win_nodes = g_node
        placements[placed + np.arange(w_use)] = win_nodes.astype(np.int32)
        qpos = inv[win_nodes]
        used_r[qpos] += demand_d[None, :]
        coll_r[qpos] += 1
        used_nodes[win_nodes] += demand_d[None, :]
        coll_nodes[win_nodes] += 1
        cache.mark_dirty(np.unique(qpos // tn))

        ring_exhausted = total < w_use * lraw
        consumed = n_real if ring_exhausted else last + 1
        offset = (offset + max(consumed, 0)) % n_real
        placed += w_use

    if _devprof.enabled():
        _devprof.count_rounds("paged", rounds, a, sharded)
    stats = cache.stats()
    stats.update({
        "rounds": rounds,
        "tiles": n_tiles,
        "tile_nodes": tn,
        "placed": placed,
        "n_pad": n_pad,
    })
    return placements, rounds, stats


# ---------------------------------------------------------------------------
# the host oracle for this regime: a pure-numpy replica of the flat
# windowed planner, float32 op-for-op (the bit-stable _pow10 included),
# so paged placements can be pinned against host-recomputed truth
# without touching the exact-np sequential oracle
# ---------------------------------------------------------------------------


def _pow10_np(x):
    """``kernel._pow10`` in numpy float32 — every op is IEEE-exact or
    correctly rounded, so the bits match the device program's."""
    x = np.clip(x.astype(np.float32), np.float32(-45.2), np.float32(45.2))
    c = np.float32(4097.0) * x
    x_hi = c - (c - x)
    x_lo = x - x_hi
    y_hi = x_hi * np.float32(_LOG2_10_HI)
    y_lo = x_hi * np.float32(_LOG2_10_LO) + x_lo * np.float32(_LOG2_10)
    n = np.round(y_hi + y_lo)
    f = (y_hi - n) + y_lo
    p = np.float32(1.535336188319500e-4)
    p = p * f + np.float32(1.339887440266574e-3)
    p = p * f + np.float32(9.618437357674640e-3)
    p = p * f + np.float32(5.550332471162809e-2)
    p = p * f + np.float32(2.402264791363012e-1)
    p = p * f + np.float32(6.931472028550421e-1)
    p = p * f + np.float32(1.0)
    n_i = n.astype(np.int32)
    n1 = np.clip(n_i, -126, 127)
    n2 = np.clip(n_i - n1, -126, 127)

    def two_pow(e):
        return ((e + 127) << 23).astype(np.int32).view(np.float32)

    return p * two_pow(n1) * two_pow(n2)


def _binpack_np(free_cpu, free_mem):
    total = _pow10_np(free_cpu) + _pow10_np(free_mem)
    return np.clip(np.float32(20.0) - total,
                   np.float32(0.0), np.float32(18.0)) / np.float32(18.0)


def plan_windowed_np(capacity, usable, feasible, perm, demand, group_count,
                     limit, n_allocs, used0, collisions0, n_real: int,
                     a_pad: int):
    """Host-numpy windowed placement — the oracle the paged planner is
    pinned against. Returns ``(placements i32[a_pad], rounds)``."""
    capacity = np.asarray(capacity, dtype=np.int32)
    usable = np.asarray(usable, dtype=np.float32)
    feasible = np.asarray(feasible, dtype=bool)
    perm = np.asarray(perm, dtype=np.int64)
    demand = np.asarray(demand, dtype=np.int32)
    used = np.asarray(used0, dtype=np.int32).copy()
    coll = np.asarray(collisions0, dtype=np.int32).copy()
    n0 = capacity.shape[0]
    positions = np.arange(n0, dtype=np.int64)
    in_ring = positions < n_real
    a = int(n_allocs)
    lraw = int(limit)
    lm = max(lraw, 1)
    gcf = np.float32(int(group_count))

    placements = np.full(a_pad, -1, np.int32)
    offset = 0
    placed = 0
    rounds = 0
    while placed < a:
        rounds += 1
        fit_nodes = feasible & np.all(used + demand[None, :] <= capacity,
                                      axis=1)
        util = used + demand[None, :]
        free_cpu = np.float32(1.0) - util[:, 0].astype(np.float32) / usable[:, 0]
        free_mem = np.float32(1.0) - util[:, 1].astype(np.float32) / usable[:, 1]
        binpack = _binpack_np(free_cpu, free_mem)
        anti_present = coll > 0
        anti = np.where(
            anti_present, -(coll.astype(np.float32) + np.float32(1.0)) / gcf,
            np.float32(0.0),
        ).astype(np.float32)
        final = (binpack + anti) / (
            np.float32(1.0) + anti_present.astype(np.float32)
        )

        fit_p = fit_nodes[perm] & in_ring
        score_p = final[perm]
        total = int(fit_p.sum())
        xc = np.cumsum(fit_p.astype(np.int64))
        xex = xc - fit_p
        x_off = xex[offset]
        feas_rank = np.where(positions >= offset, xex - x_off,
                             total - x_off + xex)
        remaining = a - placed
        w_use = min(max(total // lm, 1), remaining) if total > 0 else 0
        if w_use <= 0:
            break
        window = feas_rank // lm
        active = fit_p & (window < w_use)
        act = np.nonzero(active)[0]
        w = window[act]
        sc = score_p[act]
        rk = feas_rank[act]
        order = np.lexsort((rk, -sc.astype(np.float64), w))
        ws = w[order]
        first = np.ones(len(ws), bool)
        first[1:] = ws[1:] != ws[:-1]
        win_pos = act[order][first]
        win_w = ws[first]
        win_nodes = perm[win_pos]
        used[win_nodes] += demand[None, :]
        coll[win_nodes] += 1
        placements[placed + win_w] = win_nodes.astype(np.int32)

        rot_rank = np.where(positions >= offset, positions - offset,
                            n_real - offset + positions)
        consumed_window = fit_p & (feas_rank < w_use * lraw)
        last = int(rot_rank[consumed_window].max()) if consumed_window.any() else -1
        ring_exhausted = total < w_use * lraw
        consumed = n_real if ring_exhausted else last + 1
        offset = (offset + max(consumed, 0)) % n_real
        placed += w_use
    return placements, rounds


# one enumeration: compile ledger, recompile detector, warmup ladder and
# the bench all iterate PLANNER_JITS; registration rides this module's
# import (batch_sched imports it before routing, and
# kernel.compile_cache_size pulls it in lazily — no top-level cycle)
_kernel.PLANNER_JITS["paged"] = _tile_window_jit
_kernel.PLANNER_JITS["paged_count"] = _tile_count_jit
